#!/bin/bash
# Round-5 hardware window #2 — after window #1 (BENCH_r05_builder.jsonl
# lines 1-10) measured the int8 headline but found the int4 fusion break
# and the discuss-bench logits OOM, both fixed in-tree:
#   0. bench_microquant.py  ~1-minute per-representation fusion check
#                           (is the new bitcast int4 layout streaming
#                           packed bytes? is native-S4 viable?) — its
#                           own probe-first watchdog, like every bench;
#                           no shell `timeout` anywhere (a SIGKILLed
#                           JAX child is the suspected relay-wedge
#                           event, and windows 2-4 all died mid-run).
#   1. bench.py             re-measure all 4 configs (int4 relayout +
#                           prefill lm-head fix land here)
#   2. bench_discuss.py     config 2's FIRST hardware number (OOM fixed)
#   3. bench_suite.py all   configs 3-5 (tunnel died before them in #1)
#   4. bench_profile.py     int4 attribution (keep even if fast — the
#                           artifact shows WHERE the time goes now)
#   5. bench_realweights.py on-chip stretch goal, LAST so a hang there
#                           cannot cost any core measurement
# Same per-step commit discipline as run_hw_window.sh (shared lib).
set -u
cd "$(dirname "$0")" || exit 1
OUT=BENCH_r05_builder.jsonl
. ./hw_window_lib.sh

run_step "bench_microquant.py"         python bench_microquant.py
run_step "bench.py (config 1)"         python bench.py
run_step "bench_discuss.py (config 2)" python bench_discuss.py
run_step "bench_suite.py (configs 3-5)" python bench_suite.py all
run_step "bench_profile.py"            python bench_profile.py
# timeout sends SIGTERM (not KILL); realweights installs a clean-exit
# handler, and this is the LAST step so even a wedge costs no data.
run_step "bench_realweights.py (on-chip)" \
  timeout 900 python bench_realweights.py --min-turns 20 --budget-s 840
git add REALWEIGHTS_r05.json 2>/dev/null && \
  git commit -q -o REALWEIGHTS_r05.json \
    -m "Hardware window 2: on-chip realweights artifact

No-Verification-Needed: measurement artifact only, no source change" \
  || true
echo "window 2 complete: $(stamp)"; tail -n +1 "$OUT" | wc -l
