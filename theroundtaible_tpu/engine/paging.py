"""Paged KV cache — page-pool allocation with copy-on-write sharing.

Replaces the contiguous `[num_slots, max_seq_len, K, D]` per-layer cache
(kvcache.py) whose HBM cost is num_slots × max_seq_len regardless of use
(VERDICT r1 missing #3; PAPERS.md "Ragged Paged Attention"). Here each
layer owns a page POOL `[num_pages, page_size, K, D]` and each slot maps
its logical positions onto pool pages through a page table:

- HBM scales with tokens actually cached, not slots × max_seq_len — the
  freed budget is what lets a second model stay resident (SURVEY.md §7.3
  hard part 3).
- Pages are position-aligned (page j of a slot covers absolute positions
  [j*page_size, (j+1)*page_size)), so two slots whose token prefixes agree
  can ALIAS the same pages: cross-knight shared-prefix reuse becomes a
  refcount bump instead of a device copy. Only the boundary page where the
  prompts diverge is copied (copy-on-write).
- Page 0 is a reserved scratch page: table rows are padded with it, and
  batch rows scatter their unused tail there. It is never aliased and
  never read (valid-length masks bound every attention read).

Data-axis sharding (per-replica pools, VERDICT r3 #7): on a mesh with a
data axis the PAGE axis shards over "data" — each replica physically
holds num_pages/data pages (plus its kv-head shard on "model"), so DP
and fleet configs no longer pay data× the pool HBM. The allocator makes
the layout coherent: pages partition into per-replica ranges (each with
its own scratch page — the first page of the range — so pad-cell
scatters stay replica-local), every slot is pinned to one replica at
creation (least-loaded, deterministic) and only ever allocates from its
replica's range, and cross-replica prefix sharing falls back from page
ALIASING to page COPIES (an aliased page cannot live on two replicas).
Serving under data>1 is pool-direct too (VERDICT r4 #4): the engine
permutes each batch into contiguous per-replica row blocks — matching
how shard_map splits the batch axis — pads every block to the largest
group with scratch-table rows that start done, and the spmd kernels
rebase each shard's table to its local page range via axis_index. The
gather view survives only as the non-partitionable-heads / attn="dense"
fallback.

The device side stays simple on purpose: the engine's jit'd programs
gather `pool[table]` into the same position-aligned `[B, S, K, D]` view
the contiguous path uses — forward() and the Pallas kernels are layout-
agnostic — and scatter the updated view back through the same table. The
gather/scatter traffic equals the contiguous path's per-slot row
gather/scatter; the win is RESIDENT memory, not per-step traffic.

The reference has no counterpart (its KV memory lives inside Ollama's
llama.cpp, reference src/adapters/local-llm.ts); this is the engine-side
equivalent of vLLM/tpu-inference paged attention, re-designed for XLA.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .models.common import ModelConfig


def make_padded_copier(copy_fn: Callable, width: int = 8) -> Callable:
    """Wrap a jit'd whole-page copy `copy_fn(pools, src_ids, dst_ids)`
    so it compiles exactly ONE shape: copies run in fixed-width chunks,
    short chunks zero-padded (pad rows copy the scratch page onto
    itself — identical bytes, any scatter order). COW/boundary copies
    are typically 1-2 pages, so width=8 keeps padding waste small and
    bounds per-dispatch traffic (vs padding to pages_per_seq, which
    would move a whole sequence's worth of pages for a 1-page copy).
    Shared by both engines' paged layouts — the driver is layout-
    agnostic, only the jit'd copy differs."""

    def padded(pools, src_ids, dst_ids):
        n = int(src_ids.shape[0])
        for start in range(0, n, width):
            s_ids = src_ids[start:start + width]
            d_ids = dst_ids[start:start + width]
            pad = width - int(s_ids.shape[0])
            if pad:
                s_ids = jnp.concatenate(
                    [s_ids, jnp.zeros((pad,), jnp.int32)])
                d_ids = jnp.concatenate(
                    [d_ids, jnp.zeros((pad,), jnp.int32)])
            pools = copy_fn(pools, s_ids, d_ids)
        return pools

    return padded


@dataclass
class PagedSlot:
    """Host-side bookkeeping for one knight's slot."""

    name: str
    tokens: list[int] = field(default_factory=list)  # ids baked into cache
    pages: list[int] = field(default_factory=list)   # logical order
    replica: int = 0  # data-axis replica owning every page of this slot


class PagedKVCache:
    """Page-pool KV cache with the same slot interface as KVCache.

    `copy_pages_fn(pools, src_ids, dst_ids)` is the engine-provided jit'd
    program that copies whole pages (used for copy-on-write); it is the
    only device operation the allocator itself triggers.
    """

    def __init__(self, cfg: ModelConfig, num_slots: int,
                 max_seq_len: Optional[int] = None, dtype=jnp.bfloat16,
                 sharding=None, page_size: int = 128,
                 num_pages: Optional[int] = None,
                 copy_pages_fn: Optional[Callable] = None,
                 pool_factory: Optional[Callable] = None,
                 data_size: int = 1, kv_quant=None):
        self.cfg = cfg
        self.num_slots = num_slots
        self.max_seq_len = max_seq_len or cfg.max_seq_len
        if self.max_seq_len % page_size:
            raise ValueError(
                f"max_seq_len {self.max_seq_len} must be a multiple of "
                f"page_size {page_size}")
        self.page_size = page_size
        self.pages_per_seq = self.max_seq_len // page_size
        self.data_size = max(int(data_size), 1)
        # Quantized pages (ISSUE 11): `kv_quant` is a
        # kv_quant.KVQuantSpec — pools store int8 payload (int4: packed
        # nibbles) with a parallel per-layer per-cell scale pool.
        # Scales are indexed by the SAME page axis, so every sharing
        # mechanism (alias/adopt/COW/commit/prefix-cache/offload)
        # carries them with the page for free.
        self.kv_quant = kv_quant
        self._kv_dtype_bytes = jnp.dtype(dtype).itemsize
        if kv_quant is not None and pool_factory is not None:
            raise ValueError(
                "kv_quant is not supported with a custom pool_factory "
                "(the PP engine's stage-stacked pools decline upstream)")
        # Default pool: HALF the contiguous budget — the honest claim of
        # paging is serving the same slots in less HBM — plus one scratch
        # page per data replica (data_size == 1: page 0, as before).
        # Quantized pools keep the SAME BYTE budget (the bf16 default's
        # bytes), so the freed bytes become MORE PAGES — the
        # 2-4x-resident-sessions payoff. Page demand math everywhere is
        # in pages; the dtype dependence lives here, once.
        if num_pages is None:
            num_pages = max(num_slots * self.pages_per_seq // 2,
                            self.data_size * self.pages_per_seq)
            if kv_quant is not None:
                from .kv_quant import page_ratio
                num_pages = int(num_pages * page_ratio(
                    kv_quant, cfg.head_dim, self._kv_dtype_bytes))
            num_pages += self.data_size
        # The page axis shards over "data": round up so it divides.
        self.num_pages = -(-num_pages // self.data_size) * self.data_size
        per_replica = self.num_pages // self.data_size
        if per_replica < self.pages_per_seq + 1:
            raise ValueError(
                f"num_pages {self.num_pages} over {self.data_size} "
                f"replica(s) cannot hold even one full sequence per "
                f"replica ({self.pages_per_seq} pages + scratch)")
        if pool_factory is not None:
            # Custom pool layout (the PP engine stacks every stage's
            # layer range into ONE stage-sharded pool pair whose page
            # axis this allocator still manages; copy_pages_fn must
            # address pages in that layout).
            self._make_pools = pool_factory
        elif kv_quant is None:
            shape = (self.num_pages, page_size, cfg.num_kv_heads,
                     cfg.head_dim)
            make = (lambda: jnp.zeros(shape, dtype)) if sharding is None \
                else (lambda: jax.device_put(jnp.zeros(shape, dtype),
                                             sharding))
            self._make_pools = lambda n_pages: [
                (make(), make()) for _ in range(cfg.num_layers)]
        else:
            qshape = (self.num_pages, page_size, cfg.num_kv_heads,
                      kv_quant.packed_dim(cfg.head_dim))
            sshape = (self.num_pages, page_size, cfg.num_kv_heads,
                      kv_quant.num_groups(cfg.head_dim))

            def _mk(shape, dt):
                x = jnp.zeros(shape, dt)
                # Scale pools share the payload's sharding spec — same
                # page and kv-head axes, unsharded minor axis.
                return x if sharding is None else jax.device_put(
                    x, sharding)

            self._make_pools = lambda n_pages: [
                (_mk(qshape, jnp.int8), _mk(qshape, jnp.int8))
                for _ in range(cfg.num_layers)]
            self._make_scales = lambda n_pages: [
                (_mk(sshape, jnp.float32), _mk(sshape, jnp.float32))
                for _ in range(cfg.num_layers)]
        self.pools = self._make_pools(self.num_pages)
        self.scales = (self._make_scales(self.num_pages)
                       if kv_quant is not None else None)
        self._copy_pages_fn = copy_pages_fn
        self._slots: dict[str, PagedSlot] = {}
        # Replica r owns pages [r*per, (r+1)*per); the range's FIRST page
        # is that replica's scratch (never allocated, never aliased).
        self._per_replica = per_replica
        self._scratch = [r * per_replica for r in range(self.data_size)]
        self._free_by_replica: list[list[int]] = [
            list(range(r * per_replica + 1, (r + 1) * per_replica))
            for r in range(self.data_size)]
        self._refs: dict[int, int] = {}
        # Cross-session prefix cache (engine/prefix_cache.py, ISSUE 7):
        # attached by the engine after construction. The allocator's only
        # couplings are (a) commit() publishes complete pages into it,
        # (b) _alloc_page reclaims its refcount-0 pages before declaring
        # exhaustion, (c) flush()/revive drop it with the slots.
        self.prefix_cache = None

    # --- introspection / accounting ---

    def pages_in_use(self) -> int:
        free = sum(len(f) for f in self._free_by_replica)
        return self.num_pages - self.data_size - free

    def free_pages(self, replica: Optional[int] = None) -> int:
        """Immediately-allocatable pages (one replica's range, or all).
        Excludes everything reclaimable-under-pressure (idle evictable
        slots, refcount-0 prefix-cache nodes) — the scheduler's spill
        policy keys off this to spill idle sessions BEFORE the allocator
        destroys their caches."""
        if replica is not None:
            return len(self._free_by_replica[replica])
        return sum(len(f) for f in self._free_by_replica)

    def usable_pages(self) -> int:
        """Total non-scratch pages across every replica range."""
        return self.num_pages - self.data_size

    def pages_held(self, names: list[str]) -> int:
        """Pages currently mapped by the named slots (missing names count
        0). The scheduler's admission backpressure uses this to compute
        how much of the pool is PINNED by in-flight rows — everything
        else is reclaimable by the allocator's LRU eviction, so "free
        right now" would undercount what an admission could use."""
        return sum(len(self._slots[n].pages)
                   for n in names if n in self._slots)

    def hbm_bytes(self) -> int:
        """Resident pool bytes across all layers — payload plus, on
        quantized pools, the per-cell scale arrays (ISSUE 11)."""
        k, _ = self.pools[0]
        total = 2 * k.size * k.dtype.itemsize * len(self.pools)
        if self.scales is not None:
            s, _ = self.scales[0]
            total += 2 * s.size * s.dtype.itemsize * len(self.scales)
        return total

    def hbm_bytes_logical(self) -> int:
        """What the SAME pools would cost at the bf16 cell layout — the
        ledger's kv_bytes_logical counterpart to hbm_bytes (resident).
        Identical to hbm_bytes on unquantized pools."""
        if self.kv_quant is None:
            return self.hbm_bytes()
        return (2 * self.num_pages * self.page_size
                * self.cfg.num_kv_heads * self.cfg.head_dim
                * self._kv_dtype_bytes * len(self.pools))

    # --- combined pool pytree (ISSUE 11) ---
    #
    # The engine's donated jit programs carry pools and scales as ONE
    # pytree (per-layer (k, v) pairs, scale pairs appended), so every
    # dispatch seam moves them together and bf16 engines see exactly
    # the old list — the kill-switch byte-identity hinges on that.

    def combined_pools(self) -> list:
        if self.scales is None:
            return self.pools
        return list(self.pools) + list(self.scales)

    def set_combined(self, combined: list) -> None:
        n = len(self.pools)
        if self.scales is None:
            self.pools = combined
        else:
            self.pools = list(combined[:n])
            self.scales = list(combined[n:])

    def _run_page_copy(self, src_ids, dst_ids) -> None:
        """Whole-page device copy through the engine's jit'd copier —
        scale rows ride the same dispatch on quantized pools (a COW'd
        or adopted page without its scales would dequantize garbage)."""
        out = self._copy_pages_fn(self.combined_pools(),
                                  jnp.asarray(src_ids, jnp.int32),
                                  jnp.asarray(dst_ids, jnp.int32))
        self.set_combined(out)

    def slot_names(self) -> list[str]:
        return list(self._slots)

    def memory_ledger(self) -> dict:
        """Paged-pool accounting for the memory ledger (ISSUE 6/7):
        pages in use / usable, slot occupancy, and internal
        FRAGMENTATION — the fraction of held page cells not backing a
        cached token (decode reserve + tail waste inside each slot's
        last pages). `pages_in_use` counts pool allocation (aliased
        shared pages once). Fragmentation is REFCOUNT-AWARE (ISSUE 7
        satellite): computed over DISTINCT pages with each page's
        covered cells taken once (the max over the slots mapping it),
        so a page shared by N sessions never counts N times and the
        ledger's shared/exclusive split is honest across sessions."""
        in_use = self.pages_in_use()
        usable = self.usable_pages()
        cached_tokens = sum(len(s.tokens) for s in self._slots.values())
        ps = self.page_size
        # page -> covered cells (max over the slots mapping it): shared
        # pages counted ONCE.
        covered: dict[int, int] = {}
        map_counts: dict[int, int] = {}
        for s in self._slots.values():
            for j, p in enumerate(s.pages):
                map_counts[p] = map_counts.get(p, 0) + 1
                cov = max(0, min(len(s.tokens) - j * ps, ps))
                if cov > covered.get(p, -1):
                    covered[p] = cov
        held_cells = len(covered) * ps
        frag = (round(1.0 - min(sum(covered.values()) / held_cells, 1.0),
                      3) if held_cells else 0.0)
        # "Shared" means DEDUPLICATED bytes: ≥2 slot mappings, or a
        # non-index external holder (offload tier / earlier spill). The
        # index's own bookkeeping ref is not sharing — one session with
        # the cache on would otherwise report every committed page as
        # shared and inflate the capacity-multiplier estimate the bench
        # derives from the exclusive count (review finding).
        pc = self.prefix_cache

        def _is_shared(p: int) -> bool:
            if map_counts.get(p, 0) >= 2:
                return True
            extra = self._refs.get(p, 1) - map_counts.get(p, 0)
            if pc is not None and pc.holds_page(p):
                extra -= 1
            return extra >= 1

        shared = sum(1 for p in covered if _is_shared(p))
        cache_pages = (self.prefix_cache.page_count()
                       if self.prefix_cache is not None else 0)
        n_slots = len(self._slots)
        # Quantized-page split (ISSUE 11 satellite): resident = what
        # the pools actually cost (payload + scales), logical = what
        # the same pools would cost at bf16 cells. The saved delta
        # feeds roundtable_kv_quant_bytes_saved.
        resident = self.hbm_bytes()
        logical = self.hbm_bytes_logical()
        return {
            "layout": "paged",
            "kv_dtype": (self.kv_quant.dtype_name
                         if self.kv_quant is not None else "bf16"),
            "kv_quant_bits": (self.kv_quant.bits
                              if self.kv_quant is not None else 0),
            "kv_bytes_resident": resident,
            "kv_bytes_logical": logical,
            "kv_quant_bytes_saved": max(logical - resident, 0),
            "slots_in_use": n_slots,
            "num_slots": self.num_slots,
            "slot_occupancy": round(n_slots / max(self.num_slots, 1), 3),
            "cached_tokens": cached_tokens,
            "pages_in_use": in_use,
            "usable_pages": usable,
            "page_utilization": round(in_use / max(usable, 1), 3),
            "fragmentation": frag,
            # ISSUE 7: the cross-session sharing split. `shared_pages`
            # are slot-mapped pages with >1 holder (other slots, the
            # prefix cache, the offload tier); `prefix_cache_pages` is
            # the index's own footprint (overlaps slot-mapped pages
            # while both reference them — pool allocation still counts
            # each page once via pages_in_use).
            "shared_pages": shared,
            "exclusive_pages": len(covered) - shared,
            "prefix_cache_pages": cache_pages,
            "hbm_bytes": resident,
        }

    def revive_if_dead(self) -> bool:
        """Reallocate the page pools if a failed donated dispatch deleted
        them (KVCache.revive_if_dead's paged counterpart). Every slot,
        page mapping and refcount is dropped — the bytes are gone — so
        later prefills start from scratch. Returns True iff revived."""
        k, _ = self.pools[0]
        if not k.is_deleted():
            return False
        self.pools = self._make_pools(self.num_pages)
        if self.scales is not None:
            self.scales = self._make_scales(self.num_pages)
        self._slots.clear()
        self._refs.clear()
        per = self._per_replica
        self._free_by_replica = [
            list(range(r * per + 1, (r + 1) * per))
            for r in range(self.data_size)]
        if self.prefix_cache is not None:
            # The indexed bytes died with the pools; drop the nodes
            # WITHOUT unref (the refs table was just cleared).
            self.prefix_cache.clear(unref=False)
        return True

    # --- slot lifecycle (KVCache-compatible surface) ---

    def acquire(self, name: str, pinned: tuple[str, ...] = ()) -> PagedSlot:
        if name in self._slots:
            self._slots[name] = self._slots.pop(name)  # LRU refresh
            return self._slots[name]
        if len(self._slots) >= self.num_slots:
            victim = next((n for n in self._slots if n not in pinned), None)
            if victim is None:
                raise RuntimeError(
                    f"PagedKVCache has {self.num_slots} slots but "
                    f"{len(pinned)} knights are pinned in one batch — "
                    "raise num_slots in the tpu-llm adapter config")
            self.release(victim)
        # Pin the new slot to the replica hosting the fewest slots, with
        # free pages breaking ties (slots acquire BEFORE they allocate,
        # so free-page counts alone tie at batch start and would pile
        # every slot onto replica 0). Deterministic: depends only on the
        # call sequence — multi-host lockstep safe.
        counts = [0] * self.data_size
        for s in self._slots.values():
            counts[s.replica] += 1
        replica = min(range(self.data_size),
                      key=lambda r: (counts[r],
                                     -len(self._free_by_replica[r]), r))
        state = PagedSlot(name=name, replica=replica)
        self._slots[name] = state
        return state

    def release(self, name: str) -> None:
        state = self._slots.pop(name, None)
        if state is not None:
            for p in state.pages:
                self._decref(p)

    def flush(self) -> int:
        """Release every per-knight slot (graceful drain's KV flush,
        fleet.drain — SlotBook.flush's paged counterpart): each slot's
        pages decref and free back to their replica ranges, and the
        prefix cache drops its index the same way — every holder UNREFS
        (never force-frees), so a page momentarily shared between a slot
        and the index frees exactly when the last reference goes.
        Returns how many slots were flushed."""
        names = list(self._slots)
        for name in names:
            self.release(name)
        if self.prefix_cache is not None:
            self.prefix_cache.drop_all()
        return len(names)

    def reset_slot(self, name: str) -> None:
        if name in self._slots:
            state = self._slots[name]
            for p in state.pages:
                self._decref(p)
            state.pages = []
            state.tokens = []

    # --- refcounting ---

    def _decref(self, page: int) -> None:
        n = self._refs.get(page, 1) - 1
        if n <= 0:
            self._refs.pop(page, None)
            # A page always frees back to the replica range it belongs to.
            self._free_by_replica[page // self._per_replica].append(page)
        else:
            self._refs[page] = n

    def _incref(self, page: int) -> None:
        self._refs[page] = self._refs.get(page, 1) + 1

    def _shared(self, page: int) -> bool:
        return self._refs.get(page, 1) > 1

    def _index_only_share(self, page: int) -> bool:
        """True when `page`'s only holder besides the mapping slot is
        the prefix-cache index (refcount exactly 2 with an index hold).
        The write paths then make the page exclusive by FORGETTING the
        index entry instead of copy-on-write: the slot's divergence is
        invalidating that entry's continuation anyway, and the forget
        costs zero pages and zero dispatches where a COW under a full
        pool can be the allocation that doesn't exist (observed: a
        16-page pool serving one 16-page sequence died COWing page 0
        against the index's hold)."""
        return (self.prefix_cache is not None
                and self._refs.get(page, 1) == 2
                and self.prefix_cache.holds_page(page))

    # Public refcount surface (ISSUE 7): the prefix cache and the host
    # offload tier hold references of their own, so a page shared by N
    # sessions plus the index is stored once and only ever FREES when
    # every holder has unref'd — release/flush/retire paths decref, never
    # force-free.

    def ref(self, page: int) -> None:
        """Take one reference on `page` (index/offload-tier holders)."""
        self._incref(page)

    def unref(self, page: int) -> None:
        """Drop one reference; the page frees to its replica range only
        when the LAST holder lets go."""
        self._decref(page)

    def refcount(self, page: int) -> int:
        """Current holder count (1 = exactly one holder)."""
        return self._refs.get(page, 1)

    def replica_of_page(self, page: int) -> int:
        """The data replica whose range physically holds `page`."""
        return page // self._per_replica

    def cow_page(self, name: str, j: int,
                 pinned: tuple[str, ...] = ()) -> int:
        """Copy-on-write primitive: give `name` exclusive ownership of
        its logical page j, device-copying the shared original into a
        fresh page on the slot's replica. No-op (returns the existing
        id) when the page is already exclusive."""
        state = self._slots[name]
        p = state.pages[j]
        if not self._shared(p):
            return p
        if self._index_only_share(p):
            self.prefix_cache.forget_page(p)
            return p
        pinned = tuple(pinned) + (name,)
        fresh = self._alloc_page(pinned, state.replica)
        self._decref(p)
        state.pages[j] = fresh
        self._run_page_copy([p], [fresh])
        return fresh

    def _alloc_page(self, pinned_names: tuple[str, ...],
                    replica: int = 0) -> int:
        free = self._free_by_replica[replica]
        if not free and self.prefix_cache is not None:
            # CHEAPEST first: reclaim LRU refcount-0 prefix-cache nodes
            # on this replica (pages held ONLY by the index — a node
            # some live slot still aliases is never touched). With the
            # cache on, evicting a slot first would free almost nothing
            # (its complete pages stay index-held) while destroying the
            # slot's record — the loop could wipe every idle slot on
            # the replica before one pure-cache page was even tried.
            self.prefix_cache.reclaim(replica=replica)
        if not free:
            # Evict LRU slots (dict order = recency) until a page frees
            # ON THIS REPLICA — victims on other replicas free pages this
            # slot cannot use, so destroying their caches would cost
            # reuse without unblocking anything. A released victim's
            # index-held pages drop to refcount-0: reclaim between
            # victims so each eviction actually yields its pages.
            for victim in list(self._slots):
                if (victim in pinned_names
                        or self._slots[victim].replica != replica):
                    continue
                self.release(victim)
                if not free and self.prefix_cache is not None:
                    self.prefix_cache.reclaim(replica=replica)
                if free:
                    break
        if not free:
            raise RuntimeError(
                f"Page pool exhausted on data replica {replica}: all its "
                "pages pinned by the in-flight batch — raise num_pages "
                "(tpu-llm adapter config) or lower max_new_tokens")
        return free.pop(0)

    # --- raw page loans (ISSUE 13: tree-verify private path pages) ---

    def take_free_pages(self, n: int,
                        replica: int = 0) -> Optional[list[int]]:
        """Borrow `n` pages from the FREE list only — never evicts a
        slot and never reclaims the prefix cache, so a borrower that
        can gracefully do without (the tree verify degrades a row to
        chain speculation) cannot destroy resident state to get its
        scratch. None when the replica's free list is short."""
        free = self._free_by_replica[replica]
        if len(free) < n:
            return None
        return [free.pop(0) for _ in range(n)]

    def give_back_pages(self, pages: list[int]) -> None:
        """Return pages taken by take_free_pages (or adopted-and-
        replaced pages) — plain decref, so a page that was swapped
        into a slot's table meanwhile is NOT freed under it."""
        for p in pages:
            self._decref(p)

    def swap_in_page(self, name: str, j: int, page: int) -> None:
        """Replace slot `name`'s logical page j with `page`, whose
        cells already hold the position range's K/V (the tree verify's
        accepted path: the private page was pre-COW'd from the old
        frontier page in-dispatch, then received the accepted tokens'
        writes — a copy-on-write whose copy already happened). The old
        page decrefs (an index/donor holder keeps its copy; exclusive
        pages free), and the loaned page's reference becomes the
        slot's mapping reference."""
        state = self._slots[name]
        self._decref(state.pages[j])
        state.pages[j] = page

    # --- prefix bookkeeping ---

    @staticmethod
    def common_prefix_len(cached: list[int], new: list[int]) -> int:
        from ..native import lcp
        return lcp(cached, new)

    def reuse_plan(self, name: str, tokens: list[int],
                   pinned: tuple[str, ...] = ()) -> tuple[int, int]:
        """(-1, reuse_len) — same shape as KVCache.reuse_plan, but paged
        rows are keyed by table_for(names), never by a device slot id (the
        -1 sentinel fails loudly if ever used as an index). Truncates the
        record now (crash safety) and drops whole pages beyond the reuse
        frontier."""
        state = self.acquire(name, pinned)
        reuse = self.common_prefix_len(state.tokens, tokens)
        reuse = min(reuse, len(tokens) - 1)
        state.tokens = state.tokens[:reuse]
        self._trim_pages(state, reuse)
        # Paged layout has no device slot id — every program keys rows by
        # table_for(names). Return a sentinel so a future caller indexing
        # device arrays with it fails loudly instead of corrupting rows.
        return -1, reuse

    def _trim_pages(self, state: PagedSlot, tokens_kept: int) -> None:
        """Free pages wholly beyond ceil(tokens_kept / page_size)."""
        keep = -(-tokens_kept // self.page_size) if tokens_kept else 0
        while len(state.pages) > keep:
            self._decref(state.pages.pop())

    def commit(self, name: str, tokens: list[int],
               index: bool = True) -> None:
        # `index=False` (ISSUE 10): the slot's pages hold
        # adapter-tinted K/V — commit the token record for own-slot
        # reuse, but never publish the pages into the cross-session
        # index (base rows of other sessions must not alias them).
        state = self.acquire(name)
        state.tokens = list(tokens)
        self._trim_pages(state, len(tokens))
        if (index and self.prefix_cache is not None
                and not name.startswith("__warmup_")):
            # Publish the slot's COMPLETE pages into the content-
            # addressed index (ISSUE 7): the next session whose prompt
            # starts with the same token blocks aliases them instead of
            # re-prefilling. Warmup slots are excluded — warm rows are
            # crafted to defeat prefix sharing so every (batch, bucket)
            # program actually compiles.
            self.prefix_cache.insert(state)

    def best_donor(self, name: str,
                   tokens: list[int]) -> tuple[Optional[PagedSlot], int]:
        """Longest-common-prefix donor; prefix-length ties prefer a donor
        on the SAME replica as `name` — same-replica spans alias for free
        while cross-replica spans degrade to device copies plus duplicate
        pages out of the destination replica's range (review finding).
        Donation is intra-session only (kvcache.session_of): sessions are
        isolation domains, and a cross-session alias would couple one
        session's page lifetime to another's fault recovery."""
        from .kvcache import session_of
        dst = self._slots.get(name)
        dst_replica = dst.replica if dst is not None else 0
        scope = session_of(name)
        best, best_key = None, (0, -1)
        for state in self._slots.values():
            if state.name == name or not state.tokens:
                continue
            if session_of(state.name) != scope:
                continue
            n = self.common_prefix_len(state.tokens, tokens)
            if n == 0:
                continue
            key = (n, 1 if state.replica == dst_replica else 0)
            if key > best_key:
                best, best_key = state, key
        return best, best_key[0]

    # --- capacity + sharing ---

    def ensure_capacity(self, name: str, upto_tokens: int,
                        write_from: int,
                        pinned: tuple[str, ...] = ()) -> None:
        """Make positions [0, upto_tokens) addressable and positions
        [write_from, upto_tokens) EXCLUSIVELY owned (copy-on-write any
        shared page the upcoming prefill/decode will write)."""
        pinned = tuple(pinned) + (name,)  # never self-evict mid-alloc
        state = self.acquire(name, pinned)
        need = -(-upto_tokens // self.page_size)
        while len(state.pages) < need:
            state.pages.append(self._alloc_page(pinned, state.replica))
        # ONE definition of the fork policy (cow_page): index-only
        # shares go exclusive by forgetting the index entry (no copy,
        # no alloc — under a full pool the COW alloc may be the page
        # that doesn't exist), real shares device-copy into a fresh
        # page. Write ranges are typically 0-1 shared pages (the attach
        # frontier is page-aligned), so per-page dispatch costs nothing
        # measurable.
        for j in range(write_from // self.page_size, len(state.pages)):
            if self._shared(state.pages[j]):
                self.cow_page(name, j, pinned)

    def alias_span(self, src_name: str, dst_name: str, lo: int,
                   hi: int, pinned: tuple[str, ...] = ()) -> None:
        """Give dst the K/V for positions [lo, hi) from src: whole pages
        alias (refcount++), the partial boundary pages are device-copied.
        Precondition: src's cache covers [0, hi) and the two token streams
        agree on [0, hi) (guaranteed by LCP-based callers)."""
        # Pin BOTH endpoints: _alloc_page's eviction may otherwise release
        # the donor mid-call and the later incref loop would resurrect
        # pages already sitting in the free list — silent corruption once
        # a future alloc hands the same page to another slot.
        pinned = tuple(pinned) + (src_name, dst_name)
        src = self.acquire(src_name, pinned)
        dst = self.acquire(dst_name, pinned)
        ps = self.page_size
        lo_page, hi_page = lo // ps, hi // ps
        # Aliasing requires both slots on the SAME data replica (an
        # aliased page cannot be resident in two replicas' pool shards);
        # cross-replica sharing degrades to whole-page device COPIES into
        # dst's replica — still one dispatch, still skips the prefill.
        same_replica = src.replica == dst.replica
        # dst keeps its own pages below lo; drop anything it holds beyond.
        self._trim_pages(dst, lo)
        if len(dst.pages) < lo_page:
            # lo is dst's cached length, so this cannot happen — guard for
            # misuse rather than corrupt silently.
            raise RuntimeError("alias_span: dst does not cover up to lo")
        cow_src, cow_dst = [], []

        def copy_into_dst(j: int) -> None:
            """Give dst its own exclusively-held page j, filled from
            src's page j (COW if dst's current page j is shared)."""
            if j < len(dst.pages):
                if self._shared(dst.pages[j]):
                    fresh = self._alloc_page(pinned, dst.replica)
                    self._decref(dst.pages[j])
                    dst.pages[j] = fresh
            else:
                dst.pages.append(self._alloc_page(pinned, dst.replica))
            cow_src.append(src.pages[j])
            cow_dst.append(dst.pages[j])

        if lo % ps and lo_page < hi_page:
            # dst's partial boundary page: dst's page holds dst tokens
            # [lo_page*ps, lo) == src's (common prefix), so copying src's
            # full page is a superset update.
            copy_into_dst(lo_page)
            lo_page += 1
        # whole pages [lo_page, hi_page): pure aliasing (same replica)
        # or device copies (cross-replica)
        for j in range(lo_page, hi_page):
            if same_replica:
                if j < len(dst.pages):
                    self._decref(dst.pages[j])
                    dst.pages[j] = src.pages[j]
                else:
                    dst.pages.append(src.pages[j])
                self._incref(src.pages[j])
            else:
                copy_into_dst(j)
        # partial tail [hi_page*ps, hi): device-copy src's page
        if hi % ps and hi_page < len(src.pages):
            copy_into_dst(hi_page)
        if cow_src:
            self._run_page_copy(cow_src, cow_dst)

    def adopt_span(self, dst_name: str, src_pages: list[int], lo: int,
                   hi: int, pinned: tuple[str, ...] = ()) -> None:
        """alias_span's slot-free counterpart: give dst the K/V for
        positions [lo, hi) from an EXPLICIT page list covering [0, hi)
        at page granularity — the prefix cache's content-addressed pages
        (ISSUE 7). Whole pages on dst's replica alias (refcount++);
        pages physically on another replica, and the partial boundary
        page at lo, are device-copied into dst-owned pages. `hi` must be
        page-aligned (the index only ever matches complete blocks).

        Every source page is guard-ref'd for the duration: the COW/copy
        allocations below may trigger slot eviction and prefix-cache
        reclaim, and a refcount-0 source node freed mid-span would be
        resurrected from the free list — silent corruption once a later
        alloc hands the same page to another slot."""
        ps = self.page_size
        if hi % ps:
            raise ValueError("adopt_span: hi must be page-aligned")
        pinned = tuple(pinned) + (dst_name,)
        dst = self.acquire(dst_name, pinned)
        lo_page, hi_page = lo // ps, hi // ps
        self._trim_pages(dst, lo)
        if len(dst.pages) < lo_page:
            raise RuntimeError("adopt_span: dst does not cover up to lo")
        guards = {j: src_pages[j] for j in range(lo_page, hi_page)}
        for p in guards.values():
            self._incref(p)
        transferred: set[int] = set()
        cow_src, cow_dst = [], []

        def copy_into_dst(j: int) -> None:
            if j < len(dst.pages):
                if (self._shared(dst.pages[j])
                        and not self._index_only_share(dst.pages[j])):
                    fresh = self._alloc_page(pinned, dst.replica)
                    self._decref(dst.pages[j])
                    dst.pages[j] = fresh
                elif self._shared(dst.pages[j]):
                    # Index-only share about to be overwritten by the
                    # adopted copy: forgetting it is exclusive-for-free.
                    self.prefix_cache.forget_page(dst.pages[j])
            else:
                dst.pages.append(self._alloc_page(pinned, dst.replica))
            cow_src.append(src_pages[j])
            cow_dst.append(dst.pages[j])

        try:
            if lo % ps and lo_page < hi_page:
                # dst's partial boundary page holds tokens [lo_page*ps,
                # lo) — the source's full page is a superset update
                # (token streams agree on [0, hi), the caller's LCP
                # contract).
                copy_into_dst(lo_page)
                lo_page += 1
            for j in range(lo_page, hi_page):
                if self.replica_of_page(src_pages[j]) == dst.replica:
                    if j < len(dst.pages):
                        self._decref(dst.pages[j])
                        dst.pages[j] = src_pages[j]
                    else:
                        dst.pages.append(src_pages[j])
                    # The guard ref becomes dst's mapping reference.
                    transferred.add(j)
                else:
                    copy_into_dst(j)
            if cow_src:
                self._run_page_copy(cow_src, cow_dst)
        finally:
            for j, p in guards.items():
                if j not in transferred:
                    self._decref(p)

    # --- device tables ---

    def replica_of(self, name: str) -> int:
        """Data-axis replica owning every page of `name`'s slot — the
        engine's replica-grouped batch plan keys on this (pool-direct
        serving under data>1 shards batch rows over "data", so each row
        must sit in the batch block of the replica holding its pages)."""
        return self._slots[name].replica

    def pages_per_replica(self) -> int:
        """Usable (non-scratch) pages in each replica's range — what a
        replica's rows can collectively pin before exhaustion."""
        return self._per_replica - 1

    def scratch_page(self, replica: int) -> int:
        """The reserved scratch page of a replica's range — pad batch
        rows point their whole table here (never aliased, never read)."""
        return self._scratch[replica]

    def table_for(self, names: list[str]) -> np.ndarray:
        """[B, pages_per_seq] int32 page table, padded with each slot's
        OWN replica's scratch page (pad-cell scatters stay replica-local
        on data-sharded pools; data_size == 1 keeps page 0, as before)."""
        table = np.zeros((len(names), self.pages_per_seq), np.int32)
        for i, name in enumerate(names):
            state = self._slots[name]
            table[i, :] = self._scratch[state.replica]
            table[i, :len(state.pages)] = state.pages
        return table
