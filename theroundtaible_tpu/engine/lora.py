"""Multi-LoRA knight personas on one shared base model (ISSUE 10).

The fleet used to get knight diversity by loading a distinct checkpoint
per engine: K personas cost K× HBM and could never share a decode
batch. This module serves personas as LoRA deltas over ONE resident
base instead ("Serving Heterogeneous LoRA Adapters in Distributed LLM
Inference Systems", AdaFuse — PAPERS.md): per target projection the
serving matmul becomes `y = x·W + x·A_id^T·B_id`, where `id` is each
row's adapter slot, so a mixed-persona batch runs in the SAME compiled
program as a base batch and K personas cost K·(rank·(C+O)) extra bytes
instead of K·params.

Pieces:

- **LoraStore** — the adapter store: per-target STACKED device tensors
  `a_t [S, r, C]` / `b [S, r, O]` with S = max_adapters+1 slots (slot 0
  is the all-zero "base" adapter, so rows without a persona index 0 and
  get an exactly-zero delta — no masking anywhere on the serving path).
  Stacked shapes are a function of config alone; loading/evicting an
  adapter writes slot VALUES through one compiled setter per target, so
  hot-swaps, mixed-adapter batches and occupancy drift compile nothing
  (`ROUNDTABLE_RECOMPILE_STRICT=1` green). Residency is refcounted by
  the serving paths (scheduler rows, generate calls); eviction is LRU
  over unreferenced adapters; every load/evict moves the
  roundtable_lora_* registry series and the per-adapter bytes gauge is
  REMOVED at evict (the PR-6 gauge-leak lesson).
- **lora_scope / apply** — the trace-time context (the spmd_mesh
  pattern): engine programs enter `lora_scope((stacked, ids))` around
  forward, and models/common._einsum's tagged call sites apply the
  delta for their leaf. `ids` is per-ROW for batched programs and
  per-TOKEN for the ragged flat buffer — apply flattens the activation
  to [M, C] and broadcasts ids to match, so ONE implementation serves
  prefill, decode, ragged mixed dispatches and speculative verify.
  Routing per dispatch: the Pallas grouped BGMV kernel
  (pallas/lora.py) where the plan admits it, else the XLA grouped
  masked BMM — every decision recorded into the engine's `lora_paths`
  sink at trace time with a machine-readable `lora_decline_reason`
  (the int4_paths discipline).
- **quantize-aware pairs** — `lora: {quant: "int8"}` stores the stacked
  tensors as int8 with per-(slot, rank-row) scales
  (engine/quant.quantize_lora_stack); apply dequantizes into the
  matmul operand (LoRA tensors are tiny, so the dequant is noise) and
  the kernel declines with "quant:int8-stack".

Sharing interactions (correctness, not policy): K/V computed under
adapter X is WRONG for adapter Y, so cross-knight prefix sharing is
suppressed for mixed-adapter batches, the cross-session prefix cache
only attaches to (and is only fed by) base-adapter rows, and own-slot
reuse stays valid because a knight's adapter is stable within its
session. See ARCHITECTURE.md "Multi-LoRA personas" for the decline
table.
"""

from __future__ import annotations

import threading
import time
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

LORA_ENV = "ROUNDTABLE_LORA"

DEFAULT_RANK = 8
DEFAULT_MAX_ADAPTERS = 8
# alpha/rank folded into B at load time: delta = x·A^T·(scale·B).
DEFAULT_SCALE = 2.0
# Random-persona init: BOTH A and B are drawn nonzero (classic LoRA
# zero-init B would make an untrained persona a no-op, and a persona's
# whole point here is distinct behavior without training).
DEFAULT_INIT_STD = 0.02

PATH_KERNEL = "pallas_grouped"
PATH_XLA = "xla_grouped_bmm"


def lora_enabled(cfg_value: Any) -> bool:
    """The serving decision: LoRA needs an explicit `lora:` config
    block (unlike ragged/spec it is not a default-on fast path — it
    changes MODEL OUTPUTS), and ROUNDTABLE_LORA=0 kills it everywhere
    (the byte-identity lever)."""
    import os
    if not cfg_value:
        return False
    return os.environ.get(LORA_ENV, "") != "0"


def lora_dims(model_cfg) -> dict[str, tuple[int, int, str]]:
    """Per-target (in_dim, out_flat, tp) for the decode-hot projections
    — the leaf set models/common tags at its _einsum call sites. tp
    mirrors sharding.param_specs' convention per leaf ("col" = output
    axis model-sharded, "row" = contraction axis model-sharded), so the
    stacked tensors partition the way the base weight already does.
    MoE configs target attention only (expert matmuls have no tagged
    seam — the decline table names it)."""
    e, h, k, d, f = (model_cfg.embed_dim, model_cfg.num_heads,
                     model_cfg.num_kv_heads, model_cfg.head_dim,
                     model_cfg.mlp_dim)
    dims = {
        "q_proj": (e, h * d, "col"),
        "k_proj": (e, k * d, "col"),
        "v_proj": (e, k * d, "col"),
        "o_proj": (h * d, e, "row"),
    }
    if not model_cfg.num_experts:
        dims.update({
            "gate_proj": (e, f, "col"),
            "up_proj": (e, f, "col"),
            "down_proj": (f, e, "row"),
        })
    return dims


# ---------------------------------------------------------------------
# trace-time context (the spmd_mesh pattern)
# ---------------------------------------------------------------------

_CTX = threading.local()


class lora_scope:
    """Announce the traced (stacked, ids) pair to the enclosing jit
    trace. `payload` is None on lora-off engines — the scope is then
    inert, and the tagged _einsum call sites cost one None check.
    Thread-local for the same reason spmd_mesh is: distinct engines
    trace concurrently from different threads."""

    __slots__ = ("payload", "sink", "quant")

    def __init__(self, payload, sink: Optional[dict] = None,
                 quant: str = "none"):
        self.payload = payload
        self.sink = sink
        self.quant = quant

    def __enter__(self):
        stack = getattr(_CTX, "stack", None)
        if stack is None:
            stack = _CTX.stack = []
        stack.append(self if self.payload is not None else None)
        return self

    def __exit__(self, *exc):
        _CTX.stack.pop()
        return False


def _current_scope() -> Optional[lora_scope]:
    stack = getattr(_CTX, "stack", None)
    return stack[-1] if stack else None


def _dequant_stack(leaf, dtype):
    """A stacked tensor back to `dtype`: raw array, or the int8
    {"q","s"} pair quant.quantize_lora_stack emits (per-(slot, r) row
    scales; tiny tensors, so the materialized dequant is noise)."""
    if isinstance(leaf, dict):
        return leaf["q"].astype(dtype) * leaf["s"][..., None].astype(dtype)
    return leaf.astype(dtype)


def _xla_grouped(x2, a_t, b_s, ids2):
    """The XLA grouped-BMM baseline: dense over the adapter stack with
    a row×slot mask folded into the [S, M, r] intermediate — shape-
    static (the compute-dense-combine-sparse layout moe_mlp already
    uses), no gathers, and GSPMD partitions it like any einsum. Slot 0
    is all-zero so base rows contribute nothing twice over (mask AND
    zero weights). Cost is S× the single-adapter FLOPs on the FIRST
    matmul only — r/C of the base matmul, noise at prefill where this
    path serves."""
    s = a_t.shape[0]
    xa = jnp.einsum("mc,src->smr", x2, a_t,
                    preferred_element_type=jnp.float32)
    mask = (ids2[None, :] == jnp.arange(s)[:, None])
    xa = jnp.where(mask[:, :, None], xa, 0.0)
    return jnp.einsum("smr,sro->mo", xa.astype(b_s.dtype), b_s,
                      preferred_element_type=jnp.float32)


def _record(sink: Optional[dict], key: str, m: int, path: str,
            reason: Optional[str]) -> None:
    if sink is None:
        return
    entry = {"leaf": key, "rows": m, "path": path}
    if reason:
        entry["fallback_reason"] = reason
    sink[(key, m)] = entry


def apply_current(key: str, x: jax.Array, y: jax.Array,
                  tp: Optional[str] = None) -> jax.Array:
    """Add the active scope's LoRA delta for target `key` to the base
    einsum output `y` — the tail models/common._einsum calls for its
    tagged leaves. No-op (one attribute check) without an active
    scope or when the store doesn't target this leaf."""
    scope = _current_scope()
    if scope is None:
        return y
    stacked, ids = scope.payload
    ent = stacked.get(key)
    if ent is None:
        return y
    a_leaf, b_leaf = ent["a"], ent["b"]
    c_dim = (a_leaf["q"] if isinstance(a_leaf, dict) else a_leaf).shape[-1]
    x2 = x.reshape(-1, c_dim)
    m = x2.shape[0]
    # ids is per-row ([B]) for batched programs and per-token ([T])
    # for the ragged flat buffer; both broadcast to one id per
    # flattened row (row-major, matching the reshape).
    ids2 = ids if ids.shape[0] == m else jnp.repeat(ids, m // ids.shape[0])

    delta = None
    reason: Optional[str] = None
    from .pallas import lora as plora
    if scope.quant != "none":
        # Stack-level decline first: it names WHY the kernel can never
        # serve this store, independent of backend/env.
        reason = "quant:int8-stack"
    elif not plora.enabled():
        reason = "kernel-disabled"
    else:
        from .models.common import current_spmd_mesh
        mesh = current_spmd_mesh()
        if mesh is None:
            reason = "mesh:unannounced"
        elif mesh.size == 1:
            delta, reason = plora.lora_bgmv_or_reason(
                x2, a_leaf, b_s=b_leaf, ids=ids2)
        else:
            delta, reason = plora.lora_bgmv_spmd(
                mesh, x2, a_leaf, b_leaf, ids2, tp=tp)
    if delta is None:
        dt = x.dtype
        delta = _xla_grouped(x2, _dequant_stack(a_leaf, dt),
                             _dequant_stack(b_leaf, dt), ids2)
        _record(scope.sink, key, m, PATH_XLA, reason)
    else:
        _record(scope.sink, key, m, PATH_KERNEL, None)
    return y + delta.reshape(y.shape).astype(y.dtype)


def summarize_lora_paths(dispatches: dict) -> dict:
    """Fold the trace-time lora dispatch log into the provenance report
    describe() exposes — the summarize_int4_paths shape."""
    kernel, fallback = [], []
    for e in dispatches.values():
        (kernel if e["path"] == PATH_KERNEL else fallback).append(e)

    def order(e):
        return (e["leaf"], e["rows"])

    return {PATH_KERNEL: sorted(kernel, key=order),
            PATH_XLA: sorted(fallback, key=order)}


# ---------------------------------------------------------------------
# the adapter store
# ---------------------------------------------------------------------


class LoraStore:
    """Load/quantize-aware A·B pairs keyed by adapter id over stacked
    device tensors, with hot-swap load/evict, refcounted residency and
    HBM accounting. One per engine; every mutation happens on a thread
    that holds the engine's serve lock (the scheduler thread, or a
    generate call inside _generate_batch_locked), so swaps never race
    an in-flight dispatch's argument capture."""

    def __init__(self, model_cfg, mesh=None, *,
                 max_adapters: int = DEFAULT_MAX_ADAPTERS,
                 rank: int = DEFAULT_RANK, scale: float = DEFAULT_SCALE,
                 dtype=jnp.bfloat16, quant: str = "none",
                 adapters: Optional[dict] = None,
                 targets: Optional[list] = None,
                 engine_name: str = "", perf=None):
        if max_adapters < 1:
            raise ValueError(f"max_adapters must be >= 1, got "
                             f"{max_adapters}")
        if rank < 1:
            raise ValueError(f"lora rank must be >= 1, got {rank}")
        if quant not in ("none", "int8"):
            raise ValueError(
                f"lora quant must be none|int8, got {quant!r}")
        self.rank = rank
        self.scale = float(scale)
        self.max_adapters = max_adapters
        self.dtype = dtype
        self.quant = quant
        self.engine_name = engine_name
        self.perf = perf
        dims = lora_dims(model_cfg)
        if targets:
            unknown = [t for t in targets if t not in dims]
            if unknown:
                raise ValueError(
                    f"unknown lora targets {unknown}; serveable: "
                    f"{sorted(dims)}")
            dims = {k: v for k, v in dims.items() if k in targets}
        self.dims = dims
        self.num_layers = int(getattr(model_cfg, "num_layers", 1))
        # Registered persona configs, loadable on demand at acquire:
        # {name: {"seed": int, "init_std": float, "path": npz}}.
        self.personas: dict[str, dict] = dict(adapters or {})
        s = max_adapters + 1
        self._shardings = self._stack_shardings(mesh)
        self.stacked: dict[str, dict[str, Any]] = {}
        for key, (c, o, _tp) in dims.items():
            a = jnp.zeros((s, rank, c), dtype)
            b = jnp.zeros((s, rank, o), dtype)
            sh = self._shardings.get(key)
            if sh is not None:
                a = jax.device_put(a, sh[0])
                b = jax.device_put(b, sh[1])
            if quant == "int8":
                from .quant import quantize_lora_stack
                a = quantize_lora_stack(a, dtype)
                b = quantize_lora_stack(b, dtype)
            self.stacked[key] = {"a": a, "b": b}
        # adapter id -> slot (1..max_adapters); slot 0 is the base.
        self._slots: dict[str, int] = {}
        self._free: list[int] = list(range(1, s))
        self._refs: dict[str, int] = {}
        self._last_used: dict[str, float] = {}
        self.loads = 0
        self.evictions = 0
        self.swaps = 0

        @partial(jax.jit, donate_argnums=())
        def set_slot(stack, slot, value):
            # No donation ON PURPOSE: an in-flight dispatch may still
            # hold the pre-swap arrays; donation would delete buffers
            # under it. LoRA stacks are tiny — the copy is noise.
            return stack.at[slot].set(value.astype(stack.dtype))

        self._set_slot = set_slot

    def _stack_shardings(self, mesh):
        """NamedShardings for the stacked tensors on multi-device
        meshes, mirroring how param_specs shards the base weight
        (sharding.lora_stack_specs); dims the mesh does not divide
        replicate, matching _fallback_replicated."""
        out: dict[str, tuple] = {}
        if mesh is None or mesh.devices.size <= 1:
            return out
        from jax.sharding import NamedSharding
        from .sharding import (_fallback_replicated, lora_stack_specs,
                               model_axis_size)
        if model_axis_size(mesh) <= 1:
            return out
        s = self.max_adapters + 1
        for key, (c, o, tp) in self.dims.items():
            a_spec, b_spec = lora_stack_specs(tp)
            a_spec = _fallback_replicated(a_spec, (s, self.rank, c), mesh)
            b_spec = _fallback_replicated(b_spec, (s, self.rank, o), mesh)
            out[key] = (NamedSharding(mesh, a_spec),
                        NamedSharding(mesh, b_spec))
        return out

    # --- loading / eviction ---

    def resolvable(self, adapter_id: Optional[str]) -> bool:
        return (adapter_id is None or adapter_id in self._slots
                or adapter_id in self.personas)

    def resident(self) -> list[str]:
        return sorted(self._slots)

    def slot_of(self, adapter_id: str) -> Optional[int]:
        return self._slots.get(adapter_id)

    def adapter_bytes(self) -> int:
        """HBM bytes ONE resident adapter COSTS TO STORE (its A+B rows
        across the targets) — the per-slot price the memory ledger and
        the per-adapter gauges report. NOT the streamed cost: the one
        (tied) pair is applied at EVERY layer's tagged projections, so
        decode re-reads it num_layers times per token —
        streamed_bytes_per_token() below is the roofline number."""
        per_elt = 1 if self.quant == "int8" else jnp.dtype(
            self.dtype).itemsize
        return sum(self.rank * (c + o) * per_elt
                   for c, o, _tp in self.dims.values())

    def streamed_bytes_per_token(self) -> int:
        """HBM bytes a persona ROW streams per decode token on top of
        the base weights: the tied A/B pair re-read at each of the
        model's layers — the perfmodel decode-ceiling adjustment's
        input (storage alone would understate it ~num_layers×)."""
        return self.num_layers * self.adapter_bytes()

    def resident_bytes(self) -> int:
        return len(self._slots) * self.adapter_bytes()

    def stack_bytes(self) -> int:
        """Total resident bytes of the stacked tensors (allocated for
        every slot up front — shapes are config-static)."""
        total = 0
        for ent in self.stacked.values():
            for leaf in ent.values():
                arrs = (leaf["q"], leaf["s"]) if isinstance(leaf, dict) \
                    else (leaf,)
                total += sum(int(x.size) * x.dtype.itemsize
                             for x in arrs)
        return total

    def register(self, adapter_id: str, spec: Optional[dict] = None
                 ) -> None:
        """Register a persona config ({"seed": int, "init_std": float}
        or {"path": npz}) loadable on demand at acquire."""
        self.personas[adapter_id] = dict(spec or {})

    def make_pair_tree(self, adapter_id: str) -> dict[str, tuple]:
        """Materialize an adapter's {key: (a_t [r, C], b [r, O])} host
        tree from its registered persona config: an npz saved by
        save_pair_tree / bench_realweights --train-lora, or a
        deterministic random persona from its seed."""
        spec = self.personas.get(adapter_id)
        if spec is None:
            raise KeyError(
                f"unknown lora adapter {adapter_id!r}; registered: "
                f"{sorted(self.personas)}")
        path = spec.get("path")
        if path:
            data = np.load(path)
            out = {}
            for key in self.dims:
                if f"{key}.a" not in data:
                    raise ValueError(
                        f"lora npz {path} missing target {key!r}")
                out[key] = (np.asarray(data[f"{key}.a"]),
                            np.asarray(data[f"{key}.b"]))
            return out
        seed = int(spec.get("seed", 0))
        std = float(spec.get("init_std", DEFAULT_INIT_STD))
        root = jax.random.PRNGKey(seed ^ 0x10A4)
        out = {}
        for i, (key, (c, o, _tp)) in enumerate(sorted(self.dims.items())):
            ka, kb = jax.random.split(jax.random.fold_in(root, i))
            a = np.asarray(jax.random.normal(ka, (self.rank, c),
                                             jnp.float32)) * (c ** -0.5)
            b = np.asarray(jax.random.normal(kb, (self.rank, o),
                                             jnp.float32)) * std
            out[key] = (a, b)
        return out

    def load(self, adapter_id: str,
             pair_tree: Optional[dict] = None) -> int:
        """Load (or refresh) an adapter into a slot and return it.
        `pair_tree` {key: (a_t [r, C], b [r, O])} overrides the
        registered persona. Evicts the LRU UNREFERENCED adapter when
        the store is full; raises when every slot is pinned by an
        active serving call."""
        if adapter_id in self._slots and pair_tree is None:
            self._last_used[adapter_id] = time.monotonic()
            return self._slots[adapter_id]
        if pair_tree is None:
            pair_tree = self.make_pair_tree(adapter_id)
        slot = self._slots.get(adapter_id)
        # A SWAP means a slot's previous contents were replaced: a
        # refresh of a resident adapter, or a load that had to evict.
        # A first load into a free slot is not one — the counter's
        # name must mean what operators read into it.
        is_swap = slot is not None
        if slot is None:
            if not self._free:
                self._evict_lru()
                is_swap = True
            if not self._free:
                raise RuntimeError(
                    f"lora store exhausted: {self.max_adapters} slots "
                    f"all referenced by active rows — raise "
                    "lora.max_adapters or lower concurrency")
            slot = self._free.pop(0)
            self._slots[adapter_id] = slot
            self._refs.setdefault(adapter_id, 0)
        self._write_slot(slot, pair_tree)
        self._last_used[adapter_id] = time.monotonic()
        self.loads += 1
        self._publish()
        from ..utils import telemetry
        if is_swap:
            self.swaps += 1
            telemetry.inc("roundtable_lora_swaps_total",
                          engine=self.engine_name)
        telemetry.set_gauge("roundtable_lora_adapter_bytes",
                            self.adapter_bytes(),
                            engine=self.engine_name, adapter=adapter_id)
        return slot

    def _write_slot(self, slot: int, pair_tree: dict) -> None:
        sl = jnp.int32(slot)
        for key, ent in self.stacked.items():
            if key not in pair_tree:
                raise ValueError(f"lora pair tree missing target "
                                 f"{key!r}")
            a, b = pair_tree[key]
            c, o, _tp = self.dims[key]
            a = jnp.asarray(a, jnp.float32)
            b = jnp.asarray(b, jnp.float32) * self.scale
            if a.shape != (self.rank, c) or b.shape != (self.rank, o):
                raise ValueError(
                    f"lora target {key!r} shape mismatch: got "
                    f"A{tuple(a.shape)} B{tuple(b.shape)}, want "
                    f"A{(self.rank, c)} B{(self.rank, o)}")
            if self.quant == "int8":
                from .quant import quantize_lora_slot
                ent["a"] = quantize_lora_slot(ent["a"], sl, a,
                                              self._set_slot)
                ent["b"] = quantize_lora_slot(ent["b"], sl, b,
                                              self._set_slot)
            else:
                ent["a"] = self._set_slot(ent["a"], sl, a)
                ent["b"] = self._set_slot(ent["b"], sl, b)

    def _evict_lru(self) -> None:
        victims = [a for a, r in self._refs.items()
                   if r <= 0 and a in self._slots]
        if not victims:
            return
        victim = min(victims,
                     key=lambda a: self._last_used.get(a, 0.0))
        self.evict(victim)

    def evict(self, adapter_id: str) -> bool:
        """Drop a (non-referenced) adapter: its slot returns to the
        free list and is zeroed lazily by the next load. Per-adapter
        gauges are REMOVED — uuid-ish adapter churn must not grow the
        registry one dead series per persona ever served."""
        slot = self._slots.get(adapter_id)
        if slot is None:
            return False
        if self._refs.get(adapter_id, 0) > 0:
            raise RuntimeError(
                f"cannot evict lora adapter {adapter_id!r}: "
                f"{self._refs[adapter_id]} active row(s) reference it")
        del self._slots[adapter_id]
        self._refs.pop(adapter_id, None)
        self._last_used.pop(adapter_id, None)
        self._free.append(slot)
        self.evictions += 1
        self._publish()
        from ..utils import telemetry
        telemetry.REGISTRY.remove_gauge(
            "roundtable_lora_adapter_bytes",
            engine=self.engine_name, adapter=adapter_id)
        return True

    def _publish(self) -> None:
        from ..utils import telemetry
        telemetry.set_gauge("roundtable_lora_resident_adapters",
                            len(self._slots), engine=self.engine_name)
        if self.perf is not None:
            # Decode-ceiling adjustment (ISSUE 10 perfmodel satellite):
            # a persona row streams its adapter's bytes — once per
            # LAYER — on top of the base weights every token.
            self.perf.set_lora_row_bytes(
                self.streamed_bytes_per_token() if self._slots else 0)

    # --- residency / admission ---

    def validate(self, adapter_ids: list, n_turns: int) -> None:
        """Request-shape validation shared by the direct generate path
        and the scheduler's queue mouth: per-turn length, unknown
        personas, and more DISTINCT adapters than the store can ever
        hold (which would otherwise fail deep inside acquire() with a
        misleading 'all slots referenced' exhaustion error after
        loading part of the list)."""
        if len(adapter_ids) != n_turns:
            raise ValueError(
                f"adapters_per_turn has {len(adapter_ids)} entries "
                f"for {n_turns} turns")
        unknown = [a for a in adapter_ids
                   if a is not None and not self.resolvable(a)]
        if unknown:
            raise ValueError(
                f"unknown lora adapters {unknown}; registered: "
                f"{sorted(self.personas)}")
        distinct = {a for a in adapter_ids if a is not None}
        if len(distinct) > self.max_adapters:
            raise ValueError(
                f"request names {len(distinct)} distinct lora "
                f"adapters but the store holds at most "
                f"{self.max_adapters} — raise lora.max_adapters")

    def can_admit(self, adapter_ids: list) -> bool:
        """Would acquiring these adapters succeed right now? Free slots
        plus LRU-evictable (unreferenced) residents must cover the NEW
        distinct adapters — the scheduler's admission backpressure."""
        need = {a for a in adapter_ids
                if a is not None and a not in self._slots}
        if not need:
            return True
        evictable = sum(1 for a, r in self._refs.items()
                        if r <= 0 and a in self._slots)
        return len(need) <= len(self._free) + evictable

    def acquire(self, adapter_ids: list) -> list[int]:
        """Resolve per-row adapter ids (None = base) to slots, loading
        registered personas on demand, and take one residency ref per
        row. Callers release() with the SAME list.

        Two passes: RESIDENT adapters are ref'd first, so a later
        load's LRU eviction can never victimize an id this same
        request names (a one-pass acquire could evict the list's own
        not-yet-ref'd resident adapter, then crash — or silently
        reload it from its registered spec, discarding explicitly
        loaded weights). Exception-ATOMIC: a mid-list failure releases
        the refs this call already took before re-raising, so no
        caller path can leak refs (pinning slots forever) or
        over-release them (un-pinning another request's live adapter
        to eviction)."""
        slots: list = [None] * len(adapter_ids)
        taken: list = []
        try:
            for i, a in enumerate(adapter_ids):
                if a is None:
                    slots[i] = 0
                elif a in self._slots:
                    self._last_used[a] = time.monotonic()
                    self._refs[a] = self._refs.get(a, 0) + 1
                    taken.append(a)
                    slots[i] = self._slots[a]
            for i, a in enumerate(adapter_ids):
                if slots[i] is None:
                    slot = self.load(a)
                    self._refs[a] = self._refs.get(a, 0) + 1
                    taken.append(a)
                    slots[i] = slot
        except Exception:
            self.release(taken)
            raise
        return slots

    def release(self, adapter_ids: list) -> None:
        for a in adapter_ids:
            if a is None:
                continue
            if a in self._refs:
                self._refs[a] = max(self._refs[a] - 1, 0)

    def warm(self) -> None:
        """Compile-and-stabilize the per-target slot setters: a first
        hot-swap in steady state must compile nothing under
        ROUNDTABLE_RECOMPILE_STRICT (the warmup contract). Two loads
        reach the output-layout fixpoint; the throwaway persona is
        evicted so slot accounting is untouched."""
        name = "__lorawarm__"
        self.personas.setdefault(name, {"seed": 0})
        tree = self.make_pair_tree(name)
        for _ in range(2):
            # An explicit pair_tree forces the setter WRITE both times
            # (a bare load() early-returns once resident — which would
            # leave the setters one run short of their layout
            # fixpoint, exactly the recompile warm() exists to kill).
            self.load(name, tree)
        self.evict(name)
        self.personas.pop(name, None)

    def describe(self) -> dict[str, Any]:
        return {
            "rank": self.rank,
            "scale": self.scale,
            "quant": self.quant,
            "max_adapters": self.max_adapters,
            "targets": sorted(self.dims),
            "resident": self.resident(),
            "registered": sorted(self.personas),
            "refs": {a: r for a, r in self._refs.items() if r > 0},
            "adapter_bytes": self.adapter_bytes(),
            "resident_bytes": self.resident_bytes(),
            "stack_bytes": self.stack_bytes(),
            "loads": self.loads,
            "evictions": self.evictions,
            "swaps": self.swaps,
        }


def stack_bytes_for(model_cfg, lora_cfg, dtype_bytes: int = 2) -> int:
    """Closed-form stacked-tensor bytes for a `lora:` config block —
    the ONE place the plan-time estimate (fleet.estimate_engine_hbm_
    bytes) and the store's real allocation derive from, honoring the
    same defaults and `targets:` restriction (per-(slot, rank-row)
    int8 scales are omitted: noise next to the q bytes)."""
    lc = lora_cfg if isinstance(lora_cfg, dict) else {}
    rank = int(lc.get("rank", DEFAULT_RANK))
    slots = int(lc.get("max_adapters", DEFAULT_MAX_ADAPTERS)) + 1
    per_elt = 1 if lc.get("quant") == "int8" else dtype_bytes
    dims = lora_dims(model_cfg)
    targets = lc.get("targets")
    if targets:
        dims = {k: v for k, v in dims.items() if k in targets}
    return slots * rank * sum(c + o for c, o, _tp in dims.values()) \
        * per_elt


def save_pair_tree(path: str, pair_tree: dict) -> None:
    """Save {key: (a_t, b)} as the npz layout make_pair_tree loads —
    the bench_realweights --train-lora output format."""
    arrays = {}
    for key, (a, b) in pair_tree.items():
        arrays[f"{key}.a"] = np.asarray(a)
        arrays[f"{key}.b"] = np.asarray(b)
    np.savez(path, **arrays)


# --- test-visibility counters (tests/conftest.py `lora` guard) ---

_lock = threading.Lock()
_dispatches = 0
_max_mixed = 0


def reset_test_counters() -> None:
    global _dispatches, _max_mixed
    with _lock:
        _dispatches = 0
        _max_mixed = 0


def note_dispatch_ids(ids) -> None:
    """Record one dispatch's adapter composition: the conftest guard
    fails a `lora`-marked test whose dispatches never mixed >= 2
    distinct (non-base) adapters in ONE program."""
    global _dispatches, _max_mixed
    distinct = len({int(x) for x in np.asarray(ids).ravel()} - {0})
    with _lock:
        _dispatches += 1
        if distinct > _max_mixed:
            _max_mixed = distinct


def dispatches_seen() -> int:
    return _dispatches


def max_mixed_seen() -> int:
    return _max_mixed


# ---------------------------------------------------------------------------
# static-analysis program registration (ISSUE 15)
# ---------------------------------------------------------------------------

from ..analysis.jaxpr_audit import (ProgramSpec, Variant,  # noqa: E402
                                    analysis_register)


@analysis_register("lora_setter")
def _analysis_lora_setter(engine) -> list:
    """The adapter hot-swap setter (`LoraStore._set_slot`) for the
    jaxpr audit: per target stack, both the A and B writes trace across
    two slot values onto ONE label — a steady-state swap must be pure
    values (the warm() fixpoint contract), and the setter deliberately
    donates NOTHING (an in-flight dispatch may still hold the pre-swap
    arrays), which RT-JAXPR-DONATION confirms by absence. int8 stores
    are skipped: their stacks swap through quantize_lora_slot's
    composite write, audited transitively via the same _set_slot."""
    store = getattr(engine, "lora", None)
    if store is None or store.quant not in (None, "none"):
        return []

    def variant(key: str, tensor: str, slot: int) -> Variant:
        def thunk():
            stack = store.stacked[key][tensor]
            value = jax.ShapeDtypeStruct(stack.shape[1:], jnp.float32)
            sds = jax.ShapeDtypeStruct(stack.shape, stack.dtype)
            return jax.make_jaxpr(store._set_slot)(
                sds, jnp.int32(slot), value)
        return Variant(label=f"{key}.{tensor}", thunk=thunk,
                       situation=f"swap into slot {slot}")

    variants = [variant(key, tensor, slot)
                for key in sorted(store.stacked)
                for tensor in ("a", "b")
                for slot in (1, 2) if slot <= store.max_adapters]
    return [ProgramSpec(name="lora_setter", phase="setter",
                        variants=variants)]
