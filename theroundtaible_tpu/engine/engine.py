"""InferenceEngine — sharded prefill + decode with persistent KV slots.

The TPU-native serving stack replacing Ollama/LM Studio llama.cpp
(SURVEY.md §3.4): tokenize → chunked, bucketed prefill (delta-only thanks to
per-knight slot reuse) → jit'd while_loop decode → detokenize.

XLA discipline:
- prefill chunk lengths are bucketed (powers of two) so transcript growth
  across rounds does NOT trigger recompiles (SURVEY.md §7.3 hard part 5)
- the decode loop is ONE device program (lax.while_loop with an on-device
  all-done predicate), not a Python token loop — no per-token dispatch
- cache buffers are donated, so slot updates are in-place on HBM
- batch rows = knight slots; generate_batch serves N knights in the same
  programs with per-row offsets (SURVEY.md §7 Phase 5)
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import deadlines, faults
from .kvcache import KVCache
from .models.common import (ModelConfig, forward, init_params, param_count,
                            spmd_mesh)
from .models.registry import get_model_config
from .sampling import SamplingParams, sample_token_batch, sampling_arrays
from .serving_loop import (DECODE_SEGMENT, MAX_PREFILL_CHUNK,
                           PREFILL_BUCKETS, ReplicaGroupPlan,
                           bucket_for as _bucket,
                           chunked_prefill, decode_segments,
                           finalize_outputs, host_sync, prompt_budget)
from .sharding import build_mesh, kv_cache_spec, shard_params
from .tokenizer import load_tokenizer

# Cross-slot K/V copies are bandwidth-cheap but still a program dispatch;
# below this many shared tokens a plain prefill is faster than the copy.
MIN_SHARED_PREFIX = 64


def summarize_int4_paths(dispatches: dict) -> dict:
    """Fold the trace-time int4 dispatch log (models/common._record_int4
    entries) into the path-provenance report describe()/stats expose:
    {"pallas_w4a16": [entry...], "xla_dequant": [entry...]} with each
    entry carrying spec/shapes (and `fallback_reason` on the XLA side).
    Shared with the PP engine."""
    kernel, fallback = [], []
    for e in dispatches.values():
        (kernel if e["path"] == "pallas_w4a16" else fallback).append(e)

    def order(e):
        return (e["spec"], e["a_shape"])

    return {"pallas_w4a16": sorted(kernel, key=order),
            "xla_dequant": sorted(fallback, key=order)}


@dataclass
class GenStats:
    prefill_tokens: int = 0
    reused_tokens: int = 0
    # Of reused_tokens, how many the CROSS-SESSION prefix cache served
    # (ISSUE 7) — own-slot LCP hits and intra-session donation make up
    # the rest. 0 on contiguous / cache-off engines.
    prefix_reused_tokens: int = 0
    decode_tokens: int = 0
    prefill_seconds: float = 0.0
    decode_seconds: float = 0.0
    # int4 path provenance (ISSUE 3): which path each compiled einsum
    # dispatch took — {"pallas_w4a16": [...], "xla_dequant": [...]}.
    # Populated at trace time, snapshotted per call; None on non-int4
    # engines.
    int4_paths: Optional[dict] = None
    # Scheduler provenance (ISSUE 4): set only on calls served through
    # the continuous-batching session scheduler — queue_wait_s,
    # occupancy_mean/max (decode-batch rows while this call's rows were
    # active), segments, sessions_max. None on direct engine calls.
    sched: Optional[dict] = None

    @property
    def prefill_tps(self) -> float:
        return self.prefill_tokens / self.prefill_seconds \
            if self.prefill_seconds else 0.0

    @property
    def decode_tps(self) -> float:
        return self.decode_tokens / self.decode_seconds \
            if self.decode_seconds else 0.0


class InferenceEngine:
    """One resident model + its slot cache + compiled step programs."""

    def __init__(self, model_cfg: ModelConfig, *, checkpoint: str = "",
                 mesh_shape: Optional[dict[str, int]] = None,
                 num_slots: int = 8, dtype=jnp.bfloat16,
                 sampling: Optional[SamplingParams] = None,
                 seed: int = 0, seq_parallel: int = 0,
                 long_threshold: int = 2048,
                 long_scheme: str = "ring", attn: str = "auto",
                 devices: Optional[list[int]] = None,
                 kv_layout: str = "contiguous", page_size: int = 128,
                 num_pages: Optional[int] = None, quant: str = "none",
                 dcn_axis: Optional[str] = None,
                 prefix_cache: Optional[bool] = None,
                 prefix_cache_pages: Optional[int] = None,
                 kv_offload: Optional[bool] = None,
                 ragged_attn: Optional[bool] = None,
                 spec_decode: Optional[bool] = None,
                 spec_max_draft: Optional[int] = None,
                 lora: Optional[dict] = None,
                 kv_quant: Any = None):
        # Multi-host: join the process group BEFORE any backend/device
        # call when ROUNDTABLE_COORDINATOR is set (engine/distributed.py);
        # jax.devices() below then spans every host's chips.
        from .distributed import maybe_init_distributed
        maybe_init_distributed()
        # Persistent XLA compile cache: first-ever run compiles, every
        # later process deserializes (SURVEY.md §7.3 hard part 5).
        from . import enable_compilation_cache
        enable_compilation_cache()
        # Compile observatory (ISSUE 6): every compile this process does
        # from here on is recorded (label, duration, cache hit/miss)
        # and checked against the steady-state recompile sentinel.
        from . import compile_watch
        compile_watch.install()
        # devices: indices into jax.devices() — the fleet planner assigns
        # disjoint per-model submeshes this way (engine/fleet.py)
        device_list = None
        if devices:
            all_devices = jax.devices()
            device_list = [all_devices[i] for i in devices]
        self.mesh = build_mesh(mesh_shape, device_list, dcn_axis=dcn_axis)
        model_cfg = self._resolve_attn(model_cfg, attn, self.mesh)
        self.cfg = model_cfg
        self.max_seq_len = model_cfg.max_seq_len
        self.sampling = sampling or SamplingParams()
        self.tokenizer = load_tokenizer(checkpoint or None)

        if quant not in ("none", "int8", "int4"):
            raise ValueError(
                f"quant must be none|int8|int4, got {quant!r}")
        self.quant = quant
        self.dtype = dtype
        # int4 path-provenance sink: the trace-time dispatch log every
        # spmd_mesh context below carries (models/common._record_int4) —
        # populated as each (batch, bucket) program traces, summarized
        # by int4_path_report()/describe().
        self._int4_dispatches: dict = {}
        # Multi-LoRA provenance sink (ISSUE 10): the trace-time lora
        # routing log (engine/lora.apply_current records into it via
        # the lora_scope every jit program below opens) — the
        # int4_paths pattern, summarized by lora_describe(). The store
        # itself resolves AFTER the compiled closures are defined (it
        # needs the sharded mesh + quant mode); self.lora stays None
        # on lora-off engines and every `lora=` program argument is
        # then None, keeping those programs byte-identical.
        self._lora_dispatches: dict = {}
        self._lora_quant = "none"
        self.lora = None
        self.lora_reason: Optional[str] = None
        self._lora_tokens = 0
        self._lora_share_suppressed = 0
        # adapter-id label per slot NAME (engine-side): prefix sharing
        # and the cross-session cache must never move K/V between
        # slots served under different adapters (the bytes differ).
        self._slot_adapters: dict[str, Optional[str]] = {}

        if checkpoint:
            from .checkpoint import load_hf_checkpoint
            params = load_hf_checkpoint(checkpoint, model_cfg, dtype)
        else:
            params = init_params(model_cfg, jax.random.PRNGKey(seed), dtype)
        self.params = shard_params(params, model_cfg, self.mesh)
        # Drop the pre-shard reference NOW: on multi-device meshes the
        # unsharded tree is a distinct full copy on the default device,
        # and holding it through quantization would keep peak memory at
        # full-bf16 + int8 (on one device shard_params may alias, and
        # free_source below then deletes those same buffers).
        params = None
        if quant in ("int8", "int4"):
            # AFTER sharding: q/s are jnp ops on the sharded weights, so
            # XLA propagates the NamedShardings (engine/quant.py).
            # free_source: nothing references the bf16 tree after this, so
            # each source leaf is freed as its q lands — 7B-class int8
            # builds peak near bf16-total instead of bf16+int8.
            # model_shards: int4 packing aligns groups to the TP shard
            # boundary so the shard-aware kernel dispatch can partition
            # scales with whole groups per shard (engine/quant.py).
            from .quant import quantize_params
            from .sharding import model_axis_size
            self.params = quantize_params(
                self.params, model_cfg, act_dtype=dtype,
                free_source=True, bits=8 if quant == "int8" else 4,
                model_shards=model_axis_size(self.mesh))
        self.num_params = param_count(self.params)

        if kv_layout not in ("contiguous", "paged"):
            raise ValueError(
                f"kv_layout must be contiguous|paged, got {kv_layout!r}")
        self.kv_layout = kv_layout

        # Quantized KV pages (ISSUE 11): resolve the `kv_quant:` config
        # against the ROUNDTABLE_KV_QUANT kill-switch BEFORE the pool is
        # built — the pool's dtype, its scale arrays, and its
        # byte-budget-equal default page count all follow the spec.
        # Contiguous layouts decline (no page unit to quantize); the
        # reason is machine-readable like every other path decision.
        from .kv_quant import resolve_spec as _kvq_resolve
        self.kv_quant_spec = None
        self.kv_quant_reason: Optional[str] = None
        self.kv_quant_fallback_reason: Optional[str] = None
        self._kv_quant_dispatches: dict[str, int] = {}
        from collections import deque as _dq
        self._kv_quant_recent = _dq(maxlen=32)
        if kv_layout != "paged":
            self.kv_quant_reason = ("kv_layout:contiguous"
                                    if kv_quant and kv_quant != "none"
                                    else "disabled:config")
        else:
            self.kv_quant_spec, self.kv_quant_reason = \
                _kvq_resolve(kv_quant)

        if kv_layout == "paged":
            from jax.sharding import NamedSharding, PartitionSpec as P
            from .paging import PagedKVCache
            from .sharding import DATA_AXIS, MODEL_AXIS, _fallback_replicated
            data_size = dict(self.mesh.shape).get("data", 1)
            pool_sharding = None
            if self.mesh.devices.size > 1:
                # Per-replica pools (VERDICT r3 #7): the PAGE axis shards
                # over "data" (the allocator rounds num_pages to a
                # multiple of data_size and keeps every slot's pages on
                # one replica), kv heads over "model" — each device holds
                # pages/data x heads/model, not a full replicated pool.
                spec = _fallback_replicated(
                    P(DATA_AXIS if data_size > 1 else None, None,
                      MODEL_AXIS, None),
                    (data_size, page_size, model_cfg.num_kv_heads,
                     model_cfg.head_dim),
                    self.mesh)
                pool_sharding = NamedSharding(self.mesh, spec)

            @partial(jax.jit, donate_argnums=(0,))
            def copy_pages(pools, src_ids, dst_ids):
                # Whole-page copies (copy-on-write + alias boundaries).
                # Callers pad the id lists to a fixed width so this
                # compiles exactly one shape (pad rows copy the scratch
                # page onto itself — identical bytes, any scatter order).
                out = []
                for k, v in pools:
                    out.append((k.at[dst_ids].set(k[src_ids]),
                                v.at[dst_ids].set(v[src_ids])))
                return out

            from .paging import make_padded_copier
            copy_pages_padded = make_padded_copier(copy_pages)

            # Default pool HALVES the contiguous HBM budget — and since
            # the page axis shards over "data", that is the TOTAL across
            # replicas (each device holds total/data), not a replicated
            # per-device cost. Worst case that FITS the default:
            # ceil(num_slots/2) sequences simultaneously resident at full
            # max_seq_len, spread over the replicas their slots pin to. A
            # batch pinning MORE than that, all near max_seq_len, exhausts
            # a replica's range mid-serve with an actionable RuntimeError
            # ("raise num_pages / lower max_new_tokens") — set num_pages
            # explicitly (up to num_slots*max_seq_len/page_size +
            # data_size for contiguous-equal capacity) when every knight
            # runs long.
            self.kv = PagedKVCache(
                model_cfg, num_slots, self.max_seq_len, dtype,
                pool_sharding, page_size=page_size, num_pages=num_pages,
                copy_pages_fn=copy_pages_padded, data_size=data_size,
                kv_quant=self.kv_quant_spec)
        else:
            cache_sharding = None
            if self.mesh.devices.size > 1:
                from jax.sharding import NamedSharding
                from .sharding import _fallback_replicated
                spec = _fallback_replicated(
                    kv_cache_spec(),
                    (num_slots, self.max_seq_len, model_cfg.num_kv_heads,
                     model_cfg.head_dim),
                    self.mesh)
                cache_sharding = NamedSharding(self.mesh, spec)
            self.kv = KVCache(model_cfg, num_slots, self.max_seq_len, dtype,
                              cache_sharding)

        self._key = jax.random.PRNGKey(seed + 1)
        self._chars_per_token: Optional[float] = None
        self.last_stats = GenStats()
        # Serving mutates the slot cache (donated buffers): one generation
        # at a time per engine. Distinct engines (fleet submeshes) still
        # run concurrently — each has its own lock.
        self._serve_lock = threading.Lock()
        # Dispatch retry policy (engine/faults.py): a transient device
        # dispatch failure retries in place before surfacing to the
        # adapter's degradation ladder. from_config overrides via the
        # "dispatch_retries" key.
        self.retry = faults.DEFAULT_RETRY

        # Sequence-parallel long-context prefill (SURVEY.md §7 Phase 6):
        # ring attention (or Ulysses) over a ("seq",) mesh for fresh long
        # prompts; decode + delta prefills stay on the chunked path.
        self.long_threshold = long_threshold
        self.seq_mesh = None
        self._ring_prefill_fn = None
        if seq_parallel and seq_parallel > 1:
            from .longcontext import build_seq_mesh, make_ring_prefill
            # The seq mesh must span EXACTLY the engine mesh's devices
            # (params live there; jit reshards them into the ring program),
            # so the ring width is the engine mesh size and seq_parallel
            # acts as the opt-in. Pick the width via mesh_shape.
            devs = list(self.mesh.devices.flatten())
            self.seq_mesh = build_seq_mesh(len(devs), devs)
            self._ring_prefill_fn = make_ring_prefill(
                model_cfg, self.seq_mesh, scheme=long_scheme)

        @partial(jax.jit, donate_argnums=(0,))
        def scatter_kv(cache_layers, slot_idx, new_layers):
            # Write whole-sequence K/V from sequence-parallel prefill into
            # the slot cache at offset 0 (ring path only runs offset-0).
            out = []
            for (k, v), (nk, nv) in zip(cache_layers, new_layers):
                t = nk.shape[1]
                out.append((k.at[slot_idx, :t].set(nk.astype(k.dtype)),
                            v.at[slot_idx, :t].set(nv.astype(v.dtype))))
            return out

        self._scatter_kv = scatter_kv

        @partial(jax.jit, donate_argnums=(0,))
        def copy_spans(cache_layers, src_idx, dst_idx, lo, hi):
            # Copy K/V positions [lo_i, hi_i) from slot src_idx[i] into
            # slot dst_idx[i], per layer — the device side of cross-knight
            # prefix sharing. Positions are cache-aligned (entry s holds
            # position s) so a row-masked where is an exact copy; the
            # whole-row traffic is bandwidth-trivial next to a prefill.
            s_len = cache_layers[0][0].shape[1]
            pos = jnp.arange(s_len)[None, :, None, None]
            mask = ((pos >= lo[:, None, None, None])
                    & (pos < hi[:, None, None, None]))
            out = []
            for k, v in cache_layers:
                nk = jnp.where(mask, k[src_idx], k[dst_idx])
                nv = jnp.where(mask, v[src_idx], v[dst_idx])
                out.append((k.at[dst_idx].set(nk), v.at[dst_idx].set(nv)))
            return out

        self._copy_spans = copy_spans

        # compiled closures (per (batch, bucket) shapes, cached by jit)
        cfg = model_cfg

        mesh = self.mesh

        # Small program outputs the HOST loop reads (logits rows, token
        # ids, flags) are pinned REPLICATED: on a multi-host mesh every
        # process can then np.asarray its addressable copy and all
        # processes' host loops stay in lockstep — without this, GSPMD
        # may shard an output across hosts and the read raises. On one
        # process the constraint is a no-op.
        from jax.sharding import NamedSharding as _NS, PartitionSpec as _P
        _rep = _NS(mesh, _P())

        def host_read(*xs):
            out = tuple(jax.lax.with_sharding_constraint(x, _rep)
                        for x in xs)
            return out if len(out) > 1 else out[0]

        @partial(jax.jit, donate_argnums=(1,))
        def prefill_step(params, cache_layers, slot_idx, tokens, offsets,
                         lengths, lora=None):
            # spmd_mesh is a TRACE-time context: it tells attention() which
            # mesh to shard_map the Pallas kernels over (models/common.py).
            # `lora` ((stacked, per-row ids) or None) rides the same
            # pattern: adapter identity is a VALUE argument, so swaps
            # and mixed-adapter batches compile nothing (ISSUE 10).
            with spmd_mesh(mesh, int4_sink=self._int4_dispatches), \
                    self._lora_scope(lora):
                caches_b = [(k[slot_idx], v[slot_idx])
                            for k, v in cache_layers]
                t = tokens.shape[1]
                positions = offsets[:, None] + jnp.arange(t)[None, :]
                valid = offsets + lengths
                logits, new_b = forward(params, cfg, tokens, positions,
                                        caches_b, offsets, valid,
                                        last_pos=lengths - 1)
                new_layers = [
                    (k.at[slot_idx].set(nk), v.at[slot_idx].set(nv))
                    for (k, v), (nk, nv) in zip(cache_layers, new_b)]
                return host_read(logits[:, 0]), new_layers

        self._prefill_step = prefill_step

        def decode_while(step_fn, caches, first_token, start_valid, key,
                         budget, temps, top_ks, top_ps, row_budgets,
                         done0, max_new, greedy, lora=None):
            """The decode while_loop, ONCE for all three cache layouts
            (contiguous, paged gather-view, paged pool-direct) —
            `step_fn(last, valid, caches) -> (logits [B,1,V], caches)` is
            the only layout-specific piece. max_new is the STATIC segment
            size (one compiled program per value — always DECODE_SEGMENT
            in serving); budget is the DYNAMIC number of tokens actually
            wanted from this segment, so short tails exit early without a
            fresh compile. Sampling params AND per-row token budgets are
            per-ROW dynamic arrays (heterogeneous knight personas: a row
            whose own max_new_tokens is exhausted goes done — emitting
            eos — while hungrier rows keep decoding; no recompile per
            config) — except the all-greedy common case, where the
            STATIC greedy flag keeps the hot path a single argmax
            instead of two full-vocab sorts + softmax + cumsum per token
            (one extra compiled variant total, not one per config).
            row_budgets count REMAINING tokens at this segment's start
            (the host loop decrements across segments)."""
            b = first_token.shape[0]
            out = jnp.zeros((b, max_new), jnp.int32)
            # done carries ACROSS segments (decode_segments threads it):
            # rows already at eos / their row budget skip the whole
            # segment (cond false when all are), instead of decoding
            # trimmed-away garbage — and the pipelined speculative
            # segment after an all-done one costs microseconds.
            done = done0
            eos = jnp.int32(self.tokenizer.eos_id)

            def cond(state):
                step, _, _, done, _, _, _ = state
                return (step < max_new) & (step < budget) & ~jnp.all(done)

            def body(state):
                step, last, valid, done, out, caches, key = state
                logits, caches = step_fn(last, valid, caches)
                key, sub = jax.random.split(key)
                row_logits = logits[:, 0].astype(jnp.float32)
                if greedy:
                    nxt = jnp.argmax(row_logits, axis=-1).astype(jnp.int32)
                else:
                    nxt = sample_token_batch(
                        row_logits, sub, temps, top_ks,
                        top_ps).astype(jnp.int32)
                nxt = jnp.where(done | (step >= row_budgets), eos, nxt)
                out = out.at[:, step].set(nxt)
                new_done = done | (nxt == eos)
                valid = jnp.where(done, valid, valid + 1)
                return step + 1, nxt, valid, new_done, out, caches, key

            state = (jnp.int32(0), first_token, start_valid, done, out,
                     caches, key)
            with spmd_mesh(mesh, int4_sink=self._int4_dispatches), \
                    self._lora_scope(lora):
                step, last, valid, done, out, caches, _ = \
                    jax.lax.while_loop(cond, body, state)
            step, last, valid, done, out = host_read(
                step, last, valid, done, out)
            return out, step, last, valid, done, caches

        def cached_step(params):
            """step_fn for the position-aligned [B, S, K, D] layouts."""
            def step(last, valid, caches_b):
                return forward(params, cfg, last[:, None], valid[:, None],
                               caches_b, valid, valid + 1)
            return step

        @partial(jax.jit, donate_argnums=(1,),
                 static_argnames=("max_new", "greedy"))
        def decode_loop(params, cache_layers, slot_idx, first_token,
                        start_valid, key, budget, temps, top_ks, top_ps,
                        row_budgets, done0, max_new, greedy, lora=None):
            # The all-done guard skips the per-layer slot gather/scatter
            # too (not just the while_loop) — an all-done segment (the
            # pipelined speculative dispatch's discard case) would
            # otherwise still copy the batch's whole KV.
            def run(cache_layers):
                caches_b = [(k[slot_idx], v[slot_idx])
                            for k, v in cache_layers]
                out, step, last, valid, done, caches_b = decode_while(
                    cached_step(params), caches_b, first_token,
                    start_valid, key, budget, temps, top_ks, top_ps,
                    row_budgets, done0, max_new, greedy, lora=lora)
                new_layers = [
                    (k.at[slot_idx].set(nk), v.at[slot_idx].set(nv))
                    for (k, v), (nk, nv) in zip(cache_layers, caches_b)]
                return out, step, last, valid, done, new_layers

            def skip(cache_layers):
                b = first_token.shape[0]
                return (jnp.zeros((b, max_new), jnp.int32), jnp.int32(0),
                        first_token, start_valid, done0, cache_layers)

            return jax.lax.cond(jnp.all(done0), skip, run, cache_layers)

        self._decode_loop = decode_loop

        # --- paged variants ---
        # Prefill: pool[table] materializes the SAME position-aligned
        # [B, S, K, D] view the contiguous path gathers per slot, so
        # forward() and the Pallas kernels are layout-agnostic; the
        # updated view scatters back through the same table. Aliased
        # (shared-prefix) pages are never in any row's write range
        # (ensure_capacity copy-on-writes them), so duplicate-index
        # scatters only ever rewrite identical bytes.
        # Decode: POOL-DIRECT where supported — the page-table-aware
        # kernel reads only pages below each row's frontier and the
        # gather view (which would temporarily recreate the full
        # contiguous HBM budget) is never built
        # (engine/paged_forward.py). On multi-device meshes the kernel
        # runs under shard_map (kv heads on "model", matching the pool's
        # sharding; pallas.paged_decode_spmd); head layouts that don't
        # partition keep the gather view.
        self.paged_direct = False
        self.paged_degraded_reason: Optional[str] = None
        self._paged_replicas = 1
        if kv_layout == "paged":
            from .pallas.attention import (paged_pool_direct_supported,
                                           spmd_partitionable)
            # attn="dense" is an explicit opt-out of every Pallas kernel
            # (the _resolve_attn contract) — the pool-direct decode IS a
            # Pallas kernel, so it honors the same switch. "auto" still
            # takes pool-direct even where auto resolves the view path to
            # dense (CPU): there is no dense pool-direct equivalent, and
            # the kernel runs in interpret mode there.
            n_model = dict(self.mesh.shape).get("model", 1)
            # data > 1 (VERDICT r4 #4): the pool's page axis is
            # data-sharded and the spmd kernel shards BATCH rows over
            # "data" — generate_batch groups rows by their slot's
            # replica (ReplicaGroupPlan) so each shard_map block reads
            # only its local pages; the kernels rebase tables to the
            # local range via axis_index. No gather view on any mesh.
            kh_l = model_cfg.num_kv_heads
            if self.mesh.devices.size > 1 and kh_l % max(n_model, 1) == 0:
                kh_l //= max(n_model, 1)   # kernel sees the local shard
            group = model_cfg.num_heads // model_cfg.num_kv_heads
            self.paged_direct = (
                attn != "dense"
                and paged_pool_direct_supported(
                    MAX_PREFILL_CHUNK, page_size, model_cfg.head_dim,
                    kh_l, group)
                and (self.mesh.devices.size == 1
                     or spmd_partitionable(model_cfg.num_heads,
                                           model_cfg.num_kv_heads,
                                           n_model)))
            # Quantized pages (ISSUE 11): can the Pallas kernels
            # dequantize this pool shape IN-KERNEL? A decline (int4
            # packing/grouping on this head_dim) routes serving to the
            # XLA dequant paths — gather view for the batched
            # programs — with the machine-readable reason recorded,
            # the int4mm plan/decline discipline: no dispatch can
            # reach a Mosaic failure on chip.
            if self.kv_quant_spec is not None:
                from .pallas.attention import kv_quant_decline_reason
                self.kv_quant_fallback_reason = kv_quant_decline_reason(
                    page_size, model_cfg.head_dim, kh_l, group,
                    self.kv_quant_spec.bits, self.kv_quant_spec.group)
                if (self.kv_quant_fallback_reason is not None
                        and self.paged_direct):
                    self.paged_direct = False
                    self.paged_degraded_reason = (
                        f"kv_quant:{self.kv_quant_fallback_reason}")
            self._paged_replicas = data_size if self.paged_direct else 1
            n_pages_seq = self.max_seq_len // page_size
            _kvq_spec = self.kv_quant_spec
            _n_layers = model_cfg.num_layers
            from .kv_quant import (dequantize_cells as _kvq_deq,
                                   quantize_cells as _kvq_q,
                                   split_combined as _kvq_split)

            def gather_view(combined, tables, b):
                # Combined pools (+ scales when quantized) -> the
                # position-aligned bf16 [B, S, K, D] view forward()
                # consumes — quantized pools dequantize AT THE GATHER
                # (kv_quant.dequantize_cells, the XLA read seam).
                pools, scales = _kvq_split(combined, _n_layers)
                caches_b = []
                for li, (k_pool, v_pool) in enumerate(pools):
                    if scales is not None:
                        ks, vs = scales[li]
                        kb = _kvq_deq(k_pool[tables], ks[tables],
                                      _kvq_spec, dtype)
                        vb = _kvq_deq(v_pool[tables], vs[tables],
                                      _kvq_spec, dtype)
                        tail = (k_pool.shape[2], model_cfg.head_dim)
                    else:
                        kb, vb = k_pool[tables], v_pool[tables]
                        tail = k_pool.shape[2:]
                    caches_b.append(
                        (kb.reshape(b, n_pages_seq * page_size, *tail),
                         vb.reshape(b, n_pages_seq * page_size, *tail)))
                return caches_b

            def scatter_view(combined, tables, new_b, b):
                # The inverse write seam: the updated bf16 view
                # RE-QUANTIZES cell-by-cell before scattering back.
                # Unwritten cells round-trip exactly (requantizing a
                # dequantized cell reproduces its payload and scale —
                # the pinned stability property), so repeated
                # gather/scatter segments cannot drift.
                pools, scales = _kvq_split(combined, _n_layers)
                out_p, out_s = [], []
                for li, ((k_pool, v_pool), (nk, nv)) in enumerate(
                        zip(pools, new_b)):
                    if scales is not None:
                        ks, vs = scales[li]
                        nk_q, nk_s = _kvq_q(nk, _kvq_spec)
                        nv_q, nv_s = _kvq_q(nv, _kvq_spec)
                        qtail = k_pool.shape[2:]
                        stail = ks.shape[2:]
                        out_p.append((
                            k_pool.at[tables].set(nk_q.reshape(
                                b, n_pages_seq, page_size, *qtail)),
                            v_pool.at[tables].set(nv_q.reshape(
                                b, n_pages_seq, page_size, *qtail))))
                        out_s.append((
                            ks.at[tables].set(nk_s.reshape(
                                b, n_pages_seq, page_size, *stail)),
                            vs.at[tables].set(nv_s.reshape(
                                b, n_pages_seq, page_size, *stail))))
                    else:
                        tail = k_pool.shape[2:]
                        nk5 = nk.reshape(b, n_pages_seq, page_size,
                                         *tail)
                        nv5 = nv.reshape(b, n_pages_seq, page_size,
                                         *tail)
                        out_p.append((k_pool.at[tables].set(nk5),
                                      v_pool.at[tables].set(nv5)))
                return out_p + out_s

            @partial(jax.jit, donate_argnums=(1,))
            def prefill_step_paged(params, pools, tables, tokens, offsets,
                                   lengths, lora=None):
                with spmd_mesh(mesh, int4_sink=self._int4_dispatches), \
                        self._lora_scope(lora):
                    b, t = tokens.shape
                    caches_b = gather_view(pools, tables, b)
                    positions = offsets[:, None] + jnp.arange(t)[None, :]
                    valid = offsets + lengths
                    logits, new_b = forward(params, cfg, tokens, positions,
                                            caches_b, offsets, valid,
                                            last_pos=lengths - 1)
                    new_pools = scatter_view(pools, tables, new_b, b)
                    return host_read(logits[:, 0]), new_pools

            @partial(jax.jit, donate_argnums=(1,))
            def prefill_step_paged_direct(params, pools, tables, tokens,
                                          offsets, lengths, lora=None):
                from .paged_forward import forward_paged
                with spmd_mesh(mesh, int4_sink=self._int4_dispatches), \
                        self._lora_scope(lora):
                    t = tokens.shape[1]
                    positions = offsets[:, None] + jnp.arange(t)[None, :]
                    valid = offsets + lengths
                    pools_l, scales_l = _kvq_split(pools, _n_layers)
                    logits, new_pools = forward_paged(
                        params, cfg, tokens, positions, pools_l, tables,
                        valid, pool_replicas=data_size,
                        last_pos=lengths - 1,
                        scales=scales_l, quant_spec=_kvq_spec)
                    return host_read(logits[:, 0]), new_pools

            # Keep BOTH compiled-closure pairs: the gather-view programs
            # are the runtime degradation target when a pool-direct
            # kernel fails on chip (_degrade_paged_direct).
            self._prefill_step_paged_gather = prefill_step_paged
            self._prefill_step_paged = (prefill_step_paged_direct
                                        if self.paged_direct
                                        else prefill_step_paged)

            @partial(jax.jit, donate_argnums=(1,),
                     static_argnames=("max_new", "greedy"))
            def decode_loop_paged(params, pools, tables, first_token,
                                  start_valid, key, budget, temps, top_ks,
                                  top_ps, row_budgets, done0, max_new,
                                  greedy, lora=None):
                b = first_token.shape[0]

                # All-done guard: skip the full gather view + scatter
                # (the paged layout's whole-cache copy), not just the
                # while_loop — see decode_loop.
                def run(pools):
                    caches_b = gather_view(pools, tables, b)
                    out, step, last, valid, done, caches_b = decode_while(
                        cached_step(params), caches_b, first_token,
                        start_valid, key, budget, temps, top_ks, top_ps,
                        row_budgets, done0, max_new, greedy, lora=lora)
                    new_pools = scatter_view(pools, tables, caches_b, b)
                    return out, step, last, valid, done, new_pools

                def skip(pools):
                    return (jnp.zeros((b, max_new), jnp.int32),
                            jnp.int32(0), first_token, start_valid,
                            done0, pools)

                return jax.lax.cond(jnp.all(done0), skip, run, pools)

            @partial(jax.jit, donate_argnums=(1,),
                     static_argnames=("max_new", "greedy"))
            def decode_loop_paged_direct(params, pools, tables, first_token,
                                         start_valid, key, budget, temps,
                                         top_ks, top_ps, row_budgets,
                                         done0, max_new, greedy,
                                         lora=None):
                from .paged_forward import forward_paged

                def step_fn(last, valid, pools):
                    pools_l, scales_l = _kvq_split(pools, _n_layers)
                    return forward_paged(
                        params, cfg, last[:, None], valid[:, None],
                        pools_l, tables, valid + 1,
                        pool_replicas=data_size,
                        scales=scales_l, quant_spec=_kvq_spec)

                return decode_while(
                    step_fn, pools, first_token, start_valid, key, budget,
                    temps, top_ks, top_ps, row_budgets, done0, max_new,
                    greedy, lora=lora)

            self._decode_loop_paged_gather = decode_loop_paged
            self._decode_loop_paged = (decode_loop_paged_direct
                                       if self.paged_direct
                                       else decode_loop_paged)

            @partial(jax.jit, donate_argnums=(0,))
            def scatter_kv_paged(pools, tables, new_layers):
                # Ring-prefill writeback: whole-sequence K/V [B, Tp, K, D]
                # (Tp a multiple of page_size — _prefill enforces it)
                # scattered through each row's page table. Rows' pages are
                # write-exclusive (ensure_capacity COW'd the offset-0
                # write range); table entries past a row's allocation are
                # the scratch page, which absorbs the pad-tail garbage and
                # is never read — same contract as scatter_view.
                # Quantized pools quantize-on-write here too (ISSUE 11).
                pools_l, scales_l = _kvq_split(pools, _n_layers)
                out_p, out_s = [], []
                for li, ((k_pool, v_pool), (nk, nv)) in enumerate(
                        zip(pools_l, new_layers)):
                    b, t = nk.shape[0], nk.shape[1]
                    n = t // page_size
                    if scales_l is not None:
                        ks, vs = scales_l[li]
                        nk_q, nk_s = _kvq_q(nk.astype(dtype), _kvq_spec)
                        nv_q, nv_s = _kvq_q(nv.astype(dtype), _kvq_spec)
                        qtail = k_pool.shape[2:]
                        stail = ks.shape[2:]
                        out_p.append((
                            k_pool.at[tables[:, :n]].set(
                                nk_q.reshape(b, n, page_size, *qtail)),
                            v_pool.at[tables[:, :n]].set(
                                nv_q.reshape(b, n, page_size, *qtail))))
                        out_s.append((
                            ks.at[tables[:, :n]].set(
                                nk_s.reshape(b, n, page_size, *stail)),
                            vs.at[tables[:, :n]].set(
                                nv_s.reshape(b, n, page_size, *stail))))
                    else:
                        tail = k_pool.shape[2:]
                        nk5 = nk.reshape(b, n, page_size, *tail) \
                            .astype(k_pool.dtype)
                        nv5 = nv.reshape(b, n, page_size, *tail) \
                            .astype(v_pool.dtype)
                        out_p.append((k_pool.at[tables[:, :n]].set(nk5),
                                      v_pool.at[tables[:, :n]].set(nv5)))
                return out_p + out_s

            self._scatter_kv_paged = scatter_kv_paged

        # Cross-session prefix cache + host-RAM offload tier (ISSUE 7):
        # both are paged-pool subsystems — the contiguous layout has no
        # page-granular sharing unit. The cache attaches to the pool
        # (commit-inserts, alloc-reclaims ride the kv object); the tier
        # needs the engine (mesh, compile labels), so it lives here.
        self.prefix_cache = None
        self.kv_offload = None
        if kv_layout == "paged":
            from .prefix_cache import PrefixCache, cache_enabled
            if cache_enabled(prefix_cache):
                self.prefix_cache = PrefixCache(
                    self.kv, engine=model_cfg.name,
                    max_pages=prefix_cache_pages)
                self.kv.prefix_cache = self.prefix_cache
            from .kv_offload import HostOffloadTier, offload_enabled
            if offload_enabled(kv_offload):
                self.kv_offload = HostOffloadTier(self)

        # Ragged paged attention (ISSUE 8): mixed prefill/decode in ONE
        # dispatch over a flat token buffer — the scheduler's chunk-
        # interleaved admission path. Paged pools only (the flat buffer
        # addresses pages); data-sharded pools decline (a flat buffer
        # cannot mix replicas' rows) with the reason recorded. Within an
        # enabled engine, the KERNEL path needs the pool shape + head
        # layout to fit — otherwise every ragged dispatch runs the XLA
        # fallback and records `fallback_reason`, the int4_paths
        # pattern. ROUNDTABLE_RAGGED_ATTN=0 kills the whole seam: the
        # scheduler then serves the PR-4 admission prologue unchanged.
        from collections import deque as _deque
        self.ragged_enabled = False
        self.ragged_path: Optional[str] = None
        self.ragged_reason: Optional[str] = None
        self.ragged_fallback_reason: Optional[str] = None
        self.ragged_tokens = 0
        self.ragged_shapes: tuple[int, ...] = ()
        self.ragged_defer_min = 0
        self._ragged_dispatches: dict[str, int] = {}
        self._ragged_recent = _deque(maxlen=32)
        if kv_layout == "paged":
            from .prefix_cache import env_flag
            from .pallas import attention as _pattn
            from .serving_loop import ragged_token_budget
            n_model = dict(self.mesh.shape).get("model", 1)
            kh_l = model_cfg.num_kv_heads
            if self.mesh.devices.size > 1 and kh_l % max(n_model, 1) == 0:
                kh_l //= max(n_model, 1)
            group = model_cfg.num_heads // model_cfg.num_kv_heads
            if not env_flag(ragged_attn, "ROUNDTABLE_RAGGED_ATTN"):
                self.ragged_reason = "disabled:config/env"
            elif dict(self.mesh.shape).get("data", 1) > 1:
                # The pool's page axis shards over "data" on these
                # meshes; a flat buffer mixing replicas' rows cannot.
                self.ragged_reason = "mesh:data-axis"
            else:
                from .serving_loop import (ragged_defer_min,
                                           ragged_shape_grid)
                self.ragged_enabled = True
                self.ragged_tokens = ragged_token_budget(num_slots)
                self.ragged_shapes = ragged_shape_grid(self.ragged_tokens)
                self.ragged_defer_min = ragged_defer_min()
                if attn == "dense":
                    decline = "attn=dense"
                elif (self.mesh.devices.size > 1
                      and not _pattn.spmd_partitionable(
                          model_cfg.num_heads, model_cfg.num_kv_heads,
                          n_model)):
                    decline = "heads:model-axis"
                else:
                    decline = _pattn.ragged_decline_reason(
                        page_size, model_cfg.head_dim, kh_l, group)
                if (decline is None
                        and self.kv_quant_fallback_reason is not None):
                    # Quantized pool the kernel cannot dequantize
                    # in-kernel (ISSUE 11): ragged dispatches serve the
                    # XLA dense path with the quant decline recorded.
                    decline = f"kv_quant:{self.kv_quant_fallback_reason}"
                self.ragged_path = ("pallas_ragged" if decline is None
                                    else "xla_ragged")
                self.ragged_fallback_reason = decline

            @partial(jax.jit, donate_argnums=(1,),
                     static_argnames=("greedy", "attn_path",
                                      "score_width", "propose_width"))
            def ragged_step(params, pools, tables, tokens, positions,
                            token_pages, token_offs, token_seq,
                            seq_of_block, block_qstart, query_offsets,
                            kv_valid, last_rows, key, temps, top_ks,
                            top_ps, sample_rows=None, greedy=True,
                            attn_path="kernel", score_width=0,
                            lora=None, copy_src=None, copy_dst=None,
                            propose_width=0):
                from .paged_forward import forward_ragged
                with spmd_mesh(mesh, int4_sink=self._int4_dispatches), \
                        self._lora_scope(lora):
                    pools_l, scales_l = _kvq_split(pools, _n_layers)
                    logits, new_pools = forward_ragged(
                        params, cfg,
                        tokens, positions, pools_l, tables, seq_of_block,
                        block_qstart, query_offsets, kv_valid,
                        token_pages, token_offs, token_seq, last_rows,
                        attn_path=attn_path,
                        sample_rows=(sample_rows if score_width
                                     else None),
                        scales=scales_l, quant_spec=_kvq_spec,
                        copy_src=copy_src, copy_dst=copy_dst)
                    lf = logits.astype(jnp.float32)
                    if score_width:
                        # Speculative verify (ISSUE 9): per-position
                        # tokens [S, R] — greedy argmax, or an exact
                        # per-position sample through the SAME
                        # sample_token_batch the decode loop uses (one
                        # categorical key draws S*R independent rows).
                        s, r, v = lf.shape
                        if greedy:
                            nxt = jnp.argmax(lf, axis=-1)
                        else:
                            nxt = sample_token_batch(
                                lf.reshape(s * r, v), key,
                                jnp.repeat(temps, r),
                                jnp.repeat(top_ks, r),
                                jnp.repeat(top_ps, r)).reshape(s, r)
                        nxt = nxt.astype(jnp.int32)
                    elif greedy:
                        nxt = jnp.argmax(lf, axis=-1).astype(jnp.int32)
                    else:
                        nxt = sample_token_batch(
                            lf, key, temps, top_ks,
                            top_ps).astype(jnp.int32)
                if propose_width:
                    # Draft-model propose dispatch (ISSUE 13): alongside
                    # the greedy next token, the top-`propose_width` ids
                    # of each row's tip distribution seed the root
                    # branches of the token tree. score_width==0 here
                    # (propose batches are plain ragged dispatches), so
                    # lf is [S, V].
                    tops = jax.lax.top_k(
                        lf, propose_width)[1].astype(jnp.int32)
                    return host_read(nxt, tops), new_pools
                return host_read(nxt), new_pools

            self._ragged_step = ragged_step

        # Speculative decoding (ISSUE 9): self-drafting verify folded
        # into the scheduler's ragged segment loop. The verify dispatch
        # IS a ragged dispatch (a draft run is a short multi-token row
        # in the flat buffer), so spec resolves ON only where the
        # ragged seam did — the scheduler then drafts per row on the
        # host and the static score_width program scores every draft
        # position in one forward. ROUNDTABLE_SPEC_DECODE=0 /
        # spec_decode: False restores 1-token decode byte-identically.
        from .spec_decode import (DEFAULT_MAX_DRAFT, SpecOptions,
                                  spec_enabled)
        self.spec_decode = False
        self.spec_reason: Optional[str] = None
        # The resolved `spec_decode:` block (ISSUE 13): dict configs
        # choose the drafter + tree shape; the PR-9 bool path resolves
        # to the ngram chain defaults. Validation raises HERE so
        # from_config and the constructor fail identically.
        self.spec_options = SpecOptions.resolve(spec_decode)
        if spec_max_draft is None and self.spec_options.max_draft \
                is not None:
            spec_max_draft = self.spec_options.max_draft
        self.spec_max_draft = (DEFAULT_MAX_DRAFT if spec_max_draft is None
                               else int(spec_max_draft))
        from .serving_loop import RAGGED_BLOCK_Q
        if not 1 <= self.spec_max_draft <= RAGGED_BLOCK_Q - 1:
            # draft+1 must fit one flat-buffer tile, so a speculating
            # batch packs exactly like a plain ragged decode batch and
            # the overflow rules stay one rule.
            raise ValueError(
                f"spec_max_draft must be 1..{RAGGED_BLOCK_Q - 1} "
                f"(verify run = drafts+1 tokens in one "
                f"{RAGGED_BLOCK_Q}-row block), got {self.spec_max_draft}")
        if (self.spec_options.tree is not None
                and self.spec_options.tree["depth"] > self.spec_max_draft):
            # Every root-to-leaf run is 1 + depth tokens and the static
            # score gather is spec_max_draft + 1 wide — a deeper tree
            # would need a new compiled width.
            raise ValueError(
                f"spec_decode tree depth {self.spec_options.tree['depth']}"
                f" exceeds spec_max_draft {self.spec_max_draft} (the "
                f"static score_width must cover every root-to-leaf run)")
        self._spec_drafted = 0
        self._spec_accepted = 0
        self._spec_throttled = 0
        self._spec_dispatches = 0
        self._spec_tree_nodes = 0
        self._spec_tree_rows = 0
        # drafter kind -> [drafted, accepted] (per-proposer attribution
        # for the labeled acceptance-rate gauge).
        self._spec_by_drafter: dict[str, list[int]] = {}
        self._spec_recent = _deque(maxlen=32) if kv_layout == "paged" \
            else None
        if kv_layout != "paged":
            self.spec_reason = "kv_layout:contiguous"
        elif not spec_enabled(spec_decode):
            self.spec_reason = "disabled:config/env"
        elif not self.ragged_enabled:
            self.spec_reason = f"ragged:{self.ragged_reason}"
        else:
            self.spec_decode = True
        # Tree-verify statics (ISSUE 13): on a tree-configured engine
        # EVERY verify dispatch carries branch-times row capacity and a
        # fixed block of page-copy slots — how many tree rows (0
        # included) actually use them is a VALUE, so chain/tree/no-spec
        # mixes and acceptance drift never compile a new program. Chain
        # engines keep the PR-9 shapes exactly (branch 1, zero copy
        # slots — build_ragged_batch then adds no arrays at all).
        self.spec_tree = (self.spec_options.tree
                          if self.spec_decode else None)
        self.spec_branch = (self.spec_tree["branch"]
                            if self.spec_tree else 1)
        self.spec_s_max = num_slots * self.spec_branch + 1
        self.spec_copy_slots = num_slots * (self.spec_branch - 1)

        # Per-engine roofline model (ISSUE 6): streamed bytes from the
        # ACTUAL (quantized) tree + chip ceilings, published at event
        # rate by generate/scheduler seams and embedded in describe().
        from ..utils import perfmodel
        self.perf = perfmodel.EnginePerf.from_engine(self)

        # Multi-LoRA knight personas (ISSUE 10): K personas as LoRA
        # deltas over this ONE resident base. The store holds stacked
        # per-target A/B tensors whose SHAPES are config-static; every
        # serving program above takes (stacked, adapter ids) as a
        # VALUE argument, so mixed-adapter batches, hot-swaps and
        # occupancy drift compile nothing. Requires an explicit
        # `lora:` config block; ROUNDTABLE_LORA=0 restores base-only
        # serving byte-identically (the programs get lora=None and the
        # tagged _einsum sites short-circuit on the inert scope).
        from .lora import (DEFAULT_MAX_ADAPTERS, DEFAULT_RANK,
                           DEFAULT_SCALE, LoraStore, lora_enabled)
        if not lora:
            self.lora_reason = "disabled:config"
        elif not lora_enabled(lora):
            self.lora_reason = "disabled:env"
        elif seq_parallel and seq_parallel > 1:
            # The ring prefill program has no lora seam: serving a
            # persona row through it would bake UN-lora'd K/V that
            # decode then reads — a silent parity break, so the whole
            # feature declines instead (the decline table names it).
            self.lora_reason = "seq_parallel:ring-prefill"
        else:
            lora_cfg = lora if isinstance(lora, dict) else {}
            self.lora = LoraStore(
                model_cfg, self.mesh,
                max_adapters=int(lora_cfg.get("max_adapters",
                                              DEFAULT_MAX_ADAPTERS)),
                rank=int(lora_cfg.get("rank", DEFAULT_RANK)),
                scale=float(lora_cfg.get("scale", DEFAULT_SCALE)),
                dtype=dtype,
                quant=lora_cfg.get("quant", "none"),
                adapters=lora_cfg.get("adapters"),
                targets=lora_cfg.get("targets"),
                engine_name=model_cfg.name, perf=self.perf)
            self._lora_quant = self.lora.quant

        # Drafter resolution (ISSUE 13): which proposer actually serves
        # the speculative phase. Config VALIDATION raised above; drafter
        # AVAILABILITY falls back to the ngram chain with the reason
        # recorded (the decline-table discipline) — a missing LoRA store
        # or unreadable draft checkpoint must degrade serving, never
        # kill the engine. Resolution runs AFTER the LoRA store exists
        # so the `lora` drafter can pin its adapter slot.
        self.spec_drafter = "ngram" if self.spec_decode else None
        self.spec_drafter_reason: Optional[str] = None
        self.spec_device_drafter = None
        if self.spec_decode and self.spec_options.drafter != "ngram":
            try:
                self._install_drafter(self.spec_options.drafter,
                                      adapter=self.spec_options.adapter,
                                      checkpoint=self.spec_options
                                      .draft_checkpoint)
            except Exception as e:  # noqa: BLE001 — degrade, record
                self.spec_drafter_reason = (
                    f"{self.spec_options.drafter}:{str(e)[:120]}")

    def _install_drafter(self, kind: str, adapter: Optional[str] = None,
                         checkpoint: Optional[str] = None) -> None:
        """Build (or hot-swap to) the `kind` drafter. Drafting is pure
        VALUES through already-compiled programs — a draft-model params
        override shares the engine pytree shapes, a LoRA draft head is
        one more slot in the stacked store — so steady-state swaps
        compile nothing (the STRICT acceptance line). Raises when the
        drafter's dependency is missing; callers record the reason and
        keep the ngram chain."""
        from .spec_decode import DRAFTER_KINDS, DeviceDrafter
        if kind not in DRAFTER_KINDS:
            raise ValueError(
                f"drafter must be one of {DRAFTER_KINDS}, got {kind!r}")
        if kind == "ngram":
            self.spec_device_drafter = None
            self.spec_drafter = "ngram"
            self.spec_drafter_reason = None
            return
        if kind == "model":
            draft_params = None
            if checkpoint:
                draft_params = self._load_draft_params(checkpoint)
            self.spec_device_drafter = DeviceDrafter(
                "model", params=draft_params)
        else:  # lora
            if self.lora is None:
                raise RuntimeError(
                    f"lora drafter needs a `lora:` store "
                    f"({self.lora_reason or 'disabled:config'})")
            if not adapter:
                raise ValueError("lora drafter needs an adapter name")
            if not self.lora.resolvable(adapter):
                self.lora.register(adapter)
            # Residency ref held for the drafter's lifetime (swap to a
            # different drafter releases it) — the draft head must not
            # be LRU-evicted under an in-flight propose dispatch.
            slot = self.lora.acquire([adapter])[0]
            self.spec_device_drafter = DeviceDrafter(
                "lora", adapter_slot=slot)
            self.spec_device_drafter.adapter_id = adapter
        self.spec_drafter = kind
        self.spec_drafter_reason = None

    def set_spec_drafter(self, kind: str,
                         adapter: Optional[str] = None,
                         checkpoint: Optional[str] = None) -> None:
        """Hot-swap the active drafter per workload (ISSUE 13: drafting
        as an adapter). Values-only — no program recompiles; the old
        LoRA draft head's residency ref releases so the store can evict
        it. Raises (state unchanged) when the new drafter's dependency
        is missing or speculation is off on this engine."""
        if not self.spec_decode:
            raise RuntimeError(
                f"spec_decode is off on this engine ({self.spec_reason})")
        old = self.spec_device_drafter
        self._install_drafter(kind, adapter=adapter, checkpoint=checkpoint)
        if old is not None and old is not self.spec_device_drafter:
            if (old.kind == "lora" and self.lora is not None
                    and getattr(old, "adapter_id", None)):
                self.lora.release([old.adapter_id])
            # The outgoing device drafter's shadow slots die with it:
            # _drop_request only releases draft slots while a device
            # drafter is INSTALLED, so swapping away would otherwise
            # orphan every live row's draft pages until slot-pressure
            # eviction (free-list depletion degrades tree verify and
            # shrinks prefix-cache capacity meanwhile).
            self._release_draft_slots()

    def _release_draft_slots(self) -> None:
        """Release every shadow draft slot in the paged pool (hot-swap
        away from a device drafter; the per-row path at retire is the
        scheduler's _drop_request)."""
        from .spec_decode import DRAFT_SCOPE
        if self.kv_layout != "paged":
            return
        for name in list(self.kv._slots):
            if name.startswith(DRAFT_SCOPE):
                self.kv.release(name)

    def _load_draft_params(self, checkpoint: str):
        """Load + shard (+ quantize, matching the engine) a draft
        checkpoint onto the SAME ModelConfig shapes — the `params`
        override must be pytree-identical to self.params or the shared
        ragged program would retrace."""
        from .checkpoint import load_hf_checkpoint
        params = load_hf_checkpoint(checkpoint, self.cfg, self.dtype)
        from .sharding import shard_params
        params = shard_params(params, self.cfg, self.mesh)
        if self.quant in ("int8", "int4"):
            from .quant import quantize_params
            from .sharding import model_axis_size
            params = quantize_params(
                params, self.cfg, act_dtype=self.dtype,
                free_source=True, bits=8 if self.quant == "int8" else 4,
                model_shards=model_axis_size(self.mesh))
        return params

    @staticmethod
    def _resolve_attn(model_cfg: ModelConfig, attn: str,
                      mesh) -> ModelConfig:
        """Pick the attention implementation (SURVEY.md §7.3 hard part 1).

        "auto" enables the Pallas kernels on TPU with lane-aligned
        head_dim. On a multi-device mesh they run under shard_map with kv
        heads partitioned on the "model" axis (pallas/attention.py
        flash_attention_spmd), which requires both head counts to divide
        the model-axis size — otherwise auto stays dense (matching
        _fallback_replicated's cache layout). Explicit "flash"/"dense"
        always wins; explicit "flash" on a non-divisible mesh raises."""
        import dataclasses
        if attn not in ("auto", "flash", "dense"):
            raise ValueError(
                f"attn must be auto|flash|dense, got {attn!r}")
        from .pallas.attention import spmd_partitionable
        n_model = dict(mesh.shape).get("model", 1)
        heads_divide = spmd_partitionable(
            model_cfg.num_heads, model_cfg.num_kv_heads, n_model)
        if attn == "flash" and mesh.devices.size > 1 and not heads_divide:
            raise ValueError(
                f"attn='flash' on a {n_model}-way model axis needs head "
                f"counts divisible by it (got H={model_cfg.num_heads}, "
                f"K={model_cfg.num_kv_heads}) — use attn='auto' or 'dense'")
        if attn in ("flash", "dense"):
            return dataclasses.replace(model_cfg, attn_impl=attn)
        if (jax.default_backend() == "tpu"
                and model_cfg.head_dim % 128 == 0
                and (mesh.devices.size == 1 or heads_divide)):
            return dataclasses.replace(model_cfg, attn_impl="flash")
        return dataclasses.replace(model_cfg, attn_impl="dense")

    # --- construction from adapter config ---

    @classmethod
    def from_config(cls, config: dict[str, Any]) -> "InferenceEngine":
        model_name = config.get("model", "tiny-gemma")
        overrides = {}
        if config.get("max_seq_len"):
            overrides["max_seq_len"] = int(config["max_seq_len"])
        model_cfg = get_model_config(model_name, **overrides)
        dtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
                 "float16": jnp.float16}[config.get("dtype", "bfloat16")]
        sampling_cfg = config.get("sampling", {})
        sampling = SamplingParams(
            temperature=float(sampling_cfg.get("temperature", 0.7)),
            top_k=int(sampling_cfg.get("top_k", 0)),
            top_p=float(sampling_cfg.get("top_p", 1.0)),
            max_new_tokens=int(sampling_cfg.get("max_new_tokens", 1024)),
        )
        engine = cls(
            model_cfg,
            checkpoint=config.get("checkpoint", "") or "",
            mesh_shape=config.get("mesh"),
            num_slots=int(config.get("num_slots", 8)),
            dtype=dtype,
            sampling=sampling,
            seed=int(config.get("seed", 0)),
            seq_parallel=int(config.get("seq_parallel", 0)),
            long_threshold=int(config.get("long_threshold", 2048)),
            long_scheme=config.get("long_scheme", "ring"),
            attn=config.get("attn", "auto"),
            devices=config.get("devices"),
            kv_layout=config.get("kv_layout", "contiguous"),
            page_size=int(config.get("page_size", 128)),
            num_pages=(int(config["num_pages"])
                       if config.get("num_pages") else None),
            quant=config.get("quant", "none"),
            dcn_axis=config.get("dcn_axis"),
            prefix_cache=config.get("prefix_cache"),
            prefix_cache_pages=(int(config["prefix_cache_pages"])
                                if config.get("prefix_cache_pages")
                                else None),
            kv_offload=config.get("kv_offload"),
            ragged_attn=config.get("ragged_attn"),
            spec_decode=config.get("spec_decode"),
            # `is not None`, not truthiness: spec_max_draft: 0 must
            # surface the constructor's ValueError, not silently run
            # with the default.
            spec_max_draft=(int(config["spec_max_draft"])
                            if config.get("spec_max_draft") is not None
                            else None),
            lora=config.get("lora"),
            kv_quant=config.get("kv_quant"),
        )
        # Set by fleet.check_fleet_fits when it flips an unpinned config
        # to int8: surfaced via describe() so the degrade is visible
        # after the fact, not only in the warning stream (advisor r3).
        engine.quant_auto_degraded = bool(
            config.get("_quant_auto_degraded"))
        # Rebuild recipe (ISSUE 12): the supervisor reconstructs a dead
        # engine from exactly this config — captured here so engines
        # built outside the get_engine cache (tests, benches) are
        # supervisable too.
        engine._engine_config = dict(config)
        if "dispatch_retries" in config:
            from .faults import RetryPolicy
            engine.retry = RetryPolicy(
                max_retries=max(0, int(config["dispatch_retries"])))
        return engine

    # --- serving ---

    def warmup(self, max_prompt_tokens: int = MAX_PREFILL_CHUNK,
               batch_sizes: tuple[int, ...] = (1,)) -> float:
        """Compile-and-stabilize every serving program.

        Each (batch, bucket) prefill program and the decode segment are run
        TWICE: the first run compiles, but its donated cache outputs come
        back in XLA's preferred layout — different from the fresh
        jnp.zeros layout — so the very next serving call would recompile
        (~seconds). The second run reaches the layout fixpoint, making
        steady-state serving dispatch ~1ms. Returns seconds spent.
        """
        t0 = time.monotonic()
        # Warming is ALWAYS a sanctioned compile phase: reopen this
        # label first, so a second same-model engine's warmup (the
        # sentinel label is the model name — warmup_cmd loops engines
        # in one process) or a deliberate re-warm never counts its own
        # compiles as steady-state violations.
        from . import compile_watch
        compile_watch.reopen_warmup(self.cfg.name)
        # Warm the adapter store's slot setters FIRST (ISSUE 10): a
        # steady-state hot-swap must compile nothing under STRICT, and
        # the serving warms below should trace against setter-produced
        # stacked layouts — exactly what steady-state swaps feed them.
        if self.lora is not None:
            self.lora.warm()
        if self.paged_direct and self._paged_replicas > 1:
            # Replica-grouped padding makes the device batch shape
            # R * max(group) — a function of batch COMPOSITION, not just
            # size: a k-row batch skewed onto one replica pads to R*k
            # even though a balanced one pads to R*ceil(k/R). Warm every
            # reachable padded shape via balanced batches of that size
            # (acquire keeps per-replica slot counts within ceil(S/R),
            # bounding the worst-case group), so no composition compiles
            # mid-serve.
            R = self._paged_replicas
            cap = -(-self.kv.num_slots // R)
            sizes = set(batch_sizes)
            for k in tuple(sizes):
                for g in range(1, min(k, cap) + 1):
                    # The balanced warm batch producing padded shape R*g
                    # is R*g rows — capped at num_slots, whose balanced
                    # composition (groups of ceil(S/R) = g for g == cap)
                    # still pads to R*g.
                    sizes.add(min(R * g, self.kv.num_slots))
            batch_sizes = tuple(sorted(sizes))
        limit = min(max_prompt_tokens,
                    self.max_seq_len - DECODE_SEGMENT - 1)
        # Warm the CHUNKED programs with the ring path disabled — with
        # seq_parallel on, warmup's offset-0 long runs would otherwise be
        # hijacked by the ring program and delta prefills (offset>0, long
        # suffix) would hit an unwarmed chunked bucket mid-serve.
        ring_fn, self._ring_prefill_fn = self._ring_prefill_fn, None
        try:
            for b in batch_sizes:
                if b > self.kv.num_slots:
                    continue
                # Paged pools (default: HALF the contiguous budget) can't
                # pin every batch size at the full prompt limit — cap the
                # warm length at what the pool can hold, exactly like real
                # serving: prompts past the cap exhaust the pool at THIS
                # batch size anyway, so their buckets are unreachable and
                # need no warming.
                limit_b = min(limit, self._warm_prompt_cap(b))
                if limit_b < 2:
                    continue
                buckets = [x for x in PREFILL_BUCKETS
                           if x <= _bucket(limit_b)]
                for bucket in buckets:
                    n = min(bucket, limit_b)  # lands exactly in `bucket`
                    # Rows diverge at position 1 so cross-slot prefix
                    # sharing can't collapse the batch — warmup must
                    # compile the REAL (b, bucket) prefill programs.
                    turns = [(f"__warmup_{i}",
                              [self.tokenizer.bos_id] + [5 + i] * (n - 1))
                             for i in range(b)]
                    for _ in range(2):
                        self._release_warm_slots()
                        self.generate_batch(turns, max_new_tokens=1)
        finally:
            self._ring_prefill_fn = ring_fn

        # Ring programs are whole-prompt-sized, so their buckets run up to
        # the cache cap (not max_prompt_tokens): threshold, 2×, ... cap.
        ring_limit = self.max_seq_len - DECODE_SEGMENT - 1
        if ring_fn is not None and ring_limit >= self.long_threshold:
            for b in batch_sizes:
                if b > self.kv.num_slots:
                    continue
                cap_b = min(ring_limit, self._warm_prompt_cap(b))
                if cap_b < self.long_threshold:
                    continue
                length = self.long_threshold
                while True:
                    n = min(length, cap_b)
                    turns = [(f"__warmup_{i}",
                              [self.tokenizer.bos_id] + [5 + i] * (n - 1))
                             for i in range(b)]
                    for _ in range(2):
                        self._release_warm_slots()
                        self.generate_batch(turns, max_new_tokens=1)
                    if length >= cap_b:
                        break
                    length *= 2
        # Warm the shared-prefix copy program (copy_spans is ONE shape
        # thanks to _apply_copies' padding) and the layout fixpoint of the
        # prefill/decode programs that run right after a copy — otherwise
        # the first real round with a shared preamble compiles mid-serve.
        if (self.kv.num_slots >= 2
                and min(limit, self._warm_prompt_cap(2))
                > MIN_SHARED_PREFIX + 8):
            shared = [self.tokenizer.bos_id] + [7] * (MIN_SHARED_PREFIX + 4)
            turns = [(f"__warmup_{i}", shared + [9 + i] * 4)
                     for i in range(2)]
            for _ in range(2):
                self._release_warm_slots()
                self.generate_batch(turns, max_new_tokens=1)
        self._release_warm_slots()
        # Warm the ragged mixed-dispatch program (ISSUE 8): ONE compiled
        # shape per (budget, sampling mode) serves every prefill/decode
        # composition, so two dispatches reach its layout fixpoint and
        # scheduler joins compile nothing in steady state.
        if self.ragged_enabled:
            self._warm_ragged()
        # Warm the offload tier's fetch/write programs (ONE fixed shape
        # each, ISSUE 7): a first idle-session spill/restore in steady
        # state must compile nothing under ROUNDTABLE_RECOMPILE_STRICT.
        if self.kv_offload is not None:
            self.kv_offload.warm()
        # Warmup IS this engine's steady-state declaration (ISSUE 6):
        # from here on, any compile is a recorded mid-serve recompile —
        # counted + flight-dumped always, fatal under
        # ROUNDTABLE_RECOMPILE_STRICT=1.
        from . import compile_watch
        compile_watch.warmup_complete(self.cfg.name)
        return time.monotonic() - t0

    def _warm_ragged(self) -> None:
        """Compile-and-stabilize the ragged mixed dispatch: a two-seq
        flat buffer (one prefill chunk + one decode-shaped row) through
        the REAL _ragged_dispatch seam, twice for the donated-pool
        layout fixpoint — in the engine-default sampling mode plus
        greedy (the scheduler's parity/STRICT mode) when they differ.
        The decode-shaped row attends warm garbage; outputs are
        discarded, the compiled program is the point."""
        from .serving_loop import RaggedSeq, build_ragged_batch
        names = ("__warmup_0", "__warmup_1")
        if self.kv.num_slots < 2:
            return
        self._release_warm_slots()
        pinned = names
        self.kv.ensure_capacity(names[0], 32, write_from=0,
                                pinned=pinned)
        self.kv.ensure_capacity(names[1], 16, write_from=0,
                                pinned=pinned)
        t0 = self.kv.table_for([names[0]])[0]
        t1 = self.kv.table_for([names[1]])[0]
        bos = self.tokenizer.bos_id
        modes = {True}
        if self.sampling.temperature > 0.0:
            modes.add(False)
        for greedy in sorted(modes, reverse=True):
            temp = 0.0 if greedy else max(self.sampling.temperature, 0.1)
            seqs = [RaggedSeq([bos] + [5] * 23, 0, t0, temperature=temp),
                    RaggedSeq([7], 8, t1, temperature=temp)]
            batches = [(seqs, 0, self.kv.num_slots + 1, 0, 0)]
            if self.spec_decode:
                # Speculative verify programs (ISSUE 9 + 13): ONE extra
                # compiled variant per (shape, mode) — score_width is
                # the static spec_max_draft+1 and, on a tree-configured
                # engine, s_max/copy_slots are the static branch-scaled
                # values, so acceptance drift, throttle flips AND
                # chain/tree composition changes are values in steady
                # state (chain engines: spec_s_max == num_slots+1 and
                # zero copy slots — the PR-9 program exactly).
                r = self.spec_max_draft + 1
                batches.append((
                    [RaggedSeq([7] * r, 8, t1, temperature=temp,
                               n_scores=r),
                     RaggedSeq([9], 4, t0, temperature=temp,
                               n_scores=1)], r,
                    self.spec_s_max, self.spec_copy_slots, 0))
                if self.spec_branch > 1:
                    # The propose variant (top-k root seeding) the
                    # DeviceDrafter issues under tree config — warmed
                    # whenever the tree SHAPE exists, independent of
                    # which drafter is currently installed, so a
                    # post-warmup set_spec_drafter('model'|'lora')
                    # hot-swap stays values-only (no mid-serve
                    # compile).
                    batches.append((
                        [RaggedSeq([7], 8, t1, temperature=temp),
                         RaggedSeq([9], 4, t0, temperature=temp)],
                        0, self.kv.num_slots + 1, 0, self.spec_branch))
            for warm_seqs, score_width, s_max, copy_slots, pw in batches:
                for shape in self.ragged_shapes:
                    batch = build_ragged_batch(
                        warm_seqs, t_budget=shape,
                        s_max=s_max,
                        pages_per_seq=self.kv.pages_per_seq,
                        scratch_page=self.kv.scratch_page(0),
                        pad_id=self.tokenizer.pad_id,
                        page_size=self.kv.page_size,
                        score_width=score_width,
                        copy_slots=copy_slots)
                    if pw:
                        batch["propose_width"] = pw
                    for _ in range(2):
                        nxt = self._ragged_dispatch(batch)
                        jax.tree_util.tree_map(np.asarray, nxt)
        self._release_warm_slots()

    def _release_warm_slots(self) -> None:
        """Release every __warmup_* slot so each warm batch re-acquires
        from empty per-replica counts — the acquire balancer then spreads
        the batch ceil(b/R) per replica, which is exactly what
        _warm_prompt_cap assumes. A leftover slot from a previous warm
        stage otherwise skews the free-pages tie-break (observed: both
        rows of the shared-prefix warm pinned to one replica, exhausting
        its page range)."""
        for i in range(self.kv.num_slots):
            self.kv.release(f"__warmup_{i}")

    def _warm_prompt_cap(self, b: int) -> int:
        """Longest prompt a b-row warm batch can pin without exhausting
        the paged pool (each row pins ceil((len + DECODE_SEGMENT) /
        page_size) pages; warm slots balance over replicas, so the
        tightest replica hosts ceil(b / data) rows). Contiguous layouts
        have no cap. Real serving past this length exhausts the pool at
        this batch size with the allocator's actionable RuntimeError —
        warming those buckets would crash warmup for shapes serving can
        never reach."""
        if self.kv_layout != "paged":
            return self.max_seq_len
        rows = -(-b // max(self.kv.data_size, 1))
        return ((self.kv.pages_per_replica() // max(rows, 1))
                * self.kv.page_size - DECODE_SEGMENT)

    def _lora_scope(self, lora):
        """The trace-time lora context every compiled program opens
        (engine/lora.lora_scope): inert when `lora` is None — lora-off
        engines and base-only dispatches trace exactly as before."""
        from .lora import lora_scope
        return lora_scope(lora, sink=self._lora_dispatches,
                          quant=self._lora_quant)

    def _lora_args(self, ids):
        """Device argument pair (stacked, adapter ids) for one
        dispatch, or None on lora-off engines. `ids` is per-ROW for
        batched programs and per-TOKEN for ragged dispatches; the
        module test counter records each dispatch's adapter mix for
        the conftest `lora` guard."""
        if self.lora is None:
            return None
        from . import lora as lora_mod
        ids_np = np.asarray(ids, np.int32)
        lora_mod.note_dispatch_ids(ids_np)
        return (self.lora.stacked, jnp.asarray(ids_np))

    def note_lora_tokens(self, n: int) -> None:
        """Account tokens served THROUGH a persona adapter (ISSUE 10
        telemetry satellite) — bumped by the serving paths where they
        already count tokens, so the counter moves with real work."""
        if n <= 0:
            return
        self._lora_tokens += n
        from ..utils import telemetry
        telemetry.inc("roundtable_lora_apply_tokens_total", n,
                      engine=self.cfg.name)

    def lora_describe(self) -> dict[str, Any]:
        """Multi-LoRA provenance (ISSUE 10): the resolved state, the
        adapter store's residency/accounting, per-leaf routing paths
        (grouped kernel vs XLA grouped BMM, with machine-readable
        decline reasons) — embedded in describe() the way
        int4_paths/ragged/spec_decode are."""
        from .lora import summarize_lora_paths
        info: dict[str, Any] = {
            "enabled": self.lora is not None,
            "reason": self.lora_reason,
            "apply_tokens": self._lora_tokens,
            "share_suppressed": self._lora_share_suppressed,
        }
        if self.lora is not None:
            info["store"] = self.lora.describe()
            info["lora_paths"] = summarize_lora_paths(
                self._lora_dispatches)
        return info

    def int4_path_report(self) -> Optional[dict]:
        """Which path each int4 einsum dispatch COMPILED to (ISSUE 3):
        {"pallas_w4a16": [...], "xla_dequant": [{..., "fallback_reason"}]}
        keyed by (spec, shapes). Populated at trace time — warmup or the
        first serve of each (batch, bucket) shape — so bench windows can
        attribute their numbers to the kernel, not a silent fallback.
        None on non-int4 engines."""
        if self.quant != "int4":
            return None
        return summarize_int4_paths(self._int4_dispatches)

    def revive_kv_if_dead(self) -> bool:
        """Reallocate KV buffers killed by a failed donated dispatch
        (the adapter's serial-retry rung calls this so 'batched → serial'
        recovery also holds for failures that surface AFTER donation
        consumed the cache). True iff fresh buffers were allocated."""
        revived = self.kv.revive_if_dead()
        if revived and self.kv_offload is not None:
            # Spilled records reference pages of the DEAD pools (kept
            # shared pages) — they cannot be restored into the fresh
            # ones. Host bytes go with them: revive semantics are "all
            # cached content lost", tiers included.
            self.kv_offload.drop_all()
        return revived

    def _degrade_paged_direct(self, reason: str) -> bool:
        """Route paged serving off the pool-direct Pallas kernels onto
        the layout-agnostic gather-view programs, permanently for this
        engine. The degradation rung for a kernel that compiled-checked
        clean but fails on chip (Mosaic compile failure, VMEM overrun):
        the request in flight re-dispatches through the gather view and
        every later call skips the kernels entirely. Returns False when
        already degraded / never pool-direct (caller re-raises)."""
        if not self.paged_direct:
            return False
        import warnings
        warnings.warn(
            f"paged pool-direct serving degraded to gather-view: {reason}",
            stacklevel=3)
        from ..utils import telemetry
        telemetry.inc("roundtable_degradations_total",
                      rung="gather_view")
        telemetry.recorder().record(
            "ladder_escalation", rung="gather_view",
            engine=self.cfg.name, error=reason[:200])
        self.paged_direct = False
        self.paged_degraded_reason = reason
        self._prefill_step_paged = self._prefill_step_paged_gather
        self._decode_loop_paged = self._decode_loop_paged_gather
        return True

    def _degrade_ragged(self, reason: str) -> bool:
        """Route ragged dispatches off the Pallas kernel onto the XLA
        fallback path, permanently for this engine — the same rung as
        _degrade_paged_direct for a kernel that compile-checked clean
        but fails on chip. Returns False when already on the fallback
        (caller re-raises)."""
        if self.ragged_path != "pallas_ragged":
            return False
        import warnings
        warnings.warn(
            f"ragged paged attention degraded to XLA fallback: {reason}",
            stacklevel=3)
        from ..utils import telemetry
        telemetry.inc("roundtable_degradations_total",
                      rung="ragged_xla")
        telemetry.recorder().record(
            "ladder_escalation", rung="ragged_xla",
            engine=self.cfg.name, error=reason[:200])
        self.ragged_path = "xla_ragged"
        self.ragged_fallback_reason = f"degraded:{reason[:120]}"
        return True

    def _ragged_dispatch(self, batch: dict):
        """One mixed prefill/decode dispatch over a flat token buffer
        (serving_loop.build_ragged_batch output) — the scheduler's
        chunk-interleaved admission seam. Runs the resolved ragged path
        (Pallas kernel, or the XLA fallback with its recorded reason)
        through the kernel-degradation rung, commits the donated pools
        under commit_guard, and records per-dispatch provenance into
        the engine's ragged sink (the int4_paths pattern). Returns the
        per-sequence next-token DEVICE array [S_max]; the caller
        host-reads it through its own watchdog seam."""
        from .pallas import attention as pattn

        score_width = int(batch.get("score_width", 0) or 0)
        propose_width = int(batch.get("propose_width", 0) or 0)
        # Draft-model dispatches (ISSUE 13) ride the SAME compiled
        # programs with a params VALUE override — the draft checkpoint
        # shares the engine's pytree shapes by construction.
        params = (batch["draft_params"]
                  if batch.get("draft_params") is not None
                  else self.params)
        copy_src = batch.get("copy_src")

        def run(path):
            if path == "pallas_ragged" and faults.ARMED:
                faults.maybe_inject("mosaic_compile")
            return self._ragged_step(
                params, self.kv.combined_pools(),
                jnp.asarray(batch["tables"]),
                jnp.asarray(batch["tokens"]),
                jnp.asarray(batch["positions"]),
                jnp.asarray(batch["token_pages"]),
                jnp.asarray(batch["token_offs"]),
                jnp.asarray(batch["token_seq"]),
                jnp.asarray(batch["seq_of_block"]),
                jnp.asarray(batch["block_qstart"]),
                jnp.asarray(batch["query_offsets"]),
                jnp.asarray(batch["kv_valid"]),
                jnp.asarray(batch["last_rows"]), self._next_key(),
                jnp.asarray(batch["temps"]),
                jnp.asarray(batch["top_ks"]),
                jnp.asarray(batch["top_ps"]),
                sample_rows=(jnp.asarray(batch["sample_rows"])
                             if score_width else None),
                greedy=batch["greedy"],
                attn_path=("kernel" if path == "pallas_ragged"
                           else "xla"),
                score_width=score_width,
                lora=self._lora_args(batch["token_adapter"])
                if self.lora is not None else None,
                copy_src=(jnp.asarray(copy_src)
                          if copy_src is not None else None),
                copy_dst=(jnp.asarray(batch["copy_dst"])
                          if copy_src is not None else None),
                propose_width=propose_width)

        from . import compile_watch
        with compile_watch.label(
                f"ragged[t={len(batch['tokens'])}]",
                engine=self.cfg.name):
            try:
                nxt, pools = run(self.ragged_path)
            except Exception as e:
                if not (faults.is_kernel_failure(e)
                        and self._degrade_ragged(str(e))):
                    raise
                nxt, pools = run(self.ragged_path)
        # A watchdog-abandoned dispatch completing late must NOT commit
        # onto pools the recovery path may have revived.
        with deadlines.commit_guard():
            self.kv.set_combined(pools)
        path = self.ragged_path
        self._note_kv_quant("ragged", kernel=path == "pallas_ragged")
        self._ragged_dispatches[path] = \
            self._ragged_dispatches.get(path, 0) + 1
        entry = {"path": path, "tokens": int(batch["n_tokens"]),
                 "seqs": int(batch["n_seqs"])}
        if score_width:
            entry["spec"] = True
        if batch.get("draft"):
            # Draft-model/LoRA proposal dispatch (ISSUE 13): provenance
            # distinguishes drafting cost from verify cost.
            entry["draft"] = True
        if path != "pallas_ragged":
            entry["fallback_reason"] = (self.ragged_fallback_reason
                                        or "unknown")
        self._ragged_recent.append(entry)
        pattn.note_ragged_dispatch(kernel=path == "pallas_ragged")
        return nxt

    def ragged_describe(self) -> dict[str, Any]:
        """Ragged-path provenance (ISSUE 8): the resolved path, why the
        seam or the kernel declined, the per-dispatch counts and the
        recent-dispatch ring — embedded in describe() and bench
        records the way int4_paths is."""
        return {
            "enabled": self.ragged_enabled,
            "path": self.ragged_path,
            "reason": self.ragged_reason,
            "fallback_reason": self.ragged_fallback_reason,
            "tokens_budget": self.ragged_tokens,
            "shapes": list(self.ragged_shapes),
            "defer_min_tokens": self.ragged_defer_min,
            "dispatches": dict(self._ragged_dispatches),
            "recent": list(self._ragged_recent)[-8:],
        }

    def _note_kv_quant(self, seam: str, kernel: bool) -> None:
        """Record one serving dispatch that CONSUMED quantized pages
        (ISSUE 11): engine-owned provenance sink + the module test
        counter the conftest `kv_quant` guard reads — the
        int4_paths/ragged pattern. `kernel` = the dequant ran inside a
        Pallas kernel (pool-direct / pallas_ragged); False = the XLA
        dequant fallback (gather view / ragged dense path) served, with
        the machine-readable reason recorded per entry."""
        if self.kv_quant_spec is None:
            return
        from . import kv_quant as kvq_mod
        kvq_mod.note_quant_dispatch(kernel)
        path = "kernel_dequant" if kernel else "xla_dequant"
        key = f"{seam}:{path}"
        self._kv_quant_dispatches[key] = \
            self._kv_quant_dispatches.get(key, 0) + 1
        entry: dict[str, Any] = {"seam": seam, "path": path}
        if not kernel:
            entry["fallback_reason"] = (
                self.kv_quant_fallback_reason
                or self.paged_degraded_reason
                or (self.ragged_fallback_reason if seam == "ragged"
                    else None)
                or "gather_view:pool-direct-off")
        self._kv_quant_recent.append(entry)

    def kv_quant_describe(self) -> dict[str, Any]:
        """Quantized-KV provenance (ISSUE 11): the resolved spec, why
        the feature is off (reason) or why the kernels declined
        in-kernel dequant (fallback_reason), the per-seam dispatch
        counts and the recent-dispatch ring — embedded in describe()
        and bench records the way int4_paths/ragged/spec are."""
        spec = self.kv_quant_spec
        info: dict[str, Any] = {
            "enabled": spec is not None,
            "dtype": spec.dtype_name if spec is not None else None,
            "bits": spec.bits if spec is not None else None,
            "reason": self.kv_quant_reason,
            "fallback_reason": self.kv_quant_fallback_reason,
            "dispatches": dict(self._kv_quant_dispatches),
            "recent": list(self._kv_quant_recent)[-8:],
        }
        if spec is not None and self.kv_layout == "paged":
            info["group"] = spec.effective_group(self.cfg.head_dim)
            info["bytes_saved"] = max(
                self.kv.hbm_bytes_logical() - self.kv.hbm_bytes(), 0)
        return info

    def note_spec_dispatch(self, drafted: int, accepted: int,
                           rows: int, tree_nodes: int = 0,
                           tree_rows: int = 0) -> None:
        """Record one verify dispatch's acceptance outcome (the
        scheduler computes it host-side after the read): engine-owned
        provenance sink + the registry counter/gauge series — the
        int4_paths/ragged pattern, ISSUE 9 telemetry satellite. The
        counters carry a `drafter` label (ISSUE 13) so an acceptance
        collapse attributes to the PROPOSER, not the throttle, and tree
        dispatches additionally count their packed nodes."""
        from . import spec_decode as _sd
        self._spec_drafted += drafted
        self._spec_accepted += accepted
        self._spec_dispatches += 1
        self._spec_tree_nodes += tree_nodes
        self._spec_tree_rows += tree_rows
        drafter = self.spec_drafter or "ngram"
        # Per-DRAFTER accumulators: the labeled acceptance-rate gauge
        # must report THIS drafter's rate, not the lifetime blend — a
        # collapsing post-hot-swap drafter hiding behind a healthy
        # predecessor's rate is exactly the misattribution the label
        # exists to prevent.
        d_tot = self._spec_by_drafter.setdefault(drafter, [0, 0])
        d_tot[0] += drafted
        d_tot[1] += accepted
        if self._spec_recent is not None:
            entry = {"drafted": drafted, "accepted": accepted,
                     "rows": rows, "path": self.ragged_path,
                     "drafter": drafter}
            if tree_rows:
                entry["tree_rows"] = tree_rows
                entry["tree_nodes"] = tree_nodes
            self._spec_recent.append(entry)
        _sd.note_spec_dispatch(drafted, accepted)
        from ..utils import telemetry
        name = self.cfg.name
        if drafted:
            telemetry.inc("roundtable_spec_drafted_tokens_total",
                          drafted, engine=name, drafter=drafter)
            telemetry.inc("roundtable_spec_rejected_tokens_total",
                          drafted - accepted, engine=name,
                          drafter=drafter)
        if accepted:
            telemetry.inc("roundtable_spec_accepted_tokens_total",
                          accepted, engine=name, drafter=drafter)
        if tree_nodes:
            telemetry.inc("roundtable_spec_tree_nodes_total",
                          tree_nodes, engine=name, drafter=drafter)
        if d_tot[0]:
            telemetry.set_gauge(
                "roundtable_spec_acceptance_rate",
                d_tot[1] / d_tot[0], engine=name, drafter=drafter)

    def note_spec_throttle(self) -> None:
        self._spec_throttled += 1

    def spec_describe(self) -> dict[str, Any]:
        """Speculative-decoding provenance (ISSUE 9 + 13): the resolved
        state, the ACTIVE drafter (+ why a configured one fell back),
        the tree shape, cumulative drafted/accepted counts and the
        recent per-dispatch ring — embedded in describe() and bench
        records the way int4_paths/ragged are."""
        rate = (self._spec_accepted / self._spec_drafted
                if self._spec_drafted else None)
        dd = self.spec_device_drafter
        return {
            "enabled": self.spec_decode,
            "reason": self.spec_reason,
            "drafter": self.spec_drafter,
            "drafter_reason": self.spec_drafter_reason,
            "tree": (dict(self.spec_tree) if self.spec_tree else None),
            "max_draft": self.spec_max_draft,
            "verify_dispatches": self._spec_dispatches,
            "drafted_tokens": self._spec_drafted,
            "accepted_tokens": self._spec_accepted,
            "rejected_tokens": self._spec_drafted - self._spec_accepted,
            "acceptance_rate": (round(rate, 3)
                                if rate is not None else None),
            "throttled_rows": self._spec_throttled,
            "by_drafter": {k: {"drafted": v[0], "accepted": v[1]}
                           for k, v in self._spec_by_drafter.items()},
            "tree_nodes": self._spec_tree_nodes,
            "tree_rows": self._spec_tree_rows,
            "draft_dispatches": (dd.draft_dispatches
                                 if dd is not None else 0),
            "recent": (list(self._spec_recent)[-8:]
                       if self._spec_recent is not None else []),
        }

    def chars_per_token(self) -> float:
        if self._chars_per_token is None:
            sample = ("The quick brown fox jumps over the lazy dog. "
                      "def main(args): return 0  # typical source text\n" * 4)
            n = len(self.tokenizer.encode(sample, add_bos=False))
            self._chars_per_token = max(len(sample) / max(n, 1), 0.25)
        return self._chars_per_token

    def _next_key(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def _prefill(self, slot_ids: list[int], token_lists: list[list[int]],
                 offsets: list[int], deadline: float = float("inf"),
                 tables: Optional[np.ndarray] = None,
                 budget=None, lora_ids=None) -> jax.Array:
        """Prefill dispatch: fresh long prompts go to the sequence-parallel
        ring program; everything else (short prompts, delta prefills on a
        reused prefix) takes the chunked bucketed path."""
        if (self._ring_prefill_fn is not None
                and all(o == 0 for o in offsets)
                and max(len(t) for t in token_lists) >= self.long_threshold):
            from .longcontext import SEQ_AXIS, pad_to_ring
            n_seq = self.seq_mesh.shape[SEQ_AXIS]
            tpad = pad_to_ring(max(len(t) for t in token_lists), n_seq,
                               self.kv.max_seq_len)
            # Paged writeback scatters whole pages, so the padded length
            # must also land on a page boundary — when the bucket doesn't
            # (tpad below page_size for near-threshold prompts, or the
            # cache-cap clamp), chunked prefill is the correct fallback,
            # not an error.
            if tpad and (self.kv_layout != "paged"
                         or tpad % self.kv.page_size == 0):
                # (lora engines never build a ring program — the
                # constructor declines the feature on seq-parallel
                # engines, so lora_ids cannot reach this branch.)
                return self._prefill_ring(slot_ids, token_lists, tpad,
                                          tables)
        return self._prefill_chunked(slot_ids, token_lists, offsets,
                                     deadline, tables, budget,
                                     lora_ids=lora_ids)

    def _prefill_ring(self, slot_ids: list[int],
                      token_lists: list[list[int]], tpad: int,
                      tables: Optional[np.ndarray] = None) -> jax.Array:
        """One sequence-parallel program prefills the whole batch; the
        full-sequence K/V is scattered into the slot cache (or through
        the page tables) so decode and later delta-prefills continue on
        the normal path. Under data>1 pool-direct the caller passes
        replica-padded token_lists/tables (slot_ids stay unpadded — the
        paged branch never indexes by slot), so B comes from the rows."""
        b = len(token_lists)
        tokens = np.full((b, tpad), self.tokenizer.pad_id, np.int32)
        for i, t in enumerate(token_lists):
            tokens[i, :len(t)] = t
        positions = np.broadcast_to(np.arange(tpad, dtype=np.int32),
                                    (b, tpad))
        lengths = np.asarray([len(t) for t in token_lists], np.int32)
        from . import compile_watch
        with compile_watch.label(f"ring_prefill[b={b},t={tpad}]",
                                 engine=self.cfg.name):
            logits, caches = self._ring_prefill_fn(
                self.params, jnp.asarray(tokens), jnp.asarray(positions),
                jnp.asarray(lengths))
        if self.kv_layout == "paged":
            self.kv.set_combined(self._scatter_kv_paged(
                self.kv.combined_pools(), jnp.asarray(tables), caches))
        else:
            slot_idx = jnp.asarray(slot_ids, jnp.int32)
            self.kv.layers = self._scatter_kv(self.kv.layers, slot_idx,
                                              caches)
        return logits

    def _prefill_chunked(self, slot_ids: list[int],
                         token_lists: list[list[int]], offsets: list[int],
                         deadline: float = float("inf"),
                         tables: Optional[np.ndarray] = None,
                         budget=None, lora_ids=None) -> jax.Array:
        """Chunked, bucketed prefill for B rows (serving_loop loop with
        this engine's step program). Returns last-token logits [B, V].

        `tables` is the caller-built page table for the whole call
        (capacity is ensured before any prefill dispatch; under data>1
        pool-direct it is already replica-grouped and padded)."""
        slot_idx = jnp.asarray(slot_ids, jnp.int32)
        if self.kv_layout == "paged":
            tables = jnp.asarray(tables)
        else:
            tables = None
        # Per-row adapter slots for the whole call (ISSUE 10): chunk
        # composition varies, the ids do not — one device arg serves
        # every chunk dispatch.
        lora_arg = None
        if self.lora is not None:
            lora_arg = self._lora_args(
                lora_ids if lora_ids is not None
                else [0] * len(token_lists))

        def paged_prefill(chunk, offs, lengths):
            if self.paged_direct and faults.ARMED:
                faults.maybe_inject("mosaic_compile")
            return self._prefill_step_paged(
                self.params, self.kv.combined_pools(), tables,
                jnp.asarray(chunk), jnp.asarray(offs, jnp.int32),
                jnp.asarray(lengths), lora=lora_arg)

        from . import compile_watch

        def dispatch(chunk, offs, lengths):
            # Compile-attribution window (ISSUE 6): a compile fired by
            # this chunk's program records under its (batch, bucket).
            with compile_watch.label(
                    f"prefill[b={chunk.shape[0]},bucket={chunk.shape[1]}]",
                    engine=self.cfg.name):
                if tables is not None:
                    try:
                        last, pools = paged_prefill(chunk, offs, lengths)
                    except Exception as e:
                        # Kernel-path failure on a pool-direct engine:
                        # degrade to the gather-view programs and
                        # re-dispatch this chunk (inputs are host arrays,
                        # pools were not consumed by a failed compile).
                        # Anything else goes to the retry policy / the
                        # adapter ladder.
                        if not (faults.is_kernel_failure(e)
                                and self._degrade_paged_direct(str(e))):
                            raise
                        last, pools = paged_prefill(chunk, offs, lengths)
                    # A watchdog-abandoned dispatch completing late must
                    # NOT commit onto pools the recovery path may have
                    # revived (the guard holds the ticket lock across
                    # the commit).
                    with deadlines.commit_guard():
                        self.kv.set_combined(pools)
                    self._note_kv_quant("prefill",
                                        kernel=self.paged_direct)
                else:
                    last, layers = self._prefill_step(
                        self.params, self.kv.layers, slot_idx,
                        jnp.asarray(chunk), jnp.asarray(offs, jnp.int32),
                        jnp.asarray(lengths), lora=lora_arg)
                    with deadlines.commit_guard():
                        self.kv.layers = layers
                return last

        return chunked_prefill(dispatch, token_lists, offsets,
                               self.kv.max_seq_len, self.tokenizer.pad_id,
                               deadline, retry=self.retry, budget=budget)

    def _apply_copies(self, copies: list[tuple[int, int, int, int]]) -> None:
        """Dispatch queued (src_slot, dst_slot, lo, hi) K/V span copies.

        The list is padded to num_slots rows so copy_spans compiles exactly
        ONE shape per engine (no mid-serve recompiles as batch compositions
        vary). Pad rows self-copy an empty span of a slot that is NOT a
        real destination — dst indices must stay distinct because scatter
        order among duplicate indices is unspecified."""
        if not copies:
            return
        width = self.kv.num_slots
        if len(copies) < width:
            used = {c[1] for c in copies}
            pad_dst = next(i for i in range(width) if i not in used)
            copies = copies + [(pad_dst, pad_dst, 0, 0)] * (width -
                                                            len(copies))
        self.kv.layers = self._copy_spans(
            self.kv.layers,
            jnp.asarray([c[0] for c in copies], jnp.int32),
            jnp.asarray([c[1] for c in copies], jnp.int32),
            jnp.asarray([c[2] for c in copies], jnp.int32),
            jnp.asarray([c[3] for c in copies], jnp.int32))

    def _share_prefixes(self, names: list[str], slot_ids: list[int],
                        all_tokens: list[list[int]], offsets: list[int],
                        deadline: float, budget=None,
                        extra_pinned: tuple[str, ...] = (),
                        defer_span=None, row_adapters=None,
                        row_lora_slots=None) -> tuple[list[int], int]:
        """Cross-knight shared-prefix reuse (SURVEY.md §7.3 hard part 2;
        reference prompt assembly src/orchestrator.ts:397-425 makes all
        knights share the giant context+transcript preamble, which the
        orchestrator here lays out as a common PREFIX).

        Two mechanisms, both copying position-aligned K/V between slots:
        (a) donor pass — a slot committed by an earlier call (another
            knight's turn) that shares a longer token prefix than this
            row's own history donates its K/V span;
        (b) leader pass — within one batch of fresh rows, the row with the
            most cache coverage prefills the batch-wide common span ONCE
            (ring-eligible when long) and the others copy it.

        Returns (updated offsets, leader-prefilled token count). Prefill
        FLOPs for the shared span are paid once instead of N times; HBM
        still holds per-slot copies (true page-level dedup is the paged-KV
        allocator's job). The pass structure itself lives in
        kvcache.share_prefixes (shared with the PP engine); this method
        provides the device mechanics: paged caches ALIAS the donor's
        whole pages (refcount, zero copy; partial boundary pages are
        device-copied), contiguous caches queue K/V span copies, and the
        leader span prefills via _prefill so a fresh long shared span
        takes the ring path on sequence-parallel engines."""
        from .kvcache import share_prefixes
        paged = self.kv_layout == "paged"
        pinned = tuple(names) + tuple(extra_pinned)
        copies: list[tuple[int, int, int, int]] = []

        def add_share(donor, i, lo, hi):
            if paged:
                self.kv.alias_span(donor.name, names[i], lo, hi, pinned)
            else:
                copies.append((donor.slot_id, slot_ids[i], lo, hi))

        def flush_shares():
            self._apply_copies(copies)
            copies.clear()

        def prefill_span(m, lo, hi):
            l_ids = ([row_lora_slots[m]] if row_lora_slots is not None
                     else None)
            if paged:
                self.kv.ensure_capacity(names[m], hi, write_from=lo,
                                        pinned=pinned)
                table = self.kv.table_for([names[m]])
                toks, offs = [all_tokens[m][lo:hi]], [lo]
                if self.paged_direct and self._paged_replicas > 1:
                    # Single-row leader prefill under data>1 pool-direct
                    # pads to one row per replica, like generate_batch.
                    p = ReplicaGroupPlan(
                        [self.kv.replica_of(names[m])],
                        self._paged_replicas)
                    table = p.pad_table(table, self.kv.scratch_page)
                    toks = p.scatter_list(toks, [self.tokenizer.pad_id])
                    offs = p.scatter_list(offs, 0)
                    if l_ids is not None:
                        l_ids = p.scatter_list(l_ids, 0)
                self._prefill([slot_ids[m]], toks, offs, deadline,
                              tables=table, budget=budget,
                              lora_ids=l_ids)
            else:
                self._prefill([slot_ids[m]], [all_tokens[m][lo:hi]],
                              [lo], deadline, budget=budget,
                              lora_ids=l_ids)

        # Adapter-identity donor filter (ISSUE 10): K/V baked under one
        # adapter is WRONG under another, so a donor only serves rows
        # whose adapter label matches — conservative (a filtered best
        # donor is dropped rather than re-searched; the prefill it
        # saves is small next to serving wrong bytes).
        donor_ok = None
        if row_adapters is not None:
            labels = self._slot_adapters

            def donor_ok(donor, i):
                return labels.get(donor.name) == row_adapters[i]

        return share_prefixes(
            self.kv, names, all_tokens, offsets,
            min_shared=MIN_SHARED_PREFIX, add_share=add_share,
            flush_shares=flush_shares, prefill_span=prefill_span,
            extra_pinned=extra_pinned, defer_span=defer_span,
            donor_ok=donor_ok)

    def _prepare_batch(self, turns, max_new_padded, deadline, pre_budget,
                       sampling_per_turn=None,
                       extra_pinned: tuple[str, ...] = (),
                       defer_prefill: bool = False,
                       adapters=None) -> dict:
        """The pre-decode phase, ONE definition shared by
        generate_batch and the session scheduler's admission
        (engine/scheduler.py) so the two can never drift on token
        parity: tokenize + tail-truncate → own-slot reuse_plan →
        cross-knight share_prefixes → paged capacity/COW + replica
        plan → chunked/ring prefill → first-token sample.

        `extra_pinned` names survive every eviction this phase can
        trigger (the scheduler pins its actively-decoding rows).
        Returns a dict with: names, slot_ids, all_tokens, offsets
        (post-share), plan, tables_np (plan-padded when plan is set),
        per_row, temps/top_ks/top_ps (plan-scattered), greedy,
        first_np (ORIGINAL row order), prefill_tokens, reused_tokens.

        `adapters` (ISSUE 10): per-turn LoRA adapter ids (None =
        base), already acquire()'d by the caller so residency cannot
        change under this call. Drives the per-row slot ids the
        compiled programs consume, the adapter-flip slot guard, the
        prefix-cache base-rows-only filter and the mixed-adapter
        share suppression.

        `defer_prefill` (ISSUE 8, the mixed-dispatch seam): stop after
        the host/aliasing work — everything above EXCEPT the chunked
        prefill and first-token sample. The per-row suffixes
        (all_tokens[i][offsets[i]:]) stay unprefilled; the scheduler
        feeds them through ragged mixed dispatches interleaved with the
        live decode segment instead of this blocking prologue
        (first_np is None in the returned dict). Paged, replica-free
        engines only — the flat buffer cannot mix pool replicas."""
        pinned = tuple(name for name, _ in turns) + tuple(extra_pinned)
        if self.kv_offload is not None:
            # A spilled session resumes HERE, before reuse_plan acquires
            # its slots: the restored tokens/pages make the LCP pass see
            # the full committed prefix, so the turn prefills only its
            # real delta — no re-prefill across the idle gap (ISSUE 7).
            self.kv_offload.restore_for([n for n, _ in turns], pinned)
        ad: Optional[list] = None
        lora_slots: Optional[list[int]] = None
        if self.lora is not None:
            ad = (list(adapters) if adapters is not None
                  else [None] * len(turns))
            if len(ad) != len(turns):
                raise ValueError(
                    f"adapters has {len(ad)} entries for "
                    f"{len(turns)} turns")
            lora_slots = []
            for a in ad:
                if a is None:
                    lora_slots.append(0)
                    continue
                slot = self.lora.slot_of(a)
                if slot is None:
                    raise RuntimeError(
                        f"lora adapter {a!r} is not resident — callers "
                        "acquire() adapters before _prepare_batch")
                lora_slots.append(slot)
            # Adapter-flip guard: a slot re-served under a DIFFERENT
            # adapter must never reuse K/V computed under the old one
            # (the bytes differ) — release forces a fresh prefill.
            # AFTER the offload restore above, or a flip across a
            # spill gap would release a non-resident name (no-op) and
            # the restore would resurrect the old adapter's bytes.
            # Base rows label None, so "never seen" needs a distinct
            # sentinel: base→persona flips must release too, while a
            # genuinely fresh slot must not.
            unset = object()
            for (name, _p), a in zip(turns, ad):
                prev = self._slot_adapters.get(name, unset)
                if prev is not unset and prev != a:
                    self.kv.release(name)
                self._slot_adapters[name] = a
            if len(self._slot_adapters) > 4 * self.kv.num_slots:
                # Keep labels whose K/V still EXISTS anywhere — pool
                # slots, this batch, or sessions parked in the offload
                # tier (their slots leave kv.slot_names() but their
                # bytes come back via restore_for, and a label dropped
                # here would make a later flip undetectable).
                from .kvcache import session_of
                live = set(self.kv.slot_names()) \
                    | {name for name, _ in turns}
                spilled = (set(self.kv_offload.spilled_sessions())
                           if self.kv_offload is not None else set())
                self._slot_adapters = {
                    n: a_ for n, a_ in self._slot_adapters.items()
                    if n in live or session_of(n) in spilled}
        slot_ids, offsets, all_tokens = [], [], []
        for name, prompt in turns:
            # A list of ids is accepted as a pre-tokenized prompt (warmup
            # uses this to hit exact bucket shapes).
            tokens = (list(prompt) if isinstance(prompt, list)
                      else self.tokenizer.encode(prompt))
            budget_tok = prompt_budget(self.max_seq_len, max_new_padded)
            if len(tokens) > budget_tok:
                # Keep the tail — the turn ask and latest transcript live
                # there (head truncation mirrors context budgeting
                # intent).
                tokens = (tokens[:1]
                          + tokens[len(tokens) - budget_tok + 1:])
            slot_id, reuse = self.kv.reuse_plan(name, tokens, pinned)
            slot_ids.append(slot_id)
            offsets.append(reuse)
            all_tokens.append(tokens)

        names = [name for name, _ in turns]
        # Cross-SESSION prefix cache (ISSUE 7): the content-addressed
        # index extends each row's reuse frontier past its own slot
        # history by aliasing pages committed by ANY earlier session —
        # the radix match is exact token equality, so this can never
        # serve wrong bytes. Warmup rows are excluded: they are crafted
        # to defeat sharing so the real prefill programs compile.
        prefix_reused = 0
        if self.prefix_cache is not None:
            if lora_slots is None or not any(lora_slots):
                prefix_reused = self.prefix_cache.attach_rows(
                    names, all_tokens, offsets, pinned)
            else:
                # Cross-session cache content is BASE-adapter K/V: a
                # persona row must neither consume it nor feed it
                # (commit gates the feed side symmetrically), so only
                # the base rows of this batch consult the index.
                base_idx = [i for i, sl in enumerate(lora_slots)
                            if sl == 0]
                if base_idx:
                    sub_off = [offsets[i] for i in base_idx]
                    prefix_reused = self.prefix_cache.attach_rows(
                        [names[i] for i in base_idx],
                        [all_tokens[i] for i in base_idx],
                        sub_off, pinned)
                    for j, i in enumerate(base_idx):
                        offsets[i] = sub_off[j]
        if defer_prefill:
            # Deferral pays off only for COLD prefills: after own-slot
            # reuse and the prefix-cache attach, a warm join's leftover
            # is often a few dozen tokens — one tiny bucket dispatch,
            # cheaper blocking than spread across segment-gated ragged
            # ticks. Resolve the mode HERE (callers read first_np is
            # None); the share passes below then defer (or not) with it.
            est = sum(len(t) - o for t, o in zip(all_tokens, offsets))
            if est < self.ragged_defer_min:
                defer_prefill = False
        # Cross-knight shared-prefix reuse raises offsets by copying (or,
        # paged, aliasing) other slots' K/V; only the per-knight deltas
        # remain to prefill. Under defer_prefill the LEADER pass defers
        # too (ISSUE 8 — it was the last blocking prologue dispatch):
        # the span is recorded here and the scheduler aliases the
        # laggards once the leader's ragged chunks have written it.
        share_plan: list[dict] = []
        defer_span = None
        if defer_prefill:
            def defer_span(m, lo, hi, followers):  # noqa: F811
                share_plan.append({"leader": m, "lo": lo, "hi": hi,
                                   "followers": followers})
        if lora_slots is not None and len(set(lora_slots)) > 1:
            # Mixed-adapter batch: no donor/leader span is valid
            # across rows with different adapters, so the share passes
            # are suppressed outright (lora_describe() counts it).
            self._lora_share_suppressed += 1
            leader_prefill = 0
        else:
            offsets, leader_prefill = self._share_prefixes(
                names, slot_ids, all_tokens, offsets, deadline,
                budget=pre_budget, extra_pinned=tuple(extra_pinned),
                defer_span=defer_span, row_adapters=ad,
                row_lora_slots=lora_slots)
        plan = None
        tables_np = None
        if self.kv_layout == "paged":
            # Allocate pages for the whole call (prompt + padded decode)
            # and copy-on-write any shared page in the write range, so
            # the jit'd programs below never allocate or touch aliased
            # pages. Deferred-share LAGGARDS skip this: their span pages
            # arrive by ALIAS once the leader's chunks write them —
            # allocating exclusive pages now would transiently demand
            # more pool than the prologue path ever did (the alias
            # would immediately replace them), and their tail capacity
            # is ensured at alias time (scheduler._apply_share_plans).
            deferred_followers = {i for p in share_plan
                                  for i, _lo in p["followers"]}
            for i, name in enumerate(names):
                if i in deferred_followers:
                    continue
                self.kv.ensure_capacity(
                    name, len(all_tokens[i]) + max_new_padded,
                    write_from=offsets[i], pinned=pinned)
            tables_np = self.kv.table_for(names)
            if self.paged_direct and self._paged_replicas > 1:
                # Pool-direct under data>1 (VERDICT r4 #4): shard_map
                # splits batch rows into contiguous per-data-index
                # blocks, so rows are permuted into the block of the
                # replica owning their slot's pages; pad rows point at
                # that replica's scratch page and start done.
                plan = ReplicaGroupPlan(
                    [self.kv.replica_of(n) for n in names],
                    self._paged_replicas)
                tables_np = plan.pad_table(tables_np,
                                           self.kv.scratch_page)
        suffixes = [t[o:] for t, o in zip(all_tokens, offsets)]
        prefill_tokens = leader_prefill + sum(len(s) for s in suffixes)
        # "reused" counts both own-slot LCP hits and copied donor spans.
        reused_tokens = sum(len(t) for t in all_tokens) - prefill_tokens
        if defer_prefill:
            if plan is not None:
                raise RuntimeError(
                    "defer_prefill requires a replica-free paged pool "
                    "(the ragged flat buffer cannot mix pool replicas)")
            per_row = sampling_per_turn or [self.sampling] * len(turns)
            if len(per_row) != len(turns):
                raise ValueError(
                    f"sampling_per_turn has {len(per_row)} entries for "
                    f"{len(turns)} turns")
            return {
                "names": names, "slot_ids": slot_ids,
                "all_tokens": all_tokens, "offsets": offsets,
                "plan": None, "tables_np": tables_np,
                "per_row": per_row, "temps": None, "top_ks": None,
                "top_ps": None,
                "greedy": all(p.temperature <= 0.0 for p in per_row),
                "first_np": None, "prefill_tokens": prefill_tokens,
                "reused_tokens": reused_tokens,
                "prefix_reused_tokens": prefix_reused,
                "share_plan": share_plan,
                "lora_slots": lora_slots, "adapters": ad,
            }
        p_offsets = offsets
        p_lora = lora_slots
        if plan is not None:
            suffixes = plan.scatter_list(suffixes,
                                         [self.tokenizer.pad_id])
            p_offsets = plan.scatter_list(offsets, 0)
            if p_lora is not None:
                p_lora = plan.scatter_list(p_lora, 0)
        last_logits = self._prefill(slot_ids, suffixes, p_offsets,
                                    deadline=deadline, tables=tables_np,
                                    budget=pre_budget, lora_ids=p_lora)
        # A scalar fetch, not block_until_ready: some PJRT transports
        # (the axon relay) return from block_until_ready before the
        # computation finishes, which would blame prefill time on decode
        # — and a blocking read, so it goes through the deadline seam (a
        # wedged prefill program freezes the host exactly here).
        host_sync(lambda: float(last_logits[0, 0]), pre_budget, "prefill")

        per_row = sampling_per_turn or [self.sampling] * len(turns)
        if len(per_row) != len(turns):
            raise ValueError(
                f"sampling_per_turn has {len(per_row)} entries for "
                f"{len(turns)} turns")
        temps, top_ks, top_ps = sampling_arrays(per_row)
        greedy = all(p.temperature <= 0.0 for p in per_row)
        if plan is not None:
            # The whole decode phase runs in padded replica-grouped row
            # order; callers read back through plan.pos.
            temps = plan.scatter_rows(temps, 1.0)
            top_ks = plan.scatter_rows(top_ks, 0)
            top_ps = plan.scatter_rows(top_ps, 1.0)
        if greedy:
            first = jnp.argmax(last_logits.astype(jnp.float32),
                               axis=-1).astype(jnp.int32)
        else:
            first = sample_token_batch(last_logits.astype(jnp.float32),
                                       self._next_key(), temps, top_ks,
                                       top_ps).astype(jnp.int32)
        if plan is not None and len(plan.pad_positions):
            # Pad rows open at eos so they are done from the first step.
            first = first.at[jnp.asarray(plan.pad_positions)].set(
                jnp.int32(self.tokenizer.eos_id))
        first_np = host_sync(lambda: np.asarray(first), pre_budget,
                             "prefill")
        if plan is not None:
            first_np = first_np[plan.pos]
        return {
            "names": names, "slot_ids": slot_ids,
            "all_tokens": all_tokens, "offsets": offsets, "plan": plan,
            "tables_np": tables_np, "per_row": per_row, "temps": temps,
            "top_ks": top_ks, "top_ps": top_ps, "greedy": greedy,
            "first_np": first_np, "prefill_tokens": prefill_tokens,
            "reused_tokens": reused_tokens,
            "prefix_reused_tokens": prefix_reused,
            "lora_slots": lora_slots, "adapters": ad,
        }

    def _decode_dispatch_paged(self, tables, last, valid, key, budget,
                               temps, top_ks, top_ps, row_budgets, done0,
                               *, greedy, max_new=DECODE_SEGMENT,
                               lora=None):
        """One paged decode-segment dispatch through the kernel-
        degradation rung (mosaic chaos point; pool-direct → gather-view
        on kernel failure, re-dispatching this segment), committing the
        donated pools under commit_guard. Shared by generate_batch's
        segment loop and the session scheduler."""
        def run():
            if self.paged_direct and faults.ARMED:
                faults.maybe_inject("mosaic_compile")
            return self._decode_loop_paged(
                self.params, self.kv.combined_pools(), tables, last,
                valid, key, budget, temps, top_ks, top_ps, row_budgets,
                done0, max_new=max_new, greedy=greedy, lora=lora)

        from . import compile_watch
        with compile_watch.label(
                f"decode[b={last.shape[0]},paged]", engine=self.cfg.name):
            try:
                out, steps, l2, v2, d2, pools = run()
            except Exception as e:
                if not (faults.is_kernel_failure(e)
                        and self._degrade_paged_direct(str(e))):
                    raise
                out, steps, l2, v2, d2, pools = run()
        # A watchdog-abandoned dispatch completing late must NOT commit
        # onto pools the recovery path may have revived.
        with deadlines.commit_guard():
            self.kv.set_combined(pools)
        self._note_kv_quant("decode", kernel=self.paged_direct)
        return out, steps, l2, v2, d2

    def _decode_dispatch_slots(self, slot_idx, last, valid, key, budget,
                               temps, top_ks, top_ps, row_budgets, done0,
                               *, greedy, max_new=DECODE_SEGMENT,
                               lora=None):
        """Contiguous-layout counterpart of _decode_dispatch_paged."""
        from . import compile_watch
        with compile_watch.label(f"decode[b={last.shape[0]}]",
                                 engine=self.cfg.name):
            out, steps, l2, v2, d2, layers = self._decode_loop(
                self.params, self.kv.layers, slot_idx, last, valid, key,
                budget, temps, top_ks, top_ps, row_budgets, done0,
                max_new=max_new, greedy=greedy, lora=lora)
        with deadlines.commit_guard():
            self.kv.layers = layers
        return out, steps, l2, v2, d2

    def generate(self, prompt: str, slot_name: str = "default",
                 max_new_tokens: Optional[int] = None,
                 timeout_s: float = 600.0, session: Optional[str] = None,
                 ) -> str:
        return self.generate_batch([(slot_name, prompt)],
                                   max_new_tokens=max_new_tokens,
                                   timeout_s=timeout_s, session=session)[0]

    def generate_batch(self, turns: list[tuple[str, str]],
                       max_new_tokens: Optional[int] = None,
                       timeout_s: float = 600.0,
                       sampling_per_turn: Optional[
                           list[SamplingParams]] = None,
                       budget=None,
                       session: Optional[str] = None,
                       adapters_per_turn: Optional[
                           list[Optional[str]]] = None) -> list[str]:
        return self.generate_batch_with_stats(
            turns, max_new_tokens=max_new_tokens, timeout_s=timeout_s,
            sampling_per_turn=sampling_per_turn, budget=budget,
            session=session, adapters_per_turn=adapters_per_turn)[0]

    def generate_batch_with_stats(
            self, turns: list[tuple[str, str]],
            max_new_tokens: Optional[int] = None,
            timeout_s: float = 600.0,
            sampling_per_turn: Optional[list[SamplingParams]] = None,
            budget=None,
            session: Optional[str] = None,
            adapters_per_turn: Optional[list[Optional[str]]] = None,
    ) -> tuple[list[str], GenStats]:
        """Serve N (slot_name, prompt) turns as one batched program pair.

        sampling_per_turn: per-row SamplingParams (heterogeneous knight
        personas); None = the engine default for every row. `budget`: a
        turn-rung deadlines.Budget threaded down from the adapter (the
        time ladder); None builds a local root from `timeout_s`, so
        direct engine callers get the same rung structure. `session`
        namespaces the slot names (kvcache.scoped_slot) so two concurrent
        discussions' same-named knights never collide in the LRU — the
        cross-session-contamination fix (ISSUE 4 satellite).
        `adapters_per_turn` (ISSUE 10): per-row LoRA persona adapter
        ids (None = base); a mixed list serves every persona in ONE
        batched program. Silently ignored on lora-off engines — the
        ROUNDTABLE_LORA=0 kill-switch must restore base serving
        byte-identically, not start raising. Returns
        (responses, this call's stats) — callers needing stats must take
        them from the return value, not from `last_stats`, which is a
        convenience field that concurrent callers may overwrite."""
        if session:
            from .kvcache import scoped_slot
            turns = [(scoped_slot(session, name), prompt)
                     for name, prompt in turns]
        # Admission gate (fleet.drain): one module-flag check per CALL,
        # nothing on the per-token path. In-flight generations (already
        # past this check, possibly waiting on the serve lock) complete.
        deadlines.check_admission()
        with self._serve_lock:
            # Adapter residency refs for the duration of the call —
            # under the serve lock, so a swap can never race a
            # concurrent dispatch's argument capture (ISSUE 10).
            acquired = None
            if self.lora is not None and adapters_per_turn:
                self.lora.validate(adapters_per_turn, len(turns))
                # acquire() is exception-atomic; `acquired` is set
                # only AFTER it took the refs, so the finally below
                # releases exactly what this call holds.
                self.lora.acquire(adapters_per_turn)
                acquired = list(adapters_per_turn)
            elif self.lora is None:
                adapters_per_turn = None
            try:
                # The "turn" rung of the span tree (ISSUE 5) — same
                # node the turn Budget bounds; session/engine attrs
                # make concurrent discussions separable in one trace.
                from ..utils import telemetry
                if telemetry.ACTIVE:
                    with telemetry.span("turn", engine=self.cfg.name,
                                        rows=len(turns),
                                        session=session or "",
                                        knights=[n for n, _ in turns]):
                        return self._generate_batch_locked(
                            turns, max_new_tokens, timeout_s,
                            sampling_per_turn, budget,
                            adapters_per_turn)
                return self._generate_batch_locked(
                    turns, max_new_tokens, timeout_s, sampling_per_turn,
                    budget, adapters_per_turn)
            finally:
                if acquired:
                    self.lora.release(acquired)

    def _generate_batch_locked(self, turns, max_new_tokens, timeout_s,
                               sampling_per_turn=None, budget=None,
                               adapters_per_turn=None):
        if faults.ARMED and len(turns) > 1:
            # Chaos point for the batched-round degradation ladder: a
            # "corrupted KV slot" fails the fan-out before any slot
            # bookkeeping mutates; the adapter invalidates the batch's
            # slots and retries the knights serially (tpu_llm.py).
            faults.maybe_inject("kv_corrupt")
        stats = GenStats()
        # The turn's budget node: adapters thread one down (round →
        # turn); direct callers get a local root bounded by timeout_s.
        # The float deadline stays the single source for the legacy
        # time checks — always <= every ancestor's deadline. (`budget`
        # is re-bound below for the prompt-token budget — the Budget
        # node keeps its own name.)
        turn_budget = budget if budget is not None \
            else deadlines.Budget.root(timeout_s, rung="turn")
        deadline = min(turn_budget.deadline, time.monotonic() + timeout_s)
        pre_budget = turn_budget.child("prefill")
        # One clamp definition for engines + scheduler (serving_loop
        # .clamp_max_new): drift here desynchronizes admission page
        # estimates, row budgets, and retirement output caps.
        from .serving_loop import clamp_max_new
        max_new, max_new_padded = clamp_max_new(
            max_new_tokens or self.sampling.max_new_tokens,
            self.max_seq_len)

        from ..utils import telemetry
        t0 = time.monotonic()
        with telemetry.span("prefill", engine=self.cfg.name) as _psp:
            prep = self._prepare_batch(turns, max_new_padded, deadline,
                                       pre_budget, sampling_per_turn,
                                       adapters=adapters_per_turn)
            _psp.set_attr("prefill_tokens", prep["prefill_tokens"])
            _psp.set_attr("reused_tokens", prep["reused_tokens"])
        stats.prefill_tokens = prep["prefill_tokens"]
        stats.reused_tokens = prep["reused_tokens"]
        stats.prefix_reused_tokens = prep["prefix_reused_tokens"]
        stats.prefill_seconds = time.monotonic() - t0

        plan = prep["plan"]
        all_tokens = prep["all_tokens"]
        first_np = prep["first_np"]
        per_row = prep["per_row"]
        temps, top_ks, top_ps = (prep["temps"], prep["top_ks"],
                                 prep["top_ps"])
        greedy = prep["greedy"]
        # first_np comes back in ORIGINAL row order; the decode phase
        # runs in plan order (padded replica-grouped rows) when a plan
        # exists, so scatter it back — pad rows open at eos (done).
        if plan is not None:
            first = plan.scatter_rows(
                first_np.astype(np.int32), np.int32(self.tokenizer.eos_id))
        else:
            first = jnp.asarray(first_np, jnp.int32)
        cur_valid = jnp.asarray([len(t) for t in all_tokens], jnp.int32)
        if plan is not None:
            cur_valid = plan.scatter_rows(cur_valid, 1)

        t1 = time.monotonic()
        # Decode rung budget is derived NOW, not at call start, so a
        # configured "decode" cap times the decode phase alone.
        dec_budget = turn_budget.child("decode")
        slot_idx = jnp.asarray(prep["slot_ids"], jnp.int32)
        tables = (jnp.asarray(prep["tables_np"])
                  if self.kv_layout == "paged" else None)
        # Per-row decode budgets (knight_sampling max_new_tokens): a row
        # whose own budget is smaller than the batch's stops early (goes
        # done, emits eos) while the rest keep decoding
        # (serving_loop.row_budget_fn — one definition for both engines).
        from .serving_loop import row_budget_fn
        row_remaining = row_budget_fn(per_row, sampling_per_turn, max_new)
        lora_slots = prep.get("lora_slots")
        dec_lora = None
        if self.lora is not None:
            dec_ids = list(lora_slots if lora_slots is not None
                           else [0] * len(all_tokens))
            if plan is not None:
                dec_ids = plan.scatter_list(dec_ids, 0)
            dec_lora = self._lora_args(dec_ids)

        def decode_dispatch(cur_last, cur_valid, budget, done0):
            row_budgets = row_remaining(budget)
            if plan is not None:
                row_budgets = plan.scatter_rows(row_budgets, 0)
            if tables is not None:
                return self._decode_dispatch_paged(
                    tables, cur_last, cur_valid, self._next_key(),
                    budget, temps, top_ks, top_ps, row_budgets, done0,
                    greedy=greedy, lora=dec_lora)
            return self._decode_dispatch_slots(
                slot_idx, cur_last, cur_valid, self._next_key(),
                budget, temps, top_ks, top_ps, row_budgets, done0,
                greedy=greedy, lora=dec_lora)

        with telemetry.span("decode", engine=self.cfg.name,
                            max_new=max_new):
            out_np = decode_segments(decode_dispatch, first, cur_valid,
                                     self.tokenizer.eos_id, max_new,
                                     deadline, timeout_s, retry=self.retry,
                                     budget=dec_budget)
        stats.decode_seconds = time.monotonic() - t1
        if plan is not None:
            out_np = out_np[plan.pos]

        commit = self.kv.commit
        ad = prep.get("adapters")
        if ad is not None and any(a is not None for a in ad):
            # Persona rows must not FEED the cross-session prefix
            # cache: their pages hold adapter-tinted K/V no other
            # adapter (or the base) may alias (ISSUE 10).
            idx_of = {name: (a is None)
                      for (name, _p), a in zip(turns, ad)}

            def commit(name, toks, _kv=self.kv, _idx=idx_of):
                _kv.commit(name, toks, index=_idx.get(name, True))

        results = finalize_outputs(
            turns, first_np, out_np, all_tokens, max_new,
            self.tokenizer.eos_id, commit, self.tokenizer.decode,
            stats)
        if self.lora is not None and lora_slots and any(lora_slots):
            from .serving_loop import eos_trim
            n = 0
            for i, sl in enumerate(lora_slots):
                if not sl:
                    continue
                ids_row = eos_trim(
                    [int(first_np[i])] + [int(x) for x in out_np[i]],
                    self.tokenizer.eos_id, max_new)
                n += len(ids_row) + len(all_tokens[i]) \
                    - prep["offsets"][i]
            self.note_lora_tokens(n)
        stats.int4_paths = self.int4_path_report()
        # Publish this call into the unified registry (ISSUE 5): token/
        # throughput counters plus the int4 path-provenance view — the
        # engine-stats store metrics.json/bench already read stays the
        # return value; the registry is the shared spine.
        from . import trace_hooks
        trace_hooks.publish_gen_stats(stats, self.cfg.name,
                                      perf=self.perf)
        trace_hooks.publish_int4_paths(stats.int4_paths, self.cfg.name)
        # Memory ledger at the call boundary (ISSUE 6): slot/page
        # occupancy, fragmentation, HBM — event-rate host math only.
        trace_hooks.publish_memory_ledger(self)
        self.last_stats = stats
        return results, stats

    # --- introspection ---

    def describe(self) -> dict[str, Any]:
        info = {
            "model": self.cfg.name,
            "params": self.num_params,
            "max_seq_len": self.max_seq_len,
            "mesh": dict(self.mesh.shape),
            "num_slots": self.kv.num_slots,
            "kv_layout": self.kv_layout,
            "quant": (self.quant + " (auto-degraded)"
                      if getattr(self, "quant_auto_degraded", False)
                      else self.quant),
            "devices": [str(d) for d in self.mesh.devices.flatten()],
        }
        if self.quant == "int4":
            info["int4_paths"] = self.int4_path_report()
        if self.kv_layout == "paged":
            info["page_size"] = self.kv.page_size
            info["num_pages"] = self.kv.num_pages
            info["kv_hbm_bytes"] = self.kv.hbm_bytes()
            info["paged_decode"] = ("pool-direct" if self.paged_direct
                                    else "gather-view")
            # ISSUE 7: the cross-session sharing subsystems' state.
            if self.prefix_cache is not None:
                info["prefix_cache"] = self.prefix_cache.describe()
            if self.kv_offload is not None:
                info["kv_offload"] = self.kv_offload.describe()
            # ISSUE 8: ragged mixed-dispatch path provenance.
            info["ragged"] = self.ragged_describe()
            # ISSUE 9: speculative-decoding provenance (drafter,
            # per-dispatch drafted/accepted, throttle state).
            info["spec_decode"] = self.spec_describe()
            # ISSUE 11: quantized-KV-page provenance (spec, per-seam
            # dispatch paths, kernel-decline reason, bytes saved).
            info["kv_quant"] = self.kv_quant_describe()
        # ISSUE 10: multi-LoRA persona provenance — the resolved
        # state, adapter store residency, per-leaf routing paths.
        info["lora"] = self.lora_describe()
        # Continuous-batching scheduler provenance (ISSUE 4): attached by
        # engine/scheduler.SessionScheduler — admit/queue/refuse counts,
        # queue depth, per-segment batch occupancy.
        sched = getattr(self, "_scheduler", None)
        if sched is not None:
            info["scheduler"] = sched.describe()
        # ISSUE 5: this engine's slice of the unified registry + flight
        # recorder state — describe() is a VIEW of the one store, not a
        # fifth parallel truth.
        from . import trace_hooks
        info["telemetry"] = trace_hooks.engine_telemetry_view(
            self.cfg.name)
        # ISSUE 6: live perf attribution — roofline ceilings, the
        # compile-cache decision, and the compile observatory's state.
        from . import compile_watch, get_compile_cache_decision
        info["perf"] = self.perf.describe()
        info["compile_cache"] = get_compile_cache_decision()
        info["compile_observatory"] = compile_watch.summary()
        return info


# ---------------------------------------------------------------------------
# static-analysis program registration (ISSUE 15)
# ---------------------------------------------------------------------------

from ..analysis.jaxpr_audit import (ProgramSpec, Variant,  # noqa: E402
                                    analysis_register)


def _audit_sds(x):
    """Pytree of ShapeDtypeStructs — the device-free trace argument:
    make_jaxpr abstracts by aval, so no buffer is ever materialized."""
    return jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), x)


@analysis_register("engine_core")
def _analysis_engine_programs(engine) -> list:
    """Prefill + decode serving programs for the jaxpr audit
    (`roundtable lint --jaxpr`).

    The variant grid replays runtime drift the way SERVING computes its
    shapes: prefill batches are per-(batch, bucket) programs; decode
    occupancies map through `pow2_bucket` onto the warmed batch grid —
    so two occupancies in one bucket MUST trace to one jaxpr, and a
    static argument leaking occupancy shows up as an extra distinct
    jaxpr under that label (the RECOMPILE_STRICT invariant, proven
    without a device). Argument construction mirrors
    `_prefill`/`_decode_dispatch_*`; drift between the twins fails the
    audit's trace step loudly rather than silently auditing nothing.
    """
    if not isinstance(engine, InferenceEngine):
        return []
    from .serving_loop import pow2_bucket
    paged = engine.kv_layout == "paged"
    params = _audit_sds(engine.params)
    pools = _audit_sds(engine.kv.combined_pools()) if paged else None
    layers = None if paged else _audit_sds(engine.kv.layers)
    key = jax.random.PRNGKey(0)
    num_slots = engine.kv.num_slots
    pps = engine.kv.pages_per_seq if paged else 0

    def ints(*shape):
        return jax.ShapeDtypeStruct(shape, jnp.int32)

    def floats(*shape):
        return jax.ShapeDtypeStruct(shape, jnp.float32)

    def prefill_variant(b: int, bucket: int) -> Variant:
        def thunk():
            tokens = ints(b, bucket)
            if paged:
                return jax.make_jaxpr(engine._prefill_step_paged)(
                    params, pools, ints(b, pps), tokens, ints(b),
                    ints(b))
            return jax.make_jaxpr(engine._prefill_step)(
                params, layers, ints(b), tokens, ints(b), ints(b))
        return Variant(label=f"b{b}x{bucket}", thunk=thunk,
                       situation=f"batch {b}, bucket {bucket}")

    def decode_variant(occ: int) -> Variant:
        b = pow2_bucket(occ)

        def thunk():
            budget = jnp.int32(DECODE_SEGMENT)
            # first_token, start_valid, key, budget, temps, top_ks,
            # top_ps, row_budgets, done0 — _decode_dispatch_*'s order.
            args = (ints(b), ints(b), key, budget, floats(b), ints(b),
                    floats(b), ints(b),
                    jax.ShapeDtypeStruct((b,), jnp.bool_))
            if paged:
                fn = engine._decode_loop_paged
                return jax.make_jaxpr(
                    lambda p, pl, t, *a: fn(
                        p, pl, t, *a, max_new=DECODE_SEGMENT,
                        greedy=True))(params, pools, ints(b, pps),
                                      *args)
            fn = engine._decode_loop
            return jax.make_jaxpr(
                lambda p, cl, s, *a: fn(
                    p, cl, s, *a, max_new=DECODE_SEGMENT,
                    greedy=True))(params, layers, ints(b), *args)
        return Variant(label=f"b{b}", thunk=thunk,
                       situation=f"occupancy {occ}")

    bucket = PREFILL_BUCKETS[0]
    prefill = ProgramSpec(
        name=f"prefill[{'paged' if paged else 'slots'}]",
        phase="prefill",
        variants=[prefill_variant(b, bucket)
                  for b in (1, 2) if b <= num_slots])
    decode = ProgramSpec(
        name=f"decode[{'paged' if paged else 'slots'}]",
        phase="decode",
        variants=[decode_variant(occ)
                  for occ in (1, 2, 3, 4) if occ <= num_slots])
    return [prefill, decode]
