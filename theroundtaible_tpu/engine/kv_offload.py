"""Host-RAM KV offload tier — spill idle sessions' pages, restore
byte-identical, never re-prefill.

A consensus round can sit for minutes while humans type, and PR 4's
scheduler answers HBM pressure by either queueing admissions or letting
the page allocator EVICT idle slots — destroying exactly the caches that
make the next turn cheap. This tier (ISSUE 7 tentpole, the multi-tier KV
store RTP-LLM runs in production — PAPERS.md) gives idle sessions a
third state: their pages move to host RAM, their slot records leave the
pool, and the session's next submit brings them back — `device_put` into
freshly acquired pages, byte-identical — so `reuse_plan` sees the full
committed prefix and the turn prefills only its real delta, exactly as
if the session had never left.

Page-identity bookkeeping is SESSION-level: a span aliased by several of
the session's own knights (the intra-session donor/leader sharing of
PR 4, or prefix-cache attaches) spills its bytes ONCE and restores into
ONE fresh page that every sibling re-maps — the aliasing survives the
round trip instead of inflating into per-knight copies. Only pages some
holder OUTSIDE the session (another session's slot, an earlier spill's
resident hold) still references stay in HBM under a per-mapping tier
reference — they cost no extra memory and must stay byte-stable anyway;
pages shared only with the prefix-cache index spill too (the index copy
stays independently reclaimable under pressure, and restore never
depends on it surviving).

Compile discipline: the fetch/write programs run in fixed WIDTH-page
chunks (short chunks padded with the scratch page — never read, any
bytes), so each compiles exactly ONE shape; `engine.warmup()` warms both,
and under ROUNDTABLE_RECOMPILE_STRICT=1 the restore path compiles
nothing in steady state (the ISSUE 7 acceptance bar).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import telemetry
from .kvcache import session_of

# Pages moved per fetch/write dispatch. Spills are rare (idle-session
# boundaries, not the serving hot path); 8 keeps padding waste small and
# matches paging.make_padded_copier's chunking rationale.
WIDTH = 8


def offload_enabled(flag: Optional[bool]) -> bool:
    """Config value wins, then ROUNDTABLE_KV_OFFLOAD=0/1, then ON
    (prefix_cache.env_flag — one parsing rule for both kill-switches)."""
    from .prefix_cache import env_flag
    return env_flag(flag, "ROUNDTABLE_KV_OFFLOAD")


@dataclass
class SpilledSlot:
    """One slot's layout while its session is spilled. `entries[j]` is
    ("kept", page_id) for a page left resident under a tier reference,
    or ("host", row) indexing the session record's host store. Host
    entries are keyed by STORE ROW, never by the old pool page id — the
    old page was freed, its id can be reallocated to unrelated content,
    and an id-keyed dedup across spill calls would silently serve a
    stale spill's bytes into a reborn page's slot."""

    tokens: list[int]
    replica: int
    entries: list[tuple[str, int]] = field(default_factory=list)


@dataclass
class SpilledSession:
    """One session's spill record: per-slot layouts plus the host page
    store (rows deduped per spill call, while the pages were alive)."""

    slots: dict[str, SpilledSlot] = field(default_factory=dict)
    # Per layer: (k, v) stacked [n_rows, page, K, D] numpy.
    host: list[tuple[np.ndarray, np.ndarray]] = field(
        default_factory=list)
    replicas: list[int] = field(default_factory=list)  # per store row

    def n_rows(self) -> int:
        return len(self.replicas)

    def fully_host_resident(self) -> bool:
        """No "kept" pool pages — the ONE definition of "this record
        can cross engines": restorable_sessions() reports by it and
        adopt() filters by it (a record still referencing pool pages
        would alias unrelated content on a pool that never held
        them)."""
        return not any(kind == "kept"
                       for srec in self.slots.values()
                       for kind, _p in srec.entries)

    def host_bytes(self) -> int:
        return sum(k.nbytes + v.nbytes for k, v in self.host)

    def append_rows(self, fetched, replicas: list[int]) -> None:
        if self.host:
            self.host = [
                (np.concatenate([k0, k1]), np.concatenate([v0, v1]))
                for (k0, v0), (k1, v1) in zip(self.host, fetched)]
        else:
            self.host = fetched
        self.replicas.extend(replicas)


class HostOffloadTier:
    """Spill/restore for one paged InferenceEngine's sessions."""

    def __init__(self, engine):
        self.engine = engine
        if getattr(engine, "kv_layout", None) != "paged":
            raise TypeError("HostOffloadTier requires a paged engine")
        self._spilled: dict[str, SpilledSession] = {}
        self.spills = 0
        self.restores = 0
        self._name = getattr(engine.cfg, "name", "engine")

        from jax.sharding import NamedSharding, PartitionSpec
        rep = NamedSharding(engine.mesh, PartitionSpec())

        @jax.jit
        def fetch_pages(pools, ids):
            # Replicated outputs so the host read works on any mesh
            # (the engines' host_read contract).
            out = []
            for k, v in pools:
                out.append(
                    (jax.lax.with_sharding_constraint(k[ids], rep),
                     jax.lax.with_sharding_constraint(v[ids], rep)))
            return out

        @partial(jax.jit, donate_argnums=(0,))
        def write_pages(pools, ids, data):
            # Pad rows target the scratch page with zero bytes — never
            # read, and duplicate scratch indices only ever race other
            # pads (real ids are distinct fresh allocations).
            out = []
            for (k, v), (dk, dv) in zip(pools, data):
                out.append((k.at[ids].set(dk.astype(k.dtype)),
                            v.at[ids].set(dv.astype(v.dtype))))
            return out

        self._fetch_pages = fetch_pages
        self._write_pages = write_pages

    # --- introspection ---

    def spilled_sessions(self) -> list[str]:
        return list(self._spilled)

    def restorable_sessions(self) -> list[str]:
        """Sessions whose spill records are FULLY host-resident (no
        "kept" pool pages) — exactly the set adopt() will accept onto
        a fresh engine's tier. The supervisor uses this when an
        evacuation dies mid-cycle: these sessions survive the pool
        even though the evacuation itself failed."""
        return [s for s, rec in self._spilled.items()
                if rec.fully_host_resident()]

    def has(self, session: str) -> bool:
        return session in self._spilled

    def host_bytes(self) -> int:
        return sum(rec.host_bytes() for rec in self._spilled.values())

    def describe(self) -> dict:
        return {
            "spilled_sessions": len(self._spilled),
            "spilled_slots": sum(len(rec.slots)
                                 for rec in self._spilled.values()),
            "host_bytes": self.host_bytes(),
            "spills": self.spills,
            "restores": self.restores,
        }

    def _publish(self) -> None:
        telemetry.set_gauge("roundtable_kv_spilled_sessions",
                            len(self._spilled), engine=self._name)
        telemetry.set_gauge("roundtable_kv_host_bytes",
                            self.host_bytes(), engine=self._name)

    # --- device chunk helpers (fixed WIDTH shapes) ---

    def _fetch(self, page_ids: list[int],
               replica: int) -> list[tuple[np.ndarray, np.ndarray]]:
        kv = self.engine.kv
        scratch = kv.scratch_page(replica)
        # Combined pools (ISSUE 11): quantized pools spill their scale
        # arrays as extra "layers" in the same host record — int8
        # payload + scales is the whole state, so restore is exactly
        # lossless and spill bandwidth drops with the payload width.
        per_layer: list[list[tuple[np.ndarray, np.ndarray]]] = [
            [] for _ in kv.combined_pools()]
        from . import compile_watch
        for start in range(0, len(page_ids), WIDTH):
            ids = page_ids[start:start + WIDTH]
            n = len(ids)
            ids = ids + [scratch] * (WIDTH - n)
            with compile_watch.label("kv_spill[fetch]",
                                     engine=self._name):
                out = self._fetch_pages(kv.combined_pools(),
                                        jnp.asarray(ids, jnp.int32))
            for li, (k, v) in enumerate(out):
                per_layer[li].append((np.asarray(k)[:n],
                                      np.asarray(v)[:n]))
        return [(np.concatenate([c[0] for c in chunks])
                 if chunks else np.zeros(0),
                 np.concatenate([c[1] for c in chunks])
                 if chunks else np.zeros(0))
                for chunks in per_layer]

    def _write(self, page_ids: list[int],
               host: list[tuple[np.ndarray, np.ndarray]],
               rows: list[int], replica: int) -> None:
        """Write `host` store rows `rows` into pool pages `page_ids`."""
        kv = self.engine.kv
        scratch = kv.scratch_page(replica)
        from . import compile_watch, deadlines
        for start in range(0, len(page_ids), WIDTH):
            ids = page_ids[start:start + WIDTH]
            sel = rows[start:start + WIDTH]
            n = len(ids)
            ids = ids + [scratch] * (WIDTH - n)
            data = []
            for k_all, v_all in host:
                k = k_all[sel]
                v = v_all[sel]
                if n < WIDTH:
                    pad = (WIDTH - n,) + k.shape[1:]
                    k = np.concatenate([k, np.zeros(pad, k.dtype)])
                    v = np.concatenate([v, np.zeros(pad, v.dtype)])
                data.append((jnp.asarray(k), jnp.asarray(v)))
            with compile_watch.label("kv_restore[write]",
                                     engine=self._name):
                pools = self._write_pages(
                    kv.combined_pools(), jnp.asarray(ids, jnp.int32),
                    data)
            with deadlines.commit_guard():
                kv.set_combined(pools)

    def warm(self) -> None:
        """Compile-and-stabilize the fetch/write programs (ONE shape
        each) so a first spill/restore in steady state compiles nothing
        — run twice for the donated-buffer layout fixpoint, exactly like
        engine.warmup's programs."""
        kv = self.engine.kv
        scratch = kv.scratch_page(0)
        for _ in range(2):
            host = self._fetch([scratch], 0)
            self._write([scratch], host, [0], 0)

    # --- spill ---

    def spill_session(self, session: str) -> int:
        """Move every slot of `session` out of the pool. Keep-resident
        (under one tier reference per mapping) ONLY pages some holder
        OUTSIDE the session still references — another session's slot,
        or an earlier spill's resident hold; everything else, including
        spans aliased between the session's own knights and pages shared
        only with the prefix-cache index, spills its bytes ONCE per
        unique page. Returns the number of slots spilled. The caller
        owns engine serialization (serve lock / scheduler thread)."""
        kv = self.engine.kv
        cache = getattr(kv, "prefix_cache", None)
        names = [n for n in kv.slot_names() if session_of(n) == session]
        # Pass 1 (no releases yet, so refcounts are stable): how many of
        # THIS session's own slots map each page — sibling aliases must
        # not count as external holders, or intra-session shared spans
        # (exactly the pages donor/leader sharing deduplicated) would
        # all stay resident and the spill would free almost nothing.
        own_maps: dict[int, int] = {}
        states = {}
        for name in names:
            state = kv._slots.get(name)
            if state is None:
                continue
            states[name] = state
            for p in state.pages:
                own_maps[p] = own_maps.get(p, 0) + 1
        rec = self._spilled.get(session) or SpilledSession()
        tier_refs: dict[int, int] = {}  # refs THIS call took, per page
        # Dedup WITHIN this call only (page -> store row): the pages are
        # alive and distinct for the duration, which is exactly the
        # window where id-based identity is sound.
        call_rows: dict[int, int] = {}
        spill_ids: list[int] = []
        empty: list[str] = []
        count = 0
        for name, state in states.items():
            if not state.tokens or not state.pages:
                # Release in pass 2 with the rest: dropping a sibling's
                # mappings mid-pass would skew the external-holder math
                # for pages it shares with later siblings.
                empty.append(name)
                continue
            entries: list[tuple[str, int]] = []
            for p in state.pages:
                external = (kv.refcount(p) - own_maps[p]
                            - (1 if cache is not None
                               and cache.holds_page(p) else 0)
                            - tier_refs.get(p, 0))
                if external >= 1:
                    kv.ref(p)          # per-mapping resident hold
                    tier_refs[p] = tier_refs.get(p, 0) + 1
                    entries.append(("kept", p))
                else:
                    row = call_rows.get(p)
                    if row is None:
                        row = rec.n_rows() + len(spill_ids)
                        call_rows[p] = row
                        spill_ids.append(p)
                    entries.append(("host", row))
            old = rec.slots.get(name)
            if old is not None:
                # Re-spill over a stale record (slot repopulated while
                # spilled): drop the superseded entries' resident holds
                # — the old host rows stay (row indices must remain
                # stable) and free with the record at restore.
                for kind, p in old.entries:
                    if kind == "kept":
                        kv.unref(p)
            rec.slots[name] = SpilledSlot(
                tokens=list(state.tokens), replica=state.replica,
                entries=entries)
            count += 1
        if spill_ids:
            # Fetch BEFORE any release: the pages are still alive under
            # their slots' mappings.
            rec.append_rows(self._fetch(spill_ids, 0),
                            [kv.replica_of_page(p) for p in spill_ids])
        # Pass 2: drop the slots (unrefs every mapping; host-spilled
        # pages free once their last sibling mapping goes).
        for name in states:
            if name in rec.slots or name in empty:
                kv.release(name)
        if count:
            self._spilled[session] = rec
            self.spills += count
            telemetry.inc("roundtable_kv_spills_total", count,
                          engine=self._name)
            self._publish()
        return count

    # --- restore ---

    def restore_session(self, session: str,
                        pinned: tuple[str, ...] = ()) -> int:
        """Bring a spilled session back, all-or-nothing: ONE fresh page
        per unique spilled page (sibling slots re-map it, so
        intra-session aliasing survives the round trip), host bytes
        device_put back, kept pages re-aliased (the tier's reference
        transfers to the slot mapping) — byte-identical to never having
        spilled. On failure (pool exhaustion mid-restore) every effect
        of this call is undone and the record re-filed intact. Returns
        the number of slots restored."""
        rec = self._spilled.pop(session, None)
        if rec is None:
            return 0
        kv = self.engine.kv
        pin = tuple(pinned) + tuple(rec.slots)
        fresh: dict[int, int] = {}      # store row -> fresh page
        mapped: set[int] = set()        # fresh pages already mapped once
        assigned: list[str] = []
        stale: list[str] = []
        try:
            # Staleness FIRST (a slot repopulated while spilled keeps
            # its live state), then materialize only rows a live slot's
            # entries still reference — allocating for stale records
            # would evict idle slots and reclaim warm cache nodes to
            # build pages the cleanup immediately frees.
            live = [name for name, srec in rec.slots.items()
                    if not getattr(kv._slots.get(name), "pages", None)]
            need_rows = sorted({p for name in live
                                for kind, p in rec.slots[name].entries
                                if kind == "host"})
            for row in need_rows:
                fresh[row] = kv._alloc_page(pin, rec.replicas[row])
            if fresh:
                self._write([fresh[r] for r in need_rows], rec.host,
                            need_rows, 0)
            for name, srec in rec.slots.items():
                state = kv.acquire(name, pin)
                if state.pages:
                    # Repopulated while spilled (pre-checked above, but
                    # re-verified on the live acquire) — keep the live
                    # state.
                    stale.append(name)
                    continue
                state.replica = srec.replica
                pages: list[int] = []
                for kind, p in srec.entries:
                    if kind == "kept":
                        pages.append(p)          # tier ref transfers
                    else:
                        fp = fresh[p]
                        if fp in mapped:
                            kv.ref(fp)           # sibling re-alias
                        else:
                            mapped.add(fp)
                        pages.append(fp)
                state.pages = pages
                state.tokens = list(srec.tokens)
                assigned.append(name)
        except BaseException:
            # Undo completely: re-take the tier's kept holds for
            # already-assigned slots (their release below drops the
            # transferred mapping refs), release those slots, free the
            # fresh pages nothing maps anymore, re-file the record.
            for name in assigned:
                for kind, p in rec.slots[name].entries:
                    if kind == "kept":
                        kv.ref(p)
                kv.release(name)
            for fp in fresh.values():
                if fp not in mapped:
                    kv.unref(fp)
            self._spilled[session] = rec
            raise
        # Stale slots consumed their records: drop the tier's holds AND
        # the fresh pages their skipped entries left unmapped — a fresh
        # page no slot adopted would otherwise leak out of the pool
        # until revive (review finding, reproduced).
        for name in stale:
            for kind, p in rec.slots[name].entries:
                if kind == "kept":
                    kv.unref(p)
        for fp in fresh.values():
            if fp not in mapped:
                kv.unref(fp)
        count = len(assigned)
        self.restores += count
        if count:
            telemetry.inc("roundtable_kv_restores_total", count,
                          engine=self._name)
        self._publish()
        return count

    def restore_for(self, names: list[str],
                    pinned: tuple[str, ...] = ()) -> int:
        """Restore every spilled session appearing among `names` —
        the engine-side seam `_prepare_batch` runs before reuse_plan, so
        a spilled session resumes transparently on ANY serving path
        (direct generate_batch or scheduler submit)."""
        if not self._spilled:
            return 0
        restored = 0
        # sorted: restore order drives _alloc_page's call sequence, and
        # the paged allocator's multi-host lockstep contract is
        # "deterministic given the call sequence" — set iteration order
        # is per-process hash noise.
        for session in sorted({session_of(n) for n in names}):
            if session and session in self._spilled:
                restored += self.restore_session(session, pinned)
        return restored

    # --- drain / evacuation / teardown ---

    def evacuate(self, sessions: Optional[list[str]] = None) -> dict:
        """Move sessions FULLY to host RAM and return a restorable
        manifest (ISSUE 12): first spill every still-resident targeted
        session (slots in the pool spill through spill_session — pages
        with external holders stay resident under tier refs), then
        convert those kept-resident holds to host bytes and drop them,
        so every targeted session's state lives entirely in host RAM —
        pool-independent, which is exactly what lets the supervisor
        graft the records onto a REBUILT engine's tier (adopt()) and
        restore byte-identical KV across an engine restart.

        `sessions=None` targets everything (the fleet.drain shape:
        after the flush released every slot and the index, the tier's
        kept pages are the only thing between a drained pool and zero
        pages in use). A subset selector evacuates only those sessions;
        the rest keep their pool/tier state untouched. The caller owns
        engine serialization (serve lock / scheduler thread).

        Manifest: {"pages_moved", "slots_spilled", "host_bytes",
        "sessions": {session: {"slots", "host_rows", "host_bytes"}}} —
        every listed session restores via restore_session/restore_for
        (or transparently at its next submit)."""
        kv = self.engine.kv
        targets = None if sessions is None else set(sessions)
        # Pass 1: spill targeted sessions whose slots still sit in the
        # pool (the supervisor path — fleet.drain's flush has usually
        # emptied the pool already, making this a no-op there).
        resident = sorted({session_of(n) for n in kv.slot_names()}
                          - {""})
        slots_spilled = 0
        for s in resident:
            if targets is None or s in targets:
                slots_spilled += self.spill_session(s)
        moved = 0
        for session, rec in self._spilled.items():
            if targets is not None and session not in targets:
                continue
            kept: dict[int, int] = {}   # page -> #mappings in this rec
            for srec in rec.slots.values():
                for kind, p in srec.entries:
                    if kind == "kept":
                        kept[p] = kept.get(p, 0) + 1
            if not kept:
                continue
            # Per-call page->row map (same identity rule as
            # spill_session: the pages are alive right now, so ids are
            # sound for the duration of this call only).
            ids = list(kept)
            base = rec.n_rows()
            rows = {p: base + i for i, p in enumerate(ids)}
            rec.append_rows(self._fetch(ids, 0),
                            [kv.replica_of_page(p) for p in ids])
            moved += len(ids)
            for srec in rec.slots.values():
                srec.entries = [("host", rows[p]) if kind == "kept"
                                else (kind, p)
                                for kind, p in srec.entries]
            for p, n_maps in kept.items():
                for _ in range(n_maps):
                    kv.unref(p)
        if moved or slots_spilled:
            self._publish()
        manifest: dict = {
            "pages_moved": moved,
            "slots_spilled": slots_spilled,
            "host_bytes": 0,
            "sessions": {},
        }
        for session, rec in self._spilled.items():
            if targets is not None and session not in targets:
                continue
            b = rec.host_bytes()
            manifest["sessions"][session] = {
                "slots": len(rec.slots),
                "host_rows": rec.n_rows(),
                "host_bytes": b,
            }
            manifest["host_bytes"] += b
        return manifest

    def adopt(self, other: "HostOffloadTier",
              sessions: Optional[list[str]] = None) -> list[str]:
        """Graft another tier's spill records onto THIS tier (the
        supervisor's engine rebuild: the dead engine's evacuated
        sessions become the fresh engine's restorable sessions).
        Records must be fully host-resident — evacuate() first: a
        record still holding "kept" pool pages references a pool this
        tier has never seen, and restoring it would alias unrelated
        content. Such records are refused (left on `other`, named in
        no list) rather than corrupting the new pool. Returns the
        adopted session names.

        `sessions` selects a subset (ISSUE 17: cross-replica migration
        moves ONE session's record between two live engines' tiers —
        adopting everything would steal the source replica's other
        spilled sessions); None keeps the supervisor's adopt-all shape."""
        targets = None if sessions is None else set(sessions)
        adopted: list[str] = []
        for session, rec in list(other._spilled.items()):
            if targets is not None and session not in targets:
                continue
            if not rec.fully_host_resident():
                continue
            if session in self._spilled:
                continue  # this tier's own record wins
            self._spilled[session] = rec
            del other._spilled[session]
            adopted.append(session)
        if adopted:
            self._publish()
            other._publish()
        return adopted

    def drop_all(self) -> None:
        """Forget every spilled record WITHOUT touching the pool — for
        revive_if_dead, where the pools (and the refs table) were just
        reallocated and the kept-page references no longer exist."""
        self._spilled.clear()
        self._publish()
