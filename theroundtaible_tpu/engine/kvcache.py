"""Per-knight persistent KV-cache slots.

The reference keeps no model state between turns — every turn re-sends the
full transcript, so token cost grows quadratically with rounds
(reference src/utils/prompt.ts:60-77; SURVEY.md §3.1 "hot loops"). Here each
knight owns a slot: device-resident K/V for every layer plus the host-side
token ids already baked into it. On the next turn the engine prefills only
the delta beyond the longest common token prefix.

Layout per layer: [num_slots, max_seq_len, kv_heads, head_dim], position-
aligned (cache index s holds position s). Slots ride the "data" mesh axis,
kv heads the "model" axis (sharding.kv_cache_spec).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp

from .models.common import ModelConfig

# Session-namespaced slot names (ISSUE 4 satellite: two concurrent
# discussions both acquiring "lancelot" used to map to ONE slot and
# cross-contaminate KV through reuse_plan). The separator is the ASCII
# unit separator — no tokenizer/config surface produces it, so a scoped
# name can never collide with a legal knight name.
SESSION_SEP = "\x1f"


def scoped_slot(session: Optional[str], name: str) -> str:
    """The canonical session-namespaced slot name: `session␟name`.
    None/"" session returns the bare name (single-session legacy)."""
    return f"{session}{SESSION_SEP}{name}" if session else name


def session_of(name: str) -> str:
    """The session namespace of a (possibly scoped) slot name; "" for
    un-scoped names. Used to keep cross-knight prefix DONATION within
    one session: sessions are isolation domains (a faulted session's
    slot invalidation must never ripple into another's KV lineage)."""
    return name.split(SESSION_SEP, 1)[0] if SESSION_SEP in name else ""


@dataclass
class SlotState:
    """Host-side bookkeeping for one knight's slot."""

    slot_id: int
    name: str
    tokens: list[int] = field(default_factory=list)  # ids baked into cache


class SlotBook:
    """Host-side slot bookkeeping alone — LRU allocation, LCP reuse
    planning, donor search. KVCache adds the contiguous device arrays;
    the pipeline engine (pp_serving.py) uses SlotBook directly with its
    stage-stacked caches."""

    def __init__(self, num_slots: int):
        self.num_slots = num_slots
        self._slots: dict[str, SlotState] = {}
        self._free = list(range(num_slots))

    # --- slot allocation ---

    def acquire(self, name: str, pinned: tuple[str, ...] = ()) -> SlotState:
        """Get the named knight's slot, allocating on first use.

        `pinned` names are never evicted — generate_batch pins every knight
        of the in-flight batch so two batch rows can't share a slot_id.
        """
        if name in self._slots:
            # Refresh recency so eviction below is true LRU, not FIFO.
            self._slots[name] = self._slots.pop(name)
            return self._slots[name]
        if not self._free:
            # Evict the least-recently-used slot (dict order = recency,
            # refreshed on every acquire) that is not pinned by the batch.
            victim = next((n for n in self._slots if n not in pinned), None)
            if victim is None:
                raise RuntimeError(
                    f"KVCache has {self.num_slots} slots but "
                    f"{len(pinned)} knights are pinned in one batch — "
                    "raise num_slots in the tpu-llm adapter config")
            self.release(victim)
        slot_id = self._free.pop(0)
        state = SlotState(slot_id=slot_id, name=name)
        self._slots[name] = state
        return state

    def release(self, name: str) -> None:
        state = self._slots.pop(name, None)
        if state is not None:
            self._free.append(state.slot_id)

    def reset_slot(self, name: str) -> None:
        """Forget cached tokens (cache rows need no zeroing — the valid-length
        mask makes stale entries unreachable)."""
        if name in self._slots:
            self._slots[name].tokens = []

    def forget_all(self) -> None:
        """Drop every slot record. For buffer reallocation after donation
        death (revive_if_dead): nothing cached survives, so every later
        prefill starts from scratch."""
        self._slots.clear()
        self._free = list(range(self.num_slots))

    def flush(self) -> int:
        """Release every per-knight slot through the normal release path
        (graceful drain's KV flush, fleet.drain): paged caches decref
        and free their pages, contiguous slots return to the free list.
        Returns how many slots were flushed."""
        names = list(self._slots)
        for name in names:
            self.release(name)
        return len(names)

    def revive_if_dead(self) -> bool:
        """Reallocate device buffers if a failed donated dispatch deleted
        them (jax donate_argnums consumes inputs even when the program
        faults after transfer). Base SlotBook owns no buffers — caches
        that do (KVCache, PagedKVCache) override. Returns True iff fresh
        buffers were allocated (all cached content lost)."""
        return False

    def scratch_slot(self, pinned: tuple[str, ...] = ()) -> Optional[int]:
        """A slot id safe to use as a throwaway WRITE target — the
        scheduler's bucketed decode batch points its masked pad rows
        here (all pads write identical bytes, so the duplicate-index
        scatter is deterministic; a free slot's stale cells are
        unreachable behind valid-length masks and the next real acquire
        prefills over them). Returns a free slot's id, evicting the LRU
        unpinned slot first when none is free; the id is NOT allocated
        (it stays at the head of the free list until a real acquire
        claims it), so use it within the current dispatch only. None
        when every slot is pinned."""
        if not self._free:
            victim = next((n for n in self._slots if n not in pinned),
                          None)
            if victim is None:
                return None
            self.release(victim)
        return self._free[0]

    def slot_names(self) -> list[str]:
        return list(self._slots)

    def memory_ledger(self) -> dict:
        """Slot-occupancy accounting for the memory ledger (ISSUE 6):
        the host-side view trace_hooks.publish_memory_ledger turns
        into registry gauges. Contiguous layouts pay HBM per SLOT
        regardless of use, so `cached_tokens` vs capacity is the
        interesting waste number here."""
        in_use = len(self._slots)
        return {
            "layout": "contiguous",
            "slots_in_use": in_use,
            "num_slots": self.num_slots,
            "slot_occupancy": round(in_use / max(self.num_slots, 1), 3),
            "cached_tokens": sum(len(s.tokens)
                                 for s in self._slots.values()),
            "hbm_bytes": None,  # SlotBook owns no buffers (PP stages do)
        }

    # --- prefix reuse ---

    @staticmethod
    def common_prefix_len(cached: list[int], new: list[int]) -> int:
        # native rt_lcp when built (falls back to a Python loop inside)
        from ..native import lcp
        return lcp(cached, new)

    def reuse_plan(self, name: str, tokens: list[int],
                   pinned: tuple[str, ...] = ()) -> tuple[int, int]:
        """(slot_id, reuse_len): how many leading tokens are already baked
        into the slot's cache. The caller prefills only tokens[reuse_len:].

        reuse_len is capped at len(tokens)-1 so at least one token is always
        fed (the model needs a last-token logit to start decoding)."""
        state = self.acquire(name, pinned)
        reuse = self.common_prefix_len(state.tokens, tokens)
        reuse = min(reuse, len(tokens) - 1)
        # Positions >= reuse are about to be overwritten by prefill/decode.
        # Truncate the record NOW: if the turn dies mid-flight (timeout),
        # the slot must not claim cache contents that were clobbered.
        state.tokens = state.tokens[:reuse]
        return state.slot_id, reuse

    def commit(self, name: str, tokens: list[int],
               index: bool = True) -> None:
        """Record that the slot's cache now covers exactly `tokens`.
        `index` exists for signature parity with PagedKVCache.commit
        (ISSUE 10: persona rows must not feed the cross-session prefix
        cache) — the contiguous layout has no index, so it is
        ignored."""
        del index
        self.acquire(name).tokens = list(tokens)

    def best_donor(self, name: str,
                   tokens: list[int]) -> tuple[Optional[SlotState], int]:
        """The OTHER slot sharing the longest committed token prefix with
        `tokens` — the cross-knight reuse seam (SURVEY.md §7.3 hard part 2):
        knights' prompts share the giant context+transcript preamble
        (orchestrator _build_turn_prompt lays shared text first), so knight
        B's fresh slot can copy knight A's K/V for the common span instead
        of re-prefilling it. Donor records are truncated by reuse_plan when
        they join a batch, so a donor never advertises positions that are
        about to be overwritten. Donation is INTRA-session only: sessions
        are isolation domains (scoped_slot), so a donor from another
        concurrent discussion is never consulted even when its token
        prefix happens to match."""
        best, best_len = None, 0
        scope = session_of(name)
        for state in self._slots.values():
            if state.name == name or not state.tokens:
                continue
            if session_of(state.name) != scope:
                continue
            n = self.common_prefix_len(state.tokens, tokens)
            if n > best_len:
                best, best_len = state, n
        return best, best_len


def share_prefixes(kv, names, all_tokens, offsets, *, min_shared: int,
                   add_share, flush_shares, prefill_span,
                   extra_pinned: tuple[str, ...] = (),
                   defer_span=None,
                   donor_ok=None) -> tuple[list[int], int]:
    """Two-pass cross-knight shared-prefix reuse — THE algorithm, used by
    both serving engines so the donor cap, batch-common-prefix fold,
    l_shared clamp, laggard threshold and extra_prefill accounting cannot
    drift between them (SURVEY.md §7.3 hard part 2).

    (a) donor pass — a slot committed by an earlier call that shares a
        longer token prefix than a row's own history donates its span;
    (b) leader pass — within one batch, the row with the most cache
        coverage prefills the batch-wide common span ONCE and the
        laggards copy it.

    Callbacks own the device mechanics:
      add_share(donor_state, row_i, lo, hi) — queue/apply one span share
        (contiguous: K/V copy; paged: page aliasing);
      flush_shares() — dispatch queued shares (called after each pass so
        leader-sourced copies never read a pending span);
      prefill_span(row_i, lo, hi) — prefill that row's token span
        (ring-eligible on the main engine, chunked on PP).

    `extra_pinned`: slot names OUTSIDE this batch that must survive any
    eviction the passes trigger — the session scheduler pins every
    actively-decoding row while a joining batch runs its passes.

    `defer_span(m, lo, hi, followers)` (ISSUE 8, ragged admission):
    when given and the leader's cache does NOT yet cover the common
    span, the leader pass DISPATCHES NOTHING — the leader's offset
    stays at its own coverage (its span joins the live decode segment
    as ragged chunks), the laggards' offsets still raise to the span
    end, and the callback records (leader index, leader coverage, span
    end, [(laggard, its pre-raise coverage), ...]) so the caller can
    alias the laggards AFTER the leader's chunks have written the span
    (aliasing unwritten pages would be copy-on-write'd away by the
    leader's own write-exclusivity). A leader that already covers the
    span aliases immediately — the content exists.

    `donor_ok(donor_state, row_i)` (ISSUE 10): extra donor gate —
    multi-LoRA engines pass an adapter-identity check, since K/V baked
    under one adapter is wrong under another. Conservative by design:
    a rejected best donor is dropped, not re-searched (the prefill it
    would have saved is small next to serving wrong bytes). The
    LEADER pass needs no gate — lora engines only reach it for
    uniform-adapter batches (engine._prepare_batch suppresses mixed
    ones).

    Returns (updated offsets, leader-prefilled token count)."""
    b = len(names)
    pinned = tuple(names) + tuple(extra_pinned)
    offsets = list(offsets)
    extra_prefill = 0

    for i in range(b):
        cap = len(all_tokens[i]) - 1
        donor, dlen = kv.best_donor(names[i], all_tokens[i])
        dlen = min(dlen, cap)
        if donor is not None and donor_ok is not None \
                and not donor_ok(donor, i):
            donor = None
        if donor is not None and dlen - offsets[i] >= min_shared:
            add_share(donor, i, offsets[i], dlen)
            offsets[i] = dlen
    flush_shares()

    if b < 2:
        return offsets, extra_prefill
    shared = all_tokens[0]
    for t in all_tokens[1:]:
        shared = shared[:kv.common_prefix_len(shared, t)]
    l_shared = min(len(shared), min(len(t) for t in all_tokens) - 1)
    m = max(range(b), key=lambda i: offsets[i])
    laggards = [i for i in range(b)
                if i != m and l_shared - offsets[i] >= min_shared]
    if not laggards:
        return offsets, extra_prefill
    if offsets[m] < l_shared:
        if defer_span is not None:
            defer_span(m, offsets[m], l_shared,
                       [(i, offsets[i]) for i in laggards])
            for i in laggards:
                offsets[i] = l_shared
            return offsets, extra_prefill
        prefill_span(m, offsets[m], l_shared)
        extra_prefill += l_shared - offsets[m]
        offsets[m] = l_shared
    leader = kv.acquire(names[m], pinned)
    for i in laggards:
        add_share(leader, i, offsets[i], l_shared)
        offsets[i] = l_shared
    flush_shares()
    return offsets, extra_prefill


class KVCache(SlotBook):
    """num_slots × num_layers of contiguous device KV plus SlotBook's
    bookkeeping. Layout per layer: [num_slots, max_seq_len, K, D]."""

    def __init__(self, cfg: ModelConfig, num_slots: int,
                 max_seq_len: Optional[int] = None, dtype=jnp.bfloat16,
                 sharding=None):
        super().__init__(num_slots)
        self.cfg = cfg
        self.max_seq_len = max_seq_len or cfg.max_seq_len
        shape = (num_slots, self.max_seq_len, cfg.num_kv_heads, cfg.head_dim)
        make = (lambda: jnp.zeros(shape, dtype)) if sharding is None else \
            (lambda: jax.device_put(jnp.zeros(shape, dtype), sharding))
        # Kept for revive_if_dead: reallocation after donation death.
        self._make = make
        self.layers: list[tuple[jax.Array, jax.Array]] = [
            (make(), make()) for _ in range(cfg.num_layers)]

    def revive_if_dead(self) -> bool:
        if not self.layers[0][0].is_deleted():
            return False
        self.layers = [(self._make(), self._make())
                       for _ in range(self.cfg.num_layers)]
        self.forget_all()
        return True

    def memory_ledger(self) -> dict:
        led = super().memory_ledger()
        k, _ = self.layers[0]
        led["hbm_bytes"] = 2 * k.size * k.dtype.itemsize * len(self.layers)
        return led
