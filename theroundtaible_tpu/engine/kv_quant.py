"""Quantized KV pages — int8 (and grouped int4) paged-pool storage.

KV residency dominates serving HBM (LORA_r10 recorded kv_bytes at 79%
of resident memory even at toy scale) while weights already stream at
int8/int4 (PR 3) — so bf16 KV pages are the budget line that caps max
resident sessions and sets decode's streamed-bytes roofline term. This
module is the ONE definition of the page-cell quantization contract
shared by every seam that touches it (ISSUE 11):

- **Storage**: a quantized pool keeps its [P, page_size, K, Dp] layout
  with int8 payload (Dp = D for int8, D/2 packed nibbles for int4 — the
  quant.py nibble order: even element in the LOW nibble) and a parallel
  per-layer scale pool [P, page_size, K, G] float32 — one symmetric
  absmax scale per CELL (per token per kv head) per group (G = 1 for
  int8, D/group for int4). Per-cell scales are what make
  quantize-on-write LOCAL: a token's write computes its own scale from
  its own values, never re-quantizing neighbours, so repeated
  scatter/gather round trips are bit-stable (`requant_stable` below is
  the pinned property) and host spill/restore of the int8 bytes is
  exactly lossless.
- **Write seam**: `quantize_cells` runs INSIDE the jit'd serving
  programs at the K/V scatter sites (paged_forward's per-layer scatter,
  the gather-view scatter, the ring-prefill writeback) — values in,
  values out, no shape depends on occupancy, so the PR-6 recompile
  sentinel stays green.
- **Read seam**: the Pallas kernels dequantize in-kernel
  (pallas/attention._dequant_kv: the `_prefill_accumulate` /
  `_decode_accumulate` extension), so the streamed bytes on the serving
  path are the int8 payload + scales — the quantization is free where
  it matters. The XLA fallbacks (gather view, ragged dense path)
  dequantize at gather via `dequantize_cells`, numerically the same
  math.
- **Accounting**: `cell_bytes_per_token` is the closed form the memory
  ledger, fleet plan estimate and perfmodel ceiling all derive from, so
  the plan cannot drift from the real allocation.

Everything downstream (prefix cache, host offload, spec-decode verify,
LoRA mixed batches) rides page IDs and therefore shares quantized bytes
unchanged — scales travel with their pages because they are indexed by
the same page axis. Parity discipline: attach/restore byte-identity
becomes quantization-aware — pinned rms bounds against the bf16 path
plus greedy token parity (BENCH_NOTES.md records the acceptance rule);
`ROUNDTABLE_KV_QUANT=0` restores bf16 serving byte-identically.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Optional

import jax.numpy as jnp

# Default int4 group along D: matches quant.py's w4 grouping scale
# (64 there, but KV head_dim is small — 32 keeps >= 4 groups per
# 128-wide head so group error stays local).
DEFAULT_INT4_GROUP = 32


@dataclass(frozen=True)
class KVQuantSpec:
    """Static description of a quantized page pool. `bits` is 8 or 4;
    `group` is the int4 scale group along D (ignored for int8, where
    the whole D axis is one group)."""

    bits: int = 8
    group: int = DEFAULT_INT4_GROUP

    @property
    def dtype_name(self) -> str:
        return "int8" if self.bits == 8 else "int4"

    def packed_dim(self, head_dim: int) -> int:
        """Payload width Dp for a D-wide head: int8 stores D bytes,
        int4 packs two nibbles per byte."""
        return head_dim if self.bits == 8 else head_dim // 2

    def num_groups(self, head_dim: int) -> int:
        """Scale groups G per cell (the scale pool's minor dim)."""
        if self.bits == 8:
            return 1
        return head_dim // self.effective_group(head_dim)

    def effective_group(self, head_dim: int) -> int:
        """The actual int4 group: the largest even divisor of D that is
        <= `group` (the quant.py _int4_group_for rule; int8 returns D)."""
        if self.bits == 8:
            return head_dim
        g = min(self.group, head_dim)
        while g > 1 and (head_dim % g or g % 2):
            g -= 1
        return max(g, 2)

    def cell_bytes(self, head_dim: int) -> float:
        """Resident bytes per KV cell (one token, one kv head): payload
        + float32 scales."""
        return self.packed_dim(head_dim) + 4.0 * self.num_groups(head_dim)


def bf16_cell_bytes(head_dim: int, dtype_bytes: int = 2) -> float:
    return float(head_dim * dtype_bytes)


def cell_bytes_per_token(cfg: Any, spec: Optional[KVQuantSpec],
                         dtype_bytes: int = 2) -> float:
    """KV bytes one cached token costs this model under `spec` (None =
    the bf16 layout): layers x (K + V) x kv_heads x per-cell bytes —
    the ONE closed form the ledger, the fleet estimate and perfmodel's
    streamed-KV term all share."""
    per_cell = (spec.cell_bytes(cfg.head_dim) if spec is not None
                else bf16_cell_bytes(cfg.head_dim, dtype_bytes))
    return cfg.num_layers * 2 * cfg.num_kv_heads * per_cell


def page_ratio(spec: KVQuantSpec, head_dim: int,
               dtype_bytes: int = 2) -> float:
    """How many quantized pages fit the byte budget of ONE bf16 page —
    the pool-sizing multiplier (>= 1). int8 at D=128: ~1.94x."""
    return bf16_cell_bytes(head_dim, dtype_bytes) / spec.cell_bytes(
        head_dim)


def resolve_spec(kv_quant: Any) -> tuple[Optional[KVQuantSpec],
                                         Optional[str]]:
    """(spec, decline_reason) from the `kv_quant:` config value.

    Accepts "int8" / "int4", {"bits": 8|4, "group": n}, or falsy
    (off). The ROUNDTABLE_KV_QUANT env kill-switch (=0) wins over any
    config — the machine-readable reason records which gate fired."""
    from .prefix_cache import env_flag
    if not kv_quant or kv_quant == "none":
        return None, "disabled:config"
    if not env_flag(None, "ROUNDTABLE_KV_QUANT"):
        return None, "disabled:env"
    if isinstance(kv_quant, str):
        if kv_quant not in ("int8", "int4"):
            raise ValueError(
                f"kv_quant must be none|int8|int4, got {kv_quant!r}")
        bits = 8 if kv_quant == "int8" else 4
        return KVQuantSpec(bits=bits), None
    if isinstance(kv_quant, dict):
        bits = int(kv_quant.get("bits", 8))
        if bits not in (8, 4):
            raise ValueError(
                f"kv_quant.bits must be 8 or 4, got {bits}")
        group = int(kv_quant.get("group", DEFAULT_INT4_GROUP))
        if group < 2:
            raise ValueError(
                f"kv_quant.group must be >= 2, got {group}")
        return KVQuantSpec(bits=bits, group=group), None
    raise ValueError(
        f"kv_quant must be a string or mapping, got {type(kv_quant)}")


# --- the quantize/dequantize pair (jit-safe, value in / value out) ---


def quantize_cells(x, spec: KVQuantSpec):
    """Quantize K or V values [..., D] to (payload int8 [..., Dp],
    scales f32 [..., G]) with one symmetric absmax scale per cell per
    group. Runs inside the serving programs at every scatter seam;
    shapes depend only on D and the spec, never on batch composition."""
    d = x.shape[-1]
    g = spec.effective_group(d)
    n_groups = spec.num_groups(d)
    x32 = x.astype(jnp.float32)
    xg = x32.reshape(x.shape[:-1] + (n_groups, g))
    absmax = jnp.max(jnp.abs(xg), axis=-1)
    qmax = 127.0 if spec.bits == 8 else 7.0
    s = jnp.maximum(absmax, 1e-8) / qmax
    q = jnp.clip(jnp.round(xg / s[..., None]), -qmax, qmax)
    q = q.astype(jnp.int8).reshape(x.shape[:-1] + (d,))
    if spec.bits == 4:
        q2 = q.reshape(x.shape[:-1] + (d // 2, 2))
        even, odd = q2[..., 0], q2[..., 1]
        q = (((odd.astype(jnp.int32) & 0xF) << 4)
             | (even.astype(jnp.int32) & 0xF)).astype(jnp.int8)
    return q, s


def unpack_int4(q):
    """[..., D/2] packed int8 -> [..., D] int4 values as int8 (even
    element from the LOW nibble — quantize_cells' packing order).
    Shift arithmetic only, so it lowers inside Mosaic kernels (probed
    chipless) and under plain XLA alike."""
    lo = (jnp.left_shift(q, 4) >> 4).astype(jnp.int8)
    hi = (q >> 4).astype(jnp.int8)
    return jnp.stack([lo, hi], axis=-1).reshape(q.shape[:-1]
                                                + (q.shape[-1] * 2,))


def dequantize_cells(q, s, spec: KVQuantSpec, dtype=jnp.bfloat16):
    """(payload [..., Dp], scales [..., G]) -> values [..., D] in
    `dtype` — the XLA-side read seam (gather view, ragged dense
    fallback, host-side round-trip checks). The in-kernel twin is
    pallas/attention._dequant_kv; both apply the identical scale math."""
    if spec.bits == 4:
        q = unpack_int4(q)
    d = q.shape[-1]
    n_groups = s.shape[-1]
    xg = q.astype(jnp.float32).reshape(q.shape[:-1]
                                       + (n_groups, d // n_groups))
    x = (xg * s[..., None].astype(jnp.float32)).reshape(q.shape)
    return x.astype(dtype)


# --- pool pytree helpers (combined pools + scales) ---


def split_combined(combined: list, num_layers: int):
    """The engine's jit programs carry ONE donated pytree: the per-layer
    (k, v) pools followed by the per-layer (k_scale, v_scale) pools when
    quantization is on. (pools, scales_or_None) back out."""
    if len(combined) == num_layers:
        return list(combined), None
    return list(combined[:num_layers]), list(combined[num_layers:])


def join_combined(pools: list, scales: Optional[list]) -> list:
    return list(pools) + (list(scales) if scales else [])


# --- test-visibility counters (tests/conftest.py `kv_quant` guard) ---

_lock = threading.Lock()
_kernel_dispatches = 0
_fallback_dispatches = 0


def reset_test_counters() -> None:
    global _kernel_dispatches, _fallback_dispatches
    with _lock:
        _kernel_dispatches = 0
        _fallback_dispatches = 0


def note_quant_dispatch(kernel: bool) -> None:
    """One serving dispatch consumed quantized pages — kernel-dequant
    (Pallas) or xla-dequant (gather view / ragged dense fallback)."""
    global _kernel_dispatches, _fallback_dispatches
    with _lock:
        if kernel:
            _kernel_dispatches += 1
        else:
            _fallback_dispatches += 1


def quant_dispatches() -> int:
    return _kernel_dispatches + _fallback_dispatches


def quant_kernel_dispatches() -> int:
    return _kernel_dispatches


def quant_fallback_dispatches() -> int:
    return _fallback_dispatches
