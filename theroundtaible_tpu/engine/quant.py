"""Weight quantization for serving: int8 (w8a16) and grouped int4 (w4a16).

Decode throughput is weight-streaming-bound: every generated token reads
every parameter from HBM once, so bf16 weights cap a v5e-1 at roughly
bandwidth / (2 · params) tok/s. Symmetric per-output-channel int8 halves
the bytes streamed — close to 2× the decode ceiling — while activations
stay bf16 (the int8→bf16 convert fuses into the matmul operand on the
MXU). This also mirrors what the reference's serving stack actually does:
Ollama/llama.cpp serves quantized GGUF by default (reference
src/adapters/local-llm.ts reaches 4-bit llama.cpp kernels), so bf16-only
serving would be racing a quantized baseline with one leg tied.

Representations (consumers must handle BOTH — `quantized()` is the
predicate):
- bits=8: each big matmul weight leaf becomes a dict
  {"q": int8[w.shape], "s": act_dtype[kept axes]}
  where `s` = absmax/127 over the einsum-CONTRACTED axes (w ≈ q * s with
  s broadcast over the kept/output axes). models/common.py's `_einsum`
  and `embed_tokens` dequantize by scaling the matmul OUTPUT — a fusable
  elementwise multiply — never materializing a bf16 copy of the weight.
- bits=4: an Int4Leaf (models/common.py) — two SIGNED nibbles packed per
  int8 byte along the weight's LAST axis, per-`group` absmax/7 scales
  (axis/group are static pytree metadata). Dequant is a bitcast
  (int8 → 2×int4, minor-most expansion) + convert + grouped scale that
  fuses into the consuming matmul operand on TPU; a leaf whose last dim
  cannot group falls back to the int8 dict form, so bits=4 trees are
  MIXED by design.
Norm weights stay untouched (tiny, accuracy-critical), and so does the
MoE router (tiny, and its top-k expert SELECTION amplifies quantization
error discontinuously — see the _SCALE_AXES note).

Quantization runs AFTER shard_params: q/s are computed with jnp ops on
the already-sharded weights, so XLA propagates the NamedShardings (q
inherits the weight's, s keeps the kept axes') and no separate spec tree
is needed. Absmax over a sharded contracted axis costs one all-reduce at
load time.

Scope: every serving path — the main InferenceEngine (dense + flash
attention, contiguous + paged KV, MoE), the pipeline engine (quantized
leaves stack per stage), and the ring/Ulysses sequence-parallel prefill
— all of which reach weights exclusively through the quant-aware
_einsum/embed_tokens accessors.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from .models.common import ModelConfig, Params

# Per weight key: the axes KEPT by the scale (the einsum's non-contracted
# weight axes, which land trailing in the matmul output).
_SCALE_AXES: dict[str, tuple[int, ...]] = {
    "q_proj": (1, 2),      # [E, H, D] → s[H, D]
    "k_proj": (1, 2),      # [E, K, D] → s[K, D]
    "v_proj": (1, 2),
    "o_proj": (2,),        # [H, D, E] → s[E]
    "gate_proj": (1,),     # dense [E, F] → s[F]
    "up_proj": (1,),
    "down_proj": (1,),     # dense [F, E] → s[E]
    # NOTE: the MoE "router" is deliberately ABSENT — it stays full
    # precision. Router logits pick top-k experts, a DISCONTINUOUS
    # decision: near-tied logits flip expert selection under
    # fraction-of-a-step perturbations, and a flipped expert changes
    # the output by whole-activation magnitudes (tests/test_quant.py
    # measures exactly this amplification on tiny-mixtral — even
    # embedding-quant noise upstream of an fp router can flip a
    # near-tied choice on random weights). Quantizing the decision-maker
    # itself invites those flips for E×X params of savings — bytes-
    # irrelevant — so it stays fp, which is standard MoE deployment
    # practice. Keep quantized_specs' key-for-key mirror in mind:
    # absence here makes BOTH the weights and the spec tree pass it
    # through.
    "embedding": (0,),     # [V, E] → s[V] (row scale: lookup AND lm head)
    "lm_head": (0,),
}
_EXPERT_SCALE_AXES = {
    "gate_proj": (0, 2),   # [X, E, F] → s[X, F]  ("bte,xef->btxf")
    "up_proj": (0, 2),
    "down_proj": (2,),     # [X, F, E] → s[E]     ("btxf,xfe->bte")
}


# The int4 packer always groups/packs along the weight's LAST axis: any
# axis is mathematically valid (int4 dequant is a full elementwise
# multiply before the contraction), but only the minor-most axis lets
# the unpack be a bitcast whose nibble pair expands in place — the
# layout XLA/Mosaic fuses into the matmul operand on TPU. Packing the
# contracted axis (the llama.cpp convention, used in an earlier
# revision) forced an interleaving stack+reshape that broke operand
# fusion on real TPU and decoded slower than bf16 (BENCH_r05). Scales
# remain per-group × per-every-other-coordinate, so grouping along a
# kept axis changes only which direction group error correlates.


def quantized(leaf: Any) -> bool:
    from .models.common import Int4Leaf
    return (isinstance(leaf, dict) and "q" in leaf and "s" in leaf) \
        or isinstance(leaf, Int4Leaf)


def _quantize_leaf(w, scale_axes: tuple[int, ...], act_dtype,
                   free_source: bool) -> dict[str, Any]:
    scale_axes = tuple(a % w.ndim for a in scale_axes)
    reduce_axes = tuple(a for a in range(w.ndim) if a not in scale_axes)
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=reduce_axes)
    s = jnp.maximum(absmax, 1e-8) / 127.0
    s_full = jnp.expand_dims(s, reduce_axes)
    q = jnp.clip(jnp.round(w32 / s_full), -127, 127).astype(jnp.int8)
    out = {"q": q, "s": s.astype(act_dtype)}
    if free_source and isinstance(w, jax.Array):
        # Free each source leaf the moment its int8 replacement exists:
        # quantizing a 7B-class model then peaks at bf16-total + ONE
        # leaf's q instead of bf16-total + int8-total — the difference
        # between fitting and OOMing a 16 GB chip during engine build.
        jax.block_until_ready(out)
        w.delete()
    return out


def _int4_group_for(dim: int, group: int, shards: int = 1) -> int:
    """Largest even divisor of `dim` that is <= group (0 = no valid
    grouping; the leaf then falls back to int8). When the pack axis is
    TP-sharded over `shards` devices, the group must divide the
    PER-SHARD dim so no group (and no packed byte) ever straddles a
    shard boundary — the shard-aware kernel dispatch (pallas/int4mm
    einsum_int4_spmd) partitions q4/s4 along that axis with whole
    groups per shard, and a straddling group would need cross-shard
    scale reads mid-kernel. g | dim/shards implies g | dim, so the
    full-axis grouping below stays valid."""
    if shards > 1 and dim % shards == 0:
        dim = dim // shards
    for g in range(min(group, dim), 1, -1):
        if g % 2 == 0 and dim % g == 0:
            return g
    return 0


def _quantize_leaf_int4(w, scale_axes: tuple[int, ...],
                        act_dtype, free_source: bool,
                        group: int, pack_shards: int = 1) -> Any:
    """Symmetric per-group int4 (w ≈ q4 * s4, |q4| <= 7), two nibbles
    packed per int8 byte along the LAST axis (even element → low
    nibble — the order `lax.bitcast_convert_type` unpacks, see
    dequant_int4). `pack_shards` > 1 aligns the grouping to the TP
    shard boundary (see _int4_group_for) for leaves whose pack axis is
    model-sharded. A last dim that can't group falls back to that leaf
    staying int8 — mixed trees serve fine (the einsum seam dispatches
    per leaf)."""
    from .models.common import Int4Leaf

    dim = w.shape[-1]
    g = _int4_group_for(dim, group, pack_shards)
    if g < 2:
        return _quantize_leaf(w, scale_axes, act_dtype, free_source)
    w32 = w.astype(jnp.float32)
    wg = w32.reshape(w.shape[:-1] + (dim // g, g))
    absmax = jnp.max(jnp.abs(wg), axis=-1, keepdims=True)
    s = jnp.maximum(absmax, 1e-8) / 7.0
    q = jnp.clip(jnp.round(wg / s), -8, 7).astype(jnp.int8)
    q2 = q.reshape(w.shape[:-1] + (dim // 2, 2))
    even, odd = q2[..., 0], q2[..., 1]
    packed = (((odd.astype(jnp.int32) & 0xF) << 4)
              | (even.astype(jnp.int32) & 0xF)).astype(jnp.int8)
    s4 = jnp.squeeze(s, axis=-1).astype(act_dtype)
    out = Int4Leaf(q4=packed, s4=s4, axis=w.ndim - 1, group=g)
    if free_source and isinstance(w, jax.Array):
        jax.block_until_ready((out.q4, out.s4))
        w.delete()
    return out


def quantize_params(params: Params, cfg: ModelConfig,
                    act_dtype=jnp.bfloat16,
                    free_source: bool = False, bits: int = 8,
                    group: int = 64, model_shards: int = 1) -> Params:
    """Quantize the big matmul weights; returns a new tree (norms and any
    unrecognized leaves pass through untouched).

    bits=8 → per-output-channel int8 dicts; bits=4 → per-`group` packed
    Int4Leaf (a leaf whose pack dim can't group falls back to int8).

    model_shards (bits=4): the mesh's model-axis size. Leaves whose PACK
    axis is the model-sharded axis per sharding.param_specs (dense
    gate/up: [E, F] packed AND sharded on F) get their group aligned to
    the per-shard dim, so the shard-aware kernel dispatch partitions
    scales with whole groups per shard (sharding.int4_shard_axis /
    pallas/int4mm einsum_int4_spmd). Every other leaf packs an
    unsharded axis and is unaffected.

    free_source=True deletes each source weight buffer as soon as its
    quantized replacement is materialized — the caller must own `params`
    (every serving engine does: the init/load tree is not referenced
    after quantization). Pass-through leaves are never deleted."""
    if bits not in (8, 4):
        raise ValueError(f"bits must be 8 or 4, got {bits}")

    pack_specs = None
    if bits == 4 and model_shards > 1:
        from .sharding import param_specs
        pack_specs = param_specs(cfg)

    def _pack_shards(value, key, expert):
        """model_shards when this leaf's LAST (pack) axis is the
        model-sharded axis and divides, else 1 — mirroring
        _fallback_replicated's placement decision."""
        if pack_specs is None:
            return 1
        from .sharding import MODEL_AXIS
        layer0 = pack_specs["layers"][0]
        spec = (layer0.get("experts", {}).get(key) if expert
                else pack_specs.get(key, layer0.get(key)))
        if spec is None:
            return 1
        entries = tuple(spec)
        if (len(entries) == value.ndim and entries[-1] == MODEL_AXIS
                and value.shape[-1] % model_shards == 0):
            return model_shards
        return 1

    def one(value, key, expert=False):
        scale_axes = (_EXPERT_SCALE_AXES if expert else _SCALE_AXES)[key]
        if bits == 4:
            return _quantize_leaf_int4(value, scale_axes,
                                       act_dtype, free_source, group,
                                       _pack_shards(value, key, expert))
        return _quantize_leaf(value, scale_axes, act_dtype, free_source)

    out: Params = {}
    for key, value in params.items():
        if key in ("embedding", "lm_head"):
            out[key] = one(value, key)
        elif key == "layers":
            out[key] = [_quantize_layer(layer, act_dtype, free_source,
                                        one)
                        for layer in value]
        else:
            out[key] = value
    return out


def _quantize_layer(layer: dict[str, Any], act_dtype,
                    free_source: bool, one) -> dict[str, Any]:
    new: dict[str, Any] = {}
    for key, value in layer.items():
        if key == "experts":
            new[key] = {k: one(v, k, expert=True)
                        for k, v in value.items()}
        elif key in _SCALE_AXES and "norm" not in key:
            new[key] = one(value, key)
        else:
            new[key] = value
    return new


def _spec_for_scale(spec, scale_axes: tuple[int, ...]):
    """PartitionSpec for a scale leaf: `s` keeps exactly `scale_axes` of
    the weight, so its spec keeps those axes' entries (a spec shorter
    than the weight's rank means trailing dims are unsharded)."""
    from jax.sharding import PartitionSpec as P
    entries = tuple(spec) if spec is not None else ()
    return P(*(entries[a] if a < len(entries) else None
               for a in scale_axes))


def quantize_lora_stack(stack: jax.Array, act_dtype) -> dict[str, Any]:
    """Symmetric int8 quantization of a STACKED LoRA tensor [S, r, X]
    (ISSUE 10 quantize-aware adapter store): per-(slot, rank-row)
    absmax scales over the last axis, the same w ≈ q·s contract as the
    int8 weight dicts above — so a K-adapter store streams half the
    delta bytes. The all-zero base slot quantizes to zeros exactly
    (absmax floor only guards division). Apply-side dequant
    (engine/lora._dequant_stack) materializes the tiny tensors; the
    grouped Pallas kernel declines int8 stacks ("quant:int8-stack")."""
    w32 = stack.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=-1)
    s = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w32 / s[..., None]), -127, 127)
    return {"q": q.astype(jnp.int8), "s": s.astype(act_dtype)}


def quantize_lora_slot(leaf: dict[str, Any], slot, value32,
                       set_slot) -> dict[str, Any]:
    """Hot-swap ONE slot of an int8-quantized LoRA stack: quantize the
    incoming f32 [r, X] rows with the same per-rank-row absmax rule and
    write q/s through the store's compiled setter (values only — the
    stacked shapes never change, so swaps compile nothing)."""
    absmax = jnp.max(jnp.abs(value32), axis=-1)
    s = jnp.maximum(absmax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(value32 / s[..., None]), -127, 127)
    return {"q": set_slot(leaf["q"], slot, q),
            "s": set_slot(leaf["s"], slot, s)}


def quantized_specs(specs: Params,
                    params: Optional[Params] = None) -> Params:
    """Transform a param PartitionSpec tree (sharding.param_specs) into
    the spec tree matching quantize_params' OUTPUT structure: each
    quantized weight spec becomes {"q": spec, "s": kept-axes spec} — or
    an Int4Leaf of specs mirroring the actual leaf's static axis/group
    metadata (pytree treedefs include that metadata, so explicit
    placement via tree_map needs it to MATCH; pass the quantized
    `params` tree whenever it may contain int4 leaves). Needed because
    the PP engine stacks leaves itself and cannot rely on jit sharding
    propagation.

    Mirrors quantize_params/_quantize_layer key-for-key; keep the two in
    sync when a new weight becomes quantizable."""
    out: Params = {}
    for key, value in specs.items():
        pv = params.get(key) if params is not None else None
        if key in ("embedding", "lm_head"):
            out[key] = _qspec_leaf(value, _SCALE_AXES[key], pv)
        elif key == "layers":
            out[key] = [
                _quantized_layer_specs(
                    layer, pv[i] if pv is not None else None)
                for i, layer in enumerate(value)]
        else:
            out[key] = value
    return out


def _qspec_leaf(spec, scale_axes: tuple[int, ...], param_leaf):
    from .models.common import Int4Leaf
    if isinstance(param_leaf, Int4Leaf):
        # q4 shares the weight's spec (last axis halved — placement's
        # _fallback_replicated checks divisibility against the actual
        # shape); s4 has the same rank with the last axis → n_groups,
        # so the same entries apply.
        return Int4Leaf(q4=spec, s4=spec, axis=param_leaf.axis,
                        group=param_leaf.group)
    return {"q": spec, "s": _spec_for_scale(spec, scale_axes)}


def _quantized_layer_specs(layer: dict[str, Any],
                           param_layer: Optional[dict[str, Any]] = None
                           ) -> dict[str, Any]:
    new: dict[str, Any] = {}
    for key, value in layer.items():
        pv = param_layer.get(key) if param_layer is not None else None
        if key == "experts":
            new[key] = {
                k: _qspec_leaf(v, _EXPERT_SCALE_AXES[k],
                               pv.get(k) if pv is not None else None)
                for k, v in value.items()}
        elif key in _SCALE_AXES and "norm" not in key:
            new[key] = _qspec_leaf(value, _SCALE_AXES[key], pv)
        else:
            new[key] = value
    return new
