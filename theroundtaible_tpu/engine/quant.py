"""int8 weight quantization (w8a16) for serving.

Decode throughput is weight-streaming-bound: every generated token reads
every parameter from HBM once, so bf16 weights cap a v5e-1 at roughly
bandwidth / (2 · params) tok/s. Symmetric per-output-channel int8 halves
the bytes streamed — close to 2× the decode ceiling — while activations
stay bf16 (the int8→bf16 convert fuses into the matmul operand on the
MXU). This also mirrors what the reference's serving stack actually does:
Ollama/llama.cpp serves quantized GGUF by default (reference
src/adapters/local-llm.ts reaches 4-bit llama.cpp kernels), so bf16-only
serving would be racing a quantized baseline with one leg tied.

Representation: each big matmul weight leaf becomes a dict
  {"q": int8[w.shape], "s": act_dtype[kept axes]}
where `s` = absmax/127 over the einsum-CONTRACTED axes (w ≈ q * s with s
broadcast over the kept/output axes). models/common.py's `_einsum` and
`embed_tokens` dequantize by scaling the matmul OUTPUT — a fusable
elementwise multiply — never materializing a bf16 copy of the weight.
Norm weights stay untouched (tiny, accuracy-critical).

Quantization runs AFTER shard_params: q/s are computed with jnp ops on
the already-sharded weights, so XLA propagates the NamedShardings (q
inherits the weight's, s keeps the kept axes') and no separate spec tree
is needed. Absmax over a sharded contracted axis costs one all-reduce at
load time.

Scope: every serving path — the main InferenceEngine (dense + flash
attention, contiguous + paged KV, MoE), the pipeline engine (quantized
leaves stack per stage), and the ring/Ulysses sequence-parallel prefill
— all of which reach weights exclusively through the quant-aware
_einsum/embed_tokens accessors.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from .models.common import ModelConfig, Params

# Per weight key: the axes KEPT by the scale (the einsum's non-contracted
# weight axes, which land trailing in the matmul output).
_SCALE_AXES: dict[str, tuple[int, ...]] = {
    "q_proj": (1, 2),      # [E, H, D] → s[H, D]
    "k_proj": (1, 2),      # [E, K, D] → s[K, D]
    "v_proj": (1, 2),
    "o_proj": (2,),        # [H, D, E] → s[E]
    "gate_proj": (1,),     # dense [E, F] → s[F]
    "up_proj": (1,),
    "down_proj": (1,),     # dense [F, E] → s[E]
    "router": (1,),        # [E, X] → s[X]
    "embedding": (0,),     # [V, E] → s[V] (row scale: lookup AND lm head)
    "lm_head": (0,),
}
_EXPERT_SCALE_AXES = {
    "gate_proj": (0, 2),   # [X, E, F] → s[X, F]  ("bte,xef->btxf")
    "up_proj": (0, 2),
    "down_proj": (2,),     # [X, F, E] → s[E]     ("btxf,xfe->bte")
}


def quantized(leaf: Any) -> bool:
    return isinstance(leaf, dict) and "q" in leaf and "s" in leaf


def _quantize_leaf(w, scale_axes: tuple[int, ...], act_dtype,
                   free_source: bool) -> dict[str, Any]:
    scale_axes = tuple(a % w.ndim for a in scale_axes)
    reduce_axes = tuple(a for a in range(w.ndim) if a not in scale_axes)
    w32 = w.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(w32), axis=reduce_axes)
    s = jnp.maximum(absmax, 1e-8) / 127.0
    s_full = jnp.expand_dims(s, reduce_axes)
    q = jnp.clip(jnp.round(w32 / s_full), -127, 127).astype(jnp.int8)
    out = {"q": q, "s": s.astype(act_dtype)}
    if free_source and isinstance(w, jax.Array):
        # Free each source leaf the moment its int8 replacement exists:
        # quantizing a 7B-class model then peaks at bf16-total + ONE
        # leaf's q instead of bf16-total + int8-total — the difference
        # between fitting and OOMing a 16 GB chip during engine build.
        jax.block_until_ready(out)
        w.delete()
    return out


def quantize_params(params: Params, cfg: ModelConfig,
                    act_dtype=jnp.bfloat16,
                    free_source: bool = False) -> Params:
    """Quantize the big matmul weights; returns a new tree (norms and any
    unrecognized leaves pass through untouched).

    free_source=True deletes each source weight buffer as soon as its
    quantized replacement is materialized — the caller must own `params`
    (every serving engine does: the init/load tree is not referenced
    after quantization). Pass-through leaves are never deleted."""
    out: Params = {}
    for key, value in params.items():
        if key in ("embedding", "lm_head"):
            out[key] = _quantize_leaf(value, _SCALE_AXES[key], act_dtype,
                                      free_source)
        elif key == "layers":
            out[key] = [_quantize_layer(layer, act_dtype, free_source)
                        for layer in value]
        else:
            out[key] = value
    return out


def _quantize_layer(layer: dict[str, Any], act_dtype,
                    free_source: bool) -> dict[str, Any]:
    new: dict[str, Any] = {}
    for key, value in layer.items():
        if key == "experts":
            new[key] = {k: _quantize_leaf(v, _EXPERT_SCALE_AXES[k],
                                          act_dtype, free_source)
                        for k, v in value.items()}
        elif key in _SCALE_AXES and "norm" not in key:
            new[key] = _quantize_leaf(value, _SCALE_AXES[key], act_dtype,
                                      free_source)
        else:
            new[key] = value
    return new


def _spec_for_scale(spec, scale_axes: tuple[int, ...]):
    """PartitionSpec for a scale leaf: `s` keeps exactly `scale_axes` of
    the weight, so its spec keeps those axes' entries (a spec shorter
    than the weight's rank means trailing dims are unsharded)."""
    from jax.sharding import PartitionSpec as P
    entries = tuple(spec) if spec is not None else ()
    return P(*(entries[a] if a < len(entries) else None
               for a in scale_axes))


def quantized_specs(specs: Params) -> Params:
    """Transform a param PartitionSpec tree (sharding.param_specs) into
    the spec tree matching quantize_params' OUTPUT structure: each
    quantized weight spec becomes {"q": spec, "s": kept-axes spec}, so a
    quantized tree can be explicitly placed (the PP engine stacks leaves
    itself and cannot rely on jit sharding propagation).

    Mirrors quantize_params/_quantize_layer key-for-key; keep the two in
    sync when a new weight becomes quantizable."""
    out: Params = {}
    for key, value in specs.items():
        if key in ("embedding", "lm_head"):
            out[key] = {"q": value,
                        "s": _spec_for_scale(value, _SCALE_AXES[key])}
        elif key == "layers":
            out[key] = [_quantized_layer_specs(layer) for layer in value]
        else:
            out[key] = value
    return out


def _quantized_layer_specs(layer: dict[str, Any]) -> dict[str, Any]:
    new: dict[str, Any] = {}
    for key, value in layer.items():
        if key == "experts":
            new[key] = {k: {"q": v,
                            "s": _spec_for_scale(v, _EXPERT_SCALE_AXES[k])}
                        for k, v in value.items()}
        elif key in _SCALE_AXES and "norm" not in key:
            new[key] = {"q": value,
                        "s": _spec_for_scale(value, _SCALE_AXES[key])}
        else:
            new[key] = value
    return new
