"""Model registry — the reference-targeted open-weight families
(BASELINE.md configs: Gemma-2B/7B, Llama-3-8B/3.2, Mistral-7B) plus
Mixtral (MoE), Qwen2.5 (attention bias) and tiny test presets.
Architecture behavior lives in ModelConfig flags (common.py); a family
here is a named hyperparameter set.
"""

from __future__ import annotations

from .common import ModelConfig

_REGISTRY: dict[str, ModelConfig] = {}


def register(cfg: ModelConfig) -> ModelConfig:
    _REGISTRY[cfg.name] = cfg
    return cfg


# --- Gemma (GeGLU, scaled embeddings, RMSNorm 1+w, tied head) ---

GEMMA_2B = register(ModelConfig(
    name="gemma-2b-it", vocab_size=256_000, num_layers=18, embed_dim=2048,
    num_heads=8, num_kv_heads=1, head_dim=256, mlp_dim=16_384,
    max_seq_len=8192, gelu_mlp=True, scale_embeddings=True,
    rmsnorm_unit_offset=True, tie_embeddings=True))

GEMMA_7B = register(ModelConfig(
    name="gemma-7b-it", vocab_size=256_000, num_layers=28, embed_dim=3072,
    num_heads=16, num_kv_heads=16, head_dim=256, mlp_dim=24_576,
    max_seq_len=8192, gelu_mlp=True, scale_embeddings=True,
    rmsnorm_unit_offset=True, tie_embeddings=True))

# --- Llama 3 (SiLU, GQA, untied head, big rope theta) ---

LLAMA3_8B = register(ModelConfig(
    name="llama-3-8b-instruct", vocab_size=128_256, num_layers=32,
    embed_dim=4096, num_heads=32, num_kv_heads=8, head_dim=128,
    mlp_dim=14_336, max_seq_len=8192, rope_theta=500_000.0,
    norm_eps=1e-5, tie_embeddings=False))

LLAMA32_1B = register(ModelConfig(
    name="llama-3.2-1b-instruct", vocab_size=128_256, num_layers=16,
    embed_dim=2048, num_heads=32, num_kv_heads=8, head_dim=64,
    mlp_dim=8192, max_seq_len=8192, rope_theta=500_000.0,
    norm_eps=1e-5, tie_embeddings=True))

LLAMA32_3B = register(ModelConfig(
    name="llama-3.2-3b-instruct", vocab_size=128_256, num_layers=28,
    embed_dim=3072, num_heads=24, num_kv_heads=8, head_dim=128,
    mlp_dim=8192, max_seq_len=8192, rope_theta=500_000.0,
    norm_eps=1e-5, tie_embeddings=True))

# --- Mistral (SiLU, GQA, sliding window) ---

MISTRAL_7B = register(ModelConfig(
    name="mistral-7b-instruct", vocab_size=32_000, num_layers=32,
    embed_dim=4096, num_heads=32, num_kv_heads=8, head_dim=128,
    mlp_dim=14_336, max_seq_len=8192, rope_theta=1_000_000.0,
    norm_eps=1e-5, sliding_window=4096, tie_embeddings=False))

# --- Qwen2.5 (SiLU, GQA, attention bias, tied head at small sizes) ---

QWEN25_1_5B = register(ModelConfig(
    name="qwen2.5-1.5b-instruct", vocab_size=151_936, num_layers=28,
    embed_dim=1536, num_heads=12, num_kv_heads=2, head_dim=128,
    mlp_dim=8960, max_seq_len=8192, rope_theta=1_000_000.0,
    norm_eps=1e-6, attn_bias=True, tie_embeddings=True))

# --- Mixtral (SiLU, GQA, sparse MoE, sliding window in v0.1 only) ---

MIXTRAL_8X7B = register(ModelConfig(
    name="mixtral-8x7b-instruct", vocab_size=32_000, num_layers=32,
    embed_dim=4096, num_heads=32, num_kv_heads=8, head_dim=128,
    mlp_dim=14_336, max_seq_len=8192, rope_theta=1_000_000.0,
    norm_eps=1e-5, tie_embeddings=False,
    num_experts=8, num_experts_per_tok=2))

# --- tiny presets: CPU tests, sharding dry-runs, CI ---

TINY_GEMMA = register(ModelConfig(
    name="tiny-gemma", vocab_size=512, num_layers=2, embed_dim=64,
    num_heads=4, num_kv_heads=2, head_dim=16, mlp_dim=128,
    max_seq_len=512, gelu_mlp=True, scale_embeddings=True,
    rmsnorm_unit_offset=True, tie_embeddings=True))

TINY_LLAMA = register(ModelConfig(
    name="tiny-llama", vocab_size=512, num_layers=2, embed_dim=64,
    num_heads=4, num_kv_heads=2, head_dim=16, mlp_dim=128,
    max_seq_len=512, tie_embeddings=False))

TINY_MISTRAL = register(ModelConfig(
    name="tiny-mistral", vocab_size=512, num_layers=2, embed_dim=64,
    num_heads=4, num_kv_heads=2, head_dim=16, mlp_dim=128,
    max_seq_len=512, sliding_window=64, tie_embeddings=False))

TINY_QWEN = register(ModelConfig(
    name="tiny-qwen", vocab_size=512, num_layers=2, embed_dim=64,
    num_heads=4, num_kv_heads=2, head_dim=16, mlp_dim=128,
    max_seq_len=512, attn_bias=True, tie_embeddings=True))

TINY_MIXTRAL = register(ModelConfig(
    name="tiny-mixtral", vocab_size=512, num_layers=2, embed_dim=64,
    num_heads=4, num_kv_heads=2, head_dim=16, mlp_dim=128,
    max_seq_len=512, tie_embeddings=False,
    num_experts=4, num_experts_per_tok=2))


def get_model_config(name: str, **overrides) -> ModelConfig:
    """Look up a family by name; unknown names raise with the known list."""
    if name not in _REGISTRY:
        known = ", ".join(sorted(_REGISTRY))
        raise ValueError(f"Unknown model '{name}'. Known: {known}")
    cfg = _REGISTRY[name]
    if overrides:
        import dataclasses
        cfg = dataclasses.replace(cfg, **overrides)
    return cfg


def list_models() -> list[str]:
    return sorted(_REGISTRY)
