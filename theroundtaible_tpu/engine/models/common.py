"""Transformer core shared by Gemma / Llama / Mistral — pure functional JAX.

This is the TPU-native replacement for the llama.cpp compute the reference
reaches through Ollama/LM Studio (reference src/adapters/local-llm.ts;
SURVEY.md §2.3). Design rules (SURVEY.md §7, pallas_guide):

- params are plain nested-dict pytrees (no framework state), so sharding is
  a pure tree_map of NamedSharding over the same structure
- everything below `jit` is static-shape, scan/cond only — no Python control
  flow on data
- matmuls run in bf16 with f32 accumulation (preferred_element_type), norms
  and softmax in f32: MXU-friendly, numerically safe
- attention is GQA with an explicit KV-cache slot axis; decode attends with
  a length mask instead of dynamic shapes
- architecture differences (GeGLU vs SiLU, embedding scaling, RMSNorm +1,
  sliding window, logit softcap) are ModelConfig flags, not subclasses
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp

Params = dict[str, Any]

# Masked-attention-logit sentinel — finite (not -inf) so a fully-masked row
# softmaxes to uniform instead of NaN. Shared with the Pallas kernels so
# dense and flash masking semantics cannot drift apart.
MASK_VALUE = -2.3819763e38

# SPMD mesh context: the engine sets this (at trace time, inside its jit'd
# programs) so attention() can wrap the Pallas kernels in shard_map on a
# multi-device mesh. A trace-time Python context, not a traced value — the
# mesh is static per compiled program. Thread-local because distinct
# engines (fleet submeshes) trace concurrently from different threads —
# a shared stack would hand one engine's mesh to another's trace.
import threading as _threading

_MESH_CTX = _threading.local()


class spmd_mesh:
    """Context manager announcing the mesh the enclosing jit traces
    under. `int4_sink`, when given, is a dict the int4 einsum dispatch
    records path provenance into at TRACE time (one entry per distinct
    (spec, shapes) dispatch — see _record_int4): engines pass their own
    dict so describe()/stats can report which path each compiled
    dispatch actually took."""

    def __init__(self, mesh, int4_sink=None):
        self.mesh = mesh
        self.int4_sink = int4_sink

    def __enter__(self):
        stack = getattr(_MESH_CTX, "stack", None)
        if stack is None:
            stack = _MESH_CTX.stack = []
        stack.append((self.mesh, self.int4_sink))
        return self.mesh

    def __exit__(self, *exc):
        _MESH_CTX.stack.pop()
        return False


def current_spmd_mesh():
    stack = getattr(_MESH_CTX, "stack", None)
    return stack[-1][0] if stack else None


def _current_int4_sink():
    stack = getattr(_MESH_CTX, "stack", None)
    return stack[-1][1] if stack else None


class _ManualLocalMesh:
    """Mesh sentinel for FULLY-MANUAL regions (the PP engine's stage
    bodies on pipe-only meshes): every array there is device-local and
    full-size, so single-device kernel dispatch is correct even though
    the enclosing program spans many devices. `size` mirrors Mesh so
    every existing `mesh.size` branch takes its single-device arm.
    Distinct from an UNSET context — "no announcement" still must never
    be mistaken for "single device" (a trace under GSPMD with no
    context keeps the XLA path)."""

    size = 1

    def __repr__(self):
        return "ManualLocalMesh()"


LOCAL_MESH = _ManualLocalMesh()


# Path-provenance labels for int4 einsum dispatches (ISSUE 3): the next
# hardware window's numbers must be attributable to the kernel, not a
# silent fallback, so every Int4Leaf dispatch records which path it
# compiled to — into the engine-owned sink the enclosing spmd_mesh
# carries.
PATH_KERNEL = "pallas_w4a16"
PATH_XLA = "xla_dequant"


def _record_int4(spec: str, a, leaf, path: str, reason=None) -> None:
    sink = _current_int4_sink()
    if sink is None:
        return
    entry = {"spec": spec, "a_shape": list(a.shape),
             "w_shape": list(leaf.q4.shape[:-1]) + [leaf.q4.shape[-1] * 2],
             "path": path}
    if reason:
        entry["fallback_reason"] = reason
    sink[(spec, tuple(a.shape), tuple(leaf.q4.shape))] = entry


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Architecture hyperparameters + family behavior flags."""

    name: str
    vocab_size: int
    num_layers: int
    embed_dim: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    mlp_dim: int
    max_seq_len: int = 8192
    rope_theta: float = 10_000.0
    norm_eps: float = 1e-6
    # family flags
    gelu_mlp: bool = False            # Gemma: GeGLU; Llama/Mistral: SiLU
    scale_embeddings: bool = False    # Gemma: embeddings *= sqrt(embed_dim)
    rmsnorm_unit_offset: bool = False  # Gemma: weight is (1 + w)
    post_attn_norm: bool = False      # Gemma2-style extra norms
    post_mlp_norm: bool = False
    attn_logit_softcap: Optional[float] = None   # Gemma2: 50.0
    final_logit_softcap: Optional[float] = None  # Gemma2: 30.0
    sliding_window: Optional[int] = None         # Mistral: 4096
    query_pre_attn_scalar: Optional[float] = None  # Gemma: head_dim**-0.5 default
    attn_bias: bool = False           # Qwen2: bias on q/k/v projections
    tie_embeddings: bool = True       # output head = embedding table
    # MoE (Mixtral): None = dense MLP; X experts, top-k routed
    num_experts: Optional[int] = None
    num_experts_per_tok: int = 2
    # runtime implementation choice, not architecture: "dense" = XLA einsum
    # attention; "flash" = Pallas blockwise kernels (engine/pallas/) that
    # stream KV through VMEM and skip blocks beyond each row's valid length
    attn_impl: str = "dense"

    @property
    def kv_repeat(self) -> int:
        return self.num_heads // self.num_kv_heads


# --- primitives ---


def rms_norm(x: jax.Array, weight: jax.Array, eps: float,
             unit_offset: bool) -> jax.Array:
    dtype = x.dtype
    x = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    x = x * jax.lax.rsqrt(var + eps)
    w = (1.0 + weight.astype(jnp.float32)) if unit_offset \
        else weight.astype(jnp.float32)
    return (x * w).astype(dtype)


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary position embedding. x: [B, T, H, D], positions: [B, T]."""
    head_dim = x.shape[-1]
    fraction = jnp.arange(0, head_dim // 2, dtype=jnp.float32) / (head_dim // 2)
    timescale = theta ** fraction                       # [D/2]
    angles = positions[..., None].astype(jnp.float32) / timescale  # [B,T,D/2]
    angles = angles[:, :, None, :]                      # [B, T, 1, D/2]
    sin, cos = jnp.sin(angles), jnp.cos(angles)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _softcap(x: jax.Array, cap: Optional[float]) -> jax.Array:
    if cap is None:
        return x
    return cap * jnp.tanh(x / cap)


@dataclasses.dataclass
class Int4Leaf:
    """Packed w4a16 weight (engine/quant.py, bits=4): two SIGNED nibbles
    per int8 byte along the weight's LAST axis (even element in the low
    nibble), with per-`group` absmax scales — `s4` has q4's logical
    shape except the last axis holds n_groups. Dequantization
    (`dequant_int4`) is `lax.bitcast_convert_type(int8 → 2×int4)` —
    whose nibble pair expands minor-most, exactly matching the last-axis
    pack — followed by convert, minor-dim reshapes, and the grouped
    scale multiply: no shifts, no interleaving shuffle, so XLA/Mosaic
    fuses the chain into the consuming matmul's operand read and HBM
    streams the PACKED bytes: ~4.25 bits/param vs int8's 8 — llama.cpp's
    own default serving precision class (reference adapters go through
    4-bit GGUF). An earlier revision packed along the einsum-contracted
    axis and unpacked with a stack+reshape interleave; on real TPU that
    shuffle broke operand fusion and decode measured SLOWER than bf16
    (BENCH_r05: 22.9 tok/s vs bf16's 130) — the last-axis/bitcast layout
    exists to keep the unpack inside the matmul fusion.

    `axis` is always q4.ndim-1 at pack time and is kept as metadata so
    spec mirroring (quantized_specs) and PP stage-stacking round-trip
    the treedef; packing minor-most makes it invariant under the PP
    engine's leading stage-stack. axis/group are static pytree metadata
    (register_dataclass), so tree_map / sharding / param-byte accounting
    see only q4/s4 arrays.
    """

    q4: jax.Array
    s4: jax.Array
    axis: int
    group: int


jax.tree_util.register_dataclass(
    Int4Leaf, data_fields=("q4", "s4"), meta_fields=("axis", "group"))


def dequant_int4(q4: jax.Array, s4: jax.Array, axis: int, group: int,
                 dtype) -> jax.Array:
    """Unpack + scale a last-axis int4-packed weight back to `dtype`.

    bitcast int8 → [..., 2]·int4 puts the low nibble at [..., 0], which
    is exactly the even-low/odd-high pack order, so the unpack is a
    bitcast + convert + minor-dim merge — every reshape here touches
    only trailing dims, so the whole chain stays fusable into the
    consuming matmul operand on TPU (no cross-lane shuffle). `axis`
    must be the last axis (the only layout the packer emits). On jax
    runtimes whose int8→int4 bitcast cannot lower (0.4.x), the compat
    seam substitutes a shift/stack unpack with identical numerics
    (compat.unpack_int4_pairs)."""
    assert axis == q4.ndim - 1, "int4 pack axis must be minor-most"
    from ..compat import unpack_int4_pairs
    pairs = unpack_int4_pairs(q4)                        # [..., n/2, 2]
    shape = list(q4.shape)
    shape[-1] *= 2
    w = pairs.astype(dtype).reshape(shape)               # [..., n]
    grouped = shape[:-1] + [shape[-1] // group, group]
    w = w.reshape(grouped) * s4[..., None].astype(dtype)
    return w.reshape(shape)


def _einsum(spec: str, a: jax.Array, b, tp=None, lora=None) -> jax.Array:
    # bf16 inputs, f32 accumulation on the MXU. An int8-quantized weight
    # ({"q", "s"} dict, engine/quant.py) streams half the HBM bytes: the
    # int8→activation-dtype convert fuses into the matmul operand and the
    # per-output-channel scale applies to the OUTPUT (the scale axes are
    # the weight's non-contracted axes, which land trailing). An int4
    # leaf streams a quarter: its grouped dequant is elementwise, so it
    # rides the same operand fusion.
    #
    # `tp` is the call site's TP convention hint for the shard-aware
    # int4 kernel dispatch — "col" (column-parallel: q/k/v, gate/up,
    # lm head) or "row" (row-parallel: o_proj, down_proj), mirroring
    # sharding.param_specs (see sharding.int4_shard_axis). Ignored for
    # every non-int4 leaf and on single-device meshes.
    #
    # `lora` names this call site's LoRA target leaf (ISSUE 10):
    # when the enclosing trace announced a lora_scope (engine/lora.py,
    # the spmd_mesh pattern), the per-row/per-token adapter delta
    # `x·A_id^T·B_id` is added to the base output — grouped Pallas
    # kernel or XLA grouped BMM, every routing decision recorded into
    # the engine's lora_paths sink. Untagged call sites (lm head, MoE
    # experts, router) and traces with no active scope are untouched.
    y = _einsum_base(spec, a, b, tp)
    if lora is not None:
        from ..lora import apply_current
        y = apply_current(lora, a, y, tp=tp)
    return y


def _einsum_base(spec: str, a: jax.Array, b, tp=None) -> jax.Array:
    if isinstance(b, Int4Leaf):
        # Fused VMEM-dequant kernels — the only layout that actually
        # streams packed int4 bytes on real TPU (pallas/int4mm.py; XLA
        # materializes this dequant, BENCH_r05). Gate: the kernel is
        # emitted ONLY where the enclosing program explicitly announced
        # its mesh (spmd_mesh — every engine jit does). A 1-device mesh
        # (or a fully-manual region announcing LOCAL_MESH) dispatches
        # the raw kernel; a multi-device mesh goes through
        # einsum_int4_spmd, which re-partitions the matmul and runs the
        # kernel per shard inside shard_map — a bare pallas_call under
        # GSPMD would be an opaque, unpartitionable custom call. Traces
        # with NO announced mesh keep the XLA path: "no context" must
        # never be mistaken for "single device". Every routing decision
        # is recorded into the engine's provenance sink.
        mesh = current_spmd_mesh()
        from ..pallas import int4mm
        if mesh is None:
            # No context ⇒ no sink either (they share the stack entry),
            # so this fallback is inherently unattributed — engines
            # always announce, so only direct forward() callers land
            # here.
            pass
        elif not int4mm.enabled():
            _record_int4(spec, a, b, PATH_XLA, "kernel-disabled")
        else:
            if mesh.size == 1:
                y, reason = int4mm.einsum_int4_or_reason(spec, a, b)
            else:
                y, reason = int4mm.einsum_int4_spmd(mesh, spec, a, b,
                                                    tp=tp)
            if y is not None:
                _record_int4(spec, a, b, PATH_KERNEL)
                return y
            _record_int4(spec, a, b, PATH_XLA, reason)
        return jnp.einsum(spec, a,
                          dequant_int4(b.q4, b.s4, b.axis, b.group,
                                       a.dtype),
                          preferred_element_type=jnp.float32)
    if isinstance(b, dict) and "q" in b:
        y = jnp.einsum(spec, a, b["q"].astype(a.dtype),
                       preferred_element_type=jnp.float32)
        s = b["s"].astype(jnp.float32)
        return y * s.reshape((1,) * (y.ndim - s.ndim) + s.shape)
    return jnp.einsum(spec, a, b, preferred_element_type=jnp.float32)


def embed_tokens(emb, tokens: jax.Array) -> jax.Array:
    """Embedding lookup; quantized tables dequantize per looked-up row.
    The result's dtype follows the param dtype (s carries it)."""
    if isinstance(emb, Int4Leaf):
        # rows gather keeps the packed axis (1 → tokens.ndim after the
        # gather); dequant only the looked-up rows
        rows_q = emb.q4[tokens]
        rows_s = emb.s4[tokens]
        return dequant_int4(rows_q, rows_s, tokens.ndim, emb.group,
                            emb.s4.dtype)
    if isinstance(emb, dict) and "q" in emb:
        rows = emb["q"][tokens].astype(emb["s"].dtype)
        return rows * emb["s"][tokens][..., None]
    return emb[tokens]


def project_qkv(
    x: jax.Array,                 # [B, T, E]
    layer: Params,
    cfg: ModelConfig,
    positions: jax.Array,         # [B, T] absolute positions
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """QKV projection + rope + query scaling.

    Shared by dense attention below and the sequence-parallel cores in
    longcontext.py (which replace only the softmax(QK)V part)."""
    q = _einsum("bte,ehd->bthd", x, layer["q_proj"], tp="col",
                lora="q_proj")                                  # [B,T,H,D]
    k = _einsum("bte,ekd->btkd", x, layer["k_proj"], tp="col",
                lora="k_proj")                                  # [B,T,K,D]
    v = _einsum("bte,ekd->btkd", x, layer["v_proj"], tp="col",
                lora="v_proj")

    if cfg.attn_bias:  # Qwen2: linear bias applied BEFORE rotary (HF order)
        q = q + layer["q_bias"].astype(jnp.float32)
        k = k + layer["k_bias"].astype(jnp.float32)
        v = v + layer["v_bias"].astype(jnp.float32)

    q = rope(q.astype(x.dtype), positions, cfg.rope_theta)
    k = rope(k.astype(x.dtype), positions, cfg.rope_theta)
    v = v.astype(x.dtype)

    scale = (cfg.query_pre_attn_scalar
             if cfg.query_pre_attn_scalar is not None
             else cfg.head_dim ** -0.5)
    return q * scale, k, v


def attention(
    x: jax.Array,                 # [B, T, E]
    layer: Params,
    cfg: ModelConfig,
    positions: jax.Array,         # [B, T] absolute positions
    kv_cache: Optional[tuple[jax.Array, jax.Array]],  # each [B, S, K, D]
    cache_offset: Optional[jax.Array],  # [B] write offset into the cache
    attn_mask: jax.Array,         # [B, T, S] boolean, True = attend
    kv_valid: Optional[jax.Array] = None,  # [B] valid entries after step
) -> tuple[jax.Array, tuple[jax.Array, jax.Array]]:
    """GQA attention with in-place cache update.

    Returns (output [B,T,E], updated (k_cache, v_cache)). When kv_cache is
    None the k/v of this call form the cache (prefill from scratch).
    """
    q, k, v = project_qkv(x, layer, cfg, positions)

    if kv_cache is not None:
        k_cache, v_cache = kv_cache
        # Scatter this step's K/V into each batch row at its own offset.
        def write_row(cache_row, new_row, off):
            return jax.lax.dynamic_update_slice(
                cache_row, new_row, (off, 0, 0))
        k_cache = jax.vmap(write_row)(k_cache, k, cache_offset)
        v_cache = jax.vmap(write_row)(v_cache, v, cache_offset)
        k_all, v_all = k_cache, v_cache
    else:
        k_all, v_all = k, v
        k_cache, v_cache = k, v

    if cfg.attn_impl == "flash" and kv_valid is not None:
        from ..pallas import attention as pattn
        t = q.shape[1]
        out = None
        mesh = current_spmd_mesh()
        if mesh is not None and mesh.size > 1:
            # multi-device: kernels under shard_map (kv heads on "model",
            # rows on "data"); None = not partitionable → dense below
            out = pattn.flash_attention_spmd(
                mesh, q, k_all, v_all, positions[:, 0], kv_valid,
                sliding_window=cfg.sliding_window,
                softcap=cfg.attn_logit_softcap)
        elif pattn.supported(t, k_all.shape[1], cfg.head_dim):
            if t > 1:
                out = pattn.flash_prefill_attention(
                    q, k_all, v_all, positions[:, 0], kv_valid,
                    sliding_window=cfg.sliding_window,
                    softcap=cfg.attn_logit_softcap)
            else:
                out = pattn.ragged_decode_attention(
                    q, k_all, v_all, kv_valid,
                    sliding_window=cfg.sliding_window,
                    softcap=cfg.attn_logit_softcap)
        if out is not None:
            out = _einsum("bthd,hde->bte", out, layer["o_proj"],
                          tp="row", lora="o_proj").astype(x.dtype)
            return out, (k_cache, v_cache)

    # GQA: expand K/V heads to match query heads.
    if cfg.kv_repeat > 1:
        k_att = jnp.repeat(k_all, cfg.kv_repeat, axis=2)
        v_att = jnp.repeat(v_all, cfg.kv_repeat, axis=2)
    else:
        k_att, v_att = k_all, v_all

    logits = _einsum("bthd,bshd->bhts", q, k_att)        # [B,H,T,S] f32
    logits = _softcap(logits, cfg.attn_logit_softcap)
    logits = jnp.where(attn_mask[:, None, :, :], logits, MASK_VALUE)
    probs = jax.nn.softmax(logits, axis=-1).astype(x.dtype)
    out = _einsum("bhts,bshd->bthd", probs, v_att).astype(x.dtype)
    out = _einsum("bthd,hde->bte", out, layer["o_proj"],
                  tp="row", lora="o_proj").astype(x.dtype)
    return out, (k_cache, v_cache)


def mlp(x: jax.Array, layer: Params, cfg: ModelConfig) -> jax.Array:
    if cfg.num_experts:
        return moe_mlp(x, layer, cfg)
    gate = _einsum("bte,ef->btf", x, layer["gate_proj"], tp="col",
                   lora="gate_proj")
    up = _einsum("bte,ef->btf", x, layer["up_proj"], tp="col",
                 lora="up_proj")
    act = jax.nn.gelu(gate, approximate=True) if cfg.gelu_mlp \
        else jax.nn.silu(gate)
    hidden = (act * up).astype(x.dtype)
    return _einsum("btf,fe->bte", hidden, layer["down_proj"],
                   tp="row", lora="down_proj").astype(x.dtype)


def moe_mlp(x: jax.Array, layer: Params, cfg: ModelConfig) -> jax.Array:
    """Mixtral-style sparse MoE, computed expert-dense for SPMD.

    Router picks top-k experts per token (softmax over the top-k logits,
    Mixtral semantics); the expert matmuls run batched over a leading
    expert axis and combine under the routing weights in one contraction.
    Compute-dense-combine-sparse is the EP-friendly layout: the expert
    axis shards on the mesh's "model" axis (sharding.param_specs), every
    device runs its local experts for all tokens, and the combining
    einsum's contraction over the sharded axis becomes one XLA all-reduce
    over ICI — no ragged per-expert token dispatch, fully static shapes.
    (A top-k gather path saves FLOPs at large batch; tracked as a future
    kernel.)
    """
    experts = layer["experts"]
    x_dim = cfg.num_experts
    k = cfg.num_experts_per_tok

    router_logits = _einsum("bte,ex->btx", x, layer["router"])   # f32
    top_vals, top_idx = jax.lax.top_k(router_logits, k)          # [B,T,k]
    gates = jax.nn.softmax(top_vals, axis=-1)                    # Mixtral
    # dense routing weights [B,T,X]: sum of gate * one_hot(expert)
    weights = jnp.sum(
        jax.nn.one_hot(top_idx, x_dim, dtype=jnp.float32)
        * gates[..., None], axis=-2)

    gate_h = _einsum("bte,xef->btxf", x, experts["gate_proj"])
    up_h = _einsum("bte,xef->btxf", x, experts["up_proj"])
    act = jax.nn.gelu(gate_h, approximate=True) if cfg.gelu_mlp \
        else jax.nn.silu(gate_h)
    # routing weights fold into the hidden activations elementwise, so the
    # final contraction (sharded expert axis → one all-reduce) is a plain
    # two-operand matmul
    hidden = (act * up_h * weights[..., None]).astype(x.dtype)
    out = _einsum("btxf,xfe->bte", hidden, experts["down_proj"])
    return out.astype(x.dtype)


def transformer_block(
    x: jax.Array, layer: Params, cfg: ModelConfig, positions: jax.Array,
    kv_cache, cache_offset, attn_mask, attn_fn=None, kv_valid=None,
) -> tuple[jax.Array, tuple[jax.Array, jax.Array]]:
    """One block. `attn_fn(h, layer) -> (out, (k, v))`, when given, replaces
    dense attention — the hook longcontext.py uses to drop in ring/Ulysses
    sequence-parallel cores while keeping the norm/residual/MLP wiring (and
    every family flag) in exactly one place."""
    h = rms_norm(x, layer["input_norm"], cfg.norm_eps, cfg.rmsnorm_unit_offset)
    if attn_fn is None:
        attn_out, new_cache = attention(h, layer, cfg, positions, kv_cache,
                                        cache_offset, attn_mask, kv_valid)
    else:
        attn_out, new_cache = attn_fn(h, layer)
    if cfg.post_attn_norm:
        attn_out = rms_norm(attn_out, layer["post_attn_norm"], cfg.norm_eps,
                            cfg.rmsnorm_unit_offset)
    x = x + attn_out
    h = rms_norm(x, layer["pre_mlp_norm"], cfg.norm_eps,
                 cfg.rmsnorm_unit_offset)
    mlp_out = mlp(h, layer, cfg)
    if cfg.post_mlp_norm:
        mlp_out = rms_norm(mlp_out, layer["post_mlp_norm"], cfg.norm_eps,
                           cfg.rmsnorm_unit_offset)
    return x + mlp_out, new_cache


def make_attention_mask(positions: jax.Array, kv_len: int,
                        kv_valid_len: jax.Array,
                        sliding_window: Optional[int]) -> jax.Array:
    """Causal (+ optional sliding window) mask against a padded KV cache.

    positions: [B, T] query absolute positions; kv_valid_len: [B] number of
    valid cache entries per row. Cache layout is position-aligned (entry s
    holds position s), so causality is pos_kv <= pos_q AND s < valid.
    """
    kv_pos = jnp.arange(kv_len)[None, None, :]           # [1,1,S]
    q_pos = positions[:, :, None]                        # [B,T,1]
    mask = kv_pos <= q_pos
    mask &= kv_pos < kv_valid_len[:, None, None]
    if sliding_window is not None:
        mask &= kv_pos > q_pos - sliding_window
    return mask


def forward(
    params: Params, cfg: ModelConfig,
    tokens: jax.Array,            # [B, T]
    positions: jax.Array,         # [B, T]
    kv_caches: Optional[list[tuple[jax.Array, jax.Array]]],
    cache_offset: Optional[jax.Array],   # [B]
    kv_valid_len: jax.Array,      # [B] valid entries AFTER this step
    last_pos: Optional[jax.Array] = None,   # [B] row index into T
) -> tuple[jax.Array, list[tuple[jax.Array, jax.Array]]]:
    """Full model forward. Returns (logits [B,T,V], updated caches) —
    or (logits [B,1,V]) when `last_pos` is given: the hidden state is
    gathered at last_pos BEFORE the lm-head matmul, so prefill never
    materializes full-sequence logits. On a 256k-vocab model a batched
    [B,T,V] f32 logits temp is gigabytes (B=3, T=2048 ≈ 6.3 GB — it
    OOM'd the 3-knight discuss bench on a v5e chip, BENCH_r05) and XLA
    cannot push the caller's post-hoc dynamic slice back through the
    einsum; callers that only need the last valid row must pass
    last_pos instead of slicing the result."""
    # Activations follow the param dtype: bf16 params (serving) keep the
    # whole network bf16; f32 params (HF logit-parity tests) stay f32.
    x = embed_tokens(params["embedding"], tokens)
    if cfg.scale_embeddings:
        x = x * jnp.sqrt(jnp.float32(cfg.embed_dim)).astype(x.dtype)

    kv_len = (kv_caches[0][0].shape[1] if kv_caches is not None
              else tokens.shape[1])
    mask = make_attention_mask(positions, kv_len, kv_valid_len,
                               cfg.sliding_window)

    new_caches = []
    for i, layer in enumerate(params["layers"]):
        cache_i = kv_caches[i] if kv_caches is not None else None
        x, new_cache = transformer_block(
            x, layer, cfg, positions, cache_i, cache_offset, mask,
            kv_valid=kv_valid_len)
        new_caches.append(new_cache)

    x = rms_norm(x, params["final_norm"], cfg.norm_eps,
                 cfg.rmsnorm_unit_offset)
    if last_pos is not None:
        x = gather_rows(x, last_pos)
    head = params["embedding"] if cfg.tie_embeddings else params["lm_head"]
    logits = _einsum("bte,ve->btv", x, head, tp="col")
    logits = _softcap(logits, cfg.final_logit_softcap)
    return logits, new_caches


def gather_rows(x: jax.Array, pos: jax.Array) -> jax.Array:
    """Gather one T-row per batch element: [B,T,E], [B] → [B,1,E]."""
    idx = jnp.broadcast_to(pos[:, None, None],
                           (x.shape[0], 1, x.shape[2]))
    return jnp.take_along_axis(x, idx, axis=1)


# --- initialization ---


def init_params(cfg: ModelConfig, key: jax.Array,
                dtype=jnp.bfloat16) -> Params:
    """Random init with sane scales — used for tests and weight-free bench."""
    k_embed, k_layers = jax.random.split(key)

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, dtype=jnp.float32)
                * (fan_in ** -0.5)).astype(dtype)

    layers = []
    layer_keys = jax.random.split(k_layers, cfg.num_layers)
    e, h, k_, d, f = (cfg.embed_dim, cfg.num_heads, cfg.num_kv_heads,
                      cfg.head_dim, cfg.mlp_dim)
    for lk in layer_keys:
        ks = jax.random.split(lk, 8)
        layer = {
            "q_proj": dense(ks[0], (e, h, d), e),
            "k_proj": dense(ks[1], (e, k_, d), e),
            "v_proj": dense(ks[2], (e, k_, d), e),
            "o_proj": dense(ks[3], (h, d, e), h * d),
            "input_norm": jnp.zeros((e,), dtype) if cfg.rmsnorm_unit_offset
            else jnp.ones((e,), dtype),
            "pre_mlp_norm": jnp.zeros((e,), dtype) if cfg.rmsnorm_unit_offset
            else jnp.ones((e,), dtype),
        }
        if cfg.num_experts:
            x_ = cfg.num_experts
            layer["router"] = dense(ks[7], (e, x_), e)
            layer["experts"] = {
                "gate_proj": dense(ks[4], (x_, e, f), e),
                "up_proj": dense(ks[5], (x_, e, f), e),
                "down_proj": dense(ks[6], (x_, f, e), f),
            }
        else:
            layer.update({
                "gate_proj": dense(ks[4], (e, f), e),
                "up_proj": dense(ks[5], (e, f), e),
                "down_proj": dense(ks[6], (f, e), f),
            })
        if cfg.attn_bias:
            bks = jax.random.split(jax.random.fold_in(lk, 9), 3)
            layer["q_bias"] = (jax.random.normal(bks[0], (h, d), jnp.float32)
                               * 0.02).astype(dtype)
            layer["k_bias"] = (jax.random.normal(bks[1], (k_, d), jnp.float32)
                               * 0.02).astype(dtype)
            layer["v_bias"] = (jax.random.normal(bks[2], (k_, d), jnp.float32)
                               * 0.02).astype(dtype)
        if cfg.post_attn_norm:
            layer["post_attn_norm"] = layer["input_norm"]
        if cfg.post_mlp_norm:
            layer["post_mlp_norm"] = layer["pre_mlp_norm"]
        layers.append(layer)

    params: Params = {
        "embedding": (jax.random.normal(
            k_embed, (cfg.vocab_size, cfg.embed_dim), jnp.float32)
            * (cfg.embed_dim ** -0.5)).astype(dtype),
        "layers": layers,
        "final_norm": jnp.zeros((cfg.embed_dim,), dtype)
        if cfg.rmsnorm_unit_offset else jnp.ones((cfg.embed_dim,), dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(
            jax.random.fold_in(k_embed, 1),
            (cfg.vocab_size, cfg.embed_dim), cfg.embed_dim)
    return params


def param_count(params: Params) -> int:
    """Logical parameter count: an Int4Leaf's packed byte holds TWO
    parameters, so it counts 2·q4.size (+ scales, matching how int8
    counts q + s)."""
    leaves = jax.tree_util.tree_leaves(
        params, is_leaf=lambda x: isinstance(x, Int4Leaf))
    total = 0
    for x in leaves:
        if isinstance(x, Int4Leaf):
            total += 2 * x.q4.size + x.s4.size
        else:
            total += x.size
    return total
