"""Multi-host process-group initialization (SURVEY.md §5.8).

The reference's "distributed backend" is child-process pipes and HTTP
fetches (reference src/adapters/*.ts, SURVEY.md §5.8); the TPU-native
equivalent is `jax.distributed.initialize`: every host in a pod slice (or
across slices over DCN) starts the same program, the coordinator wires the
process group, and `jax.devices()` then reports the GLOBAL device set —
`build_mesh` and every NamedSharding/pjit program in the engine work
unchanged on top, with XLA routing collectives over ICI within a slice
and DCN across slices.

Operation (mirrors the standard JAX multi-host recipe):

    ROUNDTABLE_COORDINATOR=10.0.0.2:8476 \\
    ROUNDTABLE_NUM_PROCESSES=4 ROUNDTABLE_PROCESS_ID=0 \\
    roundtable discuss "..."

Every process must build identical meshes (deterministic here: meshes are
derived from config + jax.devices()). Axis-placement guidance for
multi-slice: keep "model" (TP — latency-critical all-reduces) inside a
slice on ICI; put "data" (DP — independent slot batches, no per-token
collectives) across slices so only DCN-tolerant traffic crosses slices.

Unset ROUNDTABLE_COORDINATOR → no-op, single-process behavior identical
(this is what the driver's dryrun and the test suite exercise).

Executed, not just hooked: tests/test_distributed.py spawns two real
processes that form the group, run a TP forward whose model axis spans
the process boundary, and serve the production engine end to end with
identical generations on both hosts (host-read program outputs are
pinned replicated — engine.py host_read — so every process's host loop
stays in lockstep).
"""

from __future__ import annotations

import os
import threading

_init_lock = threading.Lock()
_initialized = False


def maybe_init_distributed() -> bool:
    """Initialize the JAX process group when ROUNDTABLE_COORDINATOR is
    set. Returns True when this call (or an earlier one) initialized it,
    False for single-process runs. Idempotent; never raises for the
    single-process case."""
    global _initialized
    coordinator = os.environ.get("ROUNDTABLE_COORDINATOR")
    if not coordinator:
        return False
    with _init_lock:
        if _initialized:
            return True
        import jax
        num_processes = int(os.environ.get("ROUNDTABLE_NUM_PROCESSES", "1"))
        process_id = int(os.environ.get("ROUNDTABLE_PROCESS_ID", "0"))
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id)
        _initialized = True
        return True


def process_info() -> dict:
    """This process's view of the group (for metrics/describe)."""
    import jax
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }
