"""Self-drafting speculative decoding on the shared batch (ISSUE 9).

int8 decode sits at 0.63-0.69 of the HBM-streaming ceiling — past
kernel wins the only way above the roofline is accepting more than one
token per forward pass. This module is the HOST side of that: a
zero-model drafter over each row's own token history, the acceptance
rule, and the per-row adaptive throttle. The DEVICE side is the PR-8
ragged seam: a verify dispatch packs each speculating row's drafts as a
short multi-token run in the flat token buffer and scores every draft
position in ONE forward (engine._ragged_dispatch with a static
`score_width` — build_ragged_batch shapes stay a function of the token
budget alone, so mixed 1-draft/4-draft compositions compile nothing).

Why a drafter with no model works here: roundtable transcripts are
unusually repetitive — quoted proposals, score scaffolding, and knight
boilerplate recur verbatim across rounds — so an n-gram lookup over the
row's OWN prompt (which carries the whole transcript) plus its
committed output proposes long runs that the target model then verifies
wholesale. RTP-LLM (PAPERS.md) ships the same composition — speculation
folded into continuous batching — in production.

Acceptance (the output-invariance contract):

- The verify run for a row is ``[last, d_0, ..., d_{k-1}]`` fed at
  positions ``valid..valid+k``. The causal mask means the scored logits
  at the row of ``last`` are EXACTLY what plain decode would compute,
  the logits at ``d_0`` are exact given ``d_0`` in context, and so on.
- Greedy: the device returns per-position argmax ``t_0..t_k``; the
  accepted prefix is the longest ``j`` with ``d_j == t_j`` and the row
  commits ``t_0..t_a`` (the first mismatch — or the bonus token after a
  fully-accepted draft — rides free). Byte-identical to 1-token decode
  by construction.
- Sampled: the device SAMPLES ``t_j`` from each position's filtered
  distribution (the same sample_token_batch the decode loop uses) and
  the host accepts while ``d_j == t_j``. For a DETERMINISTIC drafter
  (point mass at ``d_j``) this is exactly standard rejection sampling:
  acceptance fires with probability ``p(d_j)``, and the first
  mismatching ``t_j`` is distributed as the renormalized residual — so
  the emitted stream is an exact ancestral sample of the target model.

Rollback is free: rejected tail tokens only wrote K/V at positions
beyond the new committed ``valid``; every later dispatch's ``kv_valid``
stops at committed+written, so stale cells are never read and are
overwritten in place when real tokens reach those positions. The prefix
cache can never attach them either — PagedKVCache.commit publishes only
pages fully covered by the LITERAL committed token list (the paging
refcount surface), and rejected bytes live past it by definition.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional

from .prefix_cache import env_flag

SPEC_ENV = "ROUNDTABLE_SPEC_DECODE"

# Drafts per row per verify dispatch (config `spec_max_draft`). The
# default keeps a row's verify run (1 + drafts) inside ONE
# RAGGED_BLOCK_Q tile, so a speculating batch packs exactly like a
# plain ragged decode batch.
DEFAULT_MAX_DRAFT = 4

# Longest n-gram the drafter keys on; it backs off to shorter grams
# when the longer suffix has no prior occurrence.
NGRAM_MAX = 3

# Adaptive throttle: after at least SPEC_MIN_DISPATCHES verify
# dispatches, a row whose windowed acceptance rate (accepted drafts /
# drafted) sits below the floor stops drafting — drafting must never
# cost a slow row more dispatches than plain decode buys back.
# ROUNDTABLE_SPEC_ACCEPT_FLOOR raises/lowers the floor: on a high-RTT
# tunnel, where a verify dispatch's host round-trip is dearer than the
# pipelined while-loop's hidden one, a modest-acceptance row can be
# net-slower than plain decode without ever dropping below the default
# — the operator lever until the on-chip A/B settles the break-even.
SPEC_WINDOW = 16
SPEC_MIN_DISPATCHES = 6
SPEC_ACCEPT_FLOOR = 0.2


def accept_floor() -> float:
    import os
    raw = os.environ.get("ROUNDTABLE_SPEC_ACCEPT_FLOOR")
    try:
        return float(raw) if raw else SPEC_ACCEPT_FLOOR
    except ValueError:
        return SPEC_ACCEPT_FLOOR


def spec_enabled(flag: Optional[bool]) -> bool:
    """The speculative-decode on/off decision for a paged+ragged engine
    (explicit config wins, then the env kill-switch, then default ON —
    the prefix_cache/ragged_attn precedent: the fast path is the
    serving path, not an experiment)."""
    return env_flag(flag, SPEC_ENV)


class NGramDrafter:
    """Hash-indexed n-gram / prompt-lookup proposer over ONE row's
    corpus: its (transcript-carrying, prefix-cache-attached) prompt plus
    every committed output token, indexed incrementally as tokens
    retire.

    For each gram order n in NGRAM_MAX..1 the index maps the token
    tuple to the END positions of its two most recent occurrences. A
    draft looks up the context's tail gram and proposes the tokens that
    FOLLOWED it last time; the second-most-recent slot exists because
    the tail gram's own occurrence is always the most recent one and
    carries no continuation."""

    __slots__ = ("_toks", "_index")

    def __init__(self, tokens: Optional[list[int]] = None):
        self._toks: list[int] = []
        # gram tuple -> (last_end, prev_end); end = index AFTER the gram.
        self._index: dict[tuple, tuple[int, int]] = {}
        if tokens:
            self.extend(tokens)

    def __len__(self) -> int:
        return len(self._toks)

    def extend(self, tokens: list[int]) -> None:
        """Append committed tokens and index every new gram."""
        toks = self._toks
        start = len(toks)
        toks.extend(tokens)
        idx = self._index
        for end in range(start + 1, len(toks) + 1):
            for n in range(1, NGRAM_MAX + 1):
                if end < n:
                    break
                key = tuple(toks[end - n:end])
                prev = idx.get(key)
                if prev is None:
                    idx[key] = (end, -1)
                elif prev[0] != end:
                    idx[key] = (end, prev[0])

    def sync(self, context: list[int]) -> None:
        """Bring the index up to `context` (prompt + produced): extends
        with the suffix past what is already indexed, so the scheduler
        can call this before every draft regardless of which serving
        path appended the tokens."""
        if len(context) > len(self._toks):
            self.extend(context[len(self._toks):])

    def sync_parts(self, prompt: list[int], produced: list[int]) -> None:
        """sync(prompt + produced) without materializing the
        concatenation — the per-dispatch hot call (the prompt was
        indexed at construction, so only produced's tail is new)."""
        have = len(self._toks)
        need = len(prompt) + len(produced)
        if need > have:
            self.extend(produced[have - len(prompt):])

    def draft(self, max_n: int) -> list[int]:
        """Up to `max_n` candidate continuation tokens of the indexed
        context, from the most recent PRIOR occurrence of the longest
        matching tail gram; [] when nothing matches (the row then runs
        plain 1-token decode this step)."""
        toks = self._toks
        if max_n < 1 or not toks:
            return []
        for n in range(min(NGRAM_MAX, len(toks)), 0, -1):
            entry = self._index.get(tuple(toks[len(toks) - n:]))
            if entry is None:
                continue
            last, prev = entry
            # The tail gram itself is always the most recent occurrence;
            # a continuation needs an occurrence that ENDS before the
            # corpus does.
            pos = last if last < len(toks) else prev
            if pos is not None and 0 < pos < len(toks):
                return list(toks[pos:pos + max_n])
        return []


class RowSpec:
    """Per-row speculation state: the drafter plus the adaptive
    throttle's acceptance window."""

    __slots__ = ("drafter", "drafted", "accepted", "recent", "disabled")

    def __init__(self, prompt_tokens: list[int]):
        self.drafter = NGramDrafter(prompt_tokens)
        self.drafted = 0
        self.accepted = 0
        # (drafted, accepted) per verify dispatch that actually drafted.
        self.recent: deque = deque(maxlen=SPEC_WINDOW)
        self.disabled = False

    def rate(self) -> float:
        d = sum(x for x, _ in self.recent)
        return (sum(a for _, a in self.recent) / d) if d else 0.0

    def note(self, drafted: int, accepted: int) -> bool:
        """Record one verify dispatch's outcome. Returns True when THIS
        call tripped the throttle (the caller emits the one flight
        event)."""
        if drafted <= 0:
            return False
        self.drafted += drafted
        self.accepted += accepted
        self.recent.append((drafted, accepted))
        if (not self.disabled
                and len(self.recent) >= SPEC_MIN_DISPATCHES
                and self.rate() < accept_floor()):
            self.disabled = True
            return True
        return False


def accept_prefix(drafts: list[int],
                  proposals: list[int]) -> tuple[list[int], int]:
    """The acceptance rule: `proposals` are the device's per-position
    tokens for the run ``[last, d_0, ..., d_{k-1}]`` (len == k+1).
    Returns (emit, accepted): the committed tokens ``t_0..t_a`` —
    accepted drafts plus the correction/bonus token — and the accepted
    draft count a."""
    a = 0
    while a < len(drafts) and drafts[a] == proposals[a]:
        a += 1
    return list(proposals[:a + 1]), a


# --- test-visibility counters (tests/conftest.py `spec_decode` guard) ---

_lock = threading.Lock()
_drafted = 0
_accepted = 0
_dispatches = 0


def reset_test_counters() -> None:
    global _drafted, _accepted, _dispatches
    with _lock:
        _drafted = _accepted = _dispatches = 0


def note_spec_dispatch(drafted: int, accepted: int) -> None:
    global _drafted, _accepted, _dispatches
    with _lock:
        _drafted += drafted
        _accepted += accepted
        _dispatches += 1


def drafted_seen() -> int:
    return _drafted


def accepted_seen() -> int:
    return _accepted


def dispatches_seen() -> int:
    return _dispatches
