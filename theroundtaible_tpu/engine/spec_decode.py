"""Speculative decoding on the shared batch (ISSUE 9 + ISSUE 13).

int8 decode sits at 0.63-0.69 of the HBM-streaming ceiling — past
kernel wins the only way above the roofline is accepting more than one
token per forward pass. This module is the HOST side of that: the
drafter abstraction (n-gram, draft-model, LoRA-draft-head), the chain
and TREE acceptance rules, and the per-row adaptive throttle with
re-probe hysteresis. The DEVICE side is the PR-8 ragged seam: a verify
dispatch packs each speculating row's candidates as short multi-token
runs in the flat token buffer and scores every draft position in ONE
forward (engine._ragged_dispatch with a static `score_width` —
build_ragged_batch shapes stay a function of the token budget alone,
so mixed chain/tree/no-spec compositions compile nothing).

Drafters (ISSUE 13 — the `Drafter` protocol):

- ``ngram`` — the PR-9 zero-model prompt-lookup drafter. Roundtable
  transcripts are unusually repetitive (quoted proposals, score
  scaffolding, knight boilerplate recur verbatim across rounds), so an
  n-gram lookup over the row's OWN prompt plus committed output
  proposes long runs — but ONLY on scripted/repetitive traffic. On
  sampled real-weights traffic the lookup collapses and the throttle
  quietly turns speculation off fleet-wide (SPEC_r09's acceptance 1.0
  was a property of the scripted rounds, not the mechanism).
- ``model`` — a draft model served as EXTRA ROW SETS on the SAME
  engine: each target row gets a shadow draft slot in the same paged
  pool, and drafting dispatches are ordinary ragged dispatches with a
  `params` override (the draft checkpoint shares the ModelConfig
  shapes, so no second engine and no new compile shapes — different
  VALUES through already-warm programs). Default draft weights are the
  engine's own params (the distillation placeholder: zero extra HBM,
  proposals = the target's own greedy chain — on sampled traffic
  acceptance is then exactly the sampler's peakedness, which is what a
  well-distilled drafter approaches).
- ``lora`` — drafting as an ADAPTER: the draft head is a LoRA pair in
  the PR-10 `LoraStore`, so the drafter is hot-swappable per workload
  through the store's existing setter (zero recompiles), costs
  rank·(in+out) bytes, and draft rows ride the normal per-token
  adapter ids. RTP-LLM (PAPERS.md) ships draft-model speculation over
  continuous batching in production; the heterogeneous-LoRA-serving
  line motivates serving the drafter as just another adapter.

Acceptance (the output-invariance contract):

- The verify run for a chain row is ``[last, d_0, ..., d_{k-1}]`` fed
  at positions ``valid..valid+k``. The causal mask means the scored
  logits at the row of ``last`` are EXACTLY what plain decode would
  compute, the logits at ``d_0`` are exact given ``d_0`` in context,
  and so on.
- Greedy: the device returns per-position argmax ``t_0..t_k``; the
  accepted prefix is the longest ``j`` with ``d_j == t_j`` and the row
  commits ``t_0..t_a`` (the first mismatch — or the bonus token after a
  fully-accepted draft — rides free). Byte-identical to 1-token decode
  by construction.
- Sampled: the device SAMPLES ``t_j`` from each position's filtered
  distribution (the same sample_token_batch the decode loop uses) and
  the host accepts while ``d_j == t_j``. For a DETERMINISTIC drafter
  (point mass at ``d_j``) this is exactly standard rejection sampling:
  acceptance fires with probability ``p(d_j)``, and the first
  mismatching ``t_j`` is distributed as the renormalized residual — so
  the emitted stream is an exact ancestral sample of the target model.

Tree acceptance (ISSUE 13, `accept_tree`): a token TREE is expanded
into its root-to-leaf PATHS, each path a separate ``[last, path...]``
run of the SAME verify dispatch (per-path page tables keep sibling
K/V writes apart — engine/scheduler.py owns that metadata; causality
within each run is ordinary, which is why tree verify needs no new
Pallas kernel). The host then walks the tree from the root: at depth
j it takes the device's token for the CURRENT path at position j and
emits it — that token is a genuine target-model token (argmax or
exact sample) given the emitted prefix, so the emitted stream is
exact REGARDLESS of how the walk continues; if some path's node at
depth j equals the emitted token, the walk descends that path (its
deeper positions condition on exactly the accepted prefix) and the
edge counts as accepted. Greedy: at most one child can match the
argmax, so the walk is deterministic and byte-identical to 1-token
decode by the chain argument applied along the accepted path.
Sampled: each emitted token is one exact ancestral sample; matching a
point-mass child is precisely per-edge rejection sampling.

Rollback is free: rejected tail tokens only wrote K/V at positions
beyond the new committed ``valid``; every later dispatch's ``kv_valid``
stops at committed+written, so stale cells are never read and are
overwritten in place when real tokens reach those positions. The prefix
cache can never attach them either — PagedKVCache.commit publishes only
pages fully covered by the LITERAL committed token list (the paging
refcount surface), and rejected bytes live past it by definition.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Optional, Protocol, runtime_checkable

from .prefix_cache import env_flag

SPEC_ENV = "ROUNDTABLE_SPEC_DECODE"

DRAFTER_KINDS = ("ngram", "model", "lora")

# Drafts per row per verify dispatch (config `spec_max_draft`). The
# default keeps a row's verify run (1 + drafts) inside ONE
# RAGGED_BLOCK_Q tile, so a speculating batch packs exactly like a
# plain ragged decode batch.
DEFAULT_MAX_DRAFT = 4

# Longest n-gram the drafter keys on; it backs off to shorter grams
# when the longer suffix has no prior occurrence.
NGRAM_MAX = 3

# Adaptive throttle: after at least SPEC_MIN_DISPATCHES verify
# dispatches, a row whose windowed acceptance rate (accepted drafts /
# drafted) sits below the floor stops drafting — drafting must never
# cost a slow row more dispatches than plain decode buys back.
# ROUNDTABLE_SPEC_ACCEPT_FLOOR raises/lowers the floor: on a high-RTT
# tunnel, where a verify dispatch's host round-trip is dearer than the
# pipelined while-loop's hidden one, a modest-acceptance row can be
# net-slower than plain decode without ever dropping below the default
# — the operator lever until the on-chip A/B settles the break-even.
SPEC_WINDOW = 16
SPEC_MIN_DISPATCHES = 6
SPEC_ACCEPT_FLOOR = 0.2

# Re-probe hysteresis (ISSUE 13 satellite): a throttled row re-drafts
# ONCE every SPEC_REPROBE_DISPATCHES committed tokens (~dispatches while
# throttled) — a row whose context BECOMES draftable (the discussion
# looped back onto quoted scaffolding, the draft head warmed up)
# recovers speculation instead of decoding 1-token for the rest of its
# turn. A successful probe (its own acceptance >= the floor) re-enables
# with a FRESH window, so one stale all-zero window cannot instantly
# re-trip; a failed probe waits a whole interval again.
SPEC_REPROBE_DISPATCHES = 16


def accept_floor() -> float:
    import os
    raw = os.environ.get("ROUNDTABLE_SPEC_ACCEPT_FLOOR")
    try:
        return float(raw) if raw else SPEC_ACCEPT_FLOOR
    except ValueError:
        return SPEC_ACCEPT_FLOOR


def reprobe_interval() -> int:
    import os
    raw = os.environ.get("ROUNDTABLE_SPEC_REPROBE")
    try:
        n = int(raw) if raw else SPEC_REPROBE_DISPATCHES
    except ValueError:
        n = SPEC_REPROBE_DISPATCHES
    return max(n, 1)


def spec_enabled(flag) -> bool:
    """The speculative-decode on/off decision for a paged+ragged engine
    (explicit config wins, then the env kill-switch, then default ON —
    the prefix_cache/ragged_attn precedent: the fast path is the
    serving path, not an experiment). A dict config (ISSUE 13) decides
    through its optional "enabled" key, so `spec_decode: {drafter: ...}`
    keeps the ROUNDTABLE_SPEC_DECODE=0 kill-switch live while
    `{enabled: true, ...}` pins it on."""
    if isinstance(flag, dict):
        flag = flag.get("enabled")
    return env_flag(flag, SPEC_ENV)


class SpecOptions:
    """Resolved `spec_decode:` block (ISSUE 13). The config accepts the
    PR-9 bool OR a dict::

        spec_decode: {enabled?: bool, drafter: ngram|model|lora,
                      max_draft?: int, tree?: {branch: B, depth: D},
                      draft_checkpoint?: path, adapter?: name}

    Validation lives here so the engine constructor and from_config
    fail identically; drafter AVAILABILITY fallbacks (no lora store,
    say) are the engine's job and are recorded, not raised."""

    __slots__ = ("drafter", "tree", "max_draft", "draft_checkpoint",
                 "adapter")

    def __init__(self, drafter: str = "ngram",
                 tree: Optional[dict] = None,
                 max_draft: Optional[int] = None,
                 draft_checkpoint: Optional[str] = None,
                 adapter: Optional[str] = None):
        self.drafter = drafter
        self.tree = tree
        self.max_draft = max_draft
        self.draft_checkpoint = draft_checkpoint
        self.adapter = adapter

    @classmethod
    def resolve(cls, flag) -> "SpecOptions":
        if not isinstance(flag, dict):
            return cls()
        drafter = flag.get("drafter", "ngram")
        if drafter not in DRAFTER_KINDS:
            raise ValueError(
                f"spec_decode drafter must be one of {DRAFTER_KINDS}, "
                f"got {drafter!r}")
        tree = flag.get("tree") or None
        if tree is not None:
            if not isinstance(tree, dict):
                raise ValueError(
                    "spec_decode tree must be {branch: B, depth: D}")
            branch = int(tree.get("branch", 2))
            depth = int(tree.get("depth", 2))
            if branch < 2:
                raise ValueError(
                    f"spec_decode tree branch must be >= 2 (a 1-branch "
                    f"tree is the chain), got {branch}")
            if depth < 1:
                raise ValueError(
                    f"spec_decode tree depth must be >= 1, got {depth}")
            tree = {"branch": branch, "depth": depth}
        if drafter == "lora" and not flag.get("adapter"):
            raise ValueError(
                "spec_decode drafter 'lora' needs an `adapter:` name "
                "registered in the engine's lora: block")
        max_draft = flag.get("max_draft")
        return cls(drafter=drafter, tree=tree,
                   max_draft=(int(max_draft)
                              if max_draft is not None else None),
                   draft_checkpoint=flag.get("draft_checkpoint"),
                   adapter=flag.get("adapter"))


@runtime_checkable
class Drafter(Protocol):
    """Per-row host-side proposer (ISSUE 13). `sync_parts` brings the
    drafter's view up to the row's committed context before every
    draft; `draft` proposes one chain; `draft_paths` proposes up to
    `branch` root-distinct candidate paths for tree verify (chain
    drafters return a single-element list). NGramDrafter implements
    this directly; the model/LoRA drafters are device-batched across
    rows (DeviceDrafter below), so their per-row view is the draft
    slot the coordinator maintains."""

    kind: str

    def sync_parts(self, prompt: list[int],
                   produced: list[int]) -> None: ...

    def draft(self, max_n: int) -> list[int]: ...

    def draft_paths(self, max_n: int,
                    branch: int = 1) -> list[list[int]]: ...


class NGramDrafter:
    """Hash-indexed n-gram / prompt-lookup proposer over ONE row's
    corpus: its (transcript-carrying, prefix-cache-attached) prompt plus
    every committed output token, indexed incrementally as tokens
    retire.

    For each gram order n in NGRAM_MAX..1 the index maps the token
    tuple to the END positions of its two most recent occurrences. A
    draft looks up the context's tail gram and proposes the tokens that
    FOLLOWED it last time; the second-most-recent slot exists because
    the tail gram's own occurrence is always the most recent one and
    carries no continuation."""

    __slots__ = ("_toks", "_index")

    kind = "ngram"

    def __init__(self, tokens: Optional[list[int]] = None):
        self._toks: list[int] = []
        # gram tuple -> (last_end, prev_end); end = index AFTER the gram.
        self._index: dict[tuple, tuple[int, int]] = {}
        if tokens:
            self.extend(tokens)

    def __len__(self) -> int:
        return len(self._toks)

    def extend(self, tokens: list[int]) -> None:
        """Append committed tokens and index every new gram."""
        toks = self._toks
        start = len(toks)
        toks.extend(tokens)
        idx = self._index
        for end in range(start + 1, len(toks) + 1):
            for n in range(1, NGRAM_MAX + 1):
                if end < n:
                    break
                key = tuple(toks[end - n:end])
                prev = idx.get(key)
                if prev is None:
                    idx[key] = (end, -1)
                elif prev[0] != end:
                    idx[key] = (end, prev[0])

    def sync(self, context: list[int]) -> None:
        """Bring the index up to `context` (prompt + produced): extends
        with the suffix past what is already indexed, so the scheduler
        can call this before every draft regardless of which serving
        path appended the tokens."""
        if len(context) > len(self._toks):
            self.extend(context[len(self._toks):])

    def sync_parts(self, prompt: list[int], produced: list[int]) -> None:
        """sync(prompt + produced) without materializing the
        concatenation — the per-dispatch hot call (the prompt was
        indexed at construction, so only produced's tail is new)."""
        have = len(self._toks)
        need = len(prompt) + len(produced)
        if need > have:
            self.extend(produced[have - len(prompt):])

    def draft(self, max_n: int) -> list[int]:
        """Up to `max_n` candidate continuation tokens of the indexed
        context, from the most recent PRIOR occurrence of the longest
        matching tail gram; [] when nothing matches (the row then runs
        plain 1-token decode this step)."""
        paths = self.draft_paths(max_n, branch=1)
        return paths[0] if paths else []

    def draft_paths(self, max_n: int,
                    branch: int = 1) -> list[list[int]]:
        """Up to `branch` candidate continuation paths with DISTINCT
        first tokens (tree verify, ISSUE 13): the two stored
        occurrences of the longest matching tail gram propose the
        primary candidates, and shorter-gram backoff supplements extra
        branches only when the longer grams could not fill them — so
        `draft_paths(n, 1)[0]` is byte-identical to the PR-9 chain
        draft. [] when nothing matches."""
        toks = self._toks
        if max_n < 1 or not toks or branch < 1:
            return []
        paths: list[list[int]] = []
        seen_first: set[int] = set()
        for n in range(min(NGRAM_MAX, len(toks)), 0, -1):
            entry = self._index.get(tuple(toks[len(toks) - n:]))
            if entry is None:
                continue
            # The tail gram itself is always the most recent occurrence;
            # a continuation needs an occurrence that ENDS before the
            # corpus does.
            for pos in entry:
                if not 0 < pos < len(toks):
                    continue
                p = list(toks[pos:pos + max_n])
                if p and p[0] not in seen_first:
                    paths.append(p)
                    seen_first.add(p[0])
                    if len(paths) >= branch:
                        return paths
            if paths and branch == 1:
                return paths
        return paths


class RowSpec:
    """Per-row speculation state: the drafter plus the adaptive
    throttle's acceptance window and re-probe hysteresis (ISSUE 13:
    drafter-aware — `kind` labels the metrics, and a throttled row
    periodically re-probes instead of staying dark for its whole
    turn)."""

    __slots__ = ("drafter", "kind", "drafted", "accepted", "recent",
                 "disabled", "probing", "_idle_mark", "ctx")

    def __init__(self, prompt_tokens: Optional[list[int]] = None,
                 kind: str = "ngram"):
        # Device-batched drafters (model/lora) keep their state in the
        # draft slots the DeviceDrafter coordinator owns; only the
        # ngram drafter lives here per row.
        self.drafter = (NGramDrafter(prompt_tokens)
                        if kind == "ngram" else None)
        self.kind = kind
        self.drafted = 0
        self.accepted = 0
        # (drafted, accepted) per verify dispatch that actually drafted.
        self.recent: deque = deque(maxlen=SPEC_WINDOW)
        self.disabled = False
        # Re-probe bookkeeping: produced-token mark at throttle time —
        # pure function of row state, so the probe decision is
        # idempotent across the scheduler's probe and real calls.
        self.probing = False
        self._idle_mark = 0
        # Device-drafter context cache (prompt + produced), extended
        # O(delta) per tick by the scheduler instead of re-concatenated
        # O(transcript) — read-only inside DeviceDrafter.propose.
        self.ctx: Optional[list[int]] = None

    def rate(self) -> float:
        d = sum(x for x, _ in self.recent)
        return (sum(a for _, a in self.recent) / d) if d else 0.0

    def should_draft(self, produced_len: int) -> bool:
        """Whether this row drafts this tick: unthrottled rows always;
        throttled rows once every `reprobe_interval()` committed tokens
        (the re-probe — ISSUE 13 satellite). Once a probe fires it
        stays armed until the next note(), so the scheduler's probe
        call and the real segment see the same answer."""
        if not self.disabled:
            return True
        if self.probing:
            return True
        if produced_len - self._idle_mark >= reprobe_interval():
            self.probing = True
            return True
        return False

    def mark_idle(self, produced_len: int) -> None:
        """Restart the re-probe interval (called by the scheduler when
        a dispatch leaves the row throttled)."""
        self._idle_mark = produced_len

    def probe_failed(self, produced_len: int) -> None:
        """Resolve an armed probe that never reached a verify dispatch
        (the drafter proposed NOTHING for the probing row): clear the
        arm and restart the interval — otherwise `probing` stays True
        forever and the row pays per-tick draft host work for the rest
        of its turn, exactly the overhead the throttle exists to
        remove."""
        if self.probing:
            self.probing = False
            self._idle_mark = produced_len
            note_spec_reprobe(recovered=False)

    def note(self, drafted: int, accepted: int) -> bool:
        """Record one verify dispatch's outcome. Returns True when THIS
        call tripped the throttle (the caller emits the one flight
        event). A throttled row's re-probe RECOVERS here: when the
        probe's own acceptance clears the floor, the row re-enables
        with a fresh window (hysteresis — the stale all-zero window
        must not immediately re-trip it)."""
        if drafted <= 0:
            return False
        self.drafted += drafted
        self.accepted += accepted
        if self.disabled:
            self.probing = False
            if accepted / drafted >= accept_floor():
                self.disabled = False
                self.recent.clear()
                self.recent.append((drafted, accepted))
                note_spec_reprobe(recovered=True)
            else:
                self.recent.append((drafted, accepted))
                note_spec_reprobe(recovered=False)
            return False
        self.recent.append((drafted, accepted))
        if (len(self.recent) >= SPEC_MIN_DISPATCHES
                and self.rate() < accept_floor()):
            self.disabled = True
            return True
        return False


def accept_prefix(drafts: list[int],
                  proposals: list[int]) -> tuple[list[int], int]:
    """The chain acceptance rule: `proposals` are the device's
    per-position tokens for the run ``[last, d_0, ..., d_{k-1}]``
    (len == k+1). Returns (emit, accepted): the committed tokens
    ``t_0..t_a`` — accepted drafts plus the correction/bonus token —
    and the accepted draft count a."""
    a = 0
    while a < len(drafts) and drafts[a] == proposals[a]:
        a += 1
    return list(proposals[:a + 1]), a


def accept_tree(paths: list[list[int]],
                props: list[list[int]]) -> tuple[list[int], int, int]:
    """The tree acceptance walk (ISSUE 13): `paths[i]` is root-to-leaf
    candidate path i of the row's token tree, `props[i]` the device's
    per-position tokens for path i's run ``[last, paths[i]...]``
    (len == len(paths[i]) + 1, every position conditioned on path i's
    own prefix by the causal mask).

    Walk from the root: at depth j, emit the CURRENT path's device
    token `t = props[cur][j]` — an exact target-model token (argmax or
    sample) given the emitted prefix, so the output stream is exact no
    matter what happens next — then descend into any still-prefix-
    consistent path whose node j equals t (greedy: at most one child
    can match the argmax; sampled: matching a point-mass child is
    per-edge rejection sampling). Returns (emit, accepted_edges,
    winner_path): the committed tokens (accepted path nodes plus the
    correction/bonus token), how many tree edges were accepted, and
    the index of the path whose cells hold every accepted token's K/V
    (the page-adoption source — scheduler tentpole)."""
    emit: list[int] = []
    a, cur, j = 0, 0, 0
    alive = list(range(len(paths)))
    while True:
        t = int(props[cur][j])
        emit.append(t)
        alive = [i for i in alive
                 if len(paths[i]) > j and paths[i][j] == t]
        if not alive:
            return emit, a, cur
        cur = alive[0]
        a += 1
        j += 1


# --- device-batched drafters: draft model / LoRA draft head ---


class DraftUnavailable(RuntimeError):
    """Raised when the drafter cannot shadow the batch for a BENIGN
    capacity reason (no free slot for a draft slot, pool pressure) —
    the scheduler serves plain decode this tick with the reason on
    record. Deliberately distinct from device dispatch failures, which
    must flow into the donation-death / preempt-isolate ladder like
    any other ragged failure."""


DRAFT_SCOPE = "__spec_draft__"

# A draft run fed through the propose/extend dispatches never exceeds
# one RAGGED_BLOCK_Q tile, so the propose-variant program only ever
# compiles at the small end of the shape grid (engine.warmup warms
# exactly those shapes).
PROPOSE_RUN = 7


def draft_slot_name(row_name: str) -> str:
    """The shadow draft slot of a target row — namespaced under its own
    pseudo-session (kvcache.SESSION_SEP), so intra-session prefix
    DONATION can never move draft-model K/V into a real row (sessions
    are isolation domains and `__spec_draft__` is nobody's session).
    Draft slots are never committed, so the cross-session prefix cache
    never sees their pages either."""
    from .kvcache import SESSION_SEP
    return f"{DRAFT_SCOPE}{SESSION_SEP}{row_name}"


class DeviceDrafter:
    """Batch-level coordinator for the model/LoRA drafters (ISSUE 13
    tentpole): each target row gets a shadow DRAFT SLOT in the same
    paged pool ("extra row sets on the SAME engine"), kept in sync with
    the row's committed context and advanced autoregressively through
    ordinary ragged dispatches — a `params` override for the `model`
    kind (same pytree shapes, so no second engine and no new compiled
    programs), per-token adapter ids for the `lora` kind (drafting as a
    hot-swappable adapter on the PR-10 store).

    Per spec tick, `propose` runs:
      1. catch-up — plain ragged chunk dispatches feed each draft slot
         the target context it is missing (first tick: the whole
         prompt; steady state: the last verify's committed tokens);
         a diverged slot (a non-trunk tree path won) simply overwrites
         its stale cells in place, the established rollback contract.
      2. propose — ONE small dispatch scores every row's context tip;
         greedy argmax is the main chain's first node and, under tree
         config, `propose_width` top-k ids seed the root branches.
      3. extend — depth-1 plain 1-token dispatches grow the main chain
         through the draft model (root alternatives stay depth-1
         leaves: the draft slot's K/V follows the main chain only, and
         a verify that accepts an alternative root just makes the next
         catch-up overwrite from the divergence).

    The coordinator never commits draft slots (their pages can never
    enter the prefix cache) and keeps `slot.tokens` = REAL target
    context only — speculative extension cells beyond it are
    overwritten in place by the next catch-up, exactly like rejected
    verify drafts."""

    def __init__(self, kind: str, adapter_slot: int = 0,
                 params: Any = None):
        if kind not in ("model", "lora"):
            raise ValueError(f"DeviceDrafter kind must be model|lora, "
                             f"got {kind!r}")
        self.kind = kind
        self.adapter_slot = adapter_slot
        self.params = params  # None = the engine's own params
        self.draft_dispatches = 0

    # -- slot lifecycle --

    def end_row(self, engine, row_name: str) -> None:
        """Release the row's draft slot (scheduler retire/fail path)."""
        engine.kv.release(draft_slot_name(row_name))

    # -- the per-tick batched proposal --

    def _batch(self, engine, seqs, shape, propose_width=0):
        from .serving_loop import build_ragged_batch
        batch = build_ragged_batch(
            seqs, t_budget=shape, s_max=engine.kv.num_slots + 1,
            pages_per_seq=engine.kv.pages_per_seq,
            scratch_page=engine.kv.scratch_page(0),
            pad_id=engine.tokenizer.pad_id,
            page_size=engine.kv.page_size)
        batch["draft"] = True
        if propose_width:
            batch["propose_width"] = propose_width
        if self.params is not None:
            batch["draft_params"] = self.params
        return batch

    def propose(self, engine, rows, pinned=(),
                dispatch=None, read=None) -> dict:
        """rows: list of (key, row_name, ctx_tokens, depth, branch).
        Returns {key: [path, ...]} — the main chain plus up to
        branch-1 single-node root alternatives; every path non-empty.
        `dispatch`/`read` let the scheduler route the device calls
        through its run_dispatch/host_sync watchdog seams."""
        import numpy as np

        from .serving_loop import RAGGED_BLOCK_Q, RaggedSeq, \
            ragged_pick_shape

        if dispatch is None:
            dispatch = engine._ragged_dispatch
        if read is None:
            def read(h):
                # The propose dispatch returns (next_ids, top_k_ids)
                # when propose_width > 0; plain dispatches one array.
                if isinstance(h, tuple):
                    return tuple(np.asarray(x) for x in h)
                return np.asarray(h)
        kv = engine.kv
        temps = 0.0  # point-mass drafter: always greedy
        pinned = tuple(pinned) + tuple(
            draft_slot_name(name) for _, name, _, _, _ in rows)

        # 1. slots + capacity + catch-up plans. Capacity failures here
        # are BENIGN (the batch is too big to shadow — serve plain
        # decode, never evict live rows to draft for them) and must not
        # be confused with device dispatch failures below, which take
        # the ragged failure ladder.
        infos = []
        try:
            for key, name, ctx, depth, branch in rows:
                dname = draft_slot_name(name)
                st = kv.acquire(dname, pinned)
                common = kv.common_prefix_len(st.tokens, ctx)
                if common < len(st.tokens):
                    # Diverged (or freshly evicted): keep the common
                    # prefix, overwrite the rest in place.
                    st.tokens = st.tokens[:common]
                kv.ensure_capacity(dname, len(ctx) + depth,
                                   write_from=common, pinned=pinned)
                table = kv.table_for([dname])[0]
                infos.append({"key": key, "st": st, "ctx": list(ctx),
                              "depth": depth, "branch": branch,
                              "table": table})
        except RuntimeError as e:
            raise DraftUnavailable(str(e)) from e

        # 2. catch-up chunks until every remainder fits the propose run.
        while True:
            longs = [i for i in infos
                     if len(i["ctx"]) - len(i["st"].tokens) > PROPOSE_RUN]
            if not longs:
                break
            per_row = max((engine.ragged_tokens // len(longs))
                          // RAGGED_BLOCK_Q * RAGGED_BLOCK_Q,
                          RAGGED_BLOCK_Q)
            seqs, feeds = [], []
            for i in longs:
                done = len(i["st"].tokens)
                rem = len(i["ctx"]) - done
                take = min(rem - PROPOSE_RUN, per_row)
                if take < 1:
                    continue
                chunk = i["ctx"][done:done + take]
                seqs.append(RaggedSeq(chunk, done, i["table"],
                                      temperature=temps,
                                      adapter=self.adapter_slot))
                feeds.append((i, chunk))
            if not seqs:
                break
            want = sum(-(-len(s.tokens) // RAGGED_BLOCK_Q)
                       * RAGGED_BLOCK_Q for s in seqs)
            shape = ragged_pick_shape(engine.ragged_shapes,
                                      min(want, engine.ragged_tokens))
            read(dispatch(self._batch(engine, seqs, shape)))
            self.draft_dispatches += 1
            for i, chunk in feeds:
                i["st"].tokens = i["st"].tokens + chunk

        # 3. the propose dispatch: remainder runs (1..PROPOSE_RUN
        # tokens) score the context tip; top-k seeds the root branches.
        branch_max = max(i["branch"] for i in infos)
        seqs = []
        for i in infos:
            done = len(i["st"].tokens)
            rem = i["ctx"][done:]
            if not rem:
                # Fully caught up (a verify failed after the previous
                # propose advanced the slot): re-feed the last context
                # token — identical K/V bytes at its own position, and
                # the tip logits still come out.
                done -= 1
                rem = i["ctx"][-1:]
            assert 1 <= len(rem) <= PROPOSE_RUN
            seqs.append(RaggedSeq(rem, done, i["table"],
                                  temperature=temps,
                                  adapter=self.adapter_slot))
        shape = ragged_pick_shape(
            engine.ragged_shapes,
            min(RAGGED_BLOCK_Q * len(seqs), engine.ragged_tokens))
        out = read(dispatch(self._batch(
            engine, seqs, shape,
            propose_width=(branch_max if branch_max > 1 else 0))))
        self.draft_dispatches += 1
        if branch_max > 1:
            nxt, tops = out
        else:
            nxt, tops = out, None
        for idx, i in enumerate(infos):
            # Snapshot, never alias: the scheduler's per-row ctx cache
            # keeps growing across ticks, and an aliased st.tokens
            # growing with it would claim K/V the slot never received.
            i["st"].tokens = list(i["ctx"])
            c1 = int(nxt[idx])
            i["main"] = [c1]
            alts = []
            if tops is not None:
                for t in list(tops[idx])[:i["branch"]]:
                    t = int(t)
                    if t != c1 and t not in alts:
                        alts.append(t)
            i["alts"] = alts[:max(i["branch"] - 1, 0)]

        # 4. extend the main chain through the draft model.
        max_depth = max(i["depth"] for i in infos)
        for step in range(1, max_depth):
            seqs, growing = [], []
            for i in infos:
                if i["depth"] <= step:
                    continue
                pos = len(i["ctx"]) + step - 1
                seqs.append(RaggedSeq([i["main"][-1]], pos, i["table"],
                                      temperature=temps,
                                      adapter=self.adapter_slot))
                growing.append(i)
            if not seqs:
                break
            shape = ragged_pick_shape(
                engine.ragged_shapes,
                min(RAGGED_BLOCK_Q * len(seqs), engine.ragged_tokens))
            nxt = read(dispatch(self._batch(engine, seqs, shape)))
            self.draft_dispatches += 1
            for idx, i in enumerate(growing):
                i["main"].append(int(nxt[idx]))

        return {i["key"]: [i["main"]] + [[t] for t in i["alts"]]
                for i in infos}


# --- test-visibility counters (tests/conftest.py `spec_decode` guard) ---

_lock = threading.Lock()
_drafted = 0
_accepted = 0
_dispatches = 0
_tree_accepted_paths = 0
_tree_nodes = 0
_reprobes = 0
_reprobe_recoveries = 0


def reset_test_counters() -> None:
    global _drafted, _accepted, _dispatches, _tree_accepted_paths
    global _tree_nodes, _reprobes, _reprobe_recoveries
    with _lock:
        _drafted = _accepted = _dispatches = 0
        _tree_accepted_paths = _tree_nodes = 0
        _reprobes = _reprobe_recoveries = 0


def note_spec_dispatch(drafted: int, accepted: int) -> None:
    global _drafted, _accepted, _dispatches
    with _lock:
        _drafted += drafted
        _accepted += accepted
        _dispatches += 1


def note_tree_row(nodes: int, accepted_edges: int) -> None:
    """One multi-path (tree) row through a verify dispatch: `nodes`
    tree nodes packed, `accepted_edges` edges the walk accepted. A
    MULTI-NODE accepted path (>= 2 edges) is what the conftest
    `tree=True` guard requires — single-edge acceptance is
    indistinguishable from a lucky chain."""
    global _tree_nodes, _tree_accepted_paths
    with _lock:
        _tree_nodes += nodes
        if accepted_edges >= 2:
            _tree_accepted_paths += 1


def note_spec_reprobe(recovered: bool) -> None:
    global _reprobes, _reprobe_recoveries
    with _lock:
        _reprobes += 1
        if recovered:
            _reprobe_recoveries += 1


def drafted_seen() -> int:
    return _drafted


def accepted_seen() -> int:
    return _accepted


def dispatches_seen() -> int:
    return _dispatches


def tree_accepted_paths_seen() -> int:
    return _tree_accepted_paths


def tree_nodes_seen() -> int:
    return _tree_nodes


def reprobes_seen() -> int:
    return _reprobes


def reprobe_recoveries_seen() -> int:
    return _reprobe_recoveries


# ---------------------------------------------------------------------------
# static-analysis program registration (ISSUE 15)
# ---------------------------------------------------------------------------

from ..analysis.jaxpr_audit import (ProgramSpec, Variant,  # noqa: E402
                                    analysis_register)


@analysis_register("spec")
def _analysis_spec_programs(engine) -> list:
    """Speculative verify + propose program variants for the jaxpr
    audit — the same (score_width, s_max, copy_slots) and
    propose_width statics `_warm_ragged` compiles, traced device-free
    across the shape grid. Two verify compositions (one speculating
    row alone; speculating + plain rows mixed) share each shape label:
    acceptance drift and chain/tree mixes are VALUES, so extra
    distinct jaxprs under one label are a static-arg leak
    (RT-JAXPR-VARIANTS), and a host callback in a verify program is a
    per-verify host sync (RT-JAXPR-CALLBACK)."""
    if not getattr(engine, "spec_decode", False) \
            or not getattr(engine, "ragged_enabled", False):
        return []
    import numpy as np

    from .paged_forward import trace_ragged_batch
    from .serving_loop import RaggedSeq, build_ragged_batch
    kv = engine.kv
    scratch = kv.scratch_page(0)
    table = np.full((kv.pages_per_seq,), scratch, np.int32)
    r = engine.spec_max_draft + 1

    def batch(seqs, shape, score_width=0, s_max=None, copy_slots=0,
              propose_width=0):
        b = build_ragged_batch(
            seqs, t_budget=shape,
            s_max=s_max if s_max is not None else kv.num_slots + 1,
            pages_per_seq=kv.pages_per_seq, scratch_page=scratch,
            pad_id=engine.tokenizer.pad_id, page_size=kv.page_size,
            score_width=score_width, copy_slots=copy_slots)
        if propose_width:
            b["propose_width"] = propose_width
        return b

    def verify_variant(shape: int, mixed: bool) -> Variant:
        def thunk():
            seqs = [RaggedSeq([7] * r, 8, table, n_scores=r)]
            if mixed:
                seqs.append(RaggedSeq([9], 4, table, n_scores=1))
            return trace_ragged_batch(engine, batch(
                seqs, shape, score_width=r, s_max=engine.spec_s_max,
                copy_slots=engine.spec_copy_slots))
        return Variant(
            label=f"t{shape}", thunk=thunk,
            situation=("speculating+plain rows" if mixed
                       else "one speculating row") + f" in {shape}")

    specs = [ProgramSpec(
        name="spec_verify", phase="verify",
        variants=[verify_variant(shape, mixed)
                  for shape in engine.ragged_shapes
                  for mixed in (False, True)])]
    if engine.spec_branch > 1:
        def propose_variant(shape: int) -> Variant:
            def thunk():
                seqs = [RaggedSeq([7], 8, table),
                        RaggedSeq([9], 4, table)]
                return trace_ragged_batch(engine, batch(
                    seqs, shape, propose_width=engine.spec_branch))
            return Variant(label=f"t{shape}", thunk=thunk,
                           situation=f"propose in shape {shape}")
        specs.append(ProgramSpec(
            name="spec_propose", phase="propose",
            variants=[propose_variant(shape)
                      for shape in engine.ragged_shapes]))
    return specs
