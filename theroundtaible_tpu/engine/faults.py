"""Fault injection, retry/backoff, and circuit breakers.

The serving stack's failure story mirrors how it treats recompiles: a
device program that fails mid-request must DEGRADE, not crash the
discussion (RTP-LLM, arxiv 2605.29639, builds serving resilience around
bounded retry and degraded modes; the reference orchestrator survives
flaky cloud CLIs the same way). Three pieces live here:

- **Injection registry** — deterministic, config/env-armed fault points
  threaded through the engines (`mosaic_compile`, `dispatch`,
  `slow_dispatch`, `hbm_oom`, `kv_corrupt`; ISSUE 2 adds the TIME
  ladder's `hang` — a wedged wait the watchdog must classify, arming it
  auto-arms engine/deadlines.py — and `slow_wait`). Each point fires a fixed
  number of times then disarms, so a chaos test can assert "first
  dispatch fails, the retry serves". Unarmed injection is ZERO overhead
  by contract: every hot-path call site guards on the module-level
  `ARMED` flag (`if faults.ARMED: faults.maybe_inject(...)`) — one
  attribute load and branch, no dict lookups, no function call.
- **RetryPolicy** — a small backoff schedule shared by the serving loops
  and adapters. Transient dispatch failures retry in place; failure
  kinds where a blind retry cannot help (timeout — the deadline already
  passed; oom — the allocation will fail again; auth/not_installed)
  surface immediately so the next degradation rung handles them.
- **CircuitBreaker** — per-engine consecutive-failure tracking (the
  engine cache keys breakers the same way it keys engines, see
  engine/__init__.py). After `threshold` consecutive failures the
  `tpu-llm` adapter reports unavailable with the breaker's reason, which
  routes knights onto the orchestrator's existing runtime-fallback path
  instead of feeding more turns into a sick engine.

The degradation ladder these pieces implement (ARCHITECTURE.md "Fault
tolerance"): paged pool-direct → gather-view; batched round → serial
per-knight retry with invalidated KV slots; engine → adapter fallback.

Arming: `arm("dispatch", count=2)` in-process, or the environment at
import time — `ROUNDTABLE_FAULTS="dispatch:2,slow_dispatch:1@0.5"`
(point[:count][@delay_seconds]; count -1 = unlimited).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..utils import telemetry

# Module-level guard, the ONLY thing unarmed hot paths touch. Call sites
# read it as `faults.ARMED` so arm()/disarm() rebinding is visible.
ARMED = False

POINTS = ("mosaic_compile", "dispatch", "slow_dispatch", "hbm_oom",
          "kv_corrupt", "hang", "slow_wait", "device_lost",
          "engine_wedged")

# Messages are crafted so core.errors.classify_error maps each fault to
# the kind its real counterpart would carry ("hbm" → oom, "wedged" →
# hang, etc.).
_DEFAULT_MESSAGES = {
    "mosaic_compile": "injected fault: Mosaic kernel compilation failed "
                      "(scratch exceeds VMEM budget)",
    "dispatch": "injected fault: transient device dispatch failure",
    "slow_dispatch": "injected fault: slow dispatch",
    "hbm_oom": "injected fault: RESOURCE_EXHAUSTED: out of memory while "
               "allocating HBM",
    "kv_corrupt": "injected fault: corrupted KV slot detected",
    "hang": "injected fault: device dispatch wedged (hang)",
    "slow_wait": "injected fault: slow device wait",
    # ISSUE 12 supervisor-tier points. Messages classify via
    # core.errors: "(device_lost)" / "device is lost" hit the
    # device-lost markers (classified FIRST, non-retryable in place —
    # routed to the EngineSupervisor, never the dispatch retry);
    # engine_wedged carries the hang markers (the watchdog family) so
    # REPEATED firings model "hangs past the ladder" without an armed
    # watchdog — exactly what the supervisor's hang escalation counts.
    "device_lost": "injected fault: DATA_LOSS: device is lost "
                   "(device_lost)",
    "engine_wedged": "injected fault: device program wedged beyond the "
                     "dispatch ladder (hang)",
}

# Default sleep for an injected `hang` before it raises: long enough
# that an armed watchdog with a realistic rung budget fires FIRST (the
# wait is classified as a hang), short enough that an UNWATCHED chaos
# run still ladders through the raised FaultInjected within seconds.
_HANG_DEFAULT_DELAY_S = 5.0


class FaultInjected(RuntimeError):
    """Raised by an armed injection point; `point` names which one."""

    def __init__(self, message: str, point: str):
        super().__init__(message)
        self.point = point


@dataclass
class FaultSpec:
    point: str
    count: int = 1          # firings remaining; -1 = unlimited
    delay_s: float = 0.0    # slow_dispatch sleeps instead of raising
    message: str = ""
    fired: int = 0          # total firings (chaos-test assertions)


_registry: dict[str, FaultSpec] = {}

# True while THIS module is the reason the deadlines watchdog is armed
# (arming a hang/slow_wait point flipped it). An explicitly armed
# watchdog (arm_watchdog() / ROUNDTABLE_WATCHDOG=1, ACTIVE already True
# when the point armed) is never disarmed from here.
_watchdog_auto_armed = False

# The time-ladder points whose arming implies the watchdog.
_WATCHDOG_POINTS = ("hang", "slow_wait")


def _recompute_armed() -> None:
    global ARMED, _watchdog_auto_armed
    ARMED = any(s.count != 0 for s in _registry.values())
    if _watchdog_auto_armed and not any(
            s.count != 0 for p, s in _registry.items()
            if p in _WATCHDOG_POINTS):
        # Symmetric teardown: the chaos run that auto-armed the watchdog
        # is over (points exhausted or disarmed) — stop paying the
        # per-wait worker-thread cost on the now-healthy hot path.
        from . import deadlines
        deadlines.disarm_watchdog()
        _watchdog_auto_armed = False


def arm(point: str, count: int = 1, delay_s: float = 0.0,
        message: str = "") -> FaultSpec:
    """Arm an injection point for `count` firings (-1 = unlimited)."""
    if point not in POINTS:
        raise ValueError(f"unknown fault point {point!r} "
                         f"(known: {', '.join(POINTS)})")
    spec = FaultSpec(point=point, count=count, delay_s=delay_s,
                     message=message or _DEFAULT_MESSAGES[point])
    _registry[point] = spec
    if point in _WATCHDOG_POINTS:
        # The time-ladder chaos points only bite when the watchdog is
        # watching the waits — arming them arms it, so
        # ROUNDTABLE_FAULTS=hang is a one-variable chaos run. Remember
        # whether WE armed it (vs an operator's explicit arm), so point
        # exhaustion / disarm() tears it down symmetrically.
        from . import deadlines
        global _watchdog_auto_armed
        if not deadlines.ACTIVE:
            _watchdog_auto_armed = True
            deadlines.arm_watchdog()
    _recompute_armed()
    return spec


def disarm(point: Optional[str] = None) -> None:
    """Disarm one point, or every point when none is given."""
    if point is None:
        _registry.clear()
    else:
        _registry.pop(point, None)
    _recompute_armed()


def spec_for(point: str) -> Optional[FaultSpec]:
    return _registry.get(point)


def maybe_inject(point: str) -> None:
    """Fire `point` if armed: sleep for slow_dispatch, raise otherwise.
    Call sites MUST pre-guard with `if faults.ARMED:` — this function is
    never on an unarmed hot path."""
    spec = _registry.get(point)
    if spec is None or spec.count == 0:
        return
    if spec.count > 0:
        spec.count -= 1
        if spec.count == 0:
            _recompute_armed()
    spec.fired += 1
    # Chaos provenance in the shared registry (ISSUE 5): injected-fault
    # counts ride fleet_health/bench records next to the hang/breaker
    # series instead of living only in FaultSpec.fired.
    telemetry.inc("roundtable_faults_injected_total", point=point)
    telemetry.recorder().record("fault_injected", point=point)
    if point in ("slow_dispatch", "slow_wait"):
        time.sleep(spec.delay_s or 0.25)
        return
    if point == "hang":
        # Simulate a wedged device wait: block (inside the watchdog's
        # worker thread when one is watching), then RAISE rather than
        # proceed — an abandoned worker must never complete the real
        # dispatch and commit stale cache state behind the recovery
        # path. With the watchdog armed and a tighter rung budget, the
        # caller classifies the wait as a hang long before this sleep
        # ends; unwatched, the raise ladders like any dispatch fault.
        time.sleep(spec.delay_s or _HANG_DEFAULT_DELAY_S)
        raise FaultInjected(spec.message, point)
    raise FaultInjected(spec.message, point)


def inject_dispatch_faults() -> None:
    """The dispatch-stage points, in severity order. One call site in the
    serving loop covers transient failure, slowness, wedging, OOM and
    device loss."""
    maybe_inject("slow_dispatch")
    maybe_inject("slow_wait")
    maybe_inject("dispatch")
    maybe_inject("hang")
    maybe_inject("engine_wedged")
    maybe_inject("hbm_oom")
    maybe_inject("device_lost")


def _arm_from_env() -> None:
    """ROUNDTABLE_FAULTS="point[:count][@delay],..." parsed at import.
    Malformed entries warn and are skipped — the chaos knob must never
    itself take serving down with an import-time crash."""
    raw = os.environ.get("ROUNDTABLE_FAULTS", "")
    for entry in filter(None, (p.strip() for p in raw.split(","))):
        try:
            item, delay = entry, 0.0
            if "@" in item:
                item, d = item.rsplit("@", 1)
                delay = float(d)
            count = 1
            if ":" in item:
                item, c = item.rsplit(":", 1)
                count = int(c)
            arm(item, count=count, delay_s=delay)
        except ValueError as e:
            import warnings
            # Warn with the ORIGINAL entry, not the stripped-down
            # fragment — the operator needs to see which part was bad.
            warnings.warn(
                f"ignoring malformed ROUNDTABLE_FAULTS entry {entry!r}: "
                f"{e}")


_arm_from_env()


# --- degradation classification ---

# Failures of the pool-direct Pallas programs that the layout-agnostic
# gather-view path is expected to survive: kernel/compile trouble, not
# generic runtime errors (which retry or surface instead).
_DEGRADE_MARKERS = ("mosaic", "pallas", "vmem", "scratch", "kernel-legal",
                    "unsupported shapes", "not supported")


def is_kernel_failure(err: BaseException) -> bool:
    """Would routing around the Pallas kernel (gather-view fallback)
    plausibly clear this error?"""
    if isinstance(err, FaultInjected):
        return err.point == "mosaic_compile"
    msg = str(err).lower()
    return any(m in msg for m in _DEGRADE_MARKERS)


# --- retry policy ---

# Kinds where an immediate identical retry cannot succeed: the deadline
# already passed, the allocation will fail again, the config is wrong —
# or the device program is wedged (hang: the wait already consumed its
# rung budget and likely its donated buffers; only the adapter rung's
# revive + re-prefill helps). device_lost is the strongest: the chip
# itself is gone — nothing short of the supervisor's engine rebuild
# (engine/supervisor.py) can serve this config again.
_NO_RETRY_KINDS = ("timeout", "oom", "auth", "not_installed", "hang",
                   "device_lost")

# Message markers with the same property: a donated-then-failed dispatch
# leaves its inputs deleted, so re-running the identical program dies on
# the same dead buffers — only the adapter rung (revive + re-prefill)
# helps.
_NO_RETRY_MARKERS = ("has been deleted", "donated")


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry with exponential backoff, shared by the serving
    loops (device dispatch) and adapters (engine calls)."""

    max_retries: int = 1
    backoff_s: float = 0.05
    backoff_mult: float = 2.0

    def backoff(self, attempt: int) -> float:
        """Sleep before retry `attempt` (0-based)."""
        return self.backoff_s * (self.backoff_mult ** attempt)

    def retryable(self, err: BaseException) -> bool:
        if isinstance(err, (KeyboardInterrupt, SystemExit, TimeoutError)):
            return False
        msg = str(err).lower()
        if any(m in msg for m in _NO_RETRY_MARKERS):
            return False
        from ..core.errors import classify_error
        return classify_error(err) not in _NO_RETRY_KINDS

    def run(self, fn: Callable, deadline: float = float("inf"),
            on_retry: Optional[Callable[[int, BaseException], None]] = None):
        """fn() with up to max_retries retries on retryable failures,
        never sleeping past `deadline`."""
        for attempt in range(self.max_retries + 1):
            try:
                return fn()
            except Exception as e:  # noqa: BLE001 — policy decides
                if (attempt >= self.max_retries or not self.retryable(e)
                        or time.monotonic() >= deadline):
                    raise
                if on_retry is not None:
                    on_retry(attempt, e)
                pause = min(self.backoff(attempt),
                            max(deadline - time.monotonic(), 0.0))
                if pause > 0:
                    time.sleep(pause)


DEFAULT_RETRY = RetryPolicy()


# --- circuit breaker ---

@dataclass
class CircuitBreaker:
    """Consecutive-failure counter with a trip threshold. Open ⇒ the
    owner should report itself unavailable (with `reason`) until a
    success — or an explicit reset — closes it again.

    Thread-safe: the breaker is shared across every adapter of one
    resident engine, and the orchestrator dispatches batch groups from
    a thread pool — unsynchronized `failures += 1` read-modify-writes
    would lose counts, and racing should_attempt calls would admit
    several simultaneous half-open probes into a sick engine."""

    threshold: int = 3
    name: str = ""
    failures: int = 0
    total_failures: int = 0
    last_error: str = ""
    _probes: int = field(default=0, repr=False)
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False, compare=False)

    def record_failure(self, err: Optional[BaseException] = None) -> None:
        with self._lock:
            was_open = self.failures >= self.threshold
            self.failures += 1
            self.total_failures += 1
            if err is not None:
                self.last_error = str(err)
            tripped = not was_open and self.failures >= self.threshold
        telemetry.inc("roundtable_breaker_failures_total",
                      engine=self.name or "engine")
        if tripped:
            self._on_trip(err)

    def record_success(self) -> None:
        with self._lock:
            was_open = self.failures >= self.threshold
            self.failures = 0
            self._probes = 0
        if was_open:
            telemetry.set_gauge("roundtable_breaker_open", 0.0,
                                engine=self.name or "engine")

    def trip(self, err: Optional[BaseException] = None) -> None:
        """Force-open regardless of threshold, for failures known to be
        permanent rather than transient (engine construction: the
        checkpoint will not load better on the next call). A later
        success — e.g. a half-open probe after the operator fixes the
        config — still closes the breaker normally."""
        with self._lock:
            was_open = self.failures >= self.threshold
            self.failures = max(self.failures, self.threshold)
            self.total_failures += 1
            if err is not None:
                self.last_error = str(err)
        if not was_open:
            self._on_trip(err)

    def _on_trip(self, err: Optional[BaseException]) -> None:
        """Open transition: count + gauge in the shared registry, and
        ship a flight-recorder dump (ISSUE 5: a breaker trip is an
        incident — its postmortem writes itself). Runs OUTSIDE the
        breaker lock (snapshot() re-acquires it)."""
        name = self.name or "engine"
        telemetry.inc("roundtable_breaker_trips_total", engine=name)
        telemetry.set_gauge("roundtable_breaker_open", 1.0, engine=name)
        telemetry.recorder().record(
            "breaker_trip", engine=name, error=str(err or "")[:200])
        telemetry.flight_dump("breaker_trip", extra=self.snapshot())

    def reset(self) -> None:
        self.record_success()
        with self._lock:
            self.last_error = ""

    @property
    def is_open(self) -> bool:
        return self.failures >= self.threshold

    def should_attempt(self) -> bool:
        """False ⇒ the owner should fail fast. While open, every
        `threshold` fast-failed calls admits ONE half-open probe
        dispatch, so a recovered engine closes the breaker on the
        probe's success instead of staying blacklisted for the process
        lifetime (a probe that fails re-arms the full fast-fail window
        via record_failure)."""
        with self._lock:
            if self.failures < self.threshold:
                return True
            self._probes += 1
            if self._probes > self.threshold:
                self._probes = 0
                return True
            return False

    @property
    def reason(self) -> Optional[str]:
        if not self.is_open:
            return None
        return (f"circuit open after {self.failures} consecutive "
                f"failure(s) (threshold {self.threshold})"
                + (f": {self.last_error}" if self.last_error else ""))

    def snapshot(self) -> dict:
        return {"name": self.name, "open": self.is_open,
                "failures": self.failures,
                "total_failures": self.total_failures,
                "threshold": self.threshold,
                "last_error": self.last_error}
