"""Mesh construction and parameter/cache partition specs.

The scale-out design from SURVEY.md §2.3 / §5.8: shardings are expressed with
`jax.sharding.Mesh` + `NamedSharding(PartitionSpec)`, XLA inserts the
collectives (all-reduce for TP activations over ICI), nothing is hand-NCCL'd.

Axes:
- "data"  — batch/slot parallelism (DP): each replica serves different slots
- "model" — tensor parallelism (TP): attention heads and MLP hidden sharded
- ("seq" is introduced by the ring-attention path in longcontext.py)

The same spec tree works on 1 device (everything replicated), a v5e-8, or a
virtual 8-CPU mesh (tests / dryrun_multichip).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .models.common import ModelConfig, Params

DATA_AXIS = "data"
MODEL_AXIS = "model"


def build_mesh(mesh_shape: Optional[dict[str, int]] = None,
               devices: Optional[list] = None,
               dcn_axis: Optional[str] = None) -> Mesh:
    """Build a (data, model) mesh. mesh_shape like {"data": 1, "model": 8};
    -1 means "all remaining devices". Default: all devices on the model
    axis (TP-first serving — weights are the big thing to split).

    dcn_axis (multi-slice/multi-host): which mesh axis spans the DCN
    granules — slices when the backend reports them, else processes. The
    device array then comes from mesh_utils.create_hybrid_device_mesh,
    so the OTHER axis stays inside a granule on ICI. Put "data" across
    DCN (DP exchanges nothing per token) and keep "model" inside a slice
    (TP all-reduces every layer) — the module-docstring guidance, now a
    config surface. Ignored (with identical single-granule behavior)
    when there is only one granule, so the same config dryruns
    single-process."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    shape = dict(mesh_shape or {})
    data = shape.get(DATA_AXIS, 1)
    model = shape.get(MODEL_AXIS, -1)
    if model == -1:
        model = n // max(data, 1)
    if data == -1:
        data = n // max(model, 1)
    if data * model > n:
        raise ValueError(
            f"mesh {data}x{model} needs {data * model} devices, have {n}")
    if dcn_axis:
        if dcn_axis not in (DATA_AXIS, MODEL_AXIS):
            raise ValueError(
                f"dcn_axis must be {DATA_AXIS!r} or {MODEL_AXIS!r}, "
                f"got {dcn_axis!r}")
        dev_array = _hybrid_device_array(devices[:data * model],
                                         data, model, dcn_axis)
        if dev_array is not None:
            return Mesh(dev_array, (DATA_AXIS, MODEL_AXIS))
    # A strict subset is allowed — heterogeneous serving partitions the pod
    # into per-model submeshes (SURVEY.md §2.3 "heterogeneous multi-model
    # scheduler"); callers pass disjoint device lists.
    dev_array = np.array(devices[:data * model]).reshape(data, model)
    return Mesh(dev_array, (DATA_AXIS, MODEL_AXIS))


def _hybrid_device_array(devices: list, data: int, model: int,
                         dcn_axis: str):
    """Device array for a DCN-aware mesh, or None when a single granule
    makes the plain contiguous reshape equivalent.

    Granule = slice where devices report distinct slice_index values
    (real multi-slice TPU), else process (multi-host CPU/TPU pods where
    every host is its own DCN island)."""
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    if None not in slice_ids and len(slice_ids) > 1:
        n_granules, process_is_granule = len(slice_ids), False
    else:
        n_granules = len({d.process_index for d in devices})
        process_is_granule = True
    if n_granules <= 1:
        return None
    sizes = {DATA_AXIS: data, MODEL_AXIS: model}
    if sizes[dcn_axis] % n_granules:
        raise ValueError(
            f"dcn_axis={dcn_axis!r} size {sizes[dcn_axis]} must divide "
            f"into the {n_granules} DCN granules (slices/processes)")
    per = dict(sizes)
    per[dcn_axis] //= n_granules
    dcn = {a: (n_granules if a == dcn_axis else 1)
           for a in (DATA_AXIS, MODEL_AXIS)}
    from jax.experimental import mesh_utils
    return mesh_utils.create_hybrid_device_mesh(
        (per[DATA_AXIS], per[MODEL_AXIS]),
        (dcn[DATA_AXIS], dcn[MODEL_AXIS]),
        devices=devices, process_is_granule=process_is_granule)


def param_specs(cfg: ModelConfig) -> Params:
    """PartitionSpec tree matching init_params' structure.

    TP sharding: q/o on query heads, k/v on kv heads, MLP on hidden.
    Embedding sharded on vocab (big tables, cheap all-gather of one row).
    """
    layer = {
        "q_proj": P(None, MODEL_AXIS, None),    # [E, H, D] heads sharded
        "k_proj": P(None, MODEL_AXIS, None),    # [E, K, D]
        "v_proj": P(None, MODEL_AXIS, None),
        "o_proj": P(MODEL_AXIS, None, None),    # [H, D, E] contract sharded
        "input_norm": P(None),
        "pre_mlp_norm": P(None),
    }
    if cfg.attn_bias:
        layer["q_bias"] = P(MODEL_AXIS, None)   # [H, D] heads sharded
        layer["k_bias"] = P(MODEL_AXIS, None)   # [K, D]
        layer["v_bias"] = P(MODEL_AXIS, None)
    if cfg.num_experts:
        # EP: experts ride the model axis — each device computes its local
        # experts for all tokens; the combine contraction over the sharded
        # expert axis becomes one all-reduce (models/common.py moe_mlp)
        layer["router"] = P(None, None)
        layer["experts"] = {
            "gate_proj": P(MODEL_AXIS, None, None),   # [X, E, F]
            "up_proj": P(MODEL_AXIS, None, None),
            "down_proj": P(MODEL_AXIS, None, None),   # [X, F, E]
        }
    else:
        layer.update({
            "gate_proj": P(None, MODEL_AXIS),   # [E, F]
            "up_proj": P(None, MODEL_AXIS),
            "down_proj": P(MODEL_AXIS, None),   # [F, E]
        })
    if cfg.post_attn_norm:
        layer["post_attn_norm"] = P(None)
    if cfg.post_mlp_norm:
        layer["post_mlp_norm"] = P(None)
    specs: Params = {
        "embedding": P(MODEL_AXIS, None),       # [V, E] vocab sharded
        "layers": [dict(layer) for _ in range(cfg.num_layers)],
        "final_norm": P(None),
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(MODEL_AXIS, None)
    return specs


def model_axis_size(mesh: Mesh) -> int:
    """Model-axis (TP) shard count of a mesh — 1 when the axis is
    absent. The `model_shards` quantize_params needs to emit the
    shard-aligned int4 pack layout, and the shard count the int4 spmd
    kernel dispatch partitions against."""
    return dict(mesh.shape).get(MODEL_AXIS, 1)


def int4_shard_axis(tp: Optional[str], w_ndim: int, n_cont: int,
                    mode: str) -> tuple[Optional[int], bool]:
    """Which weight axis carries the model shards for a packed-int4
    kernel matmul — the partition-spec rule for packed leaves, kept HERE
    so it mirrors param_specs above and the two cannot drift. Returns
    (weight_axis | None, needs_psum).

    tp="col" — megatron column-parallel (q/k/v, gate/up, the lm head):
    param_specs puts MODEL on the first KEPT axis (heads / mlp hidden /
    vocab), each shard computes its own output slice, no collective.
    tp="row" — row-parallel (o_proj, down_proj): MODEL rides the first
    CONTRACTED axis, partial sums combine with one psum over the model
    axis — exactly the all-reduce the XLA path's sharded einsum inserts.
    `mode` is the kernel's pack classification ("out": weight dims are
    contracted-prefix + kept with the pack axis kept-minor; "contract":
    kept + one contracted pack axis — the tied lm head, where "row"
    would shard the packed contracted axis, a layout no weight uses →
    replicate). None/unknown tp replicates: the kernel still fuses, the
    partitioning is just not attempted."""
    if tp == "col":
        return (n_cont if mode == "out" else 0), False
    if tp == "row" and mode == "out":
        return 0, True
    return None, False


def lora_shard_axis(tp: Optional[str]) -> Optional[str]:
    """Which STACKED-LoRA axis carries the model shards for a target
    projection — kept HERE next to param_specs/int4_shard_axis so the
    base weight's placement and the LoRA stack's partitioning can
    never drift (ISSUE 10). tp="col" (q/k/v, gate/up): the delta's
    OUTPUT axis is the model-sharded one, so B's last axis shards and
    each device computes its own delta slice with no collective.
    tp="row" (o_proj, down_proj): the CONTRACTION axis is sharded, so
    A's last axis shards and per-shard partial deltas combine with one
    psum over "model" — the same all-reduce the base matmul inserts.
    Returns "out" | "in" | None (replicate)."""
    if tp == "col":
        return "out"
    if tp == "row":
        return "in"
    return None


def lora_stack_specs(tp: Optional[str]) -> tuple[P, P]:
    """(a_spec, b_spec) for the stacked LoRA tensors a_t [S, r, C] /
    b [S, r, O] of a target with TP convention `tp` — the resident
    placement lora_bgmv_spmd's in_specs must match (a mismatch would
    regather the stack per dispatch)."""
    which = lora_shard_axis(tp)
    a_spec = P(None, None, MODEL_AXIS if which == "in" else None)
    b_spec = P(None, None, MODEL_AXIS if which == "out" else None)
    return a_spec, b_spec


def kv_cache_spec() -> P:
    """KV cache [B, S, K, D]: slots on data axis, kv heads on model axis."""
    return P(DATA_AXIS, None, MODEL_AXIS, None)


def shardable(cfg: ModelConfig, mesh: Mesh) -> bool:
    """True when every TP/EP dimension divides by the model-axis size."""
    m = mesh.shape[MODEL_AXIS]
    mlp_ok = (cfg.num_experts % m == 0 if cfg.num_experts
              else cfg.mlp_dim % m == 0)
    return (cfg.num_heads % m == 0 and cfg.num_kv_heads % m == 0
            and mlp_ok and cfg.vocab_size % m == 0)


def _fallback_replicated(spec: P, shape: tuple[int, ...], mesh: Mesh) -> P:
    """Replace axis names whose size doesn't divide the dim with None."""
    fixed = []
    for dim, axis in zip(shape, tuple(spec) + (None,) * (len(shape) - len(spec))):
        if axis is None:
            fixed.append(None)
        elif dim % mesh.shape[axis] == 0:
            fixed.append(axis)
        else:
            fixed.append(None)
    return P(*fixed)


def shard_params(params: Params, cfg: ModelConfig, mesh: Mesh) -> Params:
    """device_put the param tree with its spec tree; any dimension that
    doesn't divide the mesh axis falls back to replication (e.g. 1 kv head
    on an 8-way model axis)."""
    specs = param_specs(cfg)

    def place(x, spec):
        spec = _fallback_replicated(spec, x.shape, mesh)
        return jax.device_put(x, NamedSharding(mesh, spec))

    # tree_map flattens `specs` up to params' treedef, so each PartitionSpec
    # (a tuple subclass) arrives whole at its matching array leaf.
    return jax.tree_util.tree_map(place, params, specs)


def logical_sharding(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)
