"""Thin telemetry seams for the engine layers.

The engines/serving loop talk to utils/telemetry through this module so
the per-call publishing lives ONCE: both engines publish a GenStats the
same way, every int4 routing decision counts the same way, and a future
engine gets the whole surface by importing two functions. Nothing here
touches jax — it is host-side counter/span plumbing only, and every
function is cheap enough to run unguarded at CALL rate (per round/turn,
never per token); hot per-segment/per-dispatch span call sites pre-guard
with `if telemetry.ACTIVE:` at the caller.
"""

from __future__ import annotations

from typing import Any, Optional

from ..utils import telemetry

span = telemetry.span  # re-export: engine call sites read trace_hooks.span


def publish_gen_stats(stats, engine_name: str) -> None:
    """Fold one generate call's GenStats into the registry — the
    engine-stats store metrics.json/bench records become views of."""
    if stats is None:
        return
    reg = telemetry.REGISTRY
    if stats.prefill_tokens:
        reg.inc("roundtable_prefill_tokens_total", stats.prefill_tokens,
                engine=engine_name)
    if stats.reused_tokens:
        reg.inc("roundtable_reused_tokens_total", stats.reused_tokens,
                engine=engine_name)
    if stats.decode_tokens:
        reg.inc("roundtable_decode_tokens_total", stats.decode_tokens,
                engine=engine_name)
    if stats.decode_seconds:
        reg.inc("roundtable_decode_seconds_total", stats.decode_seconds,
                engine=engine_name)
        reg.set_gauge("roundtable_decode_tps", stats.decode_tps,
                      engine=engine_name)
    if stats.prefill_seconds:
        reg.inc("roundtable_prefill_seconds_total",
                stats.prefill_seconds, engine=engine_name)
    sched = stats.sched or {}
    if sched.get("queue_wait_s") is not None:
        reg.observe("roundtable_queue_wait_seconds",
                    sched["queue_wait_s"])
    if sched.get("occupancy_mean") is not None:
        reg.set_gauge("roundtable_batch_occupancy",
                      sched["occupancy_mean"], engine=engine_name)


def publish_int4_paths(report: Optional[dict],
                       engine_name: str) -> None:
    """Registry view of the int4 path-provenance sink (PR 3): one gauge
    pair per engine — distinct kernel dispatches vs distinct XLA
    fallbacks — plus a counter per fallback reason, so a silent-fallback
    regression shows up on a dashboard, not only in describe()."""
    if not report:
        return
    reg = telemetry.REGISTRY
    reg.set_gauge("roundtable_int4_kernel_dispatches",
                  len(report.get("pallas_w4a16", ())),
                  engine=engine_name)
    reg.set_gauge("roundtable_int4_fallback_dispatches",
                  len(report.get("xla_dequant", ())),
                  engine=engine_name)
    for entry in report.get("xla_dequant", ()):
        reason = entry.get("fallback_reason") or "unknown"
        # Gauge not counter: the sink is cumulative per engine and this
        # re-publishes per call — a counter would multiply-count.
        reg.set_gauge("roundtable_int4_fallbacks", 1.0,
                      engine=engine_name, reason=reason[:60])


def _engine_labeled(key: str, engine_name: str) -> bool:
    """True when the flattened series key carries EXACTLY the label
    engine=<engine_name>. Label-element comparison, not substring: a
    fleet with engines 'knight' and 'knight2' must not fold knight2's
    series into knight's view on a prefix match."""
    if "{" not in key:
        return False
    labels = key[key.index("{") + 1:key.rindex("}")]
    return f"engine={engine_name}" in labels.split(",")


def engine_telemetry_view(engine_name: str) -> dict[str, Any]:
    """The describe() embed: this engine's registry series + flight
    recorder state (one store, viewed per engine)."""
    snap = telemetry.REGISTRY.snapshot_compact()
    mine = {k: v for k, v in snap.items()
            if _engine_labeled(k, engine_name)}
    rec = telemetry.recorder()
    return {"metrics": mine, "flight_dumps": rec.dumps,
            "last_flight_dump": rec.last_dump_path,
            "armed": telemetry.ACTIVE}
