"""Thin telemetry seams for the engine layers.

The engines/serving loop talk to utils/telemetry through this module so
the per-call publishing lives ONCE: both engines publish a GenStats the
same way, every int4 routing decision counts the same way, and a future
engine gets the whole surface by importing two functions. Nothing here
touches jax — it is host-side counter/span plumbing only, and every
function is cheap enough to run unguarded at CALL rate (per round/turn,
never per token); hot per-segment/per-dispatch span call sites pre-guard
with `if telemetry.ACTIVE:` at the caller.
"""

from __future__ import annotations

from typing import Any, Optional

from ..utils import telemetry

span = telemetry.span  # re-export: engine call sites read trace_hooks.span


def publish_gen_stats(stats, engine_name: str, perf=None) -> None:
    """Fold one generate call's GenStats into the registry — the
    engine-stats store metrics.json/bench records become views of.
    `perf` (utils/perfmodel.EnginePerf) additionally publishes the
    call's roofline gauges: decode bw_utilization and prefill MFU per
    engine per phase (ISSUE 6)."""
    if stats is None:
        return
    if perf is not None:
        perf.publish_call(stats)
    reg = telemetry.REGISTRY
    if stats.prefill_tokens:
        reg.inc("roundtable_prefill_tokens_total", stats.prefill_tokens,
                engine=engine_name)
    if stats.reused_tokens:
        reg.inc("roundtable_reused_tokens_total", stats.reused_tokens,
                engine=engine_name)
    if stats.decode_tokens:
        reg.inc("roundtable_decode_tokens_total", stats.decode_tokens,
                engine=engine_name)
    if stats.decode_seconds:
        reg.inc("roundtable_decode_seconds_total", stats.decode_seconds,
                engine=engine_name)
        reg.set_gauge("roundtable_decode_tps", stats.decode_tps,
                      engine=engine_name)
    if stats.prefill_seconds:
        reg.inc("roundtable_prefill_seconds_total",
                stats.prefill_seconds, engine=engine_name)
    sched = stats.sched or {}
    if sched.get("queue_wait_s") is not None:
        reg.observe("roundtable_queue_wait_seconds",
                    sched["queue_wait_s"])
    if sched.get("occupancy_mean") is not None:
        reg.set_gauge("roundtable_batch_occupancy",
                      sched["occupancy_mean"], engine=engine_name)


def publish_int4_paths(report: Optional[dict],
                       engine_name: str) -> None:
    """Registry view of the int4 path-provenance sink (PR 3): one gauge
    pair per engine — distinct kernel dispatches vs distinct XLA
    fallbacks — plus a counter per fallback reason, so a silent-fallback
    regression shows up on a dashboard, not only in describe()."""
    if not report:
        return
    reg = telemetry.REGISTRY
    reg.set_gauge("roundtable_int4_kernel_dispatches",
                  len(report.get("pallas_w4a16", ())),
                  engine=engine_name)
    reg.set_gauge("roundtable_int4_fallback_dispatches",
                  len(report.get("xla_dequant", ())),
                  engine=engine_name)
    for entry in report.get("xla_dequant", ()):
        reason = entry.get("fallback_reason") or "unknown"
        # Gauge not counter: the sink is cumulative per engine and this
        # re-publishes per call — a counter would multiply-count.
        reg.set_gauge("roundtable_int4_fallbacks", 1.0,
                      engine=engine_name, reason=reason[:60])


def publish_memory_ledger(engine) -> dict[str, Any]:
    """The memory ledger (ISSUE 6): fold one engine's KV-cache
    accounting and device HBM state into registry gauges, returning
    the ledger dict for describe()/tests.

    HBM comes from `device.memory_stats()` where the backend reports
    it; backends that don't (the axon plugin, CPU) fall back to
    `fleet.estimate_engine_hbm_bytes` under a gauge name that says so
    (`_estimated`) — an estimate must never impersonate a measurement.
    Event-rate cheap: host dict math over slot bookkeeping only."""
    reg = telemetry.REGISTRY
    name = engine.cfg.name
    ledger: dict[str, Any] = {}
    led_fn = getattr(engine.kv, "memory_ledger", None)
    if led_fn is not None:
        ledger = led_fn()
        reg.set_gauge("roundtable_kv_slots_in_use",
                      ledger["slots_in_use"], engine=name)
        reg.set_gauge("roundtable_kv_slot_occupancy",
                      ledger["slot_occupancy"], engine=name)
        reg.set_gauge("roundtable_kv_cached_tokens",
                      ledger["cached_tokens"], engine=name)
        if ledger.get("layout") == "paged":
            reg.set_gauge("roundtable_kv_pages_in_use",
                          ledger["pages_in_use"], engine=name)
            reg.set_gauge("roundtable_kv_pages_total",
                          ledger["usable_pages"], engine=name)
            reg.set_gauge("roundtable_kv_page_utilization",
                          ledger["page_utilization"], engine=name)
            reg.set_gauge("roundtable_kv_fragmentation",
                          ledger["fragmentation"], engine=name)
            # ISSUE 7: the cross-session sharing split — shared pages
            # counted ONCE in pages_in_use; this makes the dedup
            # visible (and auditable) on a dashboard.
            reg.set_gauge("roundtable_kv_shared_pages",
                          ledger.get("shared_pages", 0), engine=name)
            reg.set_gauge("roundtable_kv_exclusive_pages",
                          ledger.get("exclusive_pages", 0), engine=name)
            reg.set_gauge("roundtable_prefix_cache_pages",
                          ledger.get("prefix_cache_pages", 0),
                          engine=name)
            # ISSUE 11: the quantized-page split — resident (payload +
            # scales, what the pools actually cost) vs logical (the
            # same pools at bf16 cells); bits=0 marks a bf16 pool so a
            # dashboard can tell "quantization off" from "no data".
            reg.set_gauge("roundtable_kv_quant_bits",
                          ledger.get("kv_quant_bits", 0), engine=name)
            reg.set_gauge("roundtable_kv_bytes_logical",
                          ledger.get("kv_bytes_logical",
                                     ledger.get("hbm_bytes", 0)),
                          engine=name)
            reg.set_gauge("roundtable_kv_quant_bytes_saved",
                          ledger.get("kv_quant_bytes_saved", 0),
                          engine=name)
        if ledger.get("hbm_bytes") is not None:
            reg.set_gauge("roundtable_kv_hbm_bytes",
                          ledger["hbm_bytes"], engine=name)
    # ISSUE 10: the multi-LoRA adapter store's HBM footprint rides
    # the same ledger publish — resident personas and what each costs,
    # next to the KV split they multiply scenario coverage against.
    store = getattr(engine, "lora", None)
    if store is not None:
        ledger["lora_resident_adapters"] = len(store.resident())
        ledger["lora_adapter_bytes"] = store.adapter_bytes()
        ledger["lora_stack_bytes"] = store.stack_bytes()
        reg.set_gauge("roundtable_lora_resident_adapters",
                      ledger["lora_resident_adapters"], engine=name)
        reg.set_gauge("roundtable_lora_stack_bytes",
                      ledger["lora_stack_bytes"], engine=name)
    # ISSUE 7: the host-RAM offload tier's footprint rides the same
    # ledger publish (sessions parked out of HBM + what they cost in
    # host bytes).
    tier = getattr(engine, "kv_offload", None)
    if tier is not None:
        ledger["spilled_sessions"] = len(tier.spilled_sessions())
        ledger["host_bytes"] = tier.host_bytes()
        reg.set_gauge("roundtable_kv_spilled_sessions",
                      ledger["spilled_sessions"], engine=name)
        reg.set_gauge("roundtable_kv_host_bytes",
                      ledger["host_bytes"], engine=name)
    stats = None
    try:
        stats = engine.mesh.devices.flatten()[0].memory_stats()
    except Exception:  # noqa: BLE001 — unsupported backends return/raise
        stats = None
    if stats and stats.get("bytes_in_use") is not None:
        reg.set_gauge("roundtable_hbm_bytes_in_use",
                      stats["bytes_in_use"], engine=name)
        if stats.get("bytes_limit"):
            reg.set_gauge("roundtable_hbm_bytes_limit",
                          stats["bytes_limit"], engine=name)
        ledger["hbm_bytes_in_use"] = int(stats["bytes_in_use"])
    else:
        try:
            from .fleet import estimate_engine_hbm_bytes
            cfg_dict: dict[str, Any] = {
                "max_seq_len": engine.max_seq_len,
                "num_slots": engine.kv.num_slots,
                "kv_layout": getattr(engine, "kv_layout", "contiguous"),
            }
            if getattr(engine, "quant", "none") != "none":
                cfg_dict["quant"] = engine.quant
            est = estimate_engine_hbm_bytes(cfg_dict,
                                            model_cfg=engine.cfg)
            reg.set_gauge("roundtable_hbm_bytes_estimated", est,
                          engine=name)
            ledger["hbm_bytes_estimated"] = est
        except Exception:  # noqa: BLE001 — the ledger is best-effort
            pass
    from ..utils import perfmodel
    perfmodel.note_published(1)
    return ledger


def _engine_labeled(key: str, engine_name: str) -> bool:
    """True when the flattened series key carries EXACTLY the label
    engine=<engine_name>. Label-element comparison, not substring: a
    fleet with engines 'knight' and 'knight2' must not fold knight2's
    series into knight's view on a prefix match."""
    if "{" not in key:
        return False
    labels = key[key.index("{") + 1:key.rindex("}")]
    return f"engine={engine_name}" in labels.split(",")


def engine_telemetry_view(engine_name: str) -> dict[str, Any]:
    """The describe() embed: this engine's registry series + flight
    recorder state (one store, viewed per engine)."""
    snap = telemetry.REGISTRY.snapshot_compact()
    mine = {k: v for k, v in snap.items()
            if _engine_labeled(k, engine_name)}
    rec = telemetry.recorder()
    return {"metrics": mine, "flight_dumps": rec.dumps,
            "last_flight_dump": rec.last_dump_path,
            "armed": telemetry.ACTIVE}
