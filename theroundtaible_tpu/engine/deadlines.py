"""Hierarchical time budgets, hang detection, cooperative cancellation.

PR 1 built the FAILURE ladder (faults.py injection → retry → breakers →
re-seating); this module builds the TIME ladder production engines treat
as first-class (RTP-LLM, arxiv 2605.29639, budgets every stage of a
request; the Gemma-on-TPU serving comparison, arxiv 2605.25645,
benchmarks against per-request SLOs). Three pieces live here:

- **Budget tree** — one `Budget` node per rung of the serving hierarchy
  (`discussion → round → turn → prefill|decode → dispatch`). A child's
  deadline is the MIN of its parent's, its own timeout, and the rung's
  configured cap, so no leaf can outlive any ancestor. `CancelToken`
  rides the tree: cancelling a parent cancels every descendant, and
  engines check it cooperatively between prefill chunks and decode
  segments (a single XLA program cannot be interrupted — the checks sit
  at the program boundaries, exactly like the existing timeout checks).
- **Watchdog** — `watched_wait(fn, budget, rung)` wraps a BLOCKING device
  wait (dispatch enqueue + compile, the per-segment host sync, the
  prefill scalar fetch). The wait runs in a worker thread; if it exceeds
  min(budget remaining, rung cap) the caller raises `HangDetected`
  (classified as the `hang` fault kind, core/errors.py) and ABANDONS the
  worker — a wedged device program then degrades through the existing
  faults.py → RetryPolicy → CircuitBreaker → re-seating ladder exactly
  like a crashed one, instead of freezing the discussion on
  `jax.block_until_ready`. Unarmed, the seam is a module-flag check and
  a direct call — zero measurable overhead, same contract as
  `faults.ARMED`. An abandoned worker that LATER completes must not
  commit stale cache state: engines wrap the KV-pool mutation in
  `with commit_guard():`, which raises `StaleWait` inside the abandoned
  thread (the result is discarded; the revived pools stay
  authoritative) and holds the ticket lock across the commit so the
  abandon decision cannot interleave with it.
- **Drain gate** — `begin_drain()` flips the module-level `DRAINING`
  flag; `engine.generate_batch*` refuses NEW admissions while it is set,
  in-flight generations finish their rung, and `fleet.drain()` then
  flushes per-knight KV state (see engine/fleet.py).

This module is deliberately host-only (no jax import): the orchestrator
and adapters import it without touching a backend, and the types stay
usable in pure-unit tests.

Arming: `arm_watchdog()` in-process, `ROUNDTABLE_WATCHDOG=1` in the
environment, or arming a `hang`/`slow_wait` fault point
(`ROUNDTABLE_FAULTS=hang` — engine/faults.py arms the watchdog so the
chaos knob is one variable). Per-rung caps:
`ROUNDTABLE_RUNG_BUDGETS="dispatch:120,prefill:300"` or
`configure_rungs({...})`.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

# Module-level guards — the ONLY thing unarmed hot paths touch (one
# attribute load + branch, same pattern as faults.ARMED).
ACTIVE = False     # watchdog armed
DRAINING = False   # fleet drain in progress: refuse new admissions

# The rung hierarchy, outermost first. "prefill"/"decode" are the two
# phase rungs inside a turn; "dispatch" is a single device program's
# blocking wait (the leaf the watchdog times).
RUNGS = ("discussion", "round", "turn", "prefill", "decode", "dispatch")

_INF = float("inf")

# Per-rung wall-clock caps in seconds (None/absent = no cap beyond the
# parent's remaining time). Empty by default: the root timeout bounds
# everything, and operators opt into tighter rungs per deployment.
_rung_caps: dict[str, float] = {}


class BudgetExceeded(TimeoutError):
    """A rung's deadline passed (cooperative check, not a hang)."""

    def __init__(self, message: str, rung: str = ""):
        super().__init__(message)
        self.rung = rung


class Cancelled(RuntimeError):
    """The budget's CancelToken was cancelled (drain/abort)."""

    def __init__(self, message: str, reason: str = ""):
        super().__init__(message)
        self.reason = reason


class HangDetected(RuntimeError):
    """A blocking device wait exceeded its rung budget — the program is
    treated as wedged. The message deliberately carries the watchdog
    markers core/errors.classify_error maps to the `hang` kind, plus —
    when the flight recorder shipped a postmortem — the dump path, so
    the error-log entry an operator reads names the file to open
    (ISSUE 5 satellite)."""

    def __init__(self, rung: str, waited_s: float,
                 telemetry_dump: str = ""):
        msg = (f"watchdog: device wait at rung '{rung}' still blocked "
               f"after {waited_s:.1f}s budget — program presumed wedged "
               "(hang)")
        if telemetry_dump:
            msg += f" [telemetry_dump: {telemetry_dump}]"
        super().__init__(msg)
        self.rung = rung
        self.waited_s = waited_s
        self.telemetry_dump = telemetry_dump


class StaleWait(RuntimeError):
    """Raised by commit_guard inside an ABANDONED watched wait: the
    caller already gave up on this dispatch (HangDetected) and may have
    revived/reallocated the KV state — a late completion must discard
    its result instead of committing stale cache buffers."""


class DrainingError(RuntimeError):
    """New turn refused because the fleet is draining."""


class CancelToken:
    """Cooperative cancellation, tree-propagating: cancelling a parent
    cancels every descendant token (but never the reverse)."""

    __slots__ = ("_event", "reason", "_children", "_lock")

    def __init__(self):
        self._event = threading.Event()
        self.reason = ""
        self._children: list["CancelToken"] = []
        self._lock = threading.Lock()

    def child(self) -> "CancelToken":
        tok = CancelToken()
        with self._lock:
            self._children.append(tok)
            if self._event.is_set():
                tok.cancel(self.reason)
        return tok

    def cancel(self, reason: str = "") -> None:
        with self._lock:
            if self._event.is_set():
                return
            self.reason = reason
            self._event.set()
            children = list(self._children)
        for c in children:
            c.cancel(reason)

    @property
    def cancelled(self) -> bool:
        return self._event.is_set()

    def check(self) -> None:
        if self._event.is_set():
            raise Cancelled(
                f"cancelled{': ' + self.reason if self.reason else ''}",
                reason=self.reason)


class Budget:
    """One node of the hierarchical time-budget tree.

    `deadline` is an absolute time.monotonic() value (inf = unbounded),
    always <= every ancestor's, so the float is directly usable by the
    legacy `deadline=` seams in serving_loop/RetryPolicy."""

    __slots__ = ("rung", "deadline", "parent", "token")

    def __init__(self, rung: str, deadline: float = _INF,
                 parent: Optional["Budget"] = None,
                 token: Optional[CancelToken] = None):
        self.rung = rung
        self.deadline = deadline
        self.parent = parent
        self.token = token or CancelToken()

    @classmethod
    def root(cls, timeout_s: Optional[float] = None,
             rung: str = "discussion",
             token: Optional[CancelToken] = None) -> "Budget":
        """A tree root: `timeout_s` None means unbounded (the rung cap,
        if configured, still applies); a numeric value — including 0 —
        bounds it (0 = born expired, useful in tests and hard cutoffs)."""
        deadline = (time.monotonic() + timeout_s
                    if timeout_s is not None else _INF)
        cap = _rung_caps.get(rung)
        if cap:
            deadline = min(deadline, time.monotonic() + cap)
        return cls(rung, deadline, token=token)

    def child(self, rung: str,
              timeout_s: Optional[float] = None) -> "Budget":
        """Derive a sub-budget: deadline = min(parent, own timeout, rung
        cap). The child gets a linked CancelToken, so cancelling this
        node cancels the child but a child's cancellation stays local."""
        deadline = self.deadline
        now = time.monotonic()
        if timeout_s is not None and timeout_s >= 0:
            deadline = min(deadline, now + timeout_s)
        cap = _rung_caps.get(rung)
        if cap:
            deadline = min(deadline, now + cap)
        return Budget(rung, deadline, parent=self,
                      token=self.token.child())

    def split(self, n: int, rung: str) -> list["Budget"]:
        """n children sharing the remaining time evenly (each capped by
        this node's deadline — a child finishing early does NOT donate
        to its siblings; use sequential `child(remaining/(n-i))` calls
        for the fair-share-with-reuse pattern)."""
        share = self.remaining() / max(n, 1)
        return [self.child(rung, timeout_s=share) for _ in range(n)]

    def remaining(self) -> float:
        return max(self.deadline - time.monotonic(), 0.0) \
            if self.deadline != _INF else _INF

    @property
    def expired(self) -> bool:
        return time.monotonic() >= self.deadline

    def check(self) -> None:
        """Cooperative cancellation + deadline check — call between
        prefill chunks / decode segments (program boundaries)."""
        self.token.check()
        if time.monotonic() >= self.deadline:
            raise BudgetExceeded(
                f"{self.rung} budget exhausted (deadline passed)",
                rung=self.rung)


def rung_cap(rung: str) -> Optional[float]:
    return _rung_caps.get(rung)


def configure_rungs(caps: dict[str, float]) -> None:
    """Set per-rung wall-clock caps (seconds); None/0 removes a cap."""
    for rung, cap in caps.items():
        if rung not in RUNGS:
            raise ValueError(f"unknown rung {rung!r} "
                             f"(known: {', '.join(RUNGS)})")
        if cap:
            _rung_caps[rung] = float(cap)
        else:
            _rung_caps.pop(rung, None)


def reset_rungs() -> None:
    _rung_caps.clear()


def _configure_from_env() -> None:
    """ROUNDTABLE_RUNG_BUDGETS="rung:seconds,..." parsed at import.
    Malformed entries warn and are skipped — the ops knob must never
    itself take serving down with an import-time crash."""
    raw = os.environ.get("ROUNDTABLE_RUNG_BUDGETS", "")
    for entry in filter(None, (p.strip() for p in raw.split(","))):
        try:
            rung, sec = entry.rsplit(":", 1)
            configure_rungs({rung.strip(): float(sec)})
        except ValueError as e:
            import warnings
            warnings.warn(
                f"ignoring malformed ROUNDTABLE_RUNG_BUDGETS entry "
                f"{entry!r}: {e}")


# --- watchdog ---

_local = threading.local()

# Recent hang events (observability: fleet_health / chaos assertions).
_hang_log: list[dict] = []
_HANG_LOG_CAP = 64


class _WatchTicket:
    """State shared between a watched wait's caller and its worker.
    `lock` serializes the abandon decision against the worker's state
    commit: the caller flips `abandoned` under it, and commit_guard
    HOLDS it across the guard-check AND the commit — so either the
    commit completes before abandonment is visible (the caller's
    recovery then revives over a consistent committed state) or the
    guard sees `abandoned` and discards. Never commit-then-revive and
    revive-then-stale-commit interleaved."""

    __slots__ = ("abandoned", "rung", "lock")

    def __init__(self, rung: str):
        self.abandoned = False
        self.rung = rung
        self.lock = threading.Lock()


def arm_watchdog() -> None:
    global ACTIVE
    ACTIVE = True


def disarm_watchdog() -> None:
    global ACTIVE
    ACTIVE = False


def hang_log() -> list[dict]:
    """Recorded hang events ({rung, waited_s, at}) — newest last."""
    return list(_hang_log)


def clear_hang_log() -> None:
    _hang_log.clear()


def wait_abandoned() -> bool:
    """True inside a watched wait whose caller already raised
    HangDetected and moved on (worker thread only)."""
    ticket = getattr(_local, "ticket", None)
    return ticket is not None and ticket.abandoned


class _CommitGuard:
    """`with deadlines.commit_guard(): <commit cache state>` — inside a
    dispatch closure, wrap the cache-state mutation: a late-completing
    abandoned wait must discard its result (the caller may have revived
    the KV pools since). The guard check and the commit happen under
    the ticket's lock, and the watchdog flips `abandoned` under the same
    lock, so a worker can never pass the check and then commit stale
    state AFTER the caller's recovery revived the pools (the abandon
    either waits for the in-progress commit or is seen by the guard).
    Near-free on the unarmed hot path and outside watched waits."""

    __slots__ = ("_ticket",)

    def __enter__(self):
        ticket = getattr(_local, "ticket", None) if ACTIVE else None
        self._ticket = ticket
        if ticket is not None:
            ticket.lock.acquire()
            if ticket.abandoned:
                ticket.lock.release()
                self._ticket = None
                raise StaleWait(
                    f"watched wait at rung '{ticket.rung}' was abandoned "
                    "by the watchdog — discarding its late result instead "
                    "of committing stale cache state")
        return self

    def __exit__(self, *exc) -> bool:
        if self._ticket is not None:
            self._ticket.lock.release()
        return False


def commit_guard() -> _CommitGuard:
    return _CommitGuard()


def watched_wait(fn: Callable, budget: Optional[Budget],
                 rung: str = "dispatch"):
    """THE deadline seam for blocking device waits.

    Unarmed (ACTIVE False) or unbudgeted: a direct call — zero overhead
    beyond the flag check the call site already did. Armed: `fn` runs in
    a dedicated worker thread and the caller waits at most
    min(budget remaining, rung cap); on expiry the worker is ABANDONED
    (a wedged device wait cannot be interrupted from Python — the
    abandoned thread either blocks forever or discards its result via
    commit_guard) and HangDetected raises into the caller, where the
    fault ladder takes over."""
    if not ACTIVE or budget is None:
        return fn()
    bound = budget.remaining()
    cap = _rung_caps.get(rung)
    if cap:
        bound = min(bound, cap)
    if bound == _INF:
        return fn()
    if bound <= 0:
        # Nothing left to wait with: that is an exhausted BUDGET (the
        # cooperative-timeout classification), not a wedged program —
        # don't spawn a worker just to abandon it at t=0.
        raise BudgetExceeded(
            f"{rung} wait admitted with no remaining budget", rung=rung)
    done = threading.Event()
    box: dict = {}
    ticket = _WatchTicket(rung)

    def work():
        _local.ticket = ticket
        try:
            box["value"] = fn()
        except BaseException as e:  # noqa: BLE001 — re-raised in caller
            box["error"] = e
        finally:
            done.set()

    worker = threading.Thread(target=work, daemon=True,
                              name=f"watchdog-{rung}")
    worker.start()
    if not done.wait(timeout=max(bound, 0.0)):
        # Under the ticket lock: an in-progress commit_guard block
        # finishes first (commit-then-revive order), or the flag lands
        # before the guard runs and the worker discards (StaleWait).
        with ticket.lock:
            ticket.abandoned = True
        _hang_log.append({"rung": rung, "waited_s": bound,
                          "at": time.monotonic()})
        del _hang_log[:-_HANG_LOG_CAP]
        # Every hang ships its own postmortem (ISSUE 5): count it,
        # record it, dump the flight recorder, and carry the dump path
        # in the error the ladder/error-log surfaces.
        from ..utils import telemetry
        telemetry.inc("roundtable_hangs_total", rung=rung)
        telemetry.recorder().record("hang", rung=rung, waited_s=bound)
        dump = telemetry.flight_dump(
            "hang", extra={"rung": rung, "waited_s": bound})
        raise HangDetected(rung, bound, telemetry_dump=dump)
    if "error" in box:
        raise box["error"]
    return box["value"]


# --- drain gate ---

def begin_drain() -> None:
    """Stop admitting new turns (engine.generate_batch* checks this
    before taking the serve lock); in-flight generations finish."""
    global DRAINING
    DRAINING = True
    from ..utils import telemetry
    telemetry.set_gauge("roundtable_draining", 1.0)


def end_drain() -> None:
    global DRAINING
    DRAINING = False
    from ..utils import telemetry
    telemetry.set_gauge("roundtable_draining", 0.0)


def check_admission() -> None:
    """Raise DrainingError when the fleet is draining. One module-flag
    check per generate call — nothing on the per-token path."""
    if DRAINING:
        raise DrainingError(
            "fleet is draining: new turns are not admitted "
            "(fleet.resume() re-opens admission)")


if os.environ.get("ROUNDTABLE_WATCHDOG"):
    arm_watchdog()
_configure_from_env()
