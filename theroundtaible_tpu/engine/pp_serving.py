"""Pipeline-parallel SERVING — stage-local KV caches, prefill + decode.

Completes the PP story pipeline.py opens (VERDICT r1 #7: "wire PP into
serving"): an engine for checkpoints too large for one chip/TP group,
reachable from the tpu-llm adapter config as `mesh: {"pipe": N}`. Layers
split into N contiguous stages (params stacked on a leading stage axis,
sharded over the "pipe" mesh axis — stack_stage_params); each stage owns
the KV cache for ITS layers only (`[n_stages, per, slots, S, K, D]`,
stage-sharded), so no device ever holds the whole model or the whole
cache — the memory-capacity property PP exists for.

- Prefill: GPipe microbatch schedule (pipeline.py's rotating-buffer
  design) extended to thread per-layer stage-local caches through the
  steps; bubble steps compute garbage that is masked out of both the
  banked logits and the cache writes.
- Decode: one ppermute hop per stage per token — stages fire in
  sequence, each applying its layers against its local cache at the
  row's current position. Inactive stages run masked compute (the
  static-shape price of SPMD; PP decode is a memory-capacity play, its
  serial latency is inherent to the layer dependency).
- Slots: SlotBook (kvcache.py) gives PP the same per-knight LCP delta
  prefill as the main engine; per-row sampling params and int8 w8a16
  quant work as in the main engine (quantized {"q","s"} leaves stack
  and stage-shard like any other layer leaf). Cross-knight prefix
  sharing (donor + leader passes) copies spans on the stage-sharded
  caches — the slot axis is unsharded, so each stage copies its own
  layers' span with no cross-stage traffic.
- kv_layout="paged": a stage-stacked page pool [st, per, P, ps, K, D]
  managed by the main engine's PagedKVCache allocator (one page table
  for every layer; page aliasing replaces span copies for prefix
  sharing). Serving is POOL-DIRECT: prefill chunks and decode steps
  scatter into the rows' pages and attend through the page-table-aware
  Pallas kernels, so the position-aligned gather view (which would
  temporarily recreate the full contiguous HBM budget — precisely on
  the models PP exists for) is never built. Under TP-in-stage the
  kernels run through the paged SPMD wrappers as a NESTED shard_map
  over the auto "model" axis; attn="dense" (or a non-partitionable
  head layout) keeps the gather-view fallback.
- Attention inside stages: the Pallas flash kernels — raw single-device
  calls on pipe-only meshes (the stage body is fully manual, so
  per-stage arrays are local and full-size); under TP-in-stage the
  main engine's spmd wrappers run as a nested shard_map that
  manualizes only the still-auto "model" axis (the context mesh has
  "pipe" Manual already). Dense XLA einsums remain the opt-out and the
  non-partitionable-heads fallback.

The reference has no counterpart (its models fit one GPU via Ollama);
SURVEY.md §2.3 "PP" row is the requirement this file closes.
"""

from __future__ import annotations

import threading
import time
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..utils import telemetry
from . import compat, deadlines, faults, trace_hooks
from .compat import pcast, shard_map
from .engine import GenStats
from .kvcache import SlotBook
from .serving_loop import (DECODE_SEGMENT, PREFILL_BUCKETS, bucket_for,
                           chunked_prefill, decode_segments,
                           finalize_outputs, host_sync, prompt_budget)
from .models.common import (ModelConfig, _einsum, _softcap, embed_tokens,
                            gather_rows, init_params, make_attention_mask,
                            param_count, project_qkv, rms_norm,
                            spmd_mesh, transformer_block)
from .pipeline import PIPE_AXIS, build_pipe_mesh, stack_stage_params
from .sampling import (SamplingParams, sample_token_batch, sampling_arrays)
from .tokenizer import load_tokenizer


class PPEngine:
    """Pipeline-parallel serving engine (stage-local weights AND KV)."""

    def __init__(self, model_cfg: ModelConfig, *, checkpoint: str = "",
                 n_stages: int = 2, n_model: int = 1, n_micro: int = 2,
                 num_slots: int = 4,
                 dtype=jnp.bfloat16, quant: str = "none",
                 kv_layout: str = "contiguous", page_size: int = 128,
                 num_pages: Optional[int] = None, attn: str = "auto",
                 sampling: Optional[SamplingParams] = None, seed: int = 0,
                 devices: Optional[list[int]] = None,
                 prefix_cache: Optional[bool] = None,
                 prefix_cache_pages: Optional[int] = None):
        import dataclasses

        if quant not in ("none", "int8", "int4"):
            raise ValueError(
                f"quant must be none|int8|int4, got {quant!r}")
        if kv_layout not in ("contiguous", "paged"):
            raise ValueError(
                f"kv_layout must be contiguous|paged, got {kv_layout!r}")
        if attn not in ("auto", "flash", "dense"):
            raise ValueError(f"attn must be auto|flash|dense, got {attn!r}")

        from . import compile_watch, enable_compilation_cache
        from .distributed import maybe_init_distributed
        maybe_init_distributed()
        enable_compilation_cache()
        compile_watch.install()
        # Attention inside the stages (VERDICT r3 missing #4 — the PP
        # engine used to force dense): on a pipe-only mesh the stage body
        # is fully manual, every array is stage-local and full-size, so
        # the RAW single-device Pallas kernels apply directly
        # (the stage context announces LOCAL_MESH — size 1 — so
        # models/common.attention takes its single-device kernel branch
        # with per-shape supported() fallback, and the int4 kernels
        # dispatch single-device too). On a (pipe, model) mesh the kernels run
        # through the same spmd wrappers the main engine uses, as a
        # NESTED shard_map: the stage body is manual over "pipe" only, so
        # the wrapper manualizes the remaining auto "model" axis
        # (pallas/attention._manual_axes) — heads must divide the model
        # axis exactly as on the main engine (explicit flash on a
        # non-divisible layout raises; auto falls back to dense).
        if n_model > 1 and not compat.HAS_NATIVE_SHARD_MAP:
            # Partial-manual stage bodies (manual "pipe", auto "model")
            # lower axis_index to a PartitionId the legacy SPMD
            # partitioner refuses — TP-in-stage needs the modern
            # shard_map API. Refuse at build with the fix, instead of
            # an opaque XLA error mid-prefill.
            raise ValueError(
                "mesh={'pipe': N, 'model': M} (TP inside stages) needs "
                "jax.shard_map, which this jax version lacks — upgrade "
                "jax or use mesh={'pipe': N} / the main engine's "
                "(data, model) mesh")
        from .pallas.attention import spmd_partitionable
        heads_divide = spmd_partitionable(
            model_cfg.num_heads, model_cfg.num_kv_heads, n_model)
        if attn == "flash" and n_model > 1 and not heads_divide:
            raise ValueError(
                f"attn='flash' on a {n_model}-way model axis needs head "
                f"counts divisible by it (got H={model_cfg.num_heads}, "
                f"K={model_cfg.num_kv_heads}) — use attn='auto' or "
                "'dense'")
        if attn == "auto":
            # Mirror the main engine's auto rule: kernels on TPU with
            # lane-aligned head_dim (and a partitionable head layout
            # when TP runs inside the stages), dense elsewhere.
            resolved = ("flash" if jax.default_backend() == "tpu"
                        and model_cfg.head_dim % 128 == 0
                        and (n_model == 1 or heads_divide) else "dense")
        else:
            resolved = attn
        model_cfg = dataclasses.replace(model_cfg, attn_impl=resolved)
        self.cfg = model_cfg
        self.max_seq_len = model_cfg.max_seq_len
        self.sampling = sampling or SamplingParams()
        self.tokenizer = load_tokenizer(checkpoint or None)
        self.n_stages = n_stages
        self.n_model = n_model
        self.n_micro = n_micro
        device_list = None
        if devices:
            all_devices = jax.devices()
            device_list = [all_devices[i] for i in devices]
        # n_model > 1: a (pipe, model) mesh — each stage's weights/KV
        # shard over a TP group. The PP programs are shard_map-manual
        # over "pipe" only (axis_names below); "model" stays an auto
        # axis, so XLA inserts the same TP collectives inside each stage
        # that the main engine's jit path gets from param PartitionSpecs
        # (SURVEY §2.3's (pipeline, tensor, data) requirement).
        self.mesh = build_pipe_mesh(n_stages, device_list, n_model)

        if checkpoint:
            from .checkpoint import load_hf_checkpoint
            params = load_hf_checkpoint(checkpoint, model_cfg, dtype)
        else:
            params = init_params(model_cfg, jax.random.PRNGKey(seed), dtype)
        self.num_params = param_count(params)
        self.quant = quant
        if quant in ("int8", "int4"):
            # PP is the engine for checkpoints too big for one chip —
            # exactly where shrinking streamed weight bytes matters most.
            # Quantize BEFORE stacking: the {"q","s"} dict / Int4Leaf
            # leaves stack and shard like any other layer leaf, and the
            # stage programs reach them only through _einsum/embed_tokens
            # (which dequantize fusably, see engine/quant.py).
            # model_shards: int4 grouping aligns to the in-stage TP shard
            # boundary so the shard-aware kernel dispatch partitions
            # scales with whole groups per shard.
            from .quant import quantize_params
            params = quantize_params(params, model_cfg, act_dtype=dtype,
                                     free_source=True,
                                     bits=8 if quant == "int8" else 4,
                                     model_shards=n_model)
        self.shared, self.staged = stack_stage_params(
            params, model_cfg, n_stages, self.mesh)

        per = model_cfg.num_layers // n_stages
        # Caches [st, per, slots|pages, S|ps, K, D]: stage axis over
        # "pipe"; on a (pipe, model) mesh the KV-head dim additionally
        # shards over "model" (falling back to replicated when K doesn't
        # divide, e.g. MQA) — same layout rule as kv_cache_spec.
        from .sharding import MODEL_AXIS, _fallback_replicated
        kv_spec = P(PIPE_AXIS, None, None, None,
                    MODEL_AXIS if n_model > 1 else None, None)

        def cache_sharding_for(shape):
            return NamedSharding(
                self.mesh, _fallback_replicated(kv_spec, shape, self.mesh))

        self.kv_layout = kv_layout
        kd = (model_cfg.num_kv_heads, model_cfg.head_dim)
        # Pool-direct paged serving (VERDICT r3 missing #4): prefill
        # chunks and decode steps scatter into the rows' pages and attend
        # through the page-table-aware kernels — the [B, S, K, D] gather
        # view (which temporarily recreates the full contiguous HBM
        # budget, precisely on the models PP exists for) is never built.
        # Same gating as the main engine: attn="dense" is an explicit
        # opt-out of every Pallas kernel ("auto" still takes pool-direct
        # on CPU, where the kernel runs in interpret mode). TP-in-stage
        # meshes take the paged SPMD wrappers as a nested shard_map over
        # the auto "model" axis (head layout must partition; otherwise
        # the gather view remains).
        self._pool_direct = False
        if kv_layout == "paged":
            from .pallas.attention import paged_pool_direct_supported
            from .serving_loop import MAX_PREFILL_CHUNK
            kh_l = model_cfg.num_kv_heads
            if n_model > 1 and kh_l % n_model == 0:
                kh_l //= n_model   # kernel sees the local shard
            group = model_cfg.num_heads // model_cfg.num_kv_heads
            self._pool_direct = (
                attn != "dense"
                and paged_pool_direct_supported(
                    MAX_PREFILL_CHUNK, page_size, model_cfg.head_dim,
                    kh_l, group)
                and (n_model == 1 or heads_divide))
        if kv_layout == "paged":
            # Stage-stacked page pool [st, per, P, ps, K, D]: ONE
            # allocator manages the page axis (a slot's page mapping is
            # identical for every layer, exactly like the main engine's
            # per-layer pools sharing one table), while the leading stage
            # axis shards so each pipe device holds only its own layers'
            # pages. Serving gathers pool[table] into the same
            # [st, per, B, S, K, D] view the contiguous programs use —
            # the stage programs are layout-agnostic.
            from .paging import PagedKVCache

            def pool_factory(n_pages):
                shape = (n_stages, per, n_pages, page_size) + kd
                sh = cache_sharding_for(shape)
                return [(jax.device_put(jnp.zeros(shape, dtype), sh),
                         jax.device_put(jnp.zeros(shape, dtype), sh))]

            @partial(jax.jit, donate_argnums=(0,))
            def copy_pages(pools, src_ids, dst_ids):
                k6, v6 = pools[0]
                return [(k6.at[:, :, dst_ids].set(k6[:, :, src_ids]),
                         v6.at[:, :, dst_ids].set(v6[:, :, src_ids]))]

            from .paging import make_padded_copier
            self.kv = PagedKVCache(
                model_cfg, num_slots, self.max_seq_len, dtype,
                page_size=page_size, num_pages=num_pages,
                copy_pages_fn=make_padded_copier(copy_pages),
                pool_factory=pool_factory)
            self.kc = self.vc = None
            n_pages_seq = self.max_seq_len // page_size

            @jax.jit
            def gather_view(pools, tables):
                k6, v6 = pools[0]
                b = tables.shape[0]
                kc = k6[:, :, tables].reshape(
                    n_stages, per, b, self.max_seq_len, *kd)
                vc = v6[:, :, tables].reshape(
                    n_stages, per, b, self.max_seq_len, *kd)
                return kc, vc

            @partial(jax.jit, donate_argnums=(0, 2, 3))
            def scatter_view(pools, tables, kc, vc):
                # Duplicate table entries (pages aliased across rows)
                # only ever carry identical bytes: aliased pages sit
                # below every row's COW'd write range, so the rows' view
                # contents agree there (engine.py scatter_view contract).
                k6, v6 = pools[0]
                b = tables.shape[0]
                k7 = kc.reshape(n_stages, per, b, n_pages_seq,
                                page_size, *kd)
                v7 = vc.reshape(n_stages, per, b, n_pages_seq,
                                page_size, *kd)
                return [(k6.at[:, :, tables].set(k7),
                         v6.at[:, :, tables].set(v7))]

            self._gather_view = gather_view
            self._scatter_view = scatter_view
            # Cross-session prefix cache (ISSUE 7): the stage-stacked
            # pool is still one PagedKVCache page space, so the
            # content-addressed index works unchanged — commit inserts,
            # the prepare path attaches, _alloc_page reclaims. The host
            # offload tier stays main-engine-only (its idle policy lives
            # in the session scheduler, which serves InferenceEngine).
            from .prefix_cache import PrefixCache, cache_enabled
            self.prefix_cache = None
            if cache_enabled(prefix_cache):
                self.prefix_cache = PrefixCache(
                    self.kv, engine=model_cfg.name,
                    max_pages=prefix_cache_pages)
                self.kv.prefix_cache = self.prefix_cache
        else:
            cache_shape = (n_stages, per, num_slots,
                           self.max_seq_len) + kd
            sh = cache_sharding_for(cache_shape)
            # Kept for revive_kv_if_dead: reallocation after a failed
            # donated dispatch deleted the stage-stacked caches.
            self._make_contig = lambda: jax.device_put(
                jnp.zeros(cache_shape, dtype), sh)
            self.kc = self._make_contig()
            self.vc = self._make_contig()
            self.kv = SlotBook(num_slots)
            self.prefix_cache = None

        self._key = jax.random.PRNGKey(seed + 1)
        self._chars_per_token: Optional[float] = None
        self.last_stats = GenStats()
        self._serve_lock = threading.Lock()
        # int4 path-provenance sink (models/common._record_int4) —
        # every stage/head mesh context below carries it.
        self._int4_dispatches: dict = {}
        # Shared dispatch retry policy (engine/faults.py), same seam as
        # the main engine: transient dispatch failures retry in place.
        self.retry = faults.DEFAULT_RETRY
        # Per-engine roofline model (ISSUE 6): streamed bytes from the
        # stage-stacked (possibly quantized) tree + chip ceilings —
        # same construction seam as the main engine.
        from ..utils import perfmodel
        self.perf = perfmodel.EnginePerf.from_engine(
            self, params=(self.shared, self.staged),
            kv_itemsize=jnp.dtype(dtype).itemsize)

        cfg = model_cfg
        mesh = self.mesh
        s_len = self.max_seq_len
        # Stage bodies trace under the CONTEXT AbstractMesh whenever a
        # "model" axis exists (pipe already Manual there): the flash spmd
        # wrappers need it to run as a nested shard_map over the auto
        # "model" axis, and the int4 kernel dispatch re-partitions its
        # matmuls over the same axis (einsum_int4_spmd). On pipe-ONLY
        # meshes the stage body is FULLY manual — every array is
        # device-local and full-size — so the context announces the
        # LOCAL_MESH sentinel: the int4 kernels then dispatch
        # single-device (lifting the old "unset context → XLA dequant"
        # fallback inside PP stages, ISSUE 3) while "no announcement"
        # elsewhere still safely means the XLA path.
        mesh_in_stage = n_model > 1

        def _stage_mesh_ctx():
            from .models.common import LOCAL_MESH, spmd_mesh
            if not mesh_in_stage:
                return spmd_mesh(LOCAL_MESH,
                                 int4_sink=self._int4_dispatches)
            # Native shard_map is guaranteed here — the constructor
            # refuses TP-in-stage on old jax — so the trace-context
            # AbstractMesh is real (it carries the Manual "pipe" axis
            # the nested spmd wrappers subtract via axis_types).
            return spmd_mesh(jax.sharding.get_abstract_mesh(),
                             int4_sink=self._int4_dispatches)

        def stage_scan(stage_layers, kc_l, vc_l, h, positions, valid,
                       offsets, slot_idx, write_ok):
            """This stage's layers over h, threading per-layer caches.

            kc_l/vc_l: [per, slots, S, K, D]. write_ok masks cache writes
            (False during schedule bubbles / inactive decode hops)."""
            mask = make_attention_mask(positions, s_len, valid,
                                       cfg.sliding_window)

            def body(h, xs):
                layer, kc1, vc1 = xs
                cache = (kc1[slot_idx], vc1[slot_idx])
                h, (nk, nv) = transformer_block(
                    h, layer, cfg, positions, cache, offsets, mask,
                    kv_valid=valid)
                kc1 = kc1.at[slot_idx].set(
                    jnp.where(write_ok, nk, kc1[slot_idx]))
                vc1 = vc1.at[slot_idx].set(
                    jnp.where(write_ok, nv, vc1[slot_idx]))
                return h, (kc1, vc1)

            with _stage_mesh_ctx():
                h, (kc_l, vc_l) = jax.lax.scan(
                    body, h, (stage_layers, kc_l, vc_l))
            return h, kc_l, vc_l

        def make_pp_programs(scan_step):
            """Build the (prefill, decode) jit programs for one cache
            layout. The GPipe microbatch schedule, per-token ring
            decode, banking/psum epilogue and sampling bookkeeping exist
            ONCE here; layouts differ only in `scan_step` and in what
            `caches`/`extra` mean — contiguous threads the slot-indexed
            (kc, vc) caches with extra = slot_idx [B]; paged threads the
            stage-stacked (k6, v6) page pools with extra = tables
            [B, pages_per_seq]. (One shell, two instantiations: a
            near-verbatim second copy of these programs is exactly the
            drift hazard serving_loop.py was extracted to prevent.)

            scan_step(stage_layers, c1_l, c2_l, h, positions, valid,
            offsets_row, extra_row, write_ok) -> (h, c1_l, c2_l)."""

            @partial(jax.jit, donate_argnums=(2,))
            def pp_prefill(shared, staged, caches, extra, tokens,
                           offsets, lengths):
                c1, c2 = caches
                b, t = tokens.shape
                n_mb = self.n_micro if b % self.n_micro == 0 else 1
                mb = b // n_mb
                tok_mb = tokens.reshape(n_mb, mb, t)
                offs_mb = offsets.reshape(n_mb, mb)
                len_mb = lengths.reshape(n_mb, mb)
                extra_mb = extra.reshape((n_mb, mb) + extra.shape[1:])

                emb = embed_tokens(shared["embedding"], tok_mb)
                if cfg.scale_embeddings:
                    emb = emb * jnp.sqrt(
                        jnp.float32(cfg.embed_dim)).astype(emb.dtype)

                def per_stage(staged, c1, c2, emb, offs_mb, len_mb,
                              extra_mb):
                    stage_layers = jax.tree_util.tree_map(
                        lambda x: x[0], staged)
                    c1_l, c2_l = c1[0], c2[0]
                    stage = jax.lax.axis_index(PIPE_AXIS)
                    n_steps = self.n_stages + n_mb - 1

                    state = pcast(jnp.zeros_like(emb[0]),
                                          (PIPE_AXIS,), to="varying")
                    banked = pcast(jnp.zeros_like(emb),
                                           (PIPE_AXIS,), to="varying")
                    c1_l = pcast(c1_l, (PIPE_AXIS,), to="varying")
                    c2_l = pcast(c2_l, (PIPE_AXIS,), to="varying")

                    def step(i, carry):
                        state, banked, c1_l, c2_l = carry
                        inject = emb[jnp.clip(i, 0, n_mb - 1)]
                        x_in = jnp.where(stage == 0,
                                         jnp.where(i < n_mb, inject,
                                                   state),
                                         state)
                        my = jnp.clip(i - stage, 0, n_mb - 1)
                        in_sched = (i - stage >= 0) & (i - stage < n_mb)
                        positions = (offs_mb[my][:, None]
                                     + jnp.arange(t)[None, :])
                        valid = offs_mb[my] + len_mb[my]
                        out, c1_l, c2_l = scan_step(
                            stage_layers, c1_l, c2_l, x_in, positions,
                            valid, offs_mb[my], extra_mb[my], in_sched)
                        j = i - (self.n_stages - 1)
                        bank_now = (stage == self.n_stages - 1) & (j >= 0)
                        banked = jnp.where(
                            bank_now,
                            banked.at[jnp.clip(j, 0, n_mb - 1)].set(out),
                            banked)
                        state = jax.lax.ppermute(
                            out, PIPE_AXIS,
                            [(s, (s + 1) % self.n_stages)
                             for s in range(self.n_stages)])
                        return state, banked, c1_l, c2_l

                    _s, banked, c1_l, c2_l = jax.lax.fori_loop(
                        0, n_steps, step, (state, banked, c1_l, c2_l))
                    banked = jax.lax.psum(
                        jnp.where(stage == self.n_stages - 1, banked, 0.0)
                        .astype(jnp.float32), PIPE_AXIS) \
                        .astype(banked.dtype)
                    return banked, c1_l[None], c2_l[None]

                hidden, c1, c2 = shard_map(
                    per_stage, mesh=mesh,
                    in_specs=(P(PIPE_AXIS), P(PIPE_AXIS), P(PIPE_AXIS),
                              P(), P(), P(), P()),
                    out_specs=(P(), P(PIPE_AXIS), P(PIPE_AXIS)),
                    # Manual over "pipe" only; any "model" axis stays
                    # auto so XLA inserts the in-stage TP collectives.
                    axis_names={PIPE_AXIS},
                    check_vma=False,
                )(staged, c1, c2, emb, offs_mb, len_mb, extra_mb)

                hidden = hidden.reshape(b, t, cfg.embed_dim)
                hidden = rms_norm(hidden, shared["final_norm"],
                                  cfg.norm_eps, cfg.rmsnorm_unit_offset)
                # Gather each row's last valid hidden state BEFORE the
                # lm head: full-sequence [B,T,V] logits on a 256k vocab
                # are a multi-GB temp (see models/common.forward).
                hidden = gather_rows(hidden, lengths - 1)
                head = (shared["embedding"] if cfg.tie_embeddings
                        else shared["lm_head"])
                # The head matmul runs OUTSIDE the stage shard_map, under
                # plain jit/GSPMD over the (pipe[, model]) mesh — announce
                # that mesh so an int4 head dispatches the shard-aware
                # kernel (post-gather M = B rows, decode-kernel legal)
                # instead of the old silent XLA fallback.
                with spmd_mesh(mesh, int4_sink=self._int4_dispatches):
                    logits = _einsum("bte,ve->btv", hidden, head,
                                     tp="col")
                logits = _softcap(logits, cfg.final_logit_softcap)
                return logits[:, 0], (c1, c2)

            @partial(jax.jit, donate_argnums=(2,),
                     static_argnames=("max_new", "greedy"))
            def pp_decode(shared, staged, caches, extra, first_token,
                          start_valid, key, budget, temps, top_ks,
                          top_ps, row_budgets, done_in, max_new, greedy):
                c1, c2 = caches
                b = first_token.shape[0]
                eos = jnp.int32(self.tokenizer.eos_id)
                head = (shared["embedding"] if cfg.tie_embeddings
                        else shared["lm_head"])

                def per_stage(staged, c1, c2, first_token, start_valid,
                              key, budget, temps, top_ks, top_ps,
                              row_budgets, done_in, extra, embedding,
                              head, final_norm):
                    stage_layers = jax.tree_util.tree_map(
                        lambda x: x[0], staged)
                    c1_l = pcast(c1[0], (PIPE_AXIS,),
                                         to="varying")
                    c2_l = pcast(c2[0], (PIPE_AXIS,),
                                         to="varying")
                    stage = jax.lax.axis_index(PIPE_AXIS)
                    out0 = jnp.zeros((b, max_new), jnp.int32)
                    # done carries ACROSS segments (decode_segments
                    # threads it) — all-done speculative segments exit
                    # at the cond
                    done0 = done_in

                    def cond(state):
                        step, _, _, done, _, _, _, _ = state
                        return ((step < max_new) & (step < budget)
                                & ~jnp.all(done))

                    def tok_body(state):
                        step, last, valid, done, out, c1_l, c2_l, key = \
                            state
                        h = embed_tokens(embedding, last[:, None])
                        if cfg.scale_embeddings:
                            h = h * jnp.sqrt(jnp.float32(
                                cfg.embed_dim)).astype(h.dtype)
                        h = pcast(h, (PIPE_AXIS,), to="varying")
                        positions = valid[:, None]

                        def hop(s, carry):
                            h, c1_l, c2_l = carry
                            active = stage == s
                            h_new, c1_l, c2_l = scan_step(
                                stage_layers, c1_l, c2_l, h, positions,
                                valid + 1, valid, extra, active)
                            h = jnp.where(active, h_new, h)
                            h = jax.lax.ppermute(
                                h, PIPE_AXIS,
                                [(x, (x + 1) % self.n_stages)
                                 for x in range(self.n_stages)])
                            return h, c1_l, c2_l

                        h, c1_l, c2_l = jax.lax.fori_loop(
                            0, self.n_stages, hop, (h, c1_l, c2_l))
                        # after n_stages hops the final hidden wrapped
                        # back to stage 0; broadcast it to every stage
                        # for sampling
                        h = jax.lax.psum(
                            jnp.where(stage == 0, h, 0.0)
                            .astype(jnp.float32), PIPE_AXIS) \
                            .astype(h.dtype)
                        h = rms_norm(h, final_norm, cfg.norm_eps,
                                     cfg.rmsnorm_unit_offset)
                        # Decode lm head INSIDE the stage region (manual
                        # over "pipe"): the stage context routes an int4
                        # head onto the kernel — single-device via
                        # LOCAL_MESH on pipe-only meshes, nested
                        # shard_map over "model" under TP-in-stage.
                        with _stage_mesh_ctx():
                            logits = _einsum("bte,ve->btv", h, head,
                                             tp="col")
                        if cfg.final_logit_softcap is not None:
                            logits = cfg.final_logit_softcap * jnp.tanh(
                                logits / cfg.final_logit_softcap)
                        key, sub = jax.random.split(key)
                        row_logits = logits[:, 0]
                        if greedy:
                            nxt = jnp.argmax(row_logits, axis=-1) \
                                .astype(jnp.int32)
                        else:
                            nxt = sample_token_batch(
                                row_logits, sub, temps, top_ks,
                                top_ps).astype(jnp.int32)
                        nxt = jnp.where(done | (step >= row_budgets),
                                        eos, nxt)
                        out = out.at[:, step].set(nxt)
                        new_done = done | (nxt == eos)
                        valid = jnp.where(done, valid, valid + 1)
                        return (step + 1, nxt, valid, new_done, out,
                                c1_l, c2_l, key)

                    state = (jnp.int32(0), first_token, start_valid,
                             done0, out0, c1_l, c2_l, key)
                    step, last, valid, done, out, c1_l, c2_l, _ = \
                        jax.lax.while_loop(cond, tok_body, state)
                    return (out, step[None], last, valid, done,
                            c1_l[None], c2_l[None])

                out, step, last, valid, done, c1, c2 = shard_map(
                    per_stage, mesh=mesh,
                    in_specs=(P(PIPE_AXIS), P(PIPE_AXIS), P(PIPE_AXIS),
                              P(), P(), P(), P(), P(), P(), P(), P(),
                              P(), P(), P(), P(), P()),
                    out_specs=(P(), P(PIPE_AXIS), P(), P(), P(),
                               P(PIPE_AXIS), P(PIPE_AXIS)),
                    axis_names={PIPE_AXIS},
                    check_vma=False,
                )(staged, c1, c2, first_token, start_valid, key, budget,
                  temps, top_ks, top_ps, row_budgets, done_in, extra,
                  shared["embedding"], head, shared["final_norm"])
                return out, step[0], last, valid, done, (c1, c2)

            return pp_prefill, pp_decode

        self._pp_prefill, self._pp_decode = make_pp_programs(stage_scan)

        if self._pool_direct:
            from .pallas import attention as pattn

            def stage_scan_paged(stage_layers, kp_l, vp_l, h, positions,
                                 valid, _offsets, table, write_ok):
                """This stage's layers over h, POOL-DIRECT: kp_l/vp_l
                [per, P, ps, K, D] — each layer scatters its K/V into the
                rows' pages (masked to a same-bytes rewrite during
                schedule bubbles / inactive decode hops) and attends
                through the page-table-aware kernels, so the
                position-aligned gather view is never built. `valid`
                counts entries INCLUDING this call (kernel contract);
                write exclusivity per engine/paged_forward.py: COW +
                slot-owned frontier pages. `_offsets` (the contiguous
                layout's cache write offset) is unused: pages encode
                the position. Chunk shapes are always kernel-legal in
                serving: prompt_budget reserves ≥ DECODE_SEGMENT+1
                positions of cache tail, so chunked_prefill's bucket is
                always a power of two ≥ 8 (same contract as
                engine.paged_direct / forward_paged)."""
                b_ = h.shape[0]
                ps = kp_l.shape[2]
                pages = table[jnp.arange(b_)[:, None], positions // ps]
                offs_in = positions % ps

                def body(h, xs):
                    layer, kp1, vp1 = xs

                    def attn_fn(hh, lyr):
                        q, k, v = project_qkv(hh, lyr, cfg, positions)
                        cur_k = kp1[pages, offs_in]
                        cur_v = vp1[pages, offs_in]
                        kp2 = kp1.at[pages, offs_in].set(
                            jnp.where(write_ok, k, cur_k))
                        vp2 = vp1.at[pages, offs_in].set(
                            jnp.where(write_ok, v, cur_v))
                        if n_model > 1:
                            # TP-in-stage: the paged kernels as a nested
                            # shard_map over the auto "model" axis (the
                            # context mesh has "pipe" already Manual).
                            # The build-time gate guarantees the head
                            # layout partitions, so None cannot happen
                            # (and guarantees native shard_map, so the
                            # context AbstractMesh is real).
                            ctx = jax.sharding.get_abstract_mesh()
                            if hh.shape[1] == 1:
                                out = pattn.paged_decode_spmd(
                                    ctx, q, kp2, vp2, table, valid,
                                    sliding_window=cfg.sliding_window,
                                    softcap=cfg.attn_logit_softcap)
                            else:
                                out = pattn.paged_prefill_spmd(
                                    ctx, q, kp2, vp2, table,
                                    positions[:, 0], valid,
                                    sliding_window=cfg.sliding_window,
                                    softcap=cfg.attn_logit_softcap)
                            if out is None:
                                # The build gate already guarantees the
                                # head layout partitions, so the only
                                # reachable cause is an unsupported
                                # chunk/pool shape.
                                raise ValueError(
                                    "paged pool-direct under TP-in-stage "
                                    "could not serve this dispatch: "
                                    f"chunk T={hh.shape[1]} / page_size="
                                    f"{ps} / head_dim={q.shape[-1]} is "
                                    "not kernel-legal (or the head "
                                    "layout stopped partitioning)")
                        elif hh.shape[1] == 1:
                            out = pattn.paged_decode_attention(
                                q, kp2, vp2, table, valid,
                                sliding_window=cfg.sliding_window,
                                softcap=cfg.attn_logit_softcap)
                        else:
                            out = pattn.paged_prefill_attention(
                                q, kp2, vp2, table, positions[:, 0],
                                valid,
                                sliding_window=cfg.sliding_window,
                                softcap=cfg.attn_logit_softcap)
                        out = _einsum("bthd,hde->bte", out,
                                      lyr["o_proj"],
                                      tp="row").astype(hh.dtype)
                        return out, (kp2, vp2)

                    # (no kv_valid: with attn_fn set transformer_block
                    # ignores it — valid-length masking happens inside
                    # the paged kernels, same contract as forward_paged)
                    h, (kp1, vp1) = transformer_block(
                        h, layer, cfg, positions, None, None, None,
                        attn_fn=attn_fn)
                    return h, (kp1, vp1)

                # Same mesh context as the contiguous stage_scan: the
                # projections/MLP _einsums inside the blocks route int4
                # onto the kernel path (LOCAL_MESH on pipe-only meshes,
                # the abstract mesh under TP-in-stage).
                with _stage_mesh_ctx():
                    h, (kp_l, vp_l) = jax.lax.scan(
                        body, h, (stage_layers, kp_l, vp_l))
                return h, kp_l, vp_l

            self._pp_prefill_paged, self._pp_decode_paged = \
                make_pp_programs(stage_scan_paged)

        @partial(jax.jit, donate_argnums=(0, 1))
        def pp_copy_spans(kc, vc, src_idx, dst_idx, lo, hi):
            # Cross-knight prefix sharing, stage-sharded edition: copy K/V
            # positions [lo_i, hi_i) from slot src_idx[i] into dst_idx[i]
            # across EVERY stage's layer range. The slot axis (dim 2) is
            # unsharded, so the gather/scatter stays stage-local — no
            # cross-stage traffic (each stage copies its own layers' span).
            s_len = kc.shape[3]
            pos = jnp.arange(s_len).reshape(1, 1, 1, s_len, 1, 1)
            lo_b = lo.reshape(1, 1, -1, 1, 1, 1)
            hi_b = hi.reshape(1, 1, -1, 1, 1, 1)
            span = (pos >= lo_b) & (pos < hi_b)
            nk = jnp.where(span, kc[:, :, src_idx], kc[:, :, dst_idx])
            nv = jnp.where(span, vc[:, :, src_idx], vc[:, :, dst_idx])
            return kc.at[:, :, dst_idx].set(nk), \
                vc.at[:, :, dst_idx].set(nv)

        self._pp_copy_spans = pp_copy_spans

    # --- construction from adapter config ---

    @classmethod
    def from_config(cls, config: dict[str, Any]) -> "PPEngine":
        from .models.registry import get_model_config
        model_name = config.get("model", "tiny-gemma")
        overrides = {}
        if config.get("max_seq_len"):
            overrides["max_seq_len"] = int(config["max_seq_len"])
        model_cfg = get_model_config(model_name, **overrides)
        dtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
                 "float16": jnp.float16}[config.get("dtype", "bfloat16")]
        sampling_cfg = config.get("sampling", {})
        sampling = SamplingParams(
            temperature=float(sampling_cfg.get("temperature", 0.7)),
            top_k=int(sampling_cfg.get("top_k", 0)),
            top_p=float(sampling_cfg.get("top_p", 1.0)),
            max_new_tokens=int(sampling_cfg.get("max_new_tokens", 1024)),
        )
        mesh = config.get("mesh", {})
        # Refuse configs this engine would otherwise silently serve
        # differently than asked (the "silent config drop" class): a
        # data axis means DP inside stages (unimplemented), and
        # seq-parallel is a main-engine feature. "model" composes:
        # mesh={"pipe": N, "model": M} runs TP inside each stage.
        extra_axes = sorted(set(mesh) - {"pipe", "model"})
        if extra_axes:
            raise ValueError(
                f"mesh axes {extra_axes} are not supported alongside "
                "'pipe' — the PP engine supports mesh={'pipe': N} or "
                "mesh={'pipe': N, 'model': M} (TP inside stages); use a "
                "(data, model) mesh on the main engine for DP")
        if config.get("seq_parallel"):
            raise ValueError(
                "seq_parallel is not supported on the PP engine — use a "
                "(data, model) mesh for ring/Ulysses long-context")
        engine = cls(
            model_cfg,
            checkpoint=config.get("checkpoint", "") or "",
            n_stages=int(mesh.get("pipe", 2)),
            n_model=int(mesh.get("model", 1)),
            n_micro=int(config.get("n_micro", 2)),
            num_slots=int(config.get("num_slots", 4)),
            dtype=dtype, quant=config.get("quant", "none"),
            kv_layout=config.get("kv_layout", "contiguous"),
            page_size=int(config.get("page_size", 128)),
            num_pages=(int(config["num_pages"])
                       if config.get("num_pages") else None),
            attn=config.get("attn") or "auto",
            sampling=sampling,
            seed=int(config.get("seed", 0)),
            devices=config.get("devices"),
            prefix_cache=config.get("prefix_cache"),
            prefix_cache_pages=(int(config["prefix_cache_pages"])
                                if config.get("prefix_cache_pages")
                                else None),
        )
        # Fleet auto-degrade marker — surfaced via describe() (advisor r3).
        engine.quant_auto_degraded = bool(
            config.get("_quant_auto_degraded"))
        if "dispatch_retries" in config:
            from .faults import RetryPolicy
            engine.retry = RetryPolicy(
                max_retries=max(0, int(config["dispatch_retries"])))
        return engine

    # --- serving (same surface the adapter uses on InferenceEngine) ---

    def int4_path_report(self) -> Optional[dict]:
        """InferenceEngine.int4_path_report's PP counterpart — same
        trace-time provenance (stage matmuls AND the in-stage decode /
        post-gather prefill lm-head dispatches)."""
        if self.quant != "int4":
            return None
        from .engine import summarize_int4_paths
        return summarize_int4_paths(self._int4_dispatches)

    def revive_kv_if_dead(self) -> bool:
        """InferenceEngine.revive_kv_if_dead's PP counterpart: paged
        pools live in the allocator; contiguous stage-stacked caches
        live here next to their SlotBook."""
        if self.kv_layout == "paged":
            # Branch on the LAYOUT, not `self.kc is None`: a dispatch
            # that failed inside the gather→scatter window leaves a
            # deleted gather view behind (the finally's scatter raised
            # before resetting kc/vc). Drop the view — the pools are
            # the source of truth — then let the allocator revive them
            # if the failure consumed the pools too.
            self.kc = self.vc = None
            return self.kv.revive_if_dead()
        if not self.kc.is_deleted():
            return False
        self.kc = self._make_contig()
        self.vc = self._make_contig()
        self.kv.forget_all()
        return True

    def chars_per_token(self) -> float:
        if self._chars_per_token is None:
            sample = ("The quick brown fox jumps over the lazy dog. "
                      "def main(args): return 0  # typical source text\n" * 4)
            n = len(self.tokenizer.encode(sample, add_bos=False))
            self._chars_per_token = max(len(sample) / max(n, 1), 0.25)
        return self._chars_per_token

    def _next_key(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def warmup(self, max_prompt_tokens: int = 256,
               batch_sizes: tuple[int, ...] = (1,)) -> float:
        """Compile every (batch, bucket) prefill program ≤ the prompt
        limit plus the decode segment, twice each for the donated-buffer
        layout fixpoint — same discipline as InferenceEngine.warmup, so
        real prompts hitting smaller buckets (or multi-chunk prefills)
        never compile mid-serve on a cold cache."""
        t0 = time.monotonic()
        # Re-warm is always sanctioned — same contract as the main
        # engine's warmup (reopen first, declare at the end).
        from . import compile_watch
        compile_watch.reopen_warmup(self.cfg.name)
        limit = min(max_prompt_tokens,
                    self.max_seq_len - DECODE_SEGMENT - 1)
        buckets = [x for x in PREFILL_BUCKETS if x <= bucket_for(limit)]
        for b in batch_sizes:
            if b > self.kv.num_slots:
                continue
            for bucket in buckets:
                n = min(bucket, limit)
                turns = [(f"__warmup_{i}",
                          [self.tokenizer.bos_id] + [5 + i] * (n - 1))
                         for i in range(b)]
                for _ in range(2):
                    for name, _p in turns:
                        self.kv.release(name)
                    self.generate_batch(turns, max_new_tokens=1)
        # Warm the shared-prefix copy program (ONE shape thanks to
        # _apply_copies' padding) and the layout fixpoint of the programs
        # that consume the copied kc/vc — otherwise the first real round
        # with a shared preamble compiles mid-serve (same discipline as
        # InferenceEngine.warmup).
        from .engine import MIN_SHARED_PREFIX
        if self.kv.num_slots >= 2 and limit > MIN_SHARED_PREFIX + 8:
            shared = [self.tokenizer.bos_id] + [7] * (MIN_SHARED_PREFIX + 4)
            turns = [(f"__warmup_{i}", shared + [9 + i] * 4)
                     for i in range(2)]
            for _ in range(2):
                for name, _p in turns:
                    self.kv.release(name)
                self.generate_batch(turns, max_new_tokens=1)
        for i in range(max(max(batch_sizes), 2)):
            self.kv.release(f"__warmup_{i}")
        # Steady-state declaration (ISSUE 6): any later compile is a
        # recorded mid-serve recompile — same contract as the main
        # engine's warmup.
        from . import compile_watch
        compile_watch.warmup_complete(self.cfg.name)
        return time.monotonic() - t0

    def generate(self, prompt, slot_name: str = "default",
                 max_new_tokens: Optional[int] = None,
                 timeout_s: float = 600.0, session=None) -> str:
        return self.generate_batch([(slot_name, prompt)],
                                   max_new_tokens=max_new_tokens,
                                   timeout_s=timeout_s, session=session)[0]

    def generate_batch(self, turns, max_new_tokens=None,
                       timeout_s: float = 600.0,
                       sampling_per_turn=None, budget=None,
                       session=None) -> list[str]:
        return self.generate_batch_with_stats(
            turns, max_new_tokens=max_new_tokens, timeout_s=timeout_s,
            sampling_per_turn=sampling_per_turn, budget=budget,
            session=session)[0]

    def generate_batch_with_stats(self, turns, max_new_tokens=None,
                                  timeout_s: float = 600.0,
                                  sampling_per_turn=None, budget=None,
                                  session=None):
        # Session-namespaced slot names — same cross-session collision
        # fix as the main engine (kvcache.scoped_slot): concurrent
        # discussions sharing a PP engine keep disjoint slot lineages.
        if session:
            from .kvcache import scoped_slot
            turns = [(scoped_slot(session, name), prompt)
                     for name, prompt in turns]
        # Admission gate (fleet.drain) — same contract as the main
        # engine: one flag check per call, in-flight turns complete.
        deadlines.check_admission()
        with self._serve_lock:
            # "turn" span — same rung as the main engine (ISSUE 5) —
            # and the call-level compile-attribution window (ISSUE 6):
            # PP's stage dispatches funnel through run_dispatch, whose
            # rung-level fallback label carries no engine attr, so this
            # outer window is what makes a PP compile attributable to
            # THIS engine (and sentinel-enforceable once warm).
            from ..utils import telemetry
            from . import compile_watch
            with compile_watch.label(f"pp_serve[b={len(turns)}]",
                                     engine=self.cfg.name):
                if telemetry.ACTIVE:
                    with telemetry.span("turn", engine=self.cfg.name,
                                        rows=len(turns),
                                        session=session or "", pp=True):
                        return self._generate_locked(
                            turns, max_new_tokens, timeout_s,
                            sampling_per_turn, budget)
                return self._generate_locked(turns, max_new_tokens,
                                             timeout_s,
                                             sampling_per_turn, budget)

    def _chunked_rows(self, slot_ids, token_lists, offsets,
                      deadline, budget=None) -> jax.Array:
        """Chunked bucketed prefill of the given rows through the PP step
        program; returns last-token logits [B, V]."""
        slot_idx = jnp.asarray(slot_ids, jnp.int32)

        def prefill_dispatch(chunk, offs, lengths):
            last, caches = self._pp_prefill(
                self.shared, self.staged, (self.kc, self.vc), slot_idx,
                jnp.asarray(chunk), jnp.asarray(offs, jnp.int32),
                jnp.asarray(lengths))
            # Late completion of a watchdog-abandoned wait must not
            # clobber caches the recovery path revived (deadlines.py).
            with deadlines.commit_guard():
                self.kc, self.vc = caches
            return last

        return chunked_prefill(prefill_dispatch, token_lists, offsets,
                               self.max_seq_len, self.tokenizer.pad_id,
                               deadline, retry=self.retry, budget=budget)

    def _apply_copies(self, copies) -> None:
        """Dispatch queued (src_slot, dst_slot, lo, hi) span copies —
        padded to num_slots rows so pp_copy_spans compiles exactly ONE
        shape (same recompile guard as InferenceEngine._apply_copies);
        pad rows self-copy an empty span of a non-destination slot (dst
        indices stay distinct: scatter order among duplicates is
        unspecified)."""
        if not copies:
            return
        width = self.kv.num_slots
        if len(copies) < width:
            used = {c[1] for c in copies}
            pad_dst = next(i for i in range(width) if i not in used)
            copies = copies + [(pad_dst, pad_dst, 0, 0)] * (width -
                                                            len(copies))
        src, dst, lo, hi = (jnp.asarray(x, jnp.int32)
                            for x in zip(*copies))
        self.kc, self.vc = self._pp_copy_spans(self.kc, self.vc, src, dst,
                                               lo, hi)

    def _chunked_rows_pool_direct(self, token_lists, offsets, tables,
                                  deadline, budget=None) -> jax.Array:
        """Chunked bucketed prefill straight off the stage-stacked page
        pools (no gather view); returns last-token logits [B, V]."""
        def prefill_dispatch(chunk, offs, lengths):
            last, pools0 = self._pp_prefill_paged(
                self.shared, self.staged, self.kv.pools[0], tables,
                jnp.asarray(chunk), jnp.asarray(offs, jnp.int32),
                jnp.asarray(lengths))
            with deadlines.commit_guard():
                self.kv.pools = [pools0]
            return last

        return chunked_prefill(prefill_dispatch, token_lists, offsets,
                               self.max_seq_len, self.tokenizer.pad_id,
                               deadline, retry=self.retry, budget=budget)

    def _prefill_rows_paged(self, names_sub, token_spans, offsets_sub,
                            deadline, pinned, budget=None) -> None:
        """Prefill rows against the pool — pool-direct when the kernels
        are active, else the gather→chunked-prefill→scatter fallback.
        Either way the paged leader pass must land in the pool BEFORE
        laggards alias its pages."""
        for name, toks, off in zip(names_sub, token_spans, offsets_sub):
            self.kv.ensure_capacity(name, off + len(toks), write_from=off,
                                    pinned=pinned)
        tables = jnp.asarray(self.kv.table_for(list(names_sub)))
        if self._pool_direct:
            self._chunked_rows_pool_direct(token_spans, offsets_sub,
                                           tables, deadline, budget)
            return
        self.kc, self.vc = self._gather_view(self.kv.pools, tables)
        try:
            self._chunked_rows(list(range(len(names_sub))), token_spans,
                               offsets_sub, deadline, budget)
        finally:
            self.kv.pools = self._scatter_view(self.kv.pools, tables,
                                               self.kc, self.vc)
            self.kc = self.vc = None

    def _share_prefixes(self, names, slot_ids, all_tokens, offsets,
                        deadline, budget=None):
        """Cross-knight shared-prefix reuse on the stage-local caches —
        kvcache.share_prefixes (the same two-pass algorithm the main
        engine runs) with PP device mechanics: stage-sharded span copies
        (contiguous) or page aliasing (paged), and chunked leader
        prefill."""
        from .engine import MIN_SHARED_PREFIX
        from .kvcache import share_prefixes
        paged = self.kv_layout == "paged"
        pinned = tuple(names)
        copies: list[tuple[int, int, int, int]] = []

        def add_share(donor, i, lo, hi):
            if paged:
                self.kv.alias_span(donor.name, names[i], lo, hi, pinned)
            else:
                copies.append((donor.slot_id, slot_ids[i], lo, hi))

        def flush_shares():
            self._apply_copies(copies)
            copies.clear()

        def prefill_span(m, lo, hi):
            if paged:
                self._prefill_rows_paged(
                    [names[m]], [all_tokens[m][lo:hi]], [lo], deadline,
                    pinned, budget)
            else:
                self._chunked_rows([slot_ids[m]], [all_tokens[m][lo:hi]],
                                   [lo], deadline, budget)

        return share_prefixes(
            self.kv, names, all_tokens, offsets,
            min_shared=MIN_SHARED_PREFIX, add_share=add_share,
            flush_shares=flush_shares, prefill_span=prefill_span)

    def _prepare_batch(self, turns, max_new_padded, deadline, pre_budget,
                       stats) -> dict:
        """The PP pre-PREFILL phase — tokenize + tail-truncate →
        own-slot reuse_plan → prefix-cache attach → cross-knight
        share_prefixes → paged capacity/COW + tables/gather-view — as
        ONE seam mirroring InferenceEngine._prepare_batch's
        defer_prefill contract (ISSUE 8, the mixed-dispatch seam): the
        returned suffixes (all_tokens[i][offsets[i]:]) are NOT yet
        prefilled, so a caller can feed them through a mixed dispatch
        instead of the blocking prologue. _generate_locked is today's
        only consumer (PP's stage-pipelined programs have no ragged
        program yet) and runs the chunked prologue over the same dict."""
        pinned = tuple(name for name, _ in turns)
        slot_ids, offsets, all_tokens = [], [], []
        for name, prompt in turns:
            tokens = (list(prompt) if isinstance(prompt, list)
                      else self.tokenizer.encode(prompt))
            budget_tok = prompt_budget(self.max_seq_len, max_new_padded)
            if len(tokens) > budget_tok:
                tokens = (tokens[:1]
                          + tokens[len(tokens) - budget_tok + 1:])
            slot_id, reuse = self.kv.reuse_plan(name, tokens, pinned)
            slot_ids.append(slot_id)
            offsets.append(reuse)
            all_tokens.append(tokens)

        # Cross-session prefix cache (ISSUE 7): same consult the main
        # engine's _prepare_batch runs (prefix_cache.attach_rows — one
        # definition, so the warmup-exclusion rule and accounting can
        # never drift between the serving paths).
        prefix_reused = 0
        if getattr(self, "prefix_cache", None) is not None:
            prefix_reused = self.prefix_cache.attach_rows(
                list(pinned), all_tokens, offsets, pinned)

        offsets, extra_prefill = self._share_prefixes(
            list(pinned), slot_ids, all_tokens, offsets, deadline,
            budget=pre_budget)
        # Copied donor spans count as reused (same accounting as the main
        # engine); the leader's extra span was genuinely prefilled.
        stats.reused_tokens = sum(offsets) - extra_prefill
        stats.prefix_reused_tokens = prefix_reused
        stats.prefill_tokens = extra_prefill + sum(
            len(t) - o for t, o in zip(all_tokens, offsets))

        tables = None
        gathered = False
        if self.kv_layout == "paged":
            # Allocate pages for the whole call (prompt + padded decode),
            # COW any shared page in the write range. Pool-direct mode
            # serves straight off the stage-stacked pool through the
            # page-table-aware kernels; otherwise gather the pool into
            # the position-aligned view every PP program uses. Either
            # way the row index IS the batch index.
            for i, name in enumerate(pinned):
                self.kv.ensure_capacity(
                    name, len(all_tokens[i]) + max_new_padded,
                    write_from=offsets[i], pinned=pinned)
            tables = jnp.asarray(self.kv.table_for(list(pinned)))
            if not self._pool_direct:
                self.kc, self.vc = self._gather_view(self.kv.pools,
                                                     tables)
                gathered = True
            slot_ids = list(range(len(turns)))
        return {"pinned": pinned, "slot_ids": slot_ids,
                "offsets": offsets, "all_tokens": all_tokens,
                "tables": tables, "gathered": gathered}

    def _generate_locked(self, turns, max_new_tokens, timeout_s,
                         sampling_per_turn=None, budget=None):
        stats = GenStats()
        # Turn budget node (engine/deadlines.py) — same rung structure
        # as the main engine; the float deadline feeds the legacy
        # checks.
        turn_budget = budget if budget is not None \
            else deadlines.Budget.root(timeout_s, rung="turn")
        deadline = min(turn_budget.deadline, time.monotonic() + timeout_s)
        pre_budget = turn_budget.child("prefill")
        from .serving_loop import clamp_max_new
        max_new, max_new_padded = clamp_max_new(
            max_new_tokens or self.sampling.max_new_tokens,
            self.max_seq_len)

        prep = self._prepare_batch(turns, max_new_padded, deadline,
                                   pre_budget, stats)
        pinned = prep["pinned"]
        slot_ids = prep["slot_ids"]
        offsets = prep["offsets"]
        all_tokens = prep["all_tokens"]
        tables = prep["tables"]
        gathered = prep["gathered"]

        try:
            # Chunked bucketed prefill (shared serving_loop host loop
            # with the PP step program).
            t0 = time.monotonic()
            spans = [t[o:] for t, o in zip(all_tokens, offsets)]
            with telemetry.span("prefill", engine=self.cfg.name,
                                pp=True):
                if tables is not None and self._pool_direct:
                    last_logits = self._chunked_rows_pool_direct(
                        spans, offsets, tables, deadline, pre_budget)
                else:
                    last_logits = self._chunked_rows(slot_ids, spans,
                                                     offsets, deadline,
                                                     pre_budget)
                # Blocking scalar fetch → the deadline seam (a wedged
                # prefill program freezes the host loop exactly here).
                host_sync(lambda: float(last_logits[0, 0]), pre_budget,
                          "prefill")
            stats.prefill_seconds = time.monotonic() - t0
            slot_idx = jnp.asarray(slot_ids, jnp.int32)

            per_row = sampling_per_turn or [self.sampling] * len(turns)
            if len(per_row) != len(turns):
                raise ValueError(
                    f"sampling_per_turn has {len(per_row)} entries for "
                    f"{len(turns)} turns")
            temps, top_ks, top_ps = sampling_arrays(per_row)
            greedy = all(p.temperature <= 0.0 for p in per_row)
            if greedy:
                first = jnp.argmax(last_logits.astype(jnp.float32),
                                   axis=-1).astype(jnp.int32)
            else:
                first = sample_token_batch(
                    last_logits.astype(jnp.float32), self._next_key(),
                    temps, top_ks, top_ps).astype(jnp.int32)
            first_np = host_sync(lambda: np.asarray(first), pre_budget,
                                 "prefill")
            cur_valid = jnp.asarray([len(t) for t in all_tokens],
                                    jnp.int32)

            t1 = time.monotonic()
            # Decode rung budget derived at decode start, so a
            # configured "decode" cap times the decode phase alone.
            dec_budget = turn_budget.child("decode")
            # Per-row decode budgets (knight_sampling max_new_tokens) —
            # serving_loop.row_budget_fn, one definition for both engines.
            from .serving_loop import row_budget_fn
            row_remaining = row_budget_fn(per_row, sampling_per_turn,
                                          max_new)

            if tables is not None and self._pool_direct:
                def decode_dispatch(cur_last, valid, budget, done0):
                    row_budgets = row_remaining(budget)
                    out, steps, last, valid, done, pools0 = \
                        self._pp_decode_paged(
                            self.shared, self.staged, self.kv.pools[0],
                            tables, cur_last, valid, self._next_key(),
                            budget, temps, top_ks, top_ps, row_budgets,
                            done0, max_new=DECODE_SEGMENT, greedy=greedy)
                    with deadlines.commit_guard():
                        self.kv.pools = [pools0]
                    return out, steps, last, valid, done
            else:
                def decode_dispatch(cur_last, valid, budget, done0):
                    row_budgets = row_remaining(budget)
                    out, steps, last, valid, done, caches = \
                        self._pp_decode(
                            self.shared, self.staged, (self.kc, self.vc),
                            slot_idx, cur_last, valid, self._next_key(),
                            budget, temps, top_ks, top_ps, row_budgets,
                            done0, max_new=DECODE_SEGMENT, greedy=greedy)
                    with deadlines.commit_guard():
                        self.kc, self.vc = caches
                    return out, steps, last, valid, done

            with telemetry.span("decode", engine=self.cfg.name,
                                pp=True):
                out_np = decode_segments(decode_dispatch, first,
                                         cur_valid,
                                         self.tokenizer.eos_id, max_new,
                                         deadline, timeout_s,
                                         retry=self.retry,
                                         budget=dec_budget)
            stats.decode_seconds = time.monotonic() - t1
        finally:
            # Scatter back even on a mid-serve timeout: otherwise the
            # gathered view (the full contiguous-size budget paging
            # avoids) stays resident and every prefilled token is lost.
            # Slot records stay truncated until commit, so a partial
            # scatter only under-claims. (Pool-direct mode writes the
            # pool incrementally per dispatch — nothing to scatter.)
            if gathered:
                self.kv.pools = self._scatter_view(self.kv.pools, tables,
                                                   self.kc, self.vc)
                self.kc = self.vc = None

        results = finalize_outputs(
            turns, first_np, out_np, all_tokens, max_new,
            self.tokenizer.eos_id, self.kv.commit, self.tokenizer.decode,
            stats)
        stats.int4_paths = self.int4_path_report()
        # Unified registry publish (ISSUE 5) — same seam as the main
        # engine, so PP serving's counters land in the one store too.
        trace_hooks.publish_gen_stats(stats, self.cfg.name,
                                      perf=self.perf)
        trace_hooks.publish_int4_paths(stats.int4_paths, self.cfg.name)
        trace_hooks.publish_memory_ledger(self)
        self.last_stats = stats
        return results, stats

    # --- introspection ---

    def describe(self) -> dict[str, Any]:
        info = {
            "model": self.cfg.name,
            "params": self.num_params,
            "max_seq_len": self.max_seq_len,
            "mesh": ({"pipe": self.n_stages, "model": self.n_model}
                     if self.n_model > 1 else {"pipe": self.n_stages}),
            "n_micro": self.n_micro,
            "num_slots": self.kv.num_slots,
            "kv_layout": (f"stage-local {self.kv_layout}"
                          + (" (pool-direct)" if self._pool_direct
                             else (" (gather-view)"
                                   if self.kv_layout == "paged" else ""))),
            "attn": self.cfg.attn_impl,
            "quant": (self.quant + " (auto-degraded)"
                      if getattr(self, "quant_auto_degraded", False)
                      else self.quant),
            "scope": "PP serving: prefill + decode with stage-local KV "
                     "(contiguous or paged pool; pool-direct "
                     "page-table kernels, incl. TP-in-stage via nested "
                     "shard_map over the model axis); flash kernels "
                     "inside stages (raw on pipe-only meshes, spmd "
                     "wrappers under TP-in-stage; dense only by opt-out "
                     "or non-partitionable heads); own-slot LCP reuse; "
                     "cross-knight donor + leader prefix sharing (page "
                     "aliasing when paged); per-row sampling; int8 "
                     "w8a16; int4 w4a16 on the fused kernels inside "
                     "stages (LOCAL_MESH / nested shard_map)",
            "devices": [str(d) for d in self.mesh.devices.flatten()],
        }
        if self.quant == "int4":
            info["int4_paths"] = self.int4_path_report()
        # ISSUE 7: cross-session prefix-cache state (paged layouts).
        if getattr(self, "prefix_cache", None) is not None:
            info["prefix_cache"] = self.prefix_cache.describe()
        # ISSUE 5: the unified registry's per-engine view.
        info["telemetry"] = trace_hooks.engine_telemetry_view(
            self.cfg.name)
        # ISSUE 6: live perf attribution (same surface as the main
        # engine's describe()).
        from . import compile_watch, get_compile_cache_decision
        info["perf"] = self.perf.describe()
        info["compile_cache"] = get_compile_cache_decision()
        info["compile_observatory"] = compile_watch.summary()
        return info
