"""Tokenizer layer for the engine.

Replaces the reference's 4-chars/token estimate (local-llm.ts:58-70) with
real token counts. Two implementations behind one interface:

- HfTokenizer: any HuggingFace tokenizer (SentencePiece/BPE) loaded from a
  local path via `transformers` — used when serving real checkpoints.
- ByteTokenizer: self-contained byte-level fallback (no downloads, exact
  round-trip) — used for random-weight runs, tests, and benches.
"""

from __future__ import annotations

from typing import Optional, Protocol


class Tokenizer(Protocol):
    bos_id: int
    eos_id: int
    pad_id: int
    vocab_size: int

    def encode(self, text: str, add_bos: bool = True) -> list[int]: ...
    def decode(self, ids: list[int]) -> str: ...


class ByteTokenizer:
    """Bytes 0-255 mapped to ids 3-258; specials pad=0, bos=1, eos=2."""

    SPECIALS = 3

    def __init__(self):
        self.pad_id = 0
        self.bos_id = 1
        self.eos_id = 2
        self.vocab_size = 256 + self.SPECIALS

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = [b + self.SPECIALS for b in text.encode("utf-8")]
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: list[int]) -> str:
        # Models may carry vocab > 259 (padded vocab tables); ids beyond the
        # byte range decode to nothing rather than crashing.
        data = bytes(i - self.SPECIALS for i in ids
                     if self.SPECIALS <= i < self.SPECIALS + 256)
        return data.decode("utf-8", errors="replace")


class HfTokenizer:
    """transformers-backed tokenizer from a local checkpoint directory."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer
        self._tok = AutoTokenizer.from_pretrained(path)
        # `x if x is not None` — id 0 is a legitimate special-token id in
        # several SentencePiece vocabs; `or` would silently replace it.
        def _id(value, default):
            return value if value is not None else default
        self.bos_id = _id(self._tok.bos_token_id, 1)
        self.eos_id = _id(self._tok.eos_token_id, 2)
        self.pad_id = _id(self._tok.pad_token_id, 0)
        self.vocab_size = len(self._tok)

    def encode(self, text: str, add_bos: bool = True) -> list[int]:
        ids = self._tok.encode(text, add_special_tokens=False)
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: list[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)


_TOKENIZER_FILES = ("tokenizer.json", "tokenizer.model",
                    "tokenizer_config.json", "spiece.model")


def load_tokenizer(checkpoint_path: Optional[str]) -> Tokenizer:
    """HF tokenizer when the checkpoint dir ships one, else byte-level.

    A checkpoint WITH tokenizer files that fail to load raises — silently
    serving a 256k-vocab model through the byte tokenizer would produce
    garbage with no indication why. Checkpoints without tokenizer files
    (weight-only test fixtures) fall back to bytes.
    """
    if checkpoint_path:
        from pathlib import Path
        has_files = any((Path(checkpoint_path) / f).exists()
                        for f in _TOKENIZER_FILES)
        if has_files:
            try:
                return HfTokenizer(checkpoint_path)
            except Exception as e:
                raise RuntimeError(
                    f"Checkpoint {checkpoint_path} has tokenizer files but "
                    f"they failed to load: {e}") from e
    return ByteTokenizer()
