"""Pool-direct paged decode forward (VERDICT r2 weak #7).

The engine's fallback paged decode gathers `pool[table]` into the same
position-aligned `[B, S, K, D]` view the contiguous layout uses — layout-
agnostic and correct, but during a decode segment that view exists
ALONGSIDE the pool, temporarily recreating the full contiguous HBM
budget paging exists to avoid, and the gather/scatter traffic scales
with max_seq_len rather than tokens cached.

This module serves decode STRAIGHT off the pools: each step scatters the
new K/V row into its frontier page (`table[b, pos // ps]`, offset
`pos % ps` — a [B]-row `.at[].set`), then runs
pallas.paged_decode_attention, whose kv-block index map reads the page
table and fetches only pages below each row's frontier. All block wiring
(norms, residuals, MLP, every family flag) comes from
models/common.transformer_block via its attn_fn hook — the same seam the
ring/Ulysses cores use — so the math is defined in exactly one place.

Write-exclusivity invariant: the engine's ensure_capacity copy-on-writes
any shared page in a row's write range before dispatch, and distinct
batch rows are distinct slots owning their frontier pages exclusively,
so the per-step scatter never touches an aliased page.

Multi-device: the kernel runs under shard_map via paged_decode_spmd
(kv heads on "model" — matching the engine's pool sharding — batch
rows on "data" when divisible); head layouts that don't partition fall
back to the engine's gather-view decode at build time
(engine.paged_direct), so this module never traces an unpartitionable
kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .models.common import (ModelConfig, Params, _einsum, _softcap,
                            current_spmd_mesh, embed_tokens, project_qkv,
                            rms_norm, transformer_block)
from .pallas import attention as pattn


def forward_paged_decode(
    params: Params, cfg: ModelConfig,
    tokens: jax.Array,            # [B, 1] this step's token ids
    positions: jax.Array,         # [B, 1] absolute positions (== valid)
    pools: list,                  # per-layer (k_pool, v_pool) [P,ps,K,D]
    table: jax.Array,             # [B, pages_per_seq] int32
    kv_valid_len: jax.Array,      # [B] valid entries AFTER this step
) -> tuple[jax.Array, list]:
    """One decode step off the page pools; returns (logits [B,1,V],
    new_pools). Mirrors models/common.forward, with attention + cache
    update replaced by the pool-direct path."""
    page_size = pools[0][0].shape[1]
    b = tokens.shape[0]
    pos = positions[:, 0]                       # [B] write position
    rows = jnp.arange(b)
    pages = table[rows, pos // page_size]       # [B] frontier page ids
    offs = pos % page_size

    x = embed_tokens(params["embedding"], tokens)
    if cfg.scale_embeddings:
        x = x * jnp.sqrt(jnp.float32(cfg.embed_dim)).astype(x.dtype)

    new_pools = []
    for layer, (k_pool, v_pool) in zip(params["layers"], pools):
        def attn_fn(h, layer, k_pool=k_pool, v_pool=v_pool):
            q, k, v = project_qkv(h, layer, cfg, positions)
            # [B]-row scatter of this step's K/V into the frontier pages
            # (each row owns its write page exclusively, see module
            # docstring), BEFORE the kernel reads the pool.
            k_pool2 = k_pool.at[pages, offs].set(k[:, 0])
            v_pool2 = v_pool.at[pages, offs].set(v[:, 0])
            mesh = current_spmd_mesh()
            if mesh is not None and mesh.devices.size > 1:
                out = pattn.paged_decode_spmd(
                    mesh, q, k_pool2, v_pool2, table, kv_valid_len,
                    sliding_window=cfg.sliding_window,
                    softcap=cfg.attn_logit_softcap)
                if out is None:
                    # engine.paged_direct gates on spmd_partitionable,
                    # so this cannot happen in serving — fail loudly for
                    # direct misuse rather than silently going dense.
                    raise ValueError(
                        "paged pool-direct decode requires a head layout "
                        "that partitions over the model axis")
            else:
                out = pattn.paged_decode_attention(
                    q, k_pool2, v_pool2, table, kv_valid_len,
                    sliding_window=cfg.sliding_window,
                    softcap=cfg.attn_logit_softcap)
            out = _einsum("bthd,hde->bte", out, layer["o_proj"]) \
                .astype(h.dtype)
            return out, (k_pool2, v_pool2)

        x, new_pool = transformer_block(
            x, layer, cfg, positions, None, None, None, attn_fn=attn_fn)
        new_pools.append(new_pool)

    x = rms_norm(x, params["final_norm"], cfg.norm_eps,
                 cfg.rmsnorm_unit_offset)
    head = params["embedding"] if cfg.tie_embeddings else params["lm_head"]
    logits = _einsum("bte,ve->btv", x, head)
    logits = _softcap(logits, cfg.final_logit_softcap)
    return logits, new_pools
