"""Pool-direct paged serving forward (VERDICT r2 weak #7).

The engine's fallback paged paths gather `pool[table]` into the same
position-aligned `[B, S, K, D]` view the contiguous layout uses —
layout-agnostic and correct, but the view exists ALONGSIDE the pool
(temporarily recreating the full contiguous HBM budget paging exists to
avoid) and the gather/scatter traffic scales with max_seq_len rather
than tokens cached, per prefill chunk and per decode segment.

This module serves STRAIGHT off the pools — decode steps AND prefill
chunks: each layer scatters its K/V into the rows' pages (a [B, T]
position-indexed `.at[].set`), then attends through the page-table-
aware kernels (pallas paged_decode_attention / paged_prefill_attention)
whose kv block index maps read the table and fetch only pages inside
each row's causal/valid frontier. All block wiring (norms, residuals,
MLP, every family flag) comes from models/common.transformer_block via
its attn_fn hook — the same seam the ring/Ulysses cores use — so the
math is defined in exactly one place.

Write-exclusivity invariant: the engine's ensure_capacity copy-on-writes
any shared page in a row's write range before dispatch, and distinct
batch rows are distinct slots owning their frontier pages exclusively,
so the per-step scatter never touches an aliased page.

Multi-device: the kernel runs under shard_map via paged_decode_spmd
(kv heads on "model" — matching the engine's pool sharding — batch
rows on "data"). With pool_replicas > 1 the pool's page axis is also
data-sharded and the caller must deliver replica-grouped, padded
batches (engine ReplicaGroupPlan); the kernels rebase tables to each
shard's local page range. Head layouts that don't partition fall back
to the engine's gather-view serving at build time (engine.paged_direct),
so this module never traces an unpartitionable kernel.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from . import kv_quant as kvq
from .models.common import (MASK_VALUE, ModelConfig, Params, _einsum,
                            _softcap, current_spmd_mesh, embed_tokens,
                            gather_rows, project_qkv, rms_norm,
                            transformer_block)
from .pallas import attention as pattn


def forward_paged(
    params: Params, cfg: ModelConfig,
    tokens: jax.Array,            # [B, T] token ids (T==1: decode step)
    positions: jax.Array,         # [B, T] absolute positions
    pools: list,                  # per-layer (k_pool, v_pool) [P,ps,K,Dp]
    table: jax.Array,             # [B, pages_per_seq] int32
    kv_valid_len: jax.Array,      # [B] valid entries AFTER this call
    pool_replicas: int = 1,       # data-axis shards of the page axis
    last_pos: Optional[jax.Array] = None,   # [B] row index into T
    scales: Optional[list] = None,  # per-layer (k_s, v_s) [P,ps,K,G]
    quant_spec=None,                # kv_quant.KVQuantSpec when scales
    kernel_quant: bool = True,      # False: shapes the kernel declined
) -> tuple[jax.Array, list]:
    """One serving step off the page pools — decode (T==1) or a prefill
    chunk (T==bucket); returns (logits [B,T,V], new_combined) — [B,1,V]
    when `last_pos` is given (hidden gathered before the lm head, same
    OOM guard as models/common.forward). Mirrors
    models/common.forward, with attention + cache update replaced by the
    pool-direct path: each layer scatters its K/V into the rows' pages
    ([B,T] position-indexed — pad-tail cells land on real decode-reserve
    pages or the scratch page, both overwritten/ignored before any
    read, same contract as the gather view) and attends through the
    page-table-aware kernel.

    Quantized pools (ISSUE 11): `scales` carries the per-layer per-cell
    scale pools — the scatter seam QUANTIZES each written token's K/V
    locally (its own absmax scale, neighbours untouched), and the
    kernels dequantize in-kernel via the scale operands. The returned
    list is then pools + scales in the engine's combined-pytree order.
    `kernel_quant=False` (a shape kv_quant_decline_reason declined on
    chip) dequantizes the WHOLE pool per layer before a bf16 kernel
    call — correct but memory-heavy; the engine records the reason and
    serves the gather view instead on the hot path, so this branch only
    backs direct callers."""
    page_size = pools[0][0].shape[1]
    b, t = tokens.shape
    pages = table[jnp.arange(b)[:, None],
                  positions // page_size]       # [B, T] page ids
    offs = positions % page_size

    x = embed_tokens(params["embedding"], tokens)
    if cfg.scale_embeddings:
        x = x * jnp.sqrt(jnp.float32(cfg.embed_dim)).astype(x.dtype)

    quant = scales is not None
    kv_bits = quant_spec.bits if quant else 8
    new_pools = []
    new_scales = []
    for li, (layer, (k_pool, v_pool)) in enumerate(
            zip(params["layers"], pools)):
        k_sc, v_sc = scales[li] if quant else (None, None)

        def attn_fn(h, layer, k_pool=k_pool, v_pool=v_pool,
                    k_sc=k_sc, v_sc=v_sc):
            q, k, v = project_qkv(h, layer, cfg, positions)
            # Scatter this call's K/V into the rows' pages (write ranges
            # are exclusive after COW, see module docstring) BEFORE the
            # kernel reads the pool — quantize-on-write when the pool
            # is quantized (per-cell scales: a token's write never
            # touches its neighbours' quantization).
            if quant:
                k_q, k_s = kvq.quantize_cells(k, quant_spec)
                v_q, v_s = kvq.quantize_cells(v, quant_spec)
                k_pool2 = k_pool.at[pages, offs].set(k_q)
                v_pool2 = v_pool.at[pages, offs].set(v_q)
                k_sc2 = k_sc.at[pages, offs].set(k_s)
                v_sc2 = v_sc.at[pages, offs].set(v_s)
            else:
                k_pool2 = k_pool.at[pages, offs].set(k)
                v_pool2 = v_pool.at[pages, offs].set(v)
                k_sc2 = v_sc2 = None
            if quant and not kernel_quant:
                # Declined shape: dequantize the pool for a bf16 kernel
                # call (direct-caller fallback — the engine's serving
                # path uses the gather view for these shapes).
                kp, vp = (kvq.dequantize_cells(k_pool2, k_sc2,
                                               quant_spec, q.dtype),
                          kvq.dequantize_cells(v_pool2, v_sc2,
                                               quant_spec, q.dtype))
                ks = vs = None
            else:
                kp, vp = k_pool2, v_pool2
                ks, vs = k_sc2, v_sc2
            mesh = current_spmd_mesh()
            multi = mesh is not None and mesh.size > 1
            if t == 1:
                if multi:
                    out = pattn.paged_decode_spmd(
                        mesh, q, kp, vp, table, kv_valid_len,
                        sliding_window=cfg.sliding_window,
                        softcap=cfg.attn_logit_softcap,
                        pool_replicas=pool_replicas,
                        k_scale=ks, v_scale=vs, kv_bits=kv_bits)
                else:
                    out = pattn.paged_decode_attention(
                        q, kp, vp, table, kv_valid_len,
                        sliding_window=cfg.sliding_window,
                        softcap=cfg.attn_logit_softcap,
                        k_scale=ks, v_scale=vs, kv_bits=kv_bits)
            else:
                if multi:
                    out = pattn.paged_prefill_spmd(
                        mesh, q, kp, vp, table,
                        positions[:, 0], kv_valid_len,
                        sliding_window=cfg.sliding_window,
                        softcap=cfg.attn_logit_softcap,
                        pool_replicas=pool_replicas,
                        k_scale=ks, v_scale=vs, kv_bits=kv_bits)
                else:
                    out = pattn.paged_prefill_attention(
                        q, kp, vp, table, positions[:, 0],
                        kv_valid_len,
                        sliding_window=cfg.sliding_window,
                        softcap=cfg.attn_logit_softcap,
                        k_scale=ks, v_scale=vs, kv_bits=kv_bits)
            if out is None:
                # engine.paged_direct gates on spmd_partitionable and
                # serving buckets always satisfy the block check, so
                # this cannot happen in serving — fail loudly for direct
                # misuse rather than silently going dense.
                raise ValueError(
                    "paged pool-direct serving under a multi-device "
                    "mesh needs a head layout that partitions over the "
                    f"model axis AND a block-legal chunk (T={t}, "
                    f"ps={page_size})")
            out = _einsum("bthd,hde->bte", out, layer["o_proj"],
                          tp="row", lora="o_proj").astype(h.dtype)
            return out, (k_pool2, v_pool2, k_sc2, v_sc2)

        x, new_cache = transformer_block(
            x, layer, cfg, positions, None, None, None, attn_fn=attn_fn)
        new_pools.append(new_cache[:2])
        if quant:
            new_scales.append(new_cache[2:])

    x = rms_norm(x, params["final_norm"], cfg.norm_eps,
                 cfg.rmsnorm_unit_offset)
    if last_pos is not None:
        x = gather_rows(x, last_pos)
    head = params["embedding"] if cfg.tie_embeddings else params["lm_head"]
    logits = _einsum("bte,ve->btv", x, head, tp="col")
    logits = _softcap(logits, cfg.final_logit_softcap)
    return logits, new_pools + new_scales


# --- ragged mixed prefill/decode forward (ISSUE 8) ---


def _ragged_xla_attention(q, k_pool, v_pool, tables, token_seq,
                          positions, kv_valid, cfg: ModelConfig,
                          k_sc=None, v_sc=None, quant_spec=None):
    """XLA fallback for the ragged kernel: per-token dense attention
    against each token's sequence slice of the gather view. Memory-
    heavy ([T, L, K, D] — the gather view's budget times the buffer's
    sequence fan-in) and FLOP-dense where the kernel would skip beyond
    the frontier: this is the recorded degrade path for pools the
    kernel declines (head_dim, page_size, VMEM), never the serving
    default. q [T, H, D] → [T, H, D]. Quantized pools dequantize at
    the gather (kv_quant.dequantize_cells — identical math to the
    in-kernel dequant, so kernel and fallback agree)."""
    t, h, d = q.shape
    page_size, kh = k_pool.shape[1], k_pool.shape[2]
    s, pp = tables.shape
    length = pp * page_size
    if k_sc is not None:
        # Gather FIRST, then dequantize the gathered slices — the
        # dequant cost scales with the view, not the whole pool.
        kg = kvq.dequantize_cells(k_pool[tables], k_sc[tables],
                                  quant_spec, q.dtype) \
            .reshape(s, length, kh, d)
        vg = kvq.dequantize_cells(v_pool[tables], v_sc[tables],
                                  quant_spec, q.dtype) \
            .reshape(s, length, kh, d)
    else:
        kg = k_pool[tables].reshape(s, length, kh, d)
        vg = v_pool[tables].reshape(s, length, kh, d)
    kt = kg[token_seq]                                # [T, L, K, D]
    vt = vg[token_seq]
    if cfg.kv_repeat > 1:
        kt = jnp.repeat(kt, cfg.kv_repeat, axis=2)    # [T, L, H, D]
        vt = jnp.repeat(vt, cfg.kv_repeat, axis=2)
    logits = jnp.einsum("thd,tlhd->thl", q, kt,
                        preferred_element_type=jnp.float32)
    logits = _softcap(logits, cfg.attn_logit_softcap)
    l_pos = jnp.arange(length)[None, :]
    mask = (l_pos <= positions[:, None]) \
        & (l_pos < kv_valid[token_seq][:, None])
    if cfg.sliding_window is not None:
        mask &= l_pos > positions[:, None] - cfg.sliding_window
    logits = jnp.where(mask[:, None, :], logits, MASK_VALUE)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("thl,tlhd->thd", probs, vt).astype(q.dtype)


def forward_ragged(
    params: Params, cfg: ModelConfig,
    tokens: jax.Array,            # [T] flat token buffer
    positions: jax.Array,         # [T] absolute positions
    pools: list,                  # per-layer (k_pool, v_pool) [P,ps,K,D]
    tables: jax.Array,            # [S, pages_per_seq] int32
    seq_of_block: jax.Array,      # [T/8] sequence id per q block
    block_qstart: jax.Array,      # [T/8] block start row within its seq
    query_offsets: jax.Array,     # [S] absolute position of seq's row 0
    kv_valid: jax.Array,          # [S] valid entries AFTER this call
    token_pages: jax.Array,       # [T] pool page per token (pads→scratch)
    token_offs: jax.Array,        # [T] in-page offset per token
    token_seq: jax.Array,         # [T] owning sequence per token
    last_rows: jax.Array,         # [S] flat row of each seq's last token
    attn_path: str = "kernel",    # "kernel" | "xla" (static)
    sample_rows: Optional[jax.Array] = None,  # [S, R] rows to score
    scales: Optional[list] = None,  # per-layer (k_s, v_s) (ISSUE 11)
    quant_spec=None,
    copy_src: Optional[jax.Array] = None,  # [C] page pre-COW (ISSUE 13)
    copy_dst: Optional[jax.Array] = None,
) -> tuple[jax.Array, list]:
    """One MIXED prefill/decode step over the flat token buffer
    (serving_loop.build_ragged_batch layout): every sequence's chunk or
    decode token runs in the SAME dispatch — the admission prologue's
    replacement. Each layer scatters the buffer's K/V into the owning
    sequences' pages (pads land on the scratch page, never read), then
    attends through the ragged page-table kernel — or, with
    attn_path="xla", the dense per-token fallback the engine records a
    fallback_reason for. Returns (per-sequence last-token logits
    [S, V], new_pools); pad sequence rows carry garbage the caller
    drops. Block wiring comes from transformer_block's attn_fn hook,
    exactly like forward_paged.

    `sample_rows` [S, R] (ISSUE 9, the speculative verify): score R
    flat-buffer rows per sequence instead of one — each speculating
    row's whole ``[last, drafts...]`` run gets logits in this single
    forward, and the causal mask makes each position's logits EXACTLY
    what 1-token decode would compute given the accepted prefix (the
    output-invariance core). Returns ([S, R, V], new_pools); the lm
    head still runs on S*R gathered rows, never the full buffer.

    `copy_src`/`copy_dst` [C] (ISSUE 13, tree verify): whole pages
    device-copied pool->pool per layer BEFORE the K/V scatter — the
    pre-COW that gives each tree path's private frontier page the
    committed cells its causal reads need (pads are scratch->scratch
    self-copies; scales ride with their pages, the _run_page_copy
    contract). With this, a token TREE is just more sequences of the
    same flat buffer: per-path tables keep sibling writes apart, the
    ordinary causal mask is exact along every root-to-leaf path, and
    no kernel changes at all."""
    x = embed_tokens(params["embedding"], tokens[None])     # [1, T, E]
    if cfg.scale_embeddings:
        x = x * jnp.sqrt(jnp.float32(cfg.embed_dim)).astype(x.dtype)
    pos2 = positions[None]

    quant = scales is not None
    kv_bits = quant_spec.bits if quant else 8
    new_pools = []
    new_scales = []
    for li, (layer, (k_pool, v_pool)) in enumerate(
            zip(params["layers"], pools)):
        k_sc, v_sc = scales[li] if quant else (None, None)

        def attn_fn(h, layer, k_pool=k_pool, v_pool=v_pool,
                    k_sc=k_sc, v_sc=v_sc):
            q, k, v = project_qkv(h, layer, cfg, pos2)      # [1,T,H,D]
            if copy_src is not None:
                # Tree-path pre-COW (ISSUE 13): private frontier pages
                # receive the committed cells before this layer's
                # scatter can write draft cells into them.
                k_pool = k_pool.at[copy_dst].set(k_pool[copy_src])
                v_pool = v_pool.at[copy_dst].set(v_pool[copy_src])
                if quant:
                    k_sc = k_sc.at[copy_dst].set(k_sc[copy_src])
                    v_sc = v_sc.at[copy_dst].set(v_sc[copy_src])
            if quant:
                # Quantize-on-write (ISSUE 11): each flat-buffer token
                # writes its own payload + scale; pads land on the
                # scratch page, never read.
                k_q, k_s = kvq.quantize_cells(k[0], quant_spec)
                v_q, v_s = kvq.quantize_cells(v[0], quant_spec)
                k_pool2 = k_pool.at[token_pages, token_offs].set(k_q)
                v_pool2 = v_pool.at[token_pages, token_offs].set(v_q)
                k_sc2 = k_sc.at[token_pages, token_offs].set(k_s)
                v_sc2 = v_sc.at[token_pages, token_offs].set(v_s)
            else:
                k_pool2 = k_pool.at[token_pages, token_offs].set(k[0])
                v_pool2 = v_pool.at[token_pages, token_offs].set(v[0])
                k_sc2 = v_sc2 = None
            if attn_path == "kernel":
                mesh = current_spmd_mesh()
                if mesh is not None and mesh.size > 1:
                    out = pattn.ragged_paged_spmd(
                        mesh, q[0], k_pool2, v_pool2, tables,
                        seq_of_block, block_qstart, query_offsets,
                        kv_valid, sliding_window=cfg.sliding_window,
                        softcap=cfg.attn_logit_softcap,
                        k_scale=k_sc2, v_scale=v_sc2, kv_bits=kv_bits)
                    if out is None:
                        # The engine gates ragged_path on
                        # partitionability at build time — reaching
                        # here is direct misuse, fail loudly.
                        raise ValueError(
                            "ragged kernel cannot partition this head "
                            "layout — engine should have resolved "
                            "attn_path='xla'")
                else:
                    out = pattn.ragged_paged_attention(
                        q[0], k_pool2, v_pool2, tables, seq_of_block,
                        block_qstart, query_offsets, kv_valid,
                        sliding_window=cfg.sliding_window,
                        softcap=cfg.attn_logit_softcap,
                        k_scale=k_sc2, v_scale=v_sc2, kv_bits=kv_bits)
            else:
                out = _ragged_xla_attention(
                    q[0], k_pool2, v_pool2, tables, token_seq,
                    positions, kv_valid, cfg, k_sc=k_sc2, v_sc=v_sc2,
                    quant_spec=quant_spec)
            out = _einsum("bthd,hde->bte", out[None], layer["o_proj"],
                          tp="row", lora="o_proj").astype(h.dtype)
            return out, (k_pool2, v_pool2, k_sc2, v_sc2)

        x, new_cache = transformer_block(
            x, layer, cfg, pos2, None, None, None, attn_fn=attn_fn)
        new_pools.append(new_cache[:2])
        if quant:
            new_scales.append(new_cache[2:])

    x = rms_norm(x, params["final_norm"], cfg.norm_eps,
                 cfg.rmsnorm_unit_offset)
    if sample_rows is not None:
        s, r = sample_rows.shape
        sel = x[0, sample_rows.reshape(-1)][None]           # [1, S*R, E]
    else:
        sel = x[0, last_rows][None]                         # [1, S, E]
    head = params["embedding"] if cfg.tie_embeddings else params["lm_head"]
    logits = _einsum("bte,ve->btv", sel, head, tp="col")
    logits = _softcap(logits, cfg.final_logit_softcap)
    if sample_rows is not None:
        return logits[0].reshape(s, r, -1), new_pools + new_scales
    return logits[0], new_pools + new_scales


# ---------------------------------------------------------------------------
# static-analysis program registration (ISSUE 15)
# ---------------------------------------------------------------------------

from ..analysis.jaxpr_audit import (ProgramSpec, Variant,  # noqa: E402
                                    analysis_register)


def trace_ragged_batch(engine, batch: dict):
    """Trace one ragged dispatch's program (`engine._ragged_step`) to a
    ClosedJaxpr without dispatching — the device-free twin of
    `InferenceEngine._ragged_dispatch.run`. Argument mapping mirrors
    that seam one-to-one (same array order, same static kwargs); if the
    twins drift, the audit's trace step fails loudly, which is the
    contract — an unauditable serving program must never be skipped
    silently. Shared by the ragged provider here and the spec-decode
    provider (verify/propose variants)."""
    score_width = int(batch.get("score_width", 0) or 0)
    propose_width = int(batch.get("propose_width", 0) or 0)
    from .engine import _audit_sds
    params = _audit_sds(engine.params)
    pools = _audit_sds(engine.kv.combined_pools())
    attn_path = ("kernel" if engine.ragged_path == "pallas_ragged"
                 else "xla")
    copy_src = batch.get("copy_src")
    arrs = dict(
        tables=jnp.asarray(batch["tables"]),
        tokens=jnp.asarray(batch["tokens"]),
        positions=jnp.asarray(batch["positions"]),
        token_pages=jnp.asarray(batch["token_pages"]),
        token_offs=jnp.asarray(batch["token_offs"]),
        token_seq=jnp.asarray(batch["token_seq"]),
        seq_of_block=jnp.asarray(batch["seq_of_block"]),
        block_qstart=jnp.asarray(batch["block_qstart"]),
        query_offsets=jnp.asarray(batch["query_offsets"]),
        kv_valid=jnp.asarray(batch["kv_valid"]),
        last_rows=jnp.asarray(batch["last_rows"]),
        key=jax.random.PRNGKey(0),
        temps=jnp.asarray(batch["temps"]),
        top_ks=jnp.asarray(batch["top_ks"]),
        top_ps=jnp.asarray(batch["top_ps"]),
    )
    opt = {}
    if score_width:
        opt["sample_rows"] = jnp.asarray(batch["sample_rows"])
    if copy_src is not None:
        opt["copy_src"] = jnp.asarray(copy_src)
        opt["copy_dst"] = jnp.asarray(batch["copy_dst"])
    names = list(arrs) + list(opt)

    def call(p, pl, *flat):
        kw = dict(zip(names, flat))
        pos = [kw.pop(n) for n in arrs]
        return engine._ragged_step(
            p, pl, *pos, greedy=batch["greedy"], attn_path=attn_path,
            score_width=score_width, lora=None,
            propose_width=propose_width, **kw)

    return jax.make_jaxpr(call)(params, pools, *arrs.values(),
                                *opt.values())


def analysis_warm_seqs(engine, n_seqs: int = 2):
    """Toy RaggedSeq compositions over scratch-page tables (shape-only
    — the audit traces, never dispatches, so no page is ever really
    read or allocated). Mirrors _warm_ragged's two-seq mixed batch."""
    import numpy as np
    from .serving_loop import RaggedSeq
    kv = engine.kv
    scratch = kv.scratch_page(0)
    table = np.full((kv.pages_per_seq,), scratch, np.int32)
    bos = engine.tokenizer.bos_id
    seqs = [RaggedSeq([bos] + [5] * 23, 0, table)]
    if n_seqs > 1:
        seqs.append(RaggedSeq([7], 8, table))
    return seqs[:n_seqs]


@analysis_register("ragged")
def _analysis_ragged_programs(engine) -> list:
    """The plain ragged mixed-dispatch program across the warmed shape
    grid. Two compositions (one-seq, two-seq) trace under EVERY shape
    label: composition is values, so both must produce the one jaxpr
    that shape warmed — a leak of composition into a static argument
    fails RT-JAXPR-VARIANTS."""
    if not getattr(engine, "ragged_enabled", False):
        return []
    from .serving_loop import build_ragged_batch
    kv = engine.kv

    def variant(shape: int, n_seqs: int) -> Variant:
        def thunk():
            batch = build_ragged_batch(
                analysis_warm_seqs(engine, n_seqs), t_budget=shape,
                s_max=kv.num_slots + 1, pages_per_seq=kv.pages_per_seq,
                scratch_page=kv.scratch_page(0),
                pad_id=engine.tokenizer.pad_id,
                page_size=kv.page_size)
            return trace_ragged_batch(engine, batch)
        return Variant(label=f"t{shape}", thunk=thunk,
                       situation=f"{n_seqs} seq(s) in shape {shape}")

    return [ProgramSpec(
        name="ragged", phase="ragged",
        variants=[variant(shape, n)
                  for shape in engine.ragged_shapes for n in (1, 2)])]
