"""JAX version compatibility — the shard_map API seam.

The engine is written against the modern manual-axes API (`jax.shard_map`
with `axis_names=`/`check_vma=`, `jax.lax.pcast`, abstract-mesh contexts).
Older runtimes (jax 0.4.x) ship the same machinery as
`jax.experimental.shard_map` with the inverse `auto=` parameter, no vma
tracking and no abstract meshes. This module is the ONE place that
difference lives: every engine module imports `shard_map` (and friends)
from here instead of from jax, so a version bump in either direction is a
compat-module change, not a nine-module sweep.

Translation rules for the experimental fallback:
- `axis_names={manual...}` → `auto = mesh.axis_names - manual` (the old
  parameter names the axes NOT manualized);
- `check_vma` → `check_rep`, defaulting to False (the old rep checker
  predates pcast-style varying annotations and false-positives on them);
- `pcast(..., to="varying")` → identity (no vma tracking to convince);
- partial-manual regions (TP inside PP stages) are REFUSED at build on
  old jax (pp_serving raises with the fix), so `mesh_manual_axes` only
  needs the axis_types read on modern meshes and "manualize everything"
  on old ones.
"""

from __future__ import annotations

from typing import Optional

import jax

_native_shard_map = getattr(jax, "shard_map", None)
HAS_NATIVE_SHARD_MAP = _native_shard_map is not None

if _native_shard_map is not None:
    shard_map = _native_shard_map
else:
    from jax.experimental.shard_map import shard_map as _experimental

    def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
                  check_vma: Optional[bool] = None):
        kwargs = {"check_rep": bool(check_vma) if check_vma is not None
                  else False}
        if axis_names is not None:
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
            if auto:
                kwargs["auto"] = auto
        return _experimental(f, mesh, in_specs=in_specs,
                             out_specs=out_specs, **kwargs)


def pcast(x, axis_names, to: str = "varying"):
    """`jax.lax.pcast` where it exists; identity elsewhere (pre-vma
    runtimes don't track varying-ness, so there is nothing to cast)."""
    fn = getattr(jax.lax, "pcast", None)
    if fn is None:
        return x
    return fn(x, axis_names, to=to)


def _int4_bitcast_expands() -> bool:
    """Feature-detect `lax.bitcast_convert_type(int8 → int4)`: modern
    jax appends a minor dim of 2 (one nibble pair per byte); jax 0.4.x
    abstract-evals it at the SAME rank and then fails MLIR verification
    at lowering ("rank of smaller element type should be 1 more"). The
    probe is abstract-only (eval_shape) — no compile, no device."""
    try:
        import jax.numpy as jnp
        out = jax.eval_shape(
            lambda x: jax.lax.bitcast_convert_type(x, jnp.int4),
            jax.ShapeDtypeStruct((2,), jnp.int8))
        return out.shape == (2, 2)
    except Exception:  # noqa: BLE001 — any probe failure ⇒ fallback
        return False


HAS_INT4_BITCAST = _int4_bitcast_expands()


def unpack_int4_pairs(q4):
    """int8[..., n] → signed nibble pairs int4/int8[..., n, 2], low
    nibble first (the engine/quant.py pack order).

    Modern jax: the one-op bitcast whose nibble pair expands minor-most
    — the layout Mosaic fuses into the consuming matmul operand on TPU
    (models/common.dequant_int4's performance contract). Old jax
    (0.4.x, broken int4 bitcast — see _int4_bitcast_expands): arithmetic
    shift extraction + a minor-axis stack. The stack is an interleave
    XLA:TPU would NOT fuse (the exact layout BENCH_r05 measured slower
    than bf16), but the fallback only ever runs on runtimes where the
    bitcast cannot lower AT ALL — correctness-gated, and numerically
    identical: `(q << 4) >> 4` sign-extends the low nibble, `q >> 4`
    the high one (arithmetic shifts on int8)."""
    import jax.numpy as jnp
    if HAS_INT4_BITCAST:
        return jax.lax.bitcast_convert_type(q4, jnp.int4)
    low = jnp.right_shift(jnp.left_shift(q4, 4), 4)
    high = jnp.right_shift(q4, 4)
    return jnp.stack([low, high], axis=-1)


def mesh_manual_axes(mesh) -> set:
    """The axes a wrapper's shard_map must manualize: the mesh's AUTO
    axes. Modern meshes carry axis_types; old ones report every axis —
    correct there, because partial-manual regions (the only case where
    an axis would already be Manual) are refused at build on old jax."""
    types = getattr(mesh, "axis_types", None)
    axis_type = getattr(jax.sharding, "AxisType", None)
    if types is not None and axis_type is not None:
        return {a for a, t in zip(mesh.axis_names, types)
                if t == axis_type.Auto}
    return set(mesh.axis_names)
