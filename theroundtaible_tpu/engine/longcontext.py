"""Long-context sequence parallelism — ring attention + Ulysses all-to-all.

The reference scales sequence length DOWN: its context-budgeting subsystem
truncates sources to a min-over-knights char budget and slices git diffs to
3000 chars (reference src/orchestrator.ts:281-292, :406; SURVEY.md §5.7).
This module inverts that into genuine long-context serving for the TPU
build (SURVEY.md §2.3 "SP/CP/ring-attention", §7 Phase 6): prefill with the
sequence axis sharded over a "seq" mesh axis so activation memory and
attention FLOPs split across chips.

Two schemes, chosen per topology at mesh-build time:

- **Ring attention** (`ring_attention`): K/V shards rotate hop-by-hop over
  the ICI ring (`jax.lax.ppermute`) while each chip keeps an online-softmax
  accumulator (m, l, o) over its resident queries — attention memory stays
  O(T²/n²) per chip and the per-hop transfer is the K/V shard, which XLA
  overlaps with the block matmuls. Works for any head count.
- **Ulysses** (`ulysses_attention`): `jax.lax.all_to_all` swaps the
  sequence axis for the head axis so each chip runs full-sequence attention
  on H/n heads; two big collectives instead of n-1 small ones. The local
  core is blockwise (same online-softmax update) so memory stays bounded.

Both cores consume the q/k/v produced by `models.common.project_qkv` and
plug into `transformer_block`'s `attn_fn` hook, so family flags (GQA,
sliding window, logit softcap, Gemma norms) behave identically to the dense
path.

Integration: `InferenceEngine` uses `make_ring_prefill` for fresh long
prompts (slot offset 0) past a length threshold; the returned full-sequence
K/V is scattered into the per-knight slot cache, so decode and later
delta-prefills proceed on the normal path. Weights are replicated over the
seq axis (for long-context prefill, activations — not weights — are the
memory bound; TP×SP composition is a future mesh axis).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from .models.common import (
    ModelConfig,
    Params,
    _einsum,
    _softcap,
    embed_tokens,
    project_qkv,
    rms_norm,
    transformer_block,
)

SEQ_AXIS = "seq"
BIG_NEG = -2.3819763e38


def _shard_map(f, mesh, in_specs, out_specs):
    from .compat import shard_map
    return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)


def build_seq_mesh(n_seq: int, devices: Optional[list] = None) -> Mesh:
    """A 1-axis ("seq",) mesh over the first n_seq devices."""
    devices = devices if devices is not None else jax.devices()
    if len(devices) < n_seq:
        raise ValueError(
            f"seq mesh needs {n_seq} devices, have {len(devices)}")
    return Mesh(np.array(devices[:n_seq]), (SEQ_AXIS,))


def _gqa_expand(x: jax.Array, repeat: int) -> jax.Array:
    return jnp.repeat(x, repeat, axis=2) if repeat > 1 else x


def _online_update(m, l, o, q, k_blk, v_blk, q_pos, kv_pos, kv_valid,
                   cfg: ModelConfig, kv_repeat: int):
    """One flash-attention-style accumulation step against a K/V block.

    State (m=max, l=normalizer, o=unnormalized output) is [B,H,T] / [B,H,T]
    / [B,H,T,D] in f32. q is pre-scaled+roped [B,T,H,D]; k_blk/v_blk are
    roped KV-head blocks [B,S,K,D] with absolute positions kv_pos [B,S].
    """
    k_att = _gqa_expand(k_blk, kv_repeat)
    v_att = _gqa_expand(v_blk, kv_repeat)
    logits = jnp.einsum("bthd,bshd->bhts", q, k_att,
                        preferred_element_type=jnp.float32)
    logits = _softcap(logits, cfg.attn_logit_softcap)
    mask = kv_pos[:, None, :] <= q_pos[:, :, None]        # causal
    mask &= kv_pos[:, None, :] < kv_valid[:, None, None]  # padded rows
    if cfg.sliding_window is not None:
        mask &= kv_pos[:, None, :] > q_pos[:, :, None] - cfg.sliding_window
    mask = mask[:, None, :, :]                            # [B,1,T,S]
    logits = jnp.where(mask, logits, BIG_NEG)
    m_new = jnp.maximum(m, logits.max(axis=-1))
    # `* mask` matters: an all-masked block has logits == m_new == BIG_NEG
    # and exp(0) would otherwise contribute a spurious 1 per key.
    p = jnp.exp(logits - m_new[..., None]) * mask
    corr = jnp.exp(m - m_new)
    l = l * corr + p.sum(axis=-1)
    o = o * corr[..., None] + jnp.einsum(
        "bhts,bshd->bhtd", p, v_att.astype(jnp.float32))
    return m_new, l, o


def _finalize(l, o, dtype) -> jax.Array:
    """[B,H,T,D] accumulator → [B,T,H,D] output; fully-masked (pad) query
    rows have l == 0 and are defined as 0."""
    out = o / jnp.where(l == 0.0, 1.0, l)[..., None]
    return out.transpose(0, 2, 1, 3).astype(dtype)


def ring_attention(q, k, v, q_pos, kv_pos, kv_valid, cfg: ModelConfig,
                   axis_name: str = SEQ_AXIS,
                   axis_size: Optional[int] = None) -> jax.Array:
    """Sequence-parallel causal attention; call INSIDE shard_map.

    q: local query shard [B,Tl,H,D] (pre-scaled+roped), k/v: local KV shard
    [B,Sl,K,D] (roped), q_pos/kv_pos: absolute positions [B,Tl]/[B,Sl],
    kv_valid: [B] total valid length. Returns [B,Tl,H,D].

    The K/V shard (and its positions) makes axis_size-1 ppermute hops
    around the ring; masks are computed from absolute positions, so no
    shard-index arithmetic is needed and ragged tails just mask out.
    """
    n = axis_size if axis_size is not None else jax.lax.psum(1, axis_name)
    b, t, h, _ = q.shape
    d = q.shape[-1]
    m = jnp.full((b, cfg.num_heads, t), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, cfg.num_heads, t), jnp.float32)
    o = jnp.zeros((b, cfg.num_heads, t, d), jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]
    for step in range(n):
        m, l, o = _online_update(m, l, o, q, k, v, q_pos, kv_pos, kv_valid,
                                 cfg, cfg.kv_repeat)
        if step < n - 1:
            k = jax.lax.ppermute(k, axis_name, perm)
            v = jax.lax.ppermute(v, axis_name, perm)
            kv_pos = jax.lax.ppermute(kv_pos, axis_name, perm)
    return _finalize(l, o, q.dtype)


def blockwise_sdpa(q, k, v, q_pos, kv_pos, kv_valid, cfg: ModelConfig,
                   block: int = 512) -> jax.Array:
    """Single-device blockwise attention (online softmax over KV chunks) —
    bounded memory for full-sequence attention; the local core of Ulysses.
    q [B,T,H,D], k/v [B,S,K',D] where H % K' == 0."""
    b, t, h, d = q.shape
    s = k.shape[1]
    repeat = h // k.shape[2]
    m = jnp.full((b, h, t), -jnp.inf, jnp.float32)
    l = jnp.zeros((b, h, t), jnp.float32)
    o = jnp.zeros((b, h, t, d), jnp.float32)
    for start in range(0, s, block):
        end = min(start + block, s)
        m, l, o = _online_update(
            m, l, o, q, k[:, start:end], v[:, start:end], q_pos,
            kv_pos[:, start:end], kv_valid, cfg, repeat)
    return _finalize(l, o, q.dtype)


def ulysses_attention(q, k, v, q_pos, kv_valid, cfg: ModelConfig,
                      axis_name: str = SEQ_AXIS, axis_size: int = 1,
                      block: int = 512) -> jax.Array:
    """All-to-all sequence parallelism; call INSIDE shard_map.

    Swap seq↔heads so each chip attends over the FULL sequence with H/n
    heads (two all-to-alls instead of a ring). Needs num_heads % n == 0;
    when kv heads don't divide n, they are GQA-expanded first (more bytes
    on the wire — the topology tradeoff vs ring_attention).
    """
    n = axis_size
    if cfg.num_heads % n != 0:
        raise ValueError(f"Ulysses needs heads ({cfg.num_heads}) % n ({n}) == 0")
    if k.shape[2] % n != 0:
        k = _gqa_expand(k, cfg.kv_repeat)
        v = _gqa_expand(v, cfg.kv_repeat)
    # [B,Tl,H,D] -> [B,T,H/n,D]: split heads, concat sequence.
    q_g = jax.lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1,
                             tiled=True)
    k_g = jax.lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1,
                             tiled=True)
    v_g = jax.lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1,
                             tiled=True)
    pos_g = jax.lax.all_gather(q_pos, axis_name, axis=1, tiled=True)  # [B,T]
    out = blockwise_sdpa(q_g, k_g, v_g, pos_g, pos_g, kv_valid, cfg, block)
    # [B,T,H/n,D] -> [B,Tl,H,D]
    return jax.lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)


def make_ring_prefill(cfg: ModelConfig, mesh: Mesh, scheme: str = "ring"):
    """Build the jitted sequence-parallel prefill program.

    Returns fn(params, tokens [B,Tp], positions [B,Tp], lengths [B]) ->
    (last-token logits f32 [B,V], [(k, v)] per layer, each [B,Tp,K,D]).
    Tp must divide by the seq-axis size; pad with any token id and let
    `lengths` mask the tail. Full [B,T,V] logits are never materialized —
    only the (valid-1)-position hidden state crosses the psum.
    """
    n = mesh.shape[SEQ_AXIS]

    def shard_fn(params, tokens, positions, lengths):
        # follows the param dtype (bf16 serving, f32 parity tests) — same
        # rule as models/common.py forward; embed_tokens/_einsum handle
        # int8 {"q","s"} leaves, so quant composes with seq parallelism
        x = embed_tokens(params["embedding"], tokens)
        if cfg.scale_embeddings:
            x = x * jnp.sqrt(jnp.float32(cfg.embed_dim)).astype(x.dtype)
        q_pos = positions

        def attn_fn(h, layer):
            q, k, v = project_qkv(h, layer, cfg, q_pos)
            if scheme == "ulysses":
                core = ulysses_attention(q, k, v, q_pos, lengths, cfg,
                                         SEQ_AXIS, n)
            else:
                core = ring_attention(q, k, v, q_pos, q_pos, lengths, cfg,
                                      SEQ_AXIS, n)
            out = _einsum("bthd,hde->bte", core, layer["o_proj"],
                          tp="row").astype(h.dtype)
            return out, (k, v)

        caches = []
        for layer in params["layers"]:
            x, kv = transformer_block(x, layer, cfg, q_pos, None, None,
                                      None, attn_fn=attn_fn)
            caches.append(kv)
        x = rms_norm(x, params["final_norm"], cfg.norm_eps,
                     cfg.rmsnorm_unit_offset)
        hit = (positions == (lengths - 1)[:, None]).astype(jnp.float32)
        last_h = jnp.einsum("bt,bte->be", hit, x.astype(jnp.float32))
        last_h = jax.lax.psum(last_h, SEQ_AXIS)
        head = params["embedding"] if cfg.tie_embeddings else params["lm_head"]
        logits = _einsum("be,ve->bv", last_h, head, tp="col")
        logits = _softcap(logits, cfg.final_logit_softcap)
        return logits, caches

    kv_spec = (P(None, SEQ_AXIS), P(None, SEQ_AXIS))
    mapped = _shard_map(
        shard_fn, mesh,
        in_specs=(P(), P(None, SEQ_AXIS), P(None, SEQ_AXIS), P(None)),
        out_specs=(P(None), [kv_spec] * cfg.num_layers))
    return jax.jit(mapped)


def pad_to_ring(lengths_max: int, n_seq: int, cache_len: int) -> int:
    """Bucketed padded length for ring prefill: next power-of-two multiple
    of n_seq ≥ lengths_max (recompile guard as prompts grow), capped at the
    largest n_seq-multiple that fits the cache. Returns 0 when the prompt
    cannot fit — caller falls back to chunked prefill."""
    cap = (cache_len // n_seq) * n_seq
    if lengths_max > cap:
        return 0
    tp = n_seq
    while tp < lengths_max:
        tp *= 2
    return min(tp, cap)


__all__ = [
    "SEQ_AXIS",
    "build_seq_mesh",
    "ring_attention",
    "ulysses_attention",
    "blockwise_sdpa",
    "make_ring_prefill",
    "pad_to_ring",
]
