"""Durable session journal — crash-consistent record of committed turns.

The scheduler's host state (and the KV pool behind it) dies with the
process: a SIGKILL mid-discussion loses every session even though each
retired turn was already final. This module (ISSUE 12 tentpole, second
half) makes the COMMIT point durable: at retire time the scheduler
appends one JSONL record per session round — knight names, a prompt
hash, the committed token ids, the persona adapter ids — and fsyncs at
the turn boundary, so the record on disk is exactly the set of turns
whose results were handed back to submitters. RTP-LLM (PAPERS.md)
treats restart-surviving session state as table stakes for production
serving; this is the minimal durable form of it.

Crash consistency rules:

- **Append-only, one file per session** (`<root>/<session>.jsonl`).
  A record is written as one line + flush + fsync before the turn is
  considered journaled; a crash between retire and fsync loses at most
  the in-flight turn — which the submitter never saw complete, so the
  journal can never claim MORE than was served.
- **Torn tails are expected, not fatal.** A kill -9 mid-write leaves a
  partial last line; the reader stops at the first undecodable line and
  serves every complete record before it (the classic WAL rule).
- **Replay goes through the normal submit path.** `replay_turn_prompt`
  rebuilds the exact committed token stream of a recorded turn;
  `commands/serve.resume_from_journal` submits it with a 1-token
  budget, so the fresh engine re-prefills the transcript through the
  same reuse/prefix-cache/commit machinery as live serving (re-prefill
  is acceptable on the crash path — the prefix cache makes repeated
  spans cheap) and the session's KV ends at the exact committed turn.

Journal failures must never fail serving: the scheduler guards every
write and degrades to an event + counter (`journal_errors`) — a full
disk costs durability, not availability.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time
from pathlib import Path
from typing import Any, Iterable, Optional

from ..utils import telemetry

_SAFE = re.compile(r"[^A-Za-z0-9._-]")


def _safe_name(session: str) -> str:
    """Session ids are caller-chosen (uuid-tagged serve ids, bench
    names, test strings) — map them onto one safe filename, with a
    short hash suffix so two ids that sanitize identically ("a/b" and
    "a_b") can never share a journal file."""
    digest = hashlib.sha256(session.encode("utf-8")).hexdigest()[:8]
    return f"{_SAFE.sub('_', session)[:80]}-{digest}"


def prompt_sha(prompt: Any) -> str:
    """Stable hash of a turn's prompt (str or token-id list) — replay
    and audits verify identity without storing the raw text twice."""
    if isinstance(prompt, (list, tuple)):
        raw = ",".join(str(int(t)) for t in prompt)
    else:
        raw = str(prompt)
    return hashlib.sha256(raw.encode("utf-8")).hexdigest()


class SessionJournal:
    """Append-only per-session JSONL journal of committed turns."""

    def __init__(self, root: str | os.PathLike):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()
        # One lock PER SESSION for the write path: the journal object
        # is shared by every scheduler of a serve root, and fsync can
        # be many milliseconds — a single journal-wide lock would
        # serialize every engine's retire path behind every other
        # engine's fsync. A session is owned by one scheduler, so the
        # per-session lock gives the same turn-numbering consistency
        # with no cross-engine stall; `_lock` only guards the shared
        # dicts.
        self._session_locks: dict[str, threading.Lock] = {}
        # session -> next turn index, seeded lazily from disk so a
        # resumed process continues the numbering it crashed at.
        self._next_turn: dict[str, int] = {}
        self._names: dict[str, str] = {}   # session -> filename stem
        self.records = 0
        self.errors = 0
        # True while a replay drives the normal submit path: the
        # replayed turns would otherwise re-journal themselves as fresh
        # commits, doubling the file on every resume.
        self._suspended = False

    # --- paths / discovery ---

    def path_for(self, session: str) -> Path:
        stem = self._names.get(session)
        if stem is None:
            stem = self._names.setdefault(session, _safe_name(session))
        return self.root / f"{stem}.jsonl"

    def sessions(self) -> list[str]:
        """Every session with at least one committed record on disk
        (read from the records themselves — filenames are sanitized)."""
        out: dict[str, None] = {}
        for p in sorted(self.root.glob("*.jsonl")):
            for rec in self._read(p, limit=1):
                out.setdefault(rec["session"])
        return list(out)

    # --- writing ---

    def suspend_replay(self) -> "_Suspended":
        """Context manager: journal writes no-op while a replay drives
        the normal submit path (see module docstring)."""
        return _Suspended(self)

    def record_turn(self, session: str, rows: list[dict],
                    **meta) -> Optional[dict]:
        """Append ONE committed-turn record, fsynced before returning.

        `rows` is one dict per knight row of the round:
        {"knight": name, "prompt": str|ids, "prompt_tokens": [ids...],
         "produced": [ids...], "adapter": persona-or-None}. The record
        stores the prompt HASH plus the token ids — everything replay
        needs, nothing it doesn't. Extra `meta` (consensus scores,
        round ids) rides along verbatim. Returns the record (None when
        suspended for replay or the write failed — serving continues
        either way; failures count in `errors`)."""
        if self._suspended:
            return None
        with self._lock:
            slock = self._session_locks.setdefault(
                session, threading.Lock())
        with slock:
            with self._lock:
                turn = self._next_turn.get(session)
            if turn is None:
                turn = self._scan_next_turn(session)
            rec = {
                "v": 1,
                "session": session,
                "turn": turn,
                "ts": round(time.time(), 3),
                "rows": [
                    {
                        "knight": r["knight"],
                        "prompt_sha256": prompt_sha(
                            r.get("prompt",
                                  r.get("prompt_tokens", []))),
                        "prompt_tokens": [int(t) for t in
                                          r.get("prompt_tokens", [])],
                        "produced": [int(t) for t in
                                     r.get("produced", [])],
                        "adapter": r.get("adapter"),
                    }
                    for r in rows
                ],
            }
            for k, v in meta.items():
                if v is not None:
                    rec[k] = v
            try:
                path = self.path_for(session)
                with open(path, "a", encoding="utf-8") as f:
                    f.write(json.dumps(rec, separators=(",", ":"))
                            + "\n")
                    f.flush()
                    os.fsync(f.fileno())
            except OSError as e:
                with self._lock:
                    self.errors += 1
                telemetry.inc("roundtable_journal_errors_total")
                telemetry.recorder().record(
                    "journal_error", session=session,
                    error=str(e)[:200])
                return None
            with self._lock:
                self._next_turn[session] = turn + 1
                self.records += 1
        telemetry.inc("roundtable_journal_turns_total")
        return rec

    def _scan_next_turn(self, session: str) -> int:
        last = self.last_turn(session)
        return 0 if last is None else last + 1

    # --- reading / replay ---

    def _read(self, path: Path, limit: Optional[int] = None) -> list[dict]:
        """Complete records of one journal file, stopping at the first
        torn/undecodable line (crash-consistency: everything before a
        torn tail was fsynced by construction)."""
        out: list[dict] = []
        try:
            with open(path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        break  # torn tail: the crash's half-written line
                    if not isinstance(rec, dict) or "rows" not in rec:
                        break
                    out.append(rec)
                    if limit is not None and len(out) >= limit:
                        break
        except OSError:
            return out
        return out

    def turns(self, session: str) -> list[dict]:
        """Every committed record for `session`, in commit order."""
        return self._read(self.path_for(session))

    def last_turn(self, session: str) -> Optional[int]:
        recs = self.turns(session)
        return recs[-1]["turn"] if recs else None

    def last_replica(self, session: str) -> Optional[str]:
        """The replica that committed this session's most recent turn
        (the `replica=` meta the scheduler stamps when it serves a
        fleet replica — ISSUE 17 routing affinity). None for sessions
        served single-engine or never journaled."""
        for rec in reversed(self.turns(session)):
            rep = rec.get("replica")
            if rep is not None:
                return rep
        return None

    def describe(self) -> dict:
        return {
            "root": str(self.root),
            "sessions": len(list(self.root.glob("*.jsonl"))),
            "records_written": self.records,
            "errors": self.errors,
        }


class _Suspended:
    def __init__(self, journal: SessionJournal):
        self._j = journal

    def __enter__(self):
        self._j._suspended = True
        return self._j

    def __exit__(self, *exc):
        self._j._suspended = False
        return False


def replay_turn_prompt(row: dict) -> list[int]:
    """The exact committed token stream of one journaled row: the
    turn's prompt ids followed by every produced id. Submitting this as
    a (pre-tokenized) prompt with a 1-token budget re-prefills and
    commits the full turn through the normal serving path, leaving the
    slot's KV exactly where the retired turn left it."""
    return (list(row.get("prompt_tokens", []))
            + list(row.get("produced", [])))


def replay_turns(journal: SessionJournal, session: str,
                 submit) -> int:
    """Replay every committed turn of `session` through `submit` —
    a callable with the scheduler/engine submit signature
    `submit(session, [(knight, token_ids), ...], max_new_tokens=1)`.
    Turns replay in commit order so later turns reuse the earlier ones'
    KV (own-slot reuse + prefix cache make this cheap). Journal writes
    are suspended for the duration. Returns the number of turns
    replayed."""
    recs = journal.turns(session)
    with journal.suspend_replay():
        for rec in recs:
            turns = [(row["knight"], replay_turn_prompt(row))
                     for row in rec["rows"]]
            kwargs: dict = {"max_new_tokens": 1}
            ads = [row.get("adapter") for row in rec["rows"]]
            if any(a is not None for a in ads):
                # Persona rows must replay under their adapter: the
                # committed K/V was adapter-tinted, and a base-model
                # re-prefill would bake DIFFERENT bytes into the slot.
                kwargs["adapters_per_turn"] = ads
            submit(session, turns, **kwargs)
    return len(recs)


def iter_all_turns(journal: SessionJournal) -> Iterable[tuple[str, dict]]:
    for session in journal.sessions():
        for rec in journal.turns(session):
            yield session, rec
