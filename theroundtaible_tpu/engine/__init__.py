"""The in-tree JAX/XLA inference engine (tpu-llm backend).

`get_engine(config)` is the single construction seam used by
adapters/tpu_llm.py. Engines are cached per (model, checkpoint, mesh) so
several knights share one resident model (SURVEY.md §7.1).
"""

from __future__ import annotations

import json
import threading
from typing import Any

_engines: dict[str, Any] = {}
_lock = threading.Lock()


def _cache_key(config: dict[str, Any]) -> str:
    relevant = {k: config.get(k) for k in
                ("model", "checkpoint", "max_seq_len", "dtype", "mesh",
                 "seq_parallel", "long_scheme", "long_threshold",
                 "devices", "attn")}
    return json.dumps(relevant, sort_keys=True)


def get_engine(config: dict[str, Any]):
    """Build (or reuse) an InferenceEngine for this adapter config."""
    key = _cache_key(config)
    with _lock:
        if key not in _engines:
            from .engine import InferenceEngine
            _engines[key] = InferenceEngine.from_config(config)
        return _engines[key]


def reset_engines() -> None:
    """Drop all cached engines (tests)."""
    with _lock:
        _engines.clear()
