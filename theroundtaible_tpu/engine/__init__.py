"""The in-tree JAX/XLA inference engine (tpu-llm backend).

`get_engine(config)` is the single construction seam used by
adapters/tpu_llm.py: it joins the multi-host process group (distributed),
routes pipe meshes to the pipeline engine (pp_serving) and everything
else to InferenceEngine (engine), and caches engines by every
serving-relevant config key so knights with identical configs share one
resident model while differing ones never silently collide (SURVEY.md
§7.1; per-call settings like knight_sampling are deliberately NOT in the
key).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any

_engines: dict[str, Any] = {}
_breakers: dict[str, Any] = {}
_lock = threading.Lock()
# The one-shot cache decision (ISSUE 6 satellite): memoized for BOTH
# outcomes — the CPU no-op used to re-probe jax.default_backend() on
# every call — and recorded once into the telemetry registry and
# engine.describe() so an operator can see which it was after the fact.
_compile_cache_decision: dict[str, Any] | None = None


def enable_compilation_cache():
    """Turn on JAX's persistent compilation cache (idempotent).

    Every engine process otherwise pays a full XLA compile per
    (batch, bucket) program — minutes of cold-start on a real chip
    (SURVEY.md §7.3 hard part 5). The cache dir is stable across runs so
    `discuss` cold-start after the first ever run is dominated by
    deserialization, not compilation. Override with ROUNDTABLE_XLA_CACHE.

    CPU backends are a no-op: tiny-shape CPU compiles are seconds, and
    XLA:CPU AOT cache entries embed host machine features — reloading one
    compiled under different flags/machines warns "could lead to SIGILL".
    The dir is namespaced by backend so mixed-platform runs can't collide.

    Returns the cache dir when enabled, None for the no-op — and either
    way decides exactly ONCE per process (get_compile_cache_decision()
    exposes the memoized outcome)."""
    global _compile_cache_decision
    if _compile_cache_decision is not None:
        return _compile_cache_decision.get("dir")
    import jax
    backend = jax.default_backend()
    if backend == "cpu":
        _compile_cache_decision = {
            "enabled": False, "backend": "cpu", "dir": None,
            "reason": "cpu no-op (AOT entries embed host features)"}
        _record_cache_decision()
        return None
    cache_dir = os.path.join(
        os.environ.get(
            "ROUNDTABLE_XLA_CACHE",
            os.path.join(os.path.expanduser("~"), ".cache",
                         "theroundtaible_tpu", "xla-cache")),
        backend)
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # Cache even fast compiles: serving has many small bucket programs and
    # the default 1s threshold would skip exactly the ones that add up.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _compile_cache_decision = {
        "enabled": True, "backend": backend, "dir": cache_dir}
    _record_cache_decision()
    return cache_dir


def _record_cache_decision() -> None:
    """One registry gauge + flight event per process for the decision —
    bench records and status --perf then carry which cold-start regime
    the numbers were measured under."""
    from ..utils import telemetry
    d = _compile_cache_decision or {}
    telemetry.set_gauge("roundtable_compile_cache_enabled",
                        1.0 if d.get("enabled") else 0.0)
    telemetry.recorder().record("compile_cache_decision", **d)


def get_compile_cache_decision() -> dict[str, Any] | None:
    """The memoized enable_compilation_cache outcome (None before the
    first call) — embedded in engine.describe()."""
    return _compile_cache_decision


def _cache_key(config: dict[str, Any]) -> str:
    relevant = {k: config.get(k) for k in
                ("model", "checkpoint", "max_seq_len", "dtype", "mesh",
                 "seq_parallel", "long_scheme", "long_threshold",
                 "devices", "attn", "num_slots", "sampling", "seed",
                 "kv_layout", "page_size", "num_pages", "n_micro",
                 "quant", "dcn_axis", "prefix_cache",
                 "prefix_cache_pages", "kv_offload", "ragged_attn",
                 "spec_decode", "spec_max_draft", "lora", "kv_quant")}
    return json.dumps(relevant, sort_keys=True)


def get_engine(config: dict[str, Any]):
    """Build (or reuse) an engine for this adapter config.

    A mesh with a "pipe" axis selects the pipeline-parallel serving
    engine (stage-local weights + KV, engine/pp_serving.py); everything
    else gets the main InferenceEngine."""
    # Join the multi-host process group BEFORE any backend/device call —
    # this seam runs ahead of plan_fleet's jax.devices() and every engine
    # constructor (engine/distributed.py; jax.distributed.initialize must
    # precede backend init).
    from .distributed import maybe_init_distributed
    maybe_init_distributed()
    key = _cache_key(config)
    with _lock:
        if key not in _engines:
            if (config.get("mesh") or {}).get("pipe"):
                from .pp_serving import PPEngine
                eng = PPEngine.from_config(config)
            else:
                from .engine import InferenceEngine
                eng = InferenceEngine.from_config(config)
            # Supervision identity + rebuild recipe (ISSUE 12): the
            # EngineSupervisor rebuilds a dead engine from exactly this
            # config and keys its restart budget by this cache key.
            eng._engine_cache_key = key
            eng._engine_config = dict(config)
            _engines[key] = eng
        return _engines[key]


def replace_engine(old, new) -> bool:
    """Swap a rebuilt engine into the cache in place of the instance it
    supersedes (engine/supervisor.py restart cycle): every later
    get_engine with the same config serves the fresh engine. Returns
    whether a cache entry was replaced (False for engines constructed
    outside the cache — tests, ad-hoc instances)."""
    with _lock:
        for k, v in list(_engines.items()):
            if v is old:
                _engines[k] = new
                return True
    return False


def get_breaker(config: dict[str, Any]):
    """The circuit breaker for this engine config — keyed exactly like
    the engine cache, so every adapter sharing a resident engine shares
    its failure history (a sick engine is sick for all its knights).
    `breaker_threshold` in the config sets the consecutive-failure trip
    count (default 3) — FIRST caller wins, since breaker_threshold is
    deliberately not part of the engine cache key (it isn't
    serving-relevant); a later caller asking for a different threshold
    gets the shared breaker as-is, with a warning. Breakers exist even
    while the engine itself is unbuilt or broken: construction failures
    count too."""
    key = _cache_key(config)
    threshold = max(1, int(config.get("breaker_threshold", 3)))
    with _lock:
        breaker = _breakers.get(key)
        if breaker is None:
            from .faults import CircuitBreaker
            breaker = _breakers[key] = CircuitBreaker(
                threshold=threshold, name=config.get("model", "engine"))
        elif breaker.threshold != threshold and "breaker_threshold" \
                in config:
            import warnings
            warnings.warn(
                f"breaker_threshold {threshold} ignored: this engine's "
                f"shared breaker was created with threshold "
                f"{breaker.threshold} (first caller wins)")
        return breaker


def breaker_snapshots() -> list[dict[str, Any]]:
    """Health snapshot of every engine breaker (fleet.fleet_health)."""
    with _lock:
        return [b.snapshot() for b in _breakers.values()]


def reset_engines() -> None:
    """Drop all cached engines and their breakers (tests)."""
    with _lock:
        _engines.clear()
        _breakers.clear()


# Public multi-LoRA surface (ISSUE 10 satellite): `from
# theroundtaible_tpu.engine import LoraStore` without deep paths.
# PEP 562 lazy export — engine/__init__ must stay importable without
# pulling jax at module load (bench parents import it pre-backend).
_LORA_EXPORTS = ("LoraStore", "lora_enabled", "lora_dims",
                 "save_pair_tree")

# Public supervision surface (ISSUE 12): the supervisor singleton
# accessors, the classified dead-engine error, and the durable session
# journal — same lazy-export discipline (supervisor pulls core.errors
# only; the journal is pure host code). The singleton itself is reached
# as engine.supervisor.supervisor() — the bare name would shadow the
# submodule.
_SUPERVISION_EXPORTS = ("EngineSupervisor", "EngineDead",
                        "set_supervisor", "supervisor_snapshot")
_JOURNAL_EXPORTS = ("SessionJournal", "replay_turns",
                    "replay_turn_prompt")


def __getattr__(name: str):
    if name in _LORA_EXPORTS:
        from . import lora as _lora
        return getattr(_lora, name)
    if name in _SUPERVISION_EXPORTS:
        from . import supervisor as _sup
        return getattr(_sup, name)
    if name in _JOURNAL_EXPORTS:
        from . import session_journal as _sj
        return getattr(_sj, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
