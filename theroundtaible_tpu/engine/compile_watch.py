"""Compile observatory — every XLA compile recorded, and a steady-state
recompile sentinel that turns "pow2 buckets compile nothing mid-serve"
from a convention into an enforced, observable guarantee (ISSUE 6).

The scheduler's core invariant (engine/scheduler.py: occupancy drift
inside a bucket compiles nothing mid-serve) had zero runtime detection:
a recompile regression would show up only as mysterious tail latency.
This module hooks JAX compilation via `jax.monitoring` events (the
supported seam — fires for both fresh backend compiles and persistent-
cache retrievals, which ALSO stall the serving loop), falling back to
wrapping the lower/compile seam on jax builds without monitoring
listeners, and records every compile into the PR-5 telemetry spine:

- registry counters `roundtable_compiles_total{label=...}` /
  `roundtable_compile_seconds_total` /
  `roundtable_compile_cache_{hits,misses}_total`, a flight-recorder
  `compile` event per observation, and a bounded in-process history
  ring (`history()` — what `status --perf` renders);
- **program labels** via `label(...)`: engine dispatch seams wrap
  their device calls in a thread-local attribution window
  (`prefill[b=2,bucket=128]`, `decode[b=4]`), so a compile is
  attributable to the program that triggered it — compiles outside
  any window record as "unlabeled" (engine construction, eager ops);
- the **steady-state sentinel**: `warmup_complete(label)` (called by
  both engines' warmup() and by SessionScheduler.declare_warmup_
  complete()) declares the compile set closed. Any compile after that
  increments `roundtable_steady_state_compiles_total{label=...}`,
  records a `steady_state_compile` flight event, ships ONE flight
  dump per steady period, and — under `ROUNDTABLE_RECOMPILE_STRICT=1`
  (armed for every `scheduler`-marked test by conftest) — raises
  `RecompileInSteadyState` from the compiling call site, failing the
  serving path LOUD instead of letting a mid-serve compile hide in
  the latency tail.

Host-only at import (no jax until `install()`), same contract as the
rest of the telemetry spine.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Optional

from ..utils import telemetry

STRICT_ENV = "ROUNDTABLE_RECOMPILE_STRICT"
_HISTORY_CAP = 256

# Monitoring event names observed (jax 0.4.x): a fresh compile fires
# backend_compile_duration; a persistent-cache hit skips it and fires
# cache_retrieval_time_sec instead — BOTH are mid-serve compilation
# work from the serving loop's point of view, so both count.
_COMPILE_EVENT = "backend_compile_duration"
_RETRIEVAL_EVENT = "cache_retrieval_time_sec"
_CACHE_HIT_EVENT = "cache_hits"
_CACHE_MISS_EVENT = "cache_misses"


class RecompileInSteadyState(RuntimeError):
    """A program compiled after warmup was declared complete while
    ROUNDTABLE_RECOMPILE_STRICT=1 — the no-mid-serve-recompile
    invariant was violated by the raising call site."""


_state_lock = threading.Lock()
_installed_mode: Optional[str] = None
_history: deque = deque(maxlen=_HISTORY_CAP)
_compiles = 0
_cache_hits = 0
_cache_misses = 0
_steady_labels: set[str] = set()
_steady_compiles = 0
# Engines whose CURRENT steady period already shipped its one flight
# dump — per label, so engine B's first violation still gets its
# postmortem after engine A already dumped.
_steady_dumped: set[str] = set()
_tls = threading.local()


def strict_armed() -> bool:
    """Read the env each call so tests can monkeypatch it."""
    return bool(os.environ.get(STRICT_ENV))


class label:
    """Thread-local compile-attribution window: compiles observed while
    the window is open record under `text`. Reentrant (inner windows
    shadow outer); cost is two attribute writes per dispatch.
    `fallback=True` yields to an already-open window — the shared
    run_dispatch seam uses it so its rung-level label never clobbers
    an engine's precise (batch, bucket) one."""

    __slots__ = ("text", "attrs", "_prev", "_skip")

    def __init__(self, text: str, fallback: bool = False, **attrs):
        self.text = text
        self.attrs = attrs
        self._prev = None
        self._skip = fallback

    def __enter__(self) -> "label":
        self._prev = getattr(_tls, "label", None)
        if self._skip and self._prev is not None:
            return self
        self._skip = False
        _tls.label = (self.text, self.attrs)
        return self

    def __exit__(self, *exc) -> bool:
        if not self._skip:
            _tls.label = self._prev
        return False


def current_label() -> tuple[str, dict]:
    cur = getattr(_tls, "label", None)
    return cur if cur is not None else ("unlabeled", {})


def install() -> str:
    """Register the compile hooks (idempotent; returns the mode:
    "monitoring" | "lower-seam" | "off"). Called from both engines'
    constructors so any serving process observes its compiles."""
    global _installed_mode
    with _state_lock:
        if _installed_mode is not None:
            return _installed_mode
        mode = "off"
        try:
            import jax.monitoring as monitoring
            monitoring.register_event_duration_secs_listener(
                _on_duration)
            mode = "monitoring"
        except Exception:  # noqa: BLE001 — fall back to the lower seam
            mode = _install_lower_seam()
        if mode == "monitoring":
            # Separate try: losing the plain-event listener only costs
            # the cache-hit/miss counters — falling through to the
            # lower seam HERE would double-count every compile (the
            # duration listener above is already registered).
            try:
                monitoring.register_event_listener(_on_event)
            except Exception:  # noqa: BLE001
                pass
        _installed_mode = mode
    telemetry.set_gauge("roundtable_compile_observatory",
                        0.0 if mode == "off" else 1.0)
    return mode


def _install_lower_seam() -> str:
    """Fallback for jax builds without monitoring listeners: time the
    internal lower→compile seam. Best-effort — a jax refactor leaves
    the observatory off, never broken."""
    try:
        from jax._src.interpreters import pxla
        orig = pxla.MeshComputation.compile
        if getattr(orig, "_rt_compile_watch", False):
            return "lower-seam"

        def wrapped(self, *a, **k):
            t0 = time.monotonic()
            out = orig(self, *a, **k)
            _record_compile(time.monotonic() - t0, cache_hit=False)
            return out

        wrapped._rt_compile_watch = True
        pxla.MeshComputation.compile = wrapped
        return "lower-seam"
    except Exception:  # noqa: BLE001 — observatory off, nothing broken
        return "off"


def _on_duration(event: str, duration: float, **_kw) -> None:
    if event.endswith(_COMPILE_EVENT):
        _record_compile(duration, cache_hit=False)
    elif event.endswith(_RETRIEVAL_EVENT):
        _record_compile(duration, cache_hit=True)


def _on_event(event: str, **_kw) -> None:
    global _cache_hits, _cache_misses
    if event.endswith(_CACHE_HIT_EVENT):
        with _state_lock:
            _cache_hits += 1
        telemetry.inc("roundtable_compile_cache_hits_total")
    elif event.endswith(_CACHE_MISS_EVENT):
        with _state_lock:
            _cache_misses += 1
        telemetry.inc("roundtable_compile_cache_misses_total")


def _record_compile(duration: float, cache_hit: bool) -> None:
    global _compiles, _steady_compiles
    lbl, attrs = current_label()
    entry: dict[str, Any] = {
        "label": lbl, "dur_s": round(duration, 4),
        "at": round(time.time(), 3), "cache_hit": cache_hit,
    }
    for k, v in attrs.items():
        entry.setdefault(k, v)
    dump_now = False
    with _state_lock:
        _compiles += 1
        # Violation = the compile is attributable to an engine that
        # DECLARED steady state (the attribution window's engine attr
        # vs that engine's label). Per-engine, not process-global: in
        # a multi-engine process (warmup_cmd loops adapters), engine
        # 1's declaration must not classify engine 2's construction
        # and warmup compiles as violations. The cost: compiles with
        # no engine attribution (eager ops, construction) are never
        # violations — the labeled prefill/decode dispatch that any
        # real mid-serve shape change also triggers is what trips.
        eng = attrs.get("engine")
        steady = eng in _steady_labels
        entry["steady_state"] = steady
        _history.append(entry)
        if steady:
            _steady_compiles += 1
            if eng not in _steady_dumped:
                _steady_dumped.add(eng)
                dump_now = True
    telemetry.inc("roundtable_compiles_total", label=lbl)
    telemetry.inc("roundtable_compile_seconds_total", duration)
    telemetry.recorder().record("compile", **entry)
    if not entry["steady_state"]:
        return
    telemetry.inc("roundtable_steady_state_compiles_total", label=lbl)
    if dump_now:
        # One postmortem per steady period — a recompile-per-segment
        # pathology must not turn the dump dir into its own incident.
        telemetry.flight_dump("steady_state_compile",
                              extra={"label": lbl, "entry": entry})
    if strict_armed():
        raise RecompileInSteadyState(
            f"compile of {lbl!r} ({'cache retrieval' if cache_hit else 'backend compile'}, "
            f"{duration:.3f}s) after warmup was declared complete for "
            f"{sorted(_steady_labels)} — the no-mid-serve-recompile "
            "invariant is violated (unset ROUNDTABLE_RECOMPILE_STRICT "
            "or warm the missing shape)")


# --- steady-state declaration ---


def warmup_complete(label_name: str = "engine") -> None:
    """Declare this engine/scheduler's compile set closed: every later
    compile is a steady-state violation (counted always, fatal under
    ROUNDTABLE_RECOMPILE_STRICT=1)."""
    with _state_lock:
        _steady_labels.add(label_name)
    telemetry.set_gauge("roundtable_steady_state", 1.0,
                        engine=label_name)
    telemetry.recorder().record("warmup_complete", engine=label_name)


def reopen_warmup(label_name: str) -> None:
    """Re-enter the warmup phase for ONE label: a new compile surface
    appeared on an already-warm engine (a SessionScheduler attached —
    its pipelined-segment carries and pinned-row joins trace shapes
    direct warmup never touches), so compiles are expected again until
    the owner re-declares. The sanctioned production escape; without
    it, engine.warmup()'s auto-declaration would classify the
    scheduler's warm traffic as steady-state violations."""
    with _state_lock:
        _steady_labels.discard(label_name)
        _steady_dumped.discard(label_name)
        telemetry.set_gauge("roundtable_steady_state", 0.0,
                            engine=label_name)


def reset_steady_state() -> None:
    """Leave steady state (tests; a deliberate re-warm after a config
    change). Also zeroes the module-level violation counter so test
    assertions read per-test deltas."""
    global _steady_compiles
    with _state_lock:
        for name in _steady_labels:
            telemetry.set_gauge("roundtable_steady_state", 0.0,
                                engine=name)
        _steady_labels.clear()
        _steady_dumped.clear()
        _steady_compiles = 0


def steady_state_labels() -> tuple[str, ...]:
    with _state_lock:
        return tuple(sorted(_steady_labels))


# --- introspection ---


def compiles_seen() -> int:
    return _compiles


def cache_hits_seen() -> int:
    return _cache_hits


def steady_state_compiles() -> int:
    return _steady_compiles


def history() -> list[dict]:
    with _state_lock:
        return list(_history)


def summary(recent: int = 0) -> dict[str, Any]:
    """The describe()/status/attribution embed."""
    with _state_lock:
        out: dict[str, Any] = {
            "mode": _installed_mode or "uninstalled",
            "compiles": _compiles,
            "cache_hits": _cache_hits,
            "cache_misses": _cache_misses,
            "steady_state": sorted(_steady_labels),
            "steady_state_compiles": _steady_compiles,
            "strict": strict_armed(),
        }
        if recent:
            out["recent"] = list(_history)[-recent:]
    return out
