"""Pallas TPU grouped batched LoRA matmul (ISSUE 10).

The multi-LoRA serving path adds, per target projection, a low-rank
delta `x @ A_id^T @ B_id` on top of the shared base matmul, where `id`
is each ROW's adapter slot (0 = the zero "base" adapter). The XLA
baseline (engine/lora.py `_xla_grouped`) is a masked dense BMM over the
whole adapter stack — correct everywhere, but it computes every slot's
first matmul for every row. This module is the fast path: a
scalar-prefetched BGMV (batched gather matrix-vector) kernel in the
mold of Punica/S-LoRA's grouped kernels — per-row adapter ids steer the
A/B block DMAs, so each grid row streams ONLY its own adapter's tensors
from HBM, and consecutive rows sharing an adapter (a ragged buffer's
per-sequence runs, a co-batched session's rows) elide the re-fetch
entirely: Pallas skips a block DMA whose index map output is unchanged,
which is exactly the "grouped" property without a host-side sort.

Layouts (chosen so no in-kernel shuffle is ever needed, the int4mm
rule): A is stored TRANSPOSED as `a_t [S, r, C]` (lane dim = the
contraction C, 128-aligned for every real embed/hidden dim) and B as
`b_s [S, r, O]` (lane dim = the output axis). The kernel computes
`xa = x · a_t[id]^T` (contract C) then `xa · b_s[id]` (contract r) in
one grid step per (row, output-block).

Dispatch discipline mirrors pallas/int4mm exactly:

- `plan_bgmv` validates blocking/alignment/VMEM BEFORE any pallas_call
  is emitted, returning a machine-readable decline reason — no shape
  can reach a Mosaic failure on chip, and every decline surfaces as
  `fallback_reason` in the engine's `lora_paths` provenance sink.
- rows are capped at 64 ("rows:prefill-m"): these are DECODE kernels.
  Prefill's big-M dispatches keep the XLA grouped path, where the
  masked dense BMM amortizes over T (LoRA FLOPs are ~r/C of the base
  matmul — noise next to prefill compute).
- `lora_bgmv_spmd` runs the single-device kernel per shard inside
  shard_map, partitioned the way sharding.lora_stack_specs places the
  stacked tensors (megatron column-parallel: B's output axis sharded,
  no collective; row-parallel: A's contraction axis sharded + one psum
  over "model" — the same all-reduce the base matmul's sharded einsum
  inserts). Plans are validated against the PER-SHARD shapes before
  entering shard_map.
- on non-TPU backends the kernel runs in interpret mode when forced
  via ROUNDTABLE_LORA_MM=1 — how the CPU suite validates it.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def enabled() -> bool:
    """Kernel path on by default on real TPU; ROUNDTABLE_LORA_MM=1
    forces it elsewhere (interpret mode — the test path), =0 disables
    everywhere (the A/B lever, mirroring ROUNDTABLE_INT4_MM)."""
    v = os.environ.get("ROUNDTABLE_LORA_MM", "")
    if v == "0":
        return False
    if v == "1":
        return True
    return jax.default_backend() == "tpu"


# Mirror of int4mm._VMEM_BUDGET: the resident working set must fit or
# the dispatch declines to the XLA grouped path.
_VMEM_BUDGET = 12 * 1024 * 1024

# Decode kernels only — the int4mm._plan_rows rule. One grid step per
# row is a GEMV; past this many rows the XLA masked BMM amortizes
# better and the grid bookkeeping stops paying for itself.
_MAX_ROWS = 64


def _bgmv_vmem_est(m: int, c_dim: int, r: int, bo: int) -> int:
    # whole-array x block + per-id a/b blocks (double-buffered) + the
    # whole-rows out block, sized at 4 B/elt (>= any real dtype)
    x_blk = m * c_dim * 4
    a_blk = 2 * r * c_dim * 4
    b_blk = 2 * r * bo * 4
    out_blk = m * bo * 4
    return x_blk + a_blk + b_blk + out_blk


def plan_bgmv(m_rows: int, c_dim: int, r: int, o_dim: int):
    """((bo,), None) or (None, reason) for a grouped BGMV at these
    dims. Reasons are stable strings — they surface as the
    `fallback_reason` in the engine's lora_paths provenance."""
    if m_rows > _MAX_ROWS:
        return None, "rows:prefill-m"
    if r < 1 or r > 512:
        return None, "rank:unsupported"
    if c_dim % 128:
        return None, "dims:contract-misaligned"
    if o_dim % 128:
        return None, "dims:out-misaligned"
    for bo in (512, 256, 128):
        if o_dim % bo:
            continue
        if _bgmv_vmem_est(m_rows, c_dim, r, bo) <= _VMEM_BUDGET:
            return (bo,), None
    return None, "vmem:bgmv"


def _bgmv_kernel(ids_ref, x_ref, a_ref, b_ref, o_ref):
    # One grid step = one (output-block, row): xa = x_i · a^T (contract
    # the lane axis C), then xa · b (contract r). Both products in f32
    # on the MXU; the row's adapter blocks were DMA'd by the
    # scalar-prefetched index maps below. x and out ride WHOLE-array
    # blocks (Mosaic rejects 1-sublane row blocks on a taller array):
    # their index maps are constant per inner sweep, so the x DMA
    # happens once and the out block flushes once per output block.
    i = pl.program_id(1)
    x = x_ref[pl.ds(i, 1), :]          # [1, C] — this row
    a = a_ref[0]                       # [r, C]
    b = b_ref[0]                       # [r, bo]
    xa = jax.lax.dot_general(x, a, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    o_ref[pl.ds(i, 1), :] = jax.lax.dot_general(
        xa.astype(x.dtype), b, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("bo", "interpret"))
def _bgmv(ids, x2, a_t, b_s, bo: int, interpret: bool):
    """ids [M] int32, x2 [M, C], a_t [S, r, C], b_s [S, r, O] →
    delta [M, O] f32. Grid (O/bo, M) with the ROW innermost: the out
    block's index is constant across the inner sweep (one flush per
    output block, every row written exactly once), and the id of row i
    steers the A/B block index maps — identical consecutive ids elide
    the DMA, which is the grouped property."""
    m, c_dim = x2.shape
    o_dim = b_s.shape[2]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(o_dim // bo, m),
        in_specs=[
            pl.BlockSpec((m, c_dim), lambda j, i, ids: (0, 0)),
            pl.BlockSpec((1, a_t.shape[1], c_dim),
                         lambda j, i, ids: (ids[i], 0, 0)),
            pl.BlockSpec((1, b_s.shape[1], bo),
                         lambda j, i, ids: (ids[i], 0, j)),
        ],
        out_specs=pl.BlockSpec((m, bo), lambda j, i, ids: (0, j)),
    )
    return pl.pallas_call(
        _bgmv_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((m, o_dim), jnp.float32),
        interpret=interpret,
    )(ids.astype(jnp.int32), x2, a_t, b_s)


def lora_bgmv_or_reason(x2: jax.Array, a_t: jax.Array, b_s: jax.Array,
                        ids: jax.Array):
    """(delta [M, O] f32, None) on the kernel path, (None, reason) when
    this dispatch declines — the caller then serves the XLA grouped
    path and records the reason (the einsum_int4_or_reason contract)."""
    m, c_dim = x2.shape
    s, r, o_dim = b_s.shape
    plan, reason = plan_bgmv(m, c_dim, r, o_dim)
    if plan is None:
        return None, reason
    (bo,) = plan
    return _bgmv(ids, x2, a_t, b_s, bo, _interpret()), None


# --- shard-aware dispatch (multi-device meshes) ---


def lora_bgmv_spmd(mesh, x2: jax.Array, a_t: jax.Array, b_s: jax.Array,
                   ids: jax.Array, tp: Optional[str] = None):
    """The grouped kernel under a multi-device mesh: per-shard
    single-device dispatch inside shard_map, partitioned the way
    sharding.lora_stack_specs places the stacked tensors (the
    einsum_int4_spmd sibling).

    tp="col" (q/k/v, gate/up): B's OUTPUT axis carries the model
    shards — each shard computes its own delta slice, no collective.
    tp="row" (o_proj, down_proj): A's CONTRACTION axis carries them —
    per-shard partial deltas combine with one psum over "model",
    exactly the all-reduce the base matmul's sharded einsum inserts.
    A dim the mesh does not divide is served replicated (matching
    sharding._fallback_replicated placement). Returns
    (delta, None) or (None, fallback_reason)."""
    from jax.sharding import PartitionSpec as P

    from ..compat import mesh_manual_axes, shard_map
    from ..sharding import MODEL_AXIS, lora_shard_axis, model_axis_size

    m, c_dim = x2.shape
    s, r, o_dim = b_s.shape
    m_shards = model_axis_size(mesh)
    manual = mesh_manual_axes(mesh)
    if m_shards > 1 and MODEL_AXIS not in manual:
        return None, "mesh:model-axis-not-auto"

    which = lora_shard_axis(tp)
    if m_shards <= 1:
        which = None
    if which == "out" and o_dim % m_shards:
        which = None
    if which == "in" and c_dim % m_shards:
        which = None

    div = m_shards if which is not None else 1
    c_local = c_dim // (div if which == "in" else 1)
    o_local = o_dim // (div if which == "out" else 1)
    plan, reason = plan_bgmv(m, c_local, r, o_local)
    if plan is None:
        return None, (reason if which is None else reason + "/sharded")
    (bo,) = plan

    x_spec = P(None, MODEL_AXIS if which == "in" else None)
    a_spec = P(None, None, MODEL_AXIS if which == "in" else None)
    b_spec = P(None, None, MODEL_AXIS if which == "out" else None)
    out_spec = P(None, MODEL_AXIS if which == "out" else None)

    def body(ids_l, x_l, a_l, b_l):
        y = _bgmv(ids_l, x_l, a_l, b_l, bo, _interpret())
        if which == "in":
            y = jax.lax.psum(y, MODEL_AXIS)
        return y

    fn = shard_map(body, mesh=mesh,
                   in_specs=(P(None), x_spec, a_spec, b_spec),
                   out_specs=out_spec, axis_names=manual,
                   check_vma=False)
    return fn(ids.astype(jnp.int32), x2, a_t, b_s), None
