"""Pallas TPU fused w4a16 matmul: dequantize int4 weights in VMEM, inside
the matmul, so HBM streams the PACKED bytes.

Why a kernel at all: the XLA path (models/common.py `_einsum` →
`dequant_int4`) expresses dequant as bitcast → convert → grouped-scale
multiply → reshape and hopes XLA fuses that chain into the dot's operand
read. On real TPU it does not: BENCH_r05 hardware runs measured int4
decode at 22.9 tok/s (interleave layout) then 31.6 tok/s (bitcast
layout) against bf16's 130 and int8's 205 — the dequantized bf16 weight
was materialized (and copied) in HBM every token, so int4 streamed MORE
bytes than bf16. int8 escapes because its dequant is a plain
convert (fusable operand) plus an OUTPUT-side scale; int4's grouped
scale multiplies the weight on the CONTRACTED side of the dot and XLA
TPU will not fold a multiply-by-different-shaped-operand into a dot
input. (Reference compute equivalent: llama.cpp's q4 kernels, reached
through src/adapters/local-llm.ts — its default serving precision —
dequantize in registers for exactly this reason.)

These kernels make the fusion structural instead of heuristic. The pack
layout (engine/quant.py: two signed nibbles per byte along the weight's
LAST axis, even element in the low nibble, per-`group` scales) was
chosen so NO shuffle is ever needed in-kernel:

- `_mm_pack_out` — every per-layer matmul (qkv/o/gate/up/down: the
  packed last axis is a NON-contracted output axis). Byte k of a row
  holds output columns 2k (low nibble) and 2k+1 (high), and both share
  scale group k // (g/2). The kernel extracts nibbles with two
  arithmetic shifts, applies the group scale, and runs TWO dots — one
  producing even output columns, one odd — accumulating over contraction
  blocks in VMEM scratch. The only reorder is interleaving the two
  [bm, bp] OUTPUT accumulators at the end: 2·bm·bp elements once per
  output block, vs. the E·F weight interleave the XLA path choked on.
- `_mm_pack_contract` — the tied-embedding lm head ([V, E] packed along
  E, which the head matmul CONTRACTS). Splitting the ACTIVATION into
  even/odd columns (x[:, 0::2], x[:, 1::2] — a [M, E] strided slice,
  done once outside the kernel) turns the matmul into
  dot(x_even, low^T) + dot(x_odd, high^T): no weight interleave, no
  output interleave, scale group k // (g/2) again shared.

`einsum_int4` is the dispatch seam `_einsum` calls: it classifies the
einsum spec (contracted axes a prefix of the weight → pack-on-output;
suffix → pack-on-contraction), flattens to 2-D, pads M to sublane
multiples, and declines (with a machine-readable reason — the
`fallback_reason` the engine's path-provenance report and the benches
surface) whenever blocking/grouping/VMEM cannot be arranged — the
caller then falls back to the XLA dequant path, so MoE expert matmuls
("bte,xef->btxf") and tiny routers serve unchanged. Every dispatch is
budgeted against `_VMEM_BUDGET` BEFORE the pallas_call is emitted, so
no shape can reach a Mosaic VMEM failure on chip. These are DECODE
kernels: M is capped at 64 rows (decode and the post-last_pos-gather
lm head are always ≤ batch), because the grid iterates p innermost so
grouped scales stream once per contraction block — which makes the f32
output block round-trip per contraction block, negligible at decode M
and ruinous at prefill M. Prefill int4 keeps the XLA path, where the
materialized dequant amortizes over T.

Multi-device (the ISSUE 3 tentpole): a pallas_call inside jit-under-
GSPMD is an opaque unpartitionable custom call, so the kernels CANNOT
simply run on a sharded mesh — `einsum_int4_spmd` instead partitions
the matmul the way sharding.param_specs already shards the weight
(megatron column-parallel for qkv/gate/up/lm-head — each shard computes
its own output slice, no collective; row-parallel for o/down — each
shard contracts its input slice and one psum over the "model" axis
combines, exactly the all-reduce the XLA path's sharded einsum inserts)
and runs the single-device kernel per shard inside `shard_map` (via
engine/compat.py's version shim). The plan is checked against the
PER-SHARD shapes before entering shard_map, so the body's dispatch
never declines mid-trace; a weight axis the mesh does not divide is
served replicated (matching sharding._fallback_replicated's placement,
so the in_specs never force a per-dispatch weight regather). On non-TPU
backends the kernels run in Pallas interpret mode when forced via
ROUNDTABLE_INT4_MM=1 — how the CPU suite validates them, single-device
and sharded (tests/test_int4mm.py).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def enabled() -> bool:
    """Kernel path on by default on real TPU; ROUNDTABLE_INT4_MM=1
    forces it elsewhere (interpret mode — the test path), =0 disables
    everywhere (the A/B lever for microbenches)."""
    v = os.environ.get("ROUNDTABLE_INT4_MM", "")
    if v == "0":
        return False
    if v == "1":
        return True
    return jax.default_backend() == "tpu"


def _pick_block(n: int, candidates: tuple[int, ...],
                multiple_of: int = 1) -> Optional[int]:
    for c in candidates:
        if n % c == 0 and c % multiple_of == 0:
            return c
    return None


def _nibbles(q_ref, dtype):
    """int8 packed byte block → (low, high) int4 values in `dtype`.
    Arithmetic shifts in int32 sign-extend both nibbles; no shuffle."""
    q = q_ref[...].astype(jnp.int32)
    low = ((q << 28) >> 28).astype(dtype)
    high = (q >> 4).astype(dtype)
    return low, high


def _mm_out_kernel(x_ref, q_ref, s_ref, o_ref, acc_lo, acc_hi, *,
                   gp: int, bg: int, bp: int, n_c: int):
    # Grid is (m, c, p) with p INNERMOST: the whole-axis scale block's
    # index (c, 0) is then constant across each p sweep, so Pallas
    # elides its DMA and scales stream once per contraction block —
    # with p outside c they re-streamed every step, ~doubling HBM
    # traffic on the up/gate shape. The price: accumulators span the
    # FULL output axis (scratch [bm, P] per nibble, ≤ 8 MB at the
    # largest bm·P), and each (c==last, p) step flushes its slice.
    c, j = pl.program_id(1), pl.program_id(2)
    x = x_ref[...]
    low, high = _nibbles(q_ref, x.dtype)
    # s_ref carries the FULL scale axis for this contraction block
    # (Mosaic wants lane-aligned or whole-axis block minors; the per-p
    # slab bg = bp/gp is narrower than a lane) — slice it here.
    s = s_ref[:, pl.ds(j * bg, bg)]
    srep = jnp.repeat(s, gp, axis=1)               # [bc, bp]
    dims = (((1,), (0,)), ((), ()))
    lo = jax.lax.dot_general(x, low * srep, dims,
                             preferred_element_type=jnp.float32)
    hi = jax.lax.dot_general(x, high * srep, dims,
                             preferred_element_type=jnp.float32)
    sl = pl.ds(j * bp, bp)

    @pl.when(c == 0)
    def _set():
        acc_lo[:, sl] = lo
        acc_hi[:, sl] = hi

    @pl.when(c > 0)
    def _add():
        acc_lo[:, sl] += lo
        acc_hi[:, sl] += hi

    @pl.when(c == n_c - 1)
    def _done():
        a_lo, a_hi = acc_lo[:, sl], acc_hi[:, sl]
        bm = a_lo.shape[0]
        # interleave OUTPUT columns: even ← low nibble, odd ← high
        o_ref[...] = jnp.stack([a_lo, a_hi], axis=-1).reshape(bm, 2 * bp)


@functools.partial(jax.jit,
                   static_argnames=("gp", "bm", "bp", "bc", "interpret"))
def _mm_pack_out(x, q4, s4, gp: int, bm: int, bp: int, bc: int,
                 interpret: bool):
    """x [M, C] · unpack(q4 [C, P], s4 [C, P//gp]) → [M, 2P] f32."""
    m, c_dim = x.shape
    _, p_dim = q4.shape
    grid = (m // bm, c_dim // bc, p_dim // bp)
    kernel = functools.partial(_mm_out_kernel, gp=gp, bg=bp // gp,
                               bp=bp, n_c=grid[1])
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bc), lambda i, k, j: (i, k)),
            pl.BlockSpec((bc, bp), lambda i, k, j: (k, j)),
            pl.BlockSpec((bc, p_dim // gp), lambda i, k, j: (k, 0)),
        ],
        out_specs=pl.BlockSpec((bm, 2 * bp), lambda i, k, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, 2 * p_dim), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((bm, p_dim), jnp.float32),
            pltpu.VMEM((bm, p_dim), jnp.float32),
        ],
        interpret=interpret,
    )(x, q4, s4)


def _mm_contract_kernel(xe_ref, xo_ref, q_ref, s_ref, o_ref, *, gp: int):
    xe, xo = xe_ref[...], xo_ref[...]
    low, high = _nibbles(q_ref, xe.dtype)
    srep = jnp.repeat(s_ref[...], gp, axis=1)      # [bn, Cp]
    dims = (((1,), (1,)), ((), ()))                # contract minor×minor
    o_ref[...] = (
        jax.lax.dot_general(xe, low * srep, dims,
                            preferred_element_type=jnp.float32)
        + jax.lax.dot_general(xo, high * srep, dims,
                              preferred_element_type=jnp.float32))


@functools.partial(jax.jit,
                   static_argnames=("gp", "bm", "bn", "interpret"))
def _mm_pack_contract(x_even, x_odd, q4, s4, gp: int, bm: int, bn: int,
                      interpret: bool):
    """x_even/x_odd [M, Cp] · unpack(q4 [N, Cp], s4 [N, Cp//gp])ᵀ
    → [M, N] f32. Contraction fits one block (lm-head E is small)."""
    m, cp = x_even.shape
    n_dim = q4.shape[0]
    kernel = functools.partial(_mm_contract_kernel, gp=gp)
    return pl.pallas_call(
        kernel,
        grid=(m // bm, n_dim // bn),
        in_specs=[
            pl.BlockSpec((bm, cp), lambda i, j: (i, 0)),
            pl.BlockSpec((bm, cp), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, cp), lambda i, j: (j, 0)),
            pl.BlockSpec((bn, cp // gp), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n_dim), jnp.float32),
        interpret=interpret,
    )(x_even, x_odd, q4, s4)


def _classify(spec: str, leaf):
    """Classify an einsum spec against a packed leaf: ((mode, n_cont,
    gp), None) with mode "out" (weight = contracted-prefix + kept, pack
    axis kept-minor) or "contract" (kept + one contracted pack axis —
    the tied lm head), or (None, reason) when the kernels cannot serve
    the spec at all. Reasons are stable strings — they surface as the
    `fallback_reason` in path-provenance reports."""
    lhs, out_dims = spec.split("->")
    a_dims, b_dims = lhs.split(",")
    cont = [d for d in b_dims if d in a_dims]
    kept = [d for d in b_dims if d not in a_dims]
    if not cont or not kept:
        return None, "spec:no-contraction-or-kept"
    if a_dims[-len(cont):] != "".join(cont):
        return None, "spec:cont-not-activation-suffix"
    batch = a_dims[:-len(cont)]
    if out_dims != batch + "".join(kept):
        return None, "spec:out-layout"
    if leaf.axis != leaf.q4.ndim - 1:
        # non-minor pack: fall back (XLA path asserts loudly)
        return None, "pack:non-minor-axis"
    if leaf.group % 2:
        return None, "pack:odd-group"
    gp = leaf.group // 2
    if list(b_dims) == cont + kept:
        return ("out", len(cont), gp), None
    if list(b_dims) == kept + cont and len(cont) == 1:
        return ("contract", 1, gp), None
    return None, "spec:mixed-kept-contracted"   # MoE expert layouts


def _plan_rows(m_rows: int) -> Optional[int]:
    """Padded block_m for m_rows, or None above 64: the kernels are
    DECODE kernels (weight-streaming-bound GEMVs, where fused dequant
    is the whole win). Prefill's big-M matmuls keep the XLA path —
    there the materialized dequant amortizes over T, while the
    write-at-last output revisiting would round-trip the [M, 2P] f32
    output once per contraction block."""
    mp = max(8, -(-m_rows // 8) * 8)
    return None if mp > 64 else mp


def _plan_pack_out(m_rows: int, c_dim: int, p_dim: int, gp: int):
    """((bm, bp, bc), None) or (None, reason) for the pack-on-output
    kernel at these (possibly per-shard) dims. Block search walks the
    candidates until the working set fits `_VMEM_BUDGET`, so a plan is
    emitted only for shapes Mosaic can actually allocate."""
    bm = _plan_rows(m_rows)
    if bm is None:
        return None, "rows:prefill-m"
    for bp in (512, 256, 128):
        if p_dim % bp or bp % gp:
            continue
        for bc in (512, 1024, 256, 128):
            if c_dim % bc:
                continue
            if _pack_out_vmem_est(bm, bp, bc, p_dim, gp) <= _VMEM_BUDGET:
                return (bm, bp, bc), None
    if (_pick_block(p_dim, (512, 256, 128), multiple_of=gp) is None
            or _pick_block(c_dim, (512, 1024, 256, 128)) is None):
        return None, "blocks:unblockable"
    return None, "vmem:pack-out"


def _plan_pack_contract(m_rows: int, cp: int, n_dim: int, gp: int):
    """((bm, bn), None) or (None, reason) for the pack-on-contraction
    kernel. The whole (packed) contraction rides one block, so the
    budget check shrinks bn until the x/q/s working set fits — replacing
    the old magic `cp > 4096` gate with an actual per-shape estimate."""
    bm = _plan_rows(m_rows)
    if bm is None:
        return None, "rows:prefill-m"
    if cp % 128 or cp % gp:
        return None, "blocks:cp-misaligned"
    for bn in (512, 256, 128):
        if n_dim % bn:
            continue
        if _pack_contract_vmem_est(bm, bn, cp, gp) <= _VMEM_BUDGET:
            return (bm, bn), None
    if _pick_block(n_dim, (512, 256, 128)) is None:
        return None, "blocks:unblockable"
    return None, "vmem:pack-contract"


def einsum_int4(spec: str, a: jax.Array, leaf) -> Optional[jax.Array]:
    """Run `jnp.einsum(spec, a, dequant(leaf))` through the fused
    kernels when the spec/shape/grouping allow; None → caller falls
    back to the XLA dequant path. Result is f32 (matches the XLA path's
    preferred_element_type)."""
    return einsum_int4_or_reason(spec, a, leaf)[0]


def einsum_int4_or_reason(spec: str, a: jax.Array, leaf):
    """(result, None) on the kernel path, (None, fallback_reason) when
    this dispatch declines — the reason feeds the engine's
    path-provenance report so a silent XLA fallback is attributable."""
    cls, reason = _classify(spec, leaf)
    if cls is None:
        return None, reason
    mode, n_cont, gp = cls
    if mode == "out":
        return _dispatch_pack_out(a, leaf, n_cont, gp)
    return _dispatch_pack_contract(a, leaf, gp)


def plan_reason(spec: str, a_shape: tuple, leaf) -> Optional[str]:
    """Why `einsum_int4` would decline this dispatch (None = kernel
    path) — shape-only, no arrays traced: the benches use it to emit
    `fallback_reason` provenance without burning a dispatch."""
    cls, reason = _classify(spec, leaf)
    if cls is None:
        return reason
    mode, n_cont, gp = cls
    a_size = 1
    for s in a_shape:
        a_size *= s
    q4 = leaf.q4
    if mode == "out":
        c_dim = 1
        for s in q4.shape[:n_cont]:
            c_dim *= s
        return _plan_pack_out(a_size // c_dim, c_dim, q4.size // c_dim,
                              gp)[1]
    cp = q4.shape[-1]
    return _plan_pack_contract(a_size // (2 * cp), cp, q4.size // cp,
                               gp)[1]


# Mirror of attention._VMEM_BUDGET: a conservative per-core VMEM cap the
# kernel's resident working set must fit, else dispatch declines and the
# XLA dequant path serves. Advisor r5: _mm_pack_out's accumulators span
# the FULL output axis (scratch 2·[bm, P] f32 — the price of the
# p-innermost grid that streams scales once), so a large-enough mlp_dim
# overflowed Mosaic's scratch allocation ON CHIP instead of falling back.
_VMEM_BUDGET = 12 * 1024 * 1024


def _pack_out_vmem_est(bm: int, bp: int, bc: int, p_dim: int,
                       gp: int) -> int:
    scratch = 2 * bm * p_dim * 4          # f32 accumulators span full P
    x_blk = 2 * bm * bc * 4               # double-buffered, ≤ f32
    q_blk = 2 * bc * bp                   # packed int4 bytes
    s_blk = 2 * bc * (p_dim // gp) * 4    # whole-axis scale block
    out_blk = bm * 2 * bp * 4             # f32 output block
    return scratch + x_blk + q_blk + s_blk + out_blk


def _pack_contract_vmem_est(bm: int, bn: int, cp: int, gp: int) -> int:
    # the whole (packed) contraction axis rides one block per operand
    x_blk = 2 * 2 * bm * cp * 4           # x_even + x_odd, double-buffered
    q_blk = 2 * bn * cp                   # packed int4 bytes
    s_blk = 2 * bn * (cp // gp) * 4
    out_blk = bm * bn * 4                 # f32 output block
    return x_blk + q_blk + s_blk + out_blk


def _pad_to(x2: jax.Array, bm: int) -> jax.Array:
    m = x2.shape[0]
    return x2 if m == bm else jnp.pad(x2, ((0, bm - m), (0, 0)))


def _dispatch_pack_out(a, leaf, n_cont: int, gp: int):
    q4, s4 = leaf.q4, leaf.s4
    c_dim = 1
    for s in q4.shape[:n_cont]:
        c_dim *= s
    p_dim = q4.size // c_dim
    kept_shape = q4.shape[n_cont:-1] + (q4.shape[-1] * 2,)
    x2 = a.reshape(-1, c_dim)
    plan, reason = _plan_pack_out(x2.shape[0], c_dim, p_dim, gp)
    if plan is None:
        return None, reason
    bm, bp, bc = plan
    m = x2.shape[0]
    y = _mm_pack_out(_pad_to(x2, bm), q4.reshape(c_dim, p_dim),
                     s4.reshape(c_dim, p_dim // gp), gp, bm, bp, bc,
                     _interpret())
    return y[:m].reshape(a.shape[:-n_cont] + kept_shape), None


def _dispatch_pack_contract(a, leaf, gp: int):
    q4, s4 = leaf.q4, leaf.s4
    cp = q4.shape[-1]
    n_dim = q4.size // cp
    x2 = a.reshape(-1, 2 * cp)
    plan, reason = _plan_pack_contract(x2.shape[0], cp, n_dim, gp)
    if plan is None:
        return None, reason
    bm, bn = plan
    m = x2.shape[0]
    x_even = _pad_to(x2[:, 0::2], bm)
    x_odd = _pad_to(x2[:, 1::2], bm)
    y = _mm_pack_contract(x_even, x_odd, q4.reshape(n_dim, cp),
                          s4.reshape(n_dim, cp // gp), gp, bm, bn,
                          _interpret())
    return y[:m].reshape(a.shape[:-1] + q4.shape[:-1]), None


# --- shard-aware dispatch (multi-device meshes) ---


def einsum_int4_spmd(mesh, spec: str, a: jax.Array, leaf, tp=None):
    """The fused kernels under a multi-device mesh: per-shard
    single-device dispatch inside shard_map (compat shim), partitioned
    the way sharding.param_specs already shards the weight.

    `tp` is the call site's TP convention hint ("col" / "row" — see
    sharding.int4_shard_axis); it picks WHICH weight axis carries the
    model shards so the shard_map in_specs match the weights' resident
    placement (a mismatched spec would regather the weight every
    dispatch — the one thing a weight-streaming-bound decode cannot
    afford). Returns (result, None) or (None, fallback_reason):

    - the plan is validated against the PER-SHARD shapes before the
      shard_map is entered, so the body's dispatch never declines (and
      no shape can reach a Mosaic VMEM failure on chip);
    - a weight axis the mesh does not divide is served replicated —
      matching sharding._fallback_replicated, which replicated exactly
      those weights at placement time;
    - row-parallel shards contract locally and psum over "model",
      exactly the all-reduce the XLA path's sharded einsum inserts;
    - the manual axis set comes from compat.mesh_manual_axes, so the
      same call nests correctly inside the PP engine's manual-"pipe"
      stage bodies (model stays the only axis this wrapper manualizes
      there)."""
    from jax.sharding import PartitionSpec as P

    from ..compat import mesh_manual_axes, shard_map
    from ..sharding import MODEL_AXIS, int4_shard_axis, model_axis_size

    cls, reason = _classify(spec, leaf)
    if cls is None:
        return None, reason
    mode, n_cont, gp = cls
    q4, s4 = leaf.q4, leaf.s4
    m_shards = model_axis_size(mesh)
    manual = mesh_manual_axes(mesh)
    if m_shards > 1 and MODEL_AXIS not in manual:
        return None, "mesh:model-axis-not-auto"

    w_ax, needs_psum = int4_shard_axis(tp, q4.ndim, n_cont, mode)
    if m_shards <= 1:
        w_ax, needs_psum = None, False
    if w_ax is not None and (q4.shape[w_ax] % m_shards
                             or s4.shape[w_ax] % m_shards):
        # Mirrors _fallback_replicated: a dim the mesh doesn't divide
        # was REPLICATED at placement, so replicated in_specs match.
        w_ax, needs_psum = None, False

    div = m_shards if w_ax is not None else 1
    if mode == "out":
        c_dim = 1
        for s in q4.shape[:n_cont]:
            c_dim *= s
        p_dim = q4.size // c_dim
        m_rows = a.size // c_dim
        c_local = c_dim // (div if (w_ax is not None and w_ax < n_cont)
                            else 1)
        p_local = p_dim // (div if (w_ax is not None and w_ax >= n_cont)
                            else 1)
        plan, reason = _plan_pack_out(m_rows, c_local, p_local, gp)
    else:
        cp = q4.shape[-1]
        n_dim = q4.size // cp
        m_rows = a.size // (2 * cp)
        plan, reason = _plan_pack_contract(m_rows, cp, n_dim // div, gp)
    if plan is None:
        return None, (reason if w_ax is None else reason + "/sharded")

    def ax_spec(ndim: int, ax: Optional[int]) -> P:
        return P(*[MODEL_AXIS if i == ax else None for i in range(ndim)])

    w_spec = ax_spec(q4.ndim, w_ax)
    s_spec = ax_spec(s4.ndim, w_ax)
    if mode == "out":
        out_ndim = (a.ndim - n_cont) + (q4.ndim - n_cont)
        a_ax = (a.ndim - n_cont + w_ax) \
            if (w_ax is not None and w_ax < n_cont) else None
        out_ax = ((a.ndim - n_cont) + (w_ax - n_cont)) \
            if (w_ax is not None and w_ax >= n_cont) else None
    else:
        out_ndim = a.ndim
        a_ax = None
        out_ax = (a.ndim - 1) if w_ax is not None else None
    a_spec = ax_spec(a.ndim, a_ax)
    out_spec = ax_spec(out_ndim, out_ax)

    from ..models.common import Int4Leaf

    def body(al, q4l, s4l):
        leaf_l = Int4Leaf(q4=q4l, s4=s4l, axis=leaf.axis,
                          group=leaf.group)
        if mode == "out":
            y, why = _dispatch_pack_out(al, leaf_l, n_cont, gp)
        else:
            y, why = _dispatch_pack_contract(al, leaf_l, gp)
        if y is None:   # unreachable: plan checked on these exact shapes
            raise AssertionError(f"sharded int4 dispatch declined: {why}")
        if needs_psum:
            y = jax.lax.psum(y, MODEL_AXIS)
        return y

    fn = shard_map(body, mesh=mesh, in_specs=(a_spec, w_spec, s_spec),
                   out_specs=out_spec, axis_names=manual, check_vma=False)
    return fn(a, q4, s4), None
