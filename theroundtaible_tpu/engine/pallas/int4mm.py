"""Pallas TPU fused w4a16 matmul: dequantize int4 weights in VMEM, inside
the matmul, so HBM streams the PACKED bytes.

Why a kernel at all: the XLA path (models/common.py `_einsum` →
`dequant_int4`) expresses dequant as bitcast → convert → grouped-scale
multiply → reshape and hopes XLA fuses that chain into the dot's operand
read. On real TPU it does not: BENCH_r05 hardware runs measured int4
decode at 22.9 tok/s (interleave layout) then 31.6 tok/s (bitcast
layout) against bf16's 130 and int8's 205 — the dequantized bf16 weight
was materialized (and copied) in HBM every token, so int4 streamed MORE
bytes than bf16. int8 escapes because its dequant is a plain
convert (fusable operand) plus an OUTPUT-side scale; int4's grouped
scale multiplies the weight on the CONTRACTED side of the dot and XLA
TPU will not fold a multiply-by-different-shaped-operand into a dot
input. (Reference compute equivalent: llama.cpp's q4 kernels, reached
through src/adapters/local-llm.ts — its default serving precision —
dequantize in registers for exactly this reason.)

These kernels make the fusion structural instead of heuristic. The pack
layout (engine/quant.py: two signed nibbles per byte along the weight's
LAST axis, even element in the low nibble, per-`group` scales) was
chosen so NO shuffle is ever needed in-kernel:

- `_mm_pack_out` — every per-layer matmul (qkv/o/gate/up/down: the
  packed last axis is a NON-contracted output axis). Byte k of a row
  holds output columns 2k (low nibble) and 2k+1 (high), and both share
  scale group k // (g/2). The kernel extracts nibbles with two
  arithmetic shifts, applies the group scale, and runs TWO dots — one
  producing even output columns, one odd — accumulating over contraction
  blocks in VMEM scratch. The only reorder is interleaving the two
  [bm, bp] OUTPUT accumulators at the end: 2·bm·bp elements once per
  output block, vs. the E·F weight interleave the XLA path choked on.
- `_mm_pack_contract` — the tied-embedding lm head ([V, E] packed along
  E, which the head matmul CONTRACTS). Splitting the ACTIVATION into
  even/odd columns (x[:, 0::2], x[:, 1::2] — a [M, E] strided slice,
  done once outside the kernel) turns the matmul into
  dot(x_even, low^T) + dot(x_odd, high^T): no weight interleave, no
  output interleave, scale group k // (g/2) again shared.

`einsum_int4` is the dispatch seam `_einsum` calls: it classifies the
einsum spec (contracted axes a prefix of the weight → pack-on-output;
suffix → pack-on-contraction), flattens to 2-D, pads M to sublane
multiples, and returns None whenever blocking/grouping cannot be
arranged — the caller then falls back to the XLA dequant path, so MoE
expert matmuls ("bte,xef->btxf") and tiny routers serve unchanged.
These are DECODE kernels: M is capped at 64 rows (decode and the
post-last_pos-gather lm head are always ≤ batch), because the grid
iterates p innermost so grouped scales stream once per contraction
block — which makes the f32 output block round-trip per contraction
block, negligible at decode M and ruinous at prefill M. Prefill int4
keeps the XLA path, where the materialized dequant amortizes over T.

Single-device only by design: these run inside jit-under-GSPMD, where a
pallas_call is an opaque unpartitionable custom call. The engine gates
on mesh size (models/common.py `_einsum`); multi-chip int4 keeps the
XLA path. On non-TPU backends the kernels run in Pallas interpret mode
when forced via ROUNDTABLE_INT4_MM=1 — how the CPU suite validates them
(tests/test_int4mm.py).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def enabled() -> bool:
    """Kernel path on by default on real TPU; ROUNDTABLE_INT4_MM=1
    forces it elsewhere (interpret mode — the test path), =0 disables
    everywhere (the A/B lever for microbenches)."""
    v = os.environ.get("ROUNDTABLE_INT4_MM", "")
    if v == "0":
        return False
    if v == "1":
        return True
    return jax.default_backend() == "tpu"


def _pick_block(n: int, candidates: tuple[int, ...],
                multiple_of: int = 1) -> Optional[int]:
    for c in candidates:
        if n % c == 0 and c % multiple_of == 0:
            return c
    return None


def _nibbles(q_ref, dtype):
    """int8 packed byte block → (low, high) int4 values in `dtype`.
    Arithmetic shifts in int32 sign-extend both nibbles; no shuffle."""
    q = q_ref[...].astype(jnp.int32)
    low = ((q << 28) >> 28).astype(dtype)
    high = (q >> 4).astype(dtype)
    return low, high


def _mm_out_kernel(x_ref, q_ref, s_ref, o_ref, acc_lo, acc_hi, *,
                   gp: int, bg: int, bp: int, n_c: int):
    # Grid is (m, c, p) with p INNERMOST: the whole-axis scale block's
    # index (c, 0) is then constant across each p sweep, so Pallas
    # elides its DMA and scales stream once per contraction block —
    # with p outside c they re-streamed every step, ~doubling HBM
    # traffic on the up/gate shape. The price: accumulators span the
    # FULL output axis (scratch [bm, P] per nibble, ≤ 8 MB at the
    # largest bm·P), and each (c==last, p) step flushes its slice.
    c, j = pl.program_id(1), pl.program_id(2)
    x = x_ref[...]
    low, high = _nibbles(q_ref, x.dtype)
    # s_ref carries the FULL scale axis for this contraction block
    # (Mosaic wants lane-aligned or whole-axis block minors; the per-p
    # slab bg = bp/gp is narrower than a lane) — slice it here.
    s = s_ref[:, pl.ds(j * bg, bg)]
    srep = jnp.repeat(s, gp, axis=1)               # [bc, bp]
    dims = (((1,), (0,)), ((), ()))
    lo = jax.lax.dot_general(x, low * srep, dims,
                             preferred_element_type=jnp.float32)
    hi = jax.lax.dot_general(x, high * srep, dims,
                             preferred_element_type=jnp.float32)
    sl = pl.ds(j * bp, bp)

    @pl.when(c == 0)
    def _set():
        acc_lo[:, sl] = lo
        acc_hi[:, sl] = hi

    @pl.when(c > 0)
    def _add():
        acc_lo[:, sl] += lo
        acc_hi[:, sl] += hi

    @pl.when(c == n_c - 1)
    def _done():
        a_lo, a_hi = acc_lo[:, sl], acc_hi[:, sl]
        bm = a_lo.shape[0]
        # interleave OUTPUT columns: even ← low nibble, odd ← high
        o_ref[...] = jnp.stack([a_lo, a_hi], axis=-1).reshape(bm, 2 * bp)


@functools.partial(jax.jit,
                   static_argnames=("gp", "bm", "bp", "bc", "interpret"))
def _mm_pack_out(x, q4, s4, gp: int, bm: int, bp: int, bc: int,
                 interpret: bool):
    """x [M, C] · unpack(q4 [C, P], s4 [C, P//gp]) → [M, 2P] f32."""
    m, c_dim = x.shape
    _, p_dim = q4.shape
    grid = (m // bm, c_dim // bc, p_dim // bp)
    kernel = functools.partial(_mm_out_kernel, gp=gp, bg=bp // gp,
                               bp=bp, n_c=grid[1])
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bc), lambda i, k, j: (i, k)),
            pl.BlockSpec((bc, bp), lambda i, k, j: (k, j)),
            pl.BlockSpec((bc, p_dim // gp), lambda i, k, j: (k, 0)),
        ],
        out_specs=pl.BlockSpec((bm, 2 * bp), lambda i, k, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, 2 * p_dim), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((bm, p_dim), jnp.float32),
            pltpu.VMEM((bm, p_dim), jnp.float32),
        ],
        interpret=interpret,
    )(x, q4, s4)


def _mm_contract_kernel(xe_ref, xo_ref, q_ref, s_ref, o_ref, *, gp: int):
    xe, xo = xe_ref[...], xo_ref[...]
    low, high = _nibbles(q_ref, xe.dtype)
    srep = jnp.repeat(s_ref[...], gp, axis=1)      # [bn, Cp]
    dims = (((1,), (1,)), ((), ()))                # contract minor×minor
    o_ref[...] = (
        jax.lax.dot_general(xe, low * srep, dims,
                            preferred_element_type=jnp.float32)
        + jax.lax.dot_general(xo, high * srep, dims,
                              preferred_element_type=jnp.float32))


@functools.partial(jax.jit,
                   static_argnames=("gp", "bm", "bn", "interpret"))
def _mm_pack_contract(x_even, x_odd, q4, s4, gp: int, bm: int, bn: int,
                      interpret: bool):
    """x_even/x_odd [M, Cp] · unpack(q4 [N, Cp], s4 [N, Cp//gp])ᵀ
    → [M, N] f32. Contraction fits one block (lm-head E is small)."""
    m, cp = x_even.shape
    n_dim = q4.shape[0]
    kernel = functools.partial(_mm_contract_kernel, gp=gp)
    return pl.pallas_call(
        kernel,
        grid=(m // bm, n_dim // bn),
        in_specs=[
            pl.BlockSpec((bm, cp), lambda i, j: (i, 0)),
            pl.BlockSpec((bm, cp), lambda i, j: (i, 0)),
            pl.BlockSpec((bn, cp), lambda i, j: (j, 0)),
            pl.BlockSpec((bn, cp // gp), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n_dim), jnp.float32),
        interpret=interpret,
    )(x_even, x_odd, q4, s4)


def _pad_rows(x2: jax.Array) -> tuple[jax.Array, int, Optional[int]]:
    """Pad M to a sublane multiple; returns (padded, M, block_m).

    block_m is None above 64 rows: the kernels are DECODE kernels
    (weight-streaming-bound GEMVs, where fused dequant is the whole
    win). Prefill's big-M matmuls keep the XLA path — there the
    materialized dequant amortizes over T, while this kernel's
    write-at-last output revisiting would round-trip the [M, 2P] f32
    output once per contraction block."""
    m = x2.shape[0]
    mp = max(8, -(-m // 8) * 8)
    if mp > 64:
        return x2, m, None
    if mp != m:
        x2 = jnp.pad(x2, ((0, mp - m), (0, 0)))
    return x2, m, mp


def einsum_int4(spec: str, a: jax.Array, leaf) -> Optional[jax.Array]:
    """Run `jnp.einsum(spec, a, dequant(leaf))` through the fused
    kernels when the spec/shape/grouping allow; None → caller falls
    back to the XLA dequant path. Result is f32 (matches the XLA path's
    preferred_element_type)."""
    lhs, out_dims = spec.split("->")
    a_dims, b_dims = lhs.split(",")
    cont = [d for d in b_dims if d in a_dims]
    kept = [d for d in b_dims if d not in a_dims]
    if not cont or not kept:
        return None
    if a_dims[-len(cont):] != "".join(cont):
        return None
    batch = a_dims[:-len(cont)]
    if out_dims != batch + "".join(kept):
        return None
    if leaf.axis != leaf.q4.ndim - 1:
        return None    # non-minor pack: fall back (XLA path asserts loudly)
    group = leaf.group
    if group % 2:
        return None
    gp = group // 2

    if list(b_dims) == cont + kept:
        return _dispatch_pack_out(a, leaf, len(cont), gp)
    if list(b_dims) == kept + cont and len(cont) == 1:
        return _dispatch_pack_contract(a, leaf, gp)
    return None


# Mirror of attention._VMEM_BUDGET: a conservative per-core VMEM cap the
# kernel's resident working set must fit, else dispatch declines and the
# XLA dequant path serves. Advisor r5: _mm_pack_out's accumulators span
# the FULL output axis (scratch 2·[bm, P] f32 — the price of the
# p-innermost grid that streams scales once), so a large-enough mlp_dim
# overflowed Mosaic's scratch allocation ON CHIP instead of falling back.
_VMEM_BUDGET = 12 * 1024 * 1024


def _pack_out_vmem_est(bm: int, bp: int, bc: int, p_dim: int,
                       gp: int) -> int:
    scratch = 2 * bm * p_dim * 4          # f32 accumulators span full P
    x_blk = 2 * bm * bc * 4               # double-buffered, ≤ f32
    q_blk = 2 * bc * bp                   # packed int4 bytes
    s_blk = 2 * bc * (p_dim // gp) * 4    # whole-axis scale block
    out_blk = bm * 2 * bp * 4             # f32 output block
    return scratch + x_blk + q_blk + s_blk + out_blk


def _dispatch_pack_out(a, leaf, n_cont: int, gp: int):
    q4, s4 = leaf.q4, leaf.s4
    cont_shape = q4.shape[:n_cont]
    c_dim = 1
    for s in cont_shape:
        c_dim *= s
    p_dim = q4.size // c_dim
    kept_shape = q4.shape[n_cont:-1] + (q4.shape[-1] * 2,)
    bp = _pick_block(p_dim, (512, 256, 128), multiple_of=gp)
    bc = _pick_block(c_dim, (512, 1024, 256, 128))
    if bp is None or bc is None:
        return None
    x2 = a.reshape(-1, c_dim)
    x2, m, bm = _pad_rows(x2)
    if bm is None:
        return None
    if _pack_out_vmem_est(bm, bp, bc, p_dim, gp) > _VMEM_BUDGET:
        return None
    y = _mm_pack_out(x2, q4.reshape(c_dim, p_dim),
                     s4.reshape(c_dim, p_dim // gp), gp, bm, bp, bc,
                     _interpret())
    return y[:m].reshape(a.shape[:-n_cont] + kept_shape)


def _dispatch_pack_contract(a, leaf, gp: int):
    q4, s4 = leaf.q4, leaf.s4
    cp = q4.shape[-1]
    if cp > 4096 or cp % 128:
        return None
    n_dim = q4.size // cp
    if cp % gp:
        return None
    bn = _pick_block(n_dim, (512, 256, 128))
    if bn is None:
        return None
    x2 = a.reshape(-1, 2 * cp)
    x_even, x_odd = x2[:, 0::2], x2[:, 1::2]
    x_even, m, bm = _pad_rows(x_even)
    x_odd = _pad_rows(x_odd)[0]
    if bm is None:
        return None
    y = _mm_pack_contract(x_even, x_odd, q4.reshape(n_dim, cp),
                          s4.reshape(n_dim, cp // gp), gp, bm, bn,
                          _interpret())
    return y[:m].reshape(a.shape[:-1] + q4.shape[:-1])
