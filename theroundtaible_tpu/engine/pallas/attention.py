"""Pallas TPU attention kernels for the serving hot path.

Replaces the dense softmax(QK^T)V in models/common.py for the two op shapes
that dominate serving (SURVEY.md §7.3 hard part 1 — ragged per-knight KV
slots; reference compute equivalent: llama.cpp attention reached through
src/adapters/local-llm.ts):

- flash_prefill_attention: blockwise online-softmax attention for prefill
  chunks against a position-aligned KV cache. The dense path materializes
  [B, H, T, S] logits against the FULL cache every chunk; this kernel
  streams KV blocks through VMEM and — via scalar-prefetched per-row valid
  lengths — never fetches blocks beyond a row's causal/valid frontier.
- ragged_decode_attention: single-position decode attention over the padded
  cache. Rows with valid=600 in an S=8192 cache read 600 tokens of KV, not
  8192: the kv-block index map clamps to the row's frontier, and Pallas
  elides the DMA when consecutive grid steps map to the same block.
- paged_decode_attention: the same ragged decode DIRECTLY against the page
  POOL [P, page_size, K, D] (engine/paging.py): the kv-block index map
  reads the scalar-prefetched page TABLE, so decode never materializes the
  position-aligned [B, S, K, D] gather view — during decode the paged
  layout keeps its whole resident-memory advantage (the gather view
  temporarily recreated the full contiguous budget) and reads only the
  pages below each row's frontier.

Both kernels handle GQA natively (kv head = q head // group) so the
[B, S, K, D] cache is never repeated to [B, S, H, D] in HBM, and support
Mistral's sliding window and Gemma-2-style logit softcap.

On non-TPU backends the kernels run in Pallas interpret mode — this is how
the CPU test suite validates them against the dense reference path.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..models.common import MASK_VALUE as NEG_INF

_LANES = 128  # TPU lane width; m/l scratch is replicated across lanes


def _dequant_kv(x, s, kv_bits: int, dtype):
    """In-kernel dequant of one KV block (ISSUE 11): payload [bkv, Dp]
    int8 + per-cell scales [bkv, G] f32 -> values [bkv, D] in `dtype`.
    int4 payloads unpack through kv_quant.unpack_int4 (the ONE copy of
    the nibble-order contract — shift arithmetic only, which Mosaic
    lowers; probed chipless); the grouped scale multiply is a
    minor-axis reshape, also Mosaic-legal. This is the kernel-side
    twin of kv_quant.dequantize_cells — same unpack, same scale
    math, so the kernel and XLA fallback cannot drift."""
    if kv_bits == 4:
        from ..kv_quant import unpack_int4
        x = unpack_int4(x)
    bkv, d = x.shape
    n_groups = s.shape[-1]
    xg = x.astype(jnp.float32).reshape(bkv, n_groups, d // n_groups)
    return (xg * s[..., None].astype(jnp.float32)) \
        .reshape(bkv, d).astype(dtype)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pick_block(n: int, candidates: tuple[int, ...]) -> Optional[int]:
    for c in candidates:
        if n % c == 0:
            return c
    return None


def spmd_partitionable(num_heads: int, num_kv_heads: int,
                       n_model: int) -> bool:
    """Can flash_attention_spmd partition this head layout over an n_model-
    way model axis? Single source of truth shared with the engine's
    _resolve_attn so config-time choice and kernel-time dispatch can't
    drift. True when q heads divide AND (kv heads divide, or MQA's single
    kv head replicates)."""
    if num_heads % n_model:
        return False
    return num_kv_heads % n_model == 0 or num_kv_heads == 1


def supported(t: int, s: int, d: int) -> bool:
    """Can the kernels serve these shapes? (TPU wants lane-aligned D; any
    shape goes in interpret mode.)"""
    if _pick_block(s, (512, 256, 128, 64, 32, 16, 8)) is None:
        return False
    if t > 1 and _pick_block(t, (128, 64, 32, 16, 8)) is None:
        return False
    if not _interpret() and d % 128 != 0:
        return False
    return True


# --- prefill kernel ---


def _prefill_accumulate(q, k, v, q_start, kv_start, valid, state, *,
                        group: int, block_q: int, block_kv: int,
                        sliding_window: Optional[int],
                        softcap: Optional[float],
                        k_scale=None, v_scale=None, kv_bits: int = 8):
    """One online-softmax accumulation of a q block [G*bq, D] against one
    kv block [bkv, D] whose first entry holds absolute position kv_start.
    Shared by the contiguous (_prefill_kernel) and paged
    (_paged_prefill_kernel) prefill kernels — the two differ ONLY in how
    the kv block is addressed, so the math lives here once. Pure
    value-in/value-out over `state` = (m, l, acc) so callers can keep
    per-kv-head running state in scratch slices (the paged kernels loop
    heads in-kernel; a ref-mutating helper would pin the scratch
    layout).

    `k_scale`/`v_scale` [bkv, G] (ISSUE 11): the kv block arrived as a
    quantized page — dequantize in-kernel before the dots, so the bytes
    streamed from HBM are the int8/int4 payload + scales and the math
    past this line is IDENTICAL to the bf16 path (the numeric core of
    the quantized-parity discipline)."""
    if k_scale is not None:
        k = _dequant_kv(k, k_scale, kv_bits, q.dtype)
        v = _dequant_kv(v, v_scale, kv_bits, q.dtype)
    m_prev, l_prev, acc_prev = state
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)            # [G*bq, bkv]
    if softcap is not None:
        s = softcap * jnp.tanh(s / softcap)

    # positions only depend on the q row WITHIN the block, identical
    # across the group; build [bq, bkv] then tile over the group rows
    q_pos = q_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_kv), 0)
    kv_pos = kv_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_kv), 1)
    mask = (kv_pos <= q_pos) & (kv_pos < valid)
    if sliding_window is not None:
        mask &= kv_pos > q_pos - sliding_window
    mask = jnp.broadcast_to(mask[None], (group, block_q, block_kv)) \
        .reshape(group * block_q, block_kv)
    s = jnp.where(mask, s, NEG_INF)

    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new[:, :1])
    l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)            # [G*bq, D]
    return m_new, l_new, acc_prev * alpha[:, :1] + pv


def _prefill_blk_bounds(q_start, valid, block_q: int, block_kv: int,
                        sliding_window: Optional[int]):
    """(lo, hi) kv-block bounds for one q block — shared by the kernels
    and their index maps so the skip logic cannot drift."""
    hi = jnp.minimum((q_start + block_q - 1) // block_kv,
                     (valid - 1) // block_kv)
    if sliding_window is None:
        lo = jnp.int32(0)
    else:
        lo = jnp.maximum(0, (q_start - sliding_window + 1) // block_kv)
    return lo, hi


def _prefill_kernel(offs_ref, valid_ref, q_ref, k_ref, v_ref, o_ref,
                    m_scr, l_scr, acc_scr, *, block_q: int, block_kv: int,
                    num_kv_blocks: int, group: int,
                    sliding_window: Optional[int],
                    softcap: Optional[float]):
    # Grid (B, KV_heads, T_blocks, S_blocks): one step computes a whole GQA
    # group (all `group` query heads sharing one kv head) against one kv
    # block, so each kv block is DMA'd exactly once per (row, kv head) and
    # the output block flushes once per (row, kv head, q block) — s-block
    # steps keep the same output index, and the index maps clamp skipped
    # steps to the frontier so they fetch nothing new.
    b = pl.program_id(0)
    tb = pl.program_id(2)
    sb = pl.program_id(3)

    @pl.when(sb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    offs = offs_ref[b]
    valid = valid_ref[b]
    q_start = offs + tb * block_q
    lo, hi = _prefill_blk_bounds(q_start, valid, block_q, block_kv,
                                 sliding_window)

    @pl.when((sb >= lo) & (sb <= hi))
    def _compute():
        m_scr[:], l_scr[:], acc_scr[:] = _prefill_accumulate(
            q_ref[0, 0].reshape(group * block_q, -1), k_ref[0, 0],
            v_ref[0, 0], q_start, sb * block_kv, valid,
            (m_scr[:], l_scr[:], acc_scr[:]), group=group,
            block_q=block_q, block_kv=block_kv,
            sliding_window=sliding_window, softcap=softcap)

    @pl.when(sb == num_kv_blocks - 1)
    def _finish():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        d = o_ref.shape[-1]
        o_ref[0, 0] = (acc_scr[:] / l).astype(o_ref.dtype) \
            .reshape(group, block_q, d)


def flash_prefill_attention(
    q: jax.Array,                 # [B, T, H, D] (pre-scaled, rope'd)
    k: jax.Array,                 # [B, S, K, D] position-aligned cache
    v: jax.Array,                 # [B, S, K, D]
    offsets: jax.Array,           # [B] absolute position of q row start
    kv_valid: jax.Array,          # [B] valid cache entries per row
    *,
    sliding_window: Optional[int] = None,
    softcap: Optional[float] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Blockwise causal attention of a prefill chunk against the cache.

    Rows are assumed position-contiguous (position of q[:, i] is
    offsets[b] + i) — true for every chunked-prefill call in the engine.
    Returns [B, T, H, D] in q's dtype.
    """
    b, t, h, d = q.shape
    s, kh = k.shape[1], k.shape[2]
    group = h // kh
    block_q = _pick_block(t, (128, 64, 32, 16, 8))
    block_kv = _pick_block(s, (512, 256, 128, 64, 32, 16, 8))
    if block_q is None or block_kv is None:
        raise ValueError(f"unsupported shapes T={t} S={s}")
    interpret = _interpret() if interpret is None else interpret

    # [B, T, H, D] → [B, K, G, T, D]: q heads grouped by their kv head
    # (head kh*G+g shares kv head kh, matching the dense path's repeat)
    qt = q.transpose(0, 2, 1, 3).reshape(b, kh, group, t, d)
    kt = k.transpose(0, 2, 1, 3)        # [B, K, S, D]
    vt = v.transpose(0, 2, 1, 3)
    num_kv_blocks = s // block_kv

    def kv_index(bi, khi, tb, sb, offs_ref, valid_ref):
        q_start = offs_ref[bi] + tb * block_q
        lo_blk, hi_blk = _prefill_blk_bounds(
            q_start, valid_ref[bi], block_q, block_kv, sliding_window)
        sb = jnp.clip(sb, lo_blk, jnp.maximum(hi_blk, 0))
        return (bi, khi, sb, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, kh, t // block_q, num_kv_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, group, block_q, d),
                         lambda bi, khi, tb, sb, o_, v_:
                         (bi, khi, 0, tb, 0)),
            pl.BlockSpec((1, 1, block_kv, d), kv_index),
            pl.BlockSpec((1, 1, block_kv, d), kv_index),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, group, block_q, d),
            lambda bi, khi, tb, sb, o_, v_: (bi, khi, 0, tb, 0)),
        scratch_shapes=[
            pltpu.VMEM((group * block_q, _LANES), jnp.float32),
            pltpu.VMEM((group * block_q, _LANES), jnp.float32),
            pltpu.VMEM((group * block_q, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _prefill_kernel, block_q=block_q, block_kv=block_kv,
        num_kv_blocks=num_kv_blocks, group=group,
        sliding_window=sliding_window, softcap=softcap)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        interpret=interpret,
    )(offsets.astype(jnp.int32), kv_valid.astype(jnp.int32), qt, kt, vt)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _paged_prefill_kernel(table_ref, offs_ref, valid_ref, q_ref, k_ref,
                          v_ref, *rest,
                          block_q: int, page_size: int,
                          num_page_blocks: int, kh: int, group: int,
                          sliding_window: Optional[int],
                          softcap: Optional[float],
                          kv_bits: int = 8, quantized: bool = False):
    # Identical math to _prefill_kernel (shared _prefill_accumulate); the
    # paged differences: the kv block for grid step sb is pool page
    # table[b, sb], and ALL kv heads ride one (1, ps, K, D) block with a
    # static in-kernel head loop — per-head pool blocks are
    # Mosaic-illegal for K > 1 (see _paged_decode_kernel). Quantized
    # pools (ISSUE 11) ride two extra per-page scale blocks whose index
    # map is the kv block's, dequantized inside _prefill_accumulate.
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    tb = pl.program_id(1)
    sb = pl.program_id(2)

    @pl.when(sb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    offs = offs_ref[b]
    valid = valid_ref[b]
    q_start = offs + tb * block_q
    lo, hi = _prefill_blk_bounds(q_start, valid, block_q, page_size,
                                 sliding_window)

    @pl.when((sb >= lo) & (sb <= hi))
    def _compute():
        for khi in range(kh):
            m_scr[khi], l_scr[khi], acc_scr[khi] = _prefill_accumulate(
                q_ref[0, khi].reshape(group * block_q, -1),
                k_ref[0, :, khi, :], v_ref[0, :, khi, :], q_start,
                sb * page_size, valid,
                (m_scr[khi], l_scr[khi], acc_scr[khi]), group=group,
                block_q=block_q, block_kv=page_size,
                sliding_window=sliding_window, softcap=softcap,
                k_scale=(ks_ref[0, :, khi, :] if quantized else None),
                v_scale=(vs_ref[0, :, khi, :] if quantized else None),
                kv_bits=kv_bits)

    @pl.when(sb == num_page_blocks - 1)
    def _finish():
        d = o_ref.shape[-1]
        for khi in range(kh):
            l = jnp.maximum(l_scr[khi, :, :1], 1e-30)
            o_ref[0, khi] = (acc_scr[khi] / l).astype(o_ref.dtype) \
                .reshape(group, block_q, d)


def paged_prefill_supported(t: int, page_size: int, d: int,
                            kh: int = 1, group: int = 1) -> bool:
    """Can paged_prefill_attention serve this chunk/pool shape? kh/group
    as in paged_decode_supported — block_q shrinks until the kh-scaled
    working set fits VMEM, declining only when even block_q=8 doesn't."""
    if _paged_prefill_block_q(t, page_size, d, kh, group) is None:
        return False
    return paged_decode_supported(page_size, d, kh, group)


def paged_pool_direct_supported(chunk: int, page_size: int, d: int,
                                kh_local: int, group: int) -> bool:
    """The ONE build-time gate for pool-direct paged serving, shared by
    both engines (engine.py / pp_serving.py — the two copies drifted
    once, gating only on decode support): pool-direct runs prefill
    chunks AND decode steps off the pool, so BOTH kernels must accept
    the shape. A layout only the decode kernel fits would otherwise
    raise mid-request in the prefill wrapper instead of serving the
    gather view (ISSUE 1: degrade, don't crash). `chunk` is the largest
    serving bucket — the block_q search shrinks from there, so smaller
    buckets only relax the estimate. Pass the LOCAL kv-head count.

    paged_prefill_supported's last clause IS the decode gate, so one
    delegation covers both kernels without duplicating the conjunction
    here (the duplicate is how the engines drifted last time)."""
    return paged_prefill_supported(chunk, page_size, d, kh_local, group)


def paged_prefill_attention(
    q: jax.Array,                 # [B, T, H, D] (pre-scaled, rope'd)
    k_pool: jax.Array,            # [P, page_size, K, D] page pool
    v_pool: jax.Array,            # [P, page_size, K, D]
    table: jax.Array,             # [B, pages_per_seq] int32 page table
    offsets: jax.Array,           # [B] absolute position of q row start
    kv_valid: jax.Array,          # [B] valid cache entries per row
    *,
    sliding_window: Optional[int] = None,
    softcap: Optional[float] = None,
    interpret: Optional[bool] = None,
    k_scale: Optional[jax.Array] = None,   # [P, ps, K, G] (ISSUE 11)
    v_scale: Optional[jax.Array] = None,
    kv_bits: int = 8,
) -> jax.Array:
    """Blockwise causal prefill attention straight off the page pool.

    The caller must have scattered this chunk's K/V into the rows'
    pages already (engine/paged_forward.py); pages below a row's offset
    may be ALIASED donor pages — the kernel only reads. The kv block
    index map reads the page table, so only pages inside each q block's
    causal/window frontier are DMA'd and the [B, S, K, D] gather view is
    never built. Returns [B, T, H, D] in q's dtype.

    `k_scale`/`v_scale` (ISSUE 11): the pool holds quantized pages —
    int8 payload (int4: D/2 packed nibbles when kv_bits=4) with
    per-cell scales; the scale blocks ride the SAME page index map as
    the kv blocks and dequant happens in-kernel."""
    b, t, h, d = q.shape
    page_size, kh = k_pool.shape[1], k_pool.shape[2]
    group = h // kh
    pages_per_seq = table.shape[1]
    quantized = k_scale is not None
    block_q = _paged_prefill_block_q(t, page_size, d, kh, group)
    if block_q is None or not paged_decode_supported(page_size, d, kh,
                                                     group):
        raise ValueError(f"unsupported shapes T={t} ps={page_size} D={d}")
    interpret = _interpret() if interpret is None else interpret

    qt = q.transpose(0, 2, 1, 3).reshape(b, kh, group, t, d)

    def kv_index(bi, tb, sb, table_ref, offs_ref, valid_ref):
        q_start = offs_ref[bi] + tb * block_q
        lo_blk, hi_blk = _prefill_blk_bounds(
            q_start, valid_ref[bi], block_q, page_size, sliding_window)
        sb = jnp.clip(sb, lo_blk, jnp.maximum(hi_blk, 0))
        return (table_ref[bi, sb], 0, 0, 0)

    in_specs = [
        pl.BlockSpec((1, kh, group, block_q, d),
                     lambda bi, tb, sb, t_, o_, v_:
                     (bi, 0, 0, tb, 0)),
        pl.BlockSpec((1, page_size, kh, k_pool.shape[-1]), kv_index),
        pl.BlockSpec((1, page_size, kh, v_pool.shape[-1]), kv_index),
    ]
    operands = [qt, k_pool, v_pool]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, page_size, kh, k_scale.shape[-1]), kv_index),
            pl.BlockSpec((1, page_size, kh, v_scale.shape[-1]), kv_index),
        ]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, t // block_q, pages_per_seq),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, kh, group, block_q, d),
            lambda bi, tb, sb, t_, o_, v_: (bi, 0, 0, tb, 0)),
        scratch_shapes=[
            pltpu.VMEM((kh, group * block_q, _LANES), jnp.float32),
            pltpu.VMEM((kh, group * block_q, _LANES), jnp.float32),
            pltpu.VMEM((kh, group * block_q, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _paged_prefill_kernel, block_q=block_q, page_size=page_size,
        num_page_blocks=pages_per_seq, kh=kh, group=group,
        sliding_window=sliding_window, softcap=softcap,
        kv_bits=kv_bits, quantized=quantized)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        interpret=interpret,
    )(table.astype(jnp.int32), offsets.astype(jnp.int32),
      kv_valid.astype(jnp.int32), *operands)
    return out.reshape(b, kh * group, t, d).transpose(0, 2, 1, 3)


def paged_prefill_spmd(
    mesh,
    q: jax.Array, k_pool: jax.Array, v_pool: jax.Array,
    table: jax.Array, offsets: jax.Array, kv_valid: jax.Array,
    *,
    sliding_window: Optional[int] = None,
    softcap: Optional[float] = None,
    interpret: Optional[bool] = None,
    pool_replicas: int = 1,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    kv_bits: int = 8,
) -> Optional[jax.Array]:
    """paged_prefill_attention under a (data, model) mesh — the same
    partitioning as paged_decode_spmd (kv heads on "model" matching the
    pool's sharding; table/offsets/valid row-aligned with the batch;
    pool_replicas > 1 shards the page axis over "data" and rebases each
    shard's table to its local range — see paged_decode_spmd). Scale
    pools (ISSUE 11) partition exactly like the kv pools — same page
    and kv-head axes."""
    from ..compat import shard_map
    from jax.sharding import PartitionSpec as P

    b, t, h, d = q.shape
    page_size, kh = k_pool.shape[1], k_pool.shape[2]
    axes_t = _spmd_axes(mesh, h, kh, b)
    if axes_t is None:
        return None
    batch_ax, head_ax, kv_head_ax = axes_t
    kh_local = kh // dict(mesh.shape).get(kv_head_ax, 1) \
        if kv_head_ax else kh
    if not paged_prefill_supported(t, page_size, d, kh_local, h // kh):
        return None
    page_ax = None
    if pool_replicas > 1:
        if (batch_ax != "data"
                or dict(mesh.shape).get("data", 1) != pool_replicas):
            return None
        page_ax = "data"
    per_replica = k_pool.shape[0] // pool_replicas

    q_spec = P(batch_ax, None, head_ax, None)
    pool_spec = P(page_ax, None, kv_head_ax, None)
    quantized = k_scale is not None

    def body(ql, kp, vp, tl, ol, vl, *sc):
        if page_ax is not None:
            tl = tl - jax.lax.axis_index("data") * per_replica
        ks, vs = sc if sc else (None, None)
        return paged_prefill_attention(
            ql, kp, vp, tl, ol, vl, sliding_window=sliding_window,
            softcap=softcap, interpret=interpret,
            k_scale=ks, v_scale=vs, kv_bits=kv_bits)

    in_specs = (q_spec, pool_spec, pool_spec,
                P(batch_ax, None), P(batch_ax), P(batch_ax))
    args = [q, k_pool, v_pool, table.astype(jnp.int32),
            offsets.astype(jnp.int32), kv_valid.astype(jnp.int32)]
    if quantized:
        in_specs += (pool_spec, pool_spec)
        args += [k_scale, v_scale]
    fn = shard_map(body, mesh=mesh,
                   in_specs=in_specs,
                   out_specs=q_spec, axis_names=_manual_axes(mesh),
                   check_vma=False)
    return fn(*args)


# --- decode kernel ---


def _manual_axes(mesh):
    """The axes this wrapper's shard_map must manualize: the mesh's AUTO
    axes. On the engines' concrete meshes every axis is Auto, so this is
    the same set shard_map would manualize with no axis_names at all.
    Inside a partial-manual region — the PP engine's manual-"pipe" stage
    bodies calling these wrappers with the context AbstractMesh — the
    already-Manual "pipe" axis must be excluded, leaving a NESTED
    shard_map over "model" only."""
    from ..compat import mesh_manual_axes
    return mesh_manual_axes(mesh)


def _spmd_axes(mesh, h: int, kh: int, b: int):
    """(batch_ax, head_ax, kv_head_ax) for partitioning attention over a
    (data, model) mesh, or None when the head layout can't partition —
    the ONE derivation shared by the contiguous and paged SPMD wrappers.

    kv-head rule: when kh divides the model axis, each device's
    contiguous q-head slice maps exactly onto its kv-head slice (q head
    j ↔ kv head j // group), so both shard on "model". MQA (kh == 1)
    replicates the single kv head — matching _fallback_replicated's
    cache/pool layout — and shards only q heads. Any other non-dividing
    kh would scramble the q↔kv grouping per device
    (spmd_partitionable rejects it)."""
    axes = dict(mesh.shape)
    n_model = axes.get("model", 1)
    n_data = axes.get("data", 1)
    if not spmd_partitionable(h, kh, n_model):
        return None
    kv_head_ax = ("model" if n_model > 1 and kh % n_model == 0 else None)
    batch_ax = "data" if (n_data > 1 and b % n_data == 0) else None
    head_ax = "model" if n_model > 1 else None
    return batch_ax, head_ax, kv_head_ax


def flash_attention_spmd(
    mesh,
    q: jax.Array,                 # [B, T, H, D] (T==1 → decode)
    k: jax.Array,                 # [B, S, K, D] position-aligned cache
    v: jax.Array,                 # [B, S, K, D]
    offsets: jax.Array,           # [B] absolute position of q row start
    kv_valid: jax.Array,          # [B] valid cache entries per row
    *,
    sliding_window: Optional[int] = None,
    softcap: Optional[float] = None,
    interpret: Optional[bool] = None,
) -> Optional[jax.Array]:
    """The kernels under a multi-device (data, model) mesh via shard_map.

    A plain pallas_call inside a pjit'd program is not SPMD-partitionable;
    this wrapper partitions the problem the way TP shards it anyway — kv
    heads on "model" (each device already holds its heads' slice of the KV
    cache, sharding.kv_cache_spec), batch rows on "data" — and runs the
    kernel per-device on its local heads. Attention is embarrassingly
    parallel over (batch, kv head), so the body needs NO collectives; the
    o_proj contraction after (sharded over query heads) stays outside and
    gets its all-reduce from XLA as usual.

    Returns None when the shapes don't partition (heads don't divide the
    model axis — the engine's dense path is the fallback, matching
    _fallback_replicated's cache layout in that case).
    """
    from ..compat import shard_map
    from jax.sharding import PartitionSpec as P

    b, t, h, d = q.shape
    s, kh = k.shape[1], k.shape[2]
    axes_t = _spmd_axes(mesh, h, kh, b)
    if axes_t is None or not supported(t, s, d):
        return None
    batch_ax, head_ax, kv_head_ax = axes_t

    q_spec = P(batch_ax, None, head_ax, None)
    kv_spec = P(batch_ax, None, kv_head_ax, None)
    row_spec = P(batch_ax)
    out_spec = q_spec

    def body(ql, kl, vl, offs_l, valid_l):
        if t > 1:
            return flash_prefill_attention(
                ql, kl, vl, offs_l, valid_l,
                sliding_window=sliding_window, softcap=softcap,
                interpret=interpret)
        return ragged_decode_attention(
            ql, kl, vl, valid_l,
            sliding_window=sliding_window, softcap=softcap,
            interpret=interpret)

    fn = shard_map(body, mesh=mesh,
                   in_specs=(q_spec, kv_spec, kv_spec, row_spec, row_spec),
                   out_specs=out_spec, axis_names=_manual_axes(mesh),
                   check_vma=False)
    return fn(q, k, v, offsets.astype(jnp.int32),
              kv_valid.astype(jnp.int32))


def _decode_accumulate(q, k, v, kv_start, valid, state, *,
                       group: int, block_kv: int,
                       sliding_window: Optional[int],
                       softcap: Optional[float],
                       k_scale=None, v_scale=None, kv_bits: int = 8):
    """One online-softmax accumulation of a single-position query group
    [G, D] against one kv block [bkv, D] whose first entry holds absolute
    position kv_start. Shared by the contiguous (_decode_kernel) and
    paged (_paged_decode_kernel) decode kernels — the two differ ONLY in
    how the kv block is addressed, so the math lives here once. Pure
    value-in/value-out over `state` = (m, l, acc) — see
    _prefill_accumulate for why. `k_scale`/`v_scale`: quantized-page
    blocks dequantize in-kernel first (ISSUE 11 — ditto)."""
    if k_scale is not None:
        k = _dequant_kv(k, k_scale, kv_bits, q.dtype)
        v = _dequant_kv(v, v_scale, kv_bits, q.dtype)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                # [G, bkv]
    if softcap is not None:
        s = softcap * jnp.tanh(s / softcap)
    kv_pos = kv_start + jax.lax.broadcasted_iota(
        jnp.int32, (group, block_kv), 1)
    mask = kv_pos < valid
    if sliding_window is not None:
        mask &= kv_pos > (valid - 1) - sliding_window
    s = jnp.where(mask, s, NEG_INF)

    m_prev, l_prev, acc_prev = state
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new[:, :1])
    l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_prev * alpha[:, :1] + pv


def _decode_kernel(valid_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, block_kv: int,
                   num_kv_blocks: int, group: int,
                   sliding_window: Optional[int],
                   softcap: Optional[float]):
    b = pl.program_id(0)
    sb = pl.program_id(2)

    @pl.when(sb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    valid = valid_ref[b]
    hi = (valid - 1) // block_kv
    if sliding_window is None:
        lo = jnp.int32(0)
    else:
        lo = jnp.maximum(0, (valid - sliding_window) // block_kv)

    @pl.when((sb >= lo) & (sb <= hi))
    def _compute():
        m_scr[:], l_scr[:], acc_scr[:] = _decode_accumulate(
            q_ref[0, 0], k_ref[0, 0], v_ref[0, 0], sb * block_kv, valid,
            (m_scr[:], l_scr[:], acc_scr[:]), group=group,
            block_kv=block_kv, sliding_window=sliding_window,
            softcap=softcap)

    @pl.when(sb == num_kv_blocks - 1)
    def _finish():
        l = jnp.maximum(l_scr[:, :1], 1e-30)
        o_ref[0, 0] = (acc_scr[:] / l).astype(o_ref.dtype)


# Conservative VMEM working-set budget for the paged kernels. All kv
# heads ride one block since the per-head pool block is Mosaic-illegal
# (see _paged_decode_kernel), so q/out/kv blocks and scratch all scale
# with kh — large-GQA shapes must shrink block_q or decline to the
# gather-view fallback INSTEAD of failing Mosaic compilation on chip.
_VMEM_BUDGET = 12 * 1024 * 1024


def _paged_vmem_est(page_size: int, d: int, kh: int, group: int,
                    block_q: int) -> int:
    scratch = kh * group * block_q * (2 * _LANES + d) * 4   # f32 m/l/acc
    q_out = 2 * kh * group * block_q * d * 2                # bf16 blocks
    kv = 2 * 2 * page_size * kh * d * 2                     # 2×(k+v) bufs
    return scratch + q_out + kv


def _paged_prefill_block_q(t: int, page_size: int, d: int, kh: int,
                           group: int) -> Optional[int]:
    for bq in (128, 64, 32, 16, 8):
        if t % bq == 0 and _paged_vmem_est(page_size, d, kh, group,
                                           bq) <= _VMEM_BUDGET:
            return bq
    return None


def paged_decode_supported(page_size: int, d: int, kh: int = 1,
                           group: int = 1) -> bool:
    """Can paged_decode_attention serve this pool shape? The page is the
    kv block, so page_size must be a legal block; TPU wants lane-aligned
    D (any shape goes in interpret mode). Pass the LOCAL kv-head count
    and GQA group so the kh-scaled VMEM working set is budgeted — an
    oversized layout must route to the gather view, not fail Mosaic."""
    if page_size not in (512, 256, 128, 64, 32, 16, 8):
        return False
    if _paged_vmem_est(page_size, d, kh, group, 1) > _VMEM_BUDGET:
        return False
    return _interpret() or d % 128 == 0


def _paged_decode_kernel(table_ref, valid_ref, q_ref, k_ref, v_ref,
                         *rest, page_size: int,
                         num_page_blocks: int, kh: int, group: int,
                         sliding_window: Optional[int],
                         softcap: Optional[float],
                         kv_bits: int = 8, quantized: bool = False):
    # Identical online-softmax math to _decode_kernel; the paged
    # differences: the kv block for grid step sb is pool page
    # table[b, sb] (not cache row sb), and ALL kv heads ride one block —
    # the pool keeps its [P, ps, K, D] layout, and a per-head block
    # (1, ps, 1, D) is Mosaic-ILLEGAL for K > 1 (second-minor block dim
    # 1 is neither 8-aligned nor the full K axis; unseen on hardware
    # until GQA because gemma's MQA pool has K == 1). So the grid drops
    # its kv-head dimension, each page is DMA'd once per row with every
    # head (same total bytes as per-head page reads), and a STATIC
    # unrolled loop walks the heads against per-head scratch slices.
    # valid INCLUDES the current step's entry, which the caller has
    # already written into the pool (q position = valid - 1).
    # Quantized pools (ISSUE 11): two extra per-page scale blocks ride
    # the kv index map, dequantized inside _decode_accumulate.
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
        ks_ref = vs_ref = None
    b = pl.program_id(0)
    sb = pl.program_id(1)

    @pl.when(sb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    valid = valid_ref[b]
    hi = (valid - 1) // page_size
    if sliding_window is None:
        lo = jnp.int32(0)
    else:
        lo = jnp.maximum(0, (valid - sliding_window) // page_size)

    @pl.when((sb >= lo) & (sb <= hi))
    def _compute():
        for khi in range(kh):
            m_scr[khi], l_scr[khi], acc_scr[khi] = _decode_accumulate(
                q_ref[0, khi], k_ref[0, :, khi, :], v_ref[0, :, khi, :],
                sb * page_size, valid,
                (m_scr[khi], l_scr[khi], acc_scr[khi]), group=group,
                block_kv=page_size, sliding_window=sliding_window,
                softcap=softcap,
                k_scale=(ks_ref[0, :, khi, :] if quantized else None),
                v_scale=(vs_ref[0, :, khi, :] if quantized else None),
                kv_bits=kv_bits)

    @pl.when(sb == num_page_blocks - 1)
    def _finish():
        for khi in range(kh):
            l = jnp.maximum(l_scr[khi, :, :1], 1e-30)
            o_ref[0, khi] = (acc_scr[khi] / l).astype(o_ref.dtype)


def paged_decode_spmd(
    mesh,
    q: jax.Array,                 # [B, 1, H, D]
    k_pool: jax.Array,            # [P, page_size, K, D]
    v_pool: jax.Array,            # [P, page_size, K, D]
    table: jax.Array,             # [B, pages_per_seq]
    kv_valid: jax.Array,          # [B]
    *,
    sliding_window: Optional[int] = None,
    softcap: Optional[float] = None,
    interpret: Optional[bool] = None,
    pool_replicas: int = 1,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    kv_bits: int = 8,
) -> Optional[jax.Array]:
    """paged_decode_attention under a multi-device (data, model) mesh.

    Same partitioning as flash_attention_spmd: kv heads ride "model"
    (each device's pool slice holds its heads' pages — the engine's
    paged pool sharding), and batch rows ride "data" when divisible —
    the page table and valid lengths shard row-aligned with the batch
    (replicated when the batch doesn't divide). MQA replicates the
    single kv head and shards only q heads. Returns None when the head
    layout doesn't partition — the engine then serves paged decode
    through the gather view instead.

    pool_replicas > 1 (VERDICT r4 #4): the pool's PAGE axis is sharded
    over "data" (per-replica pools, engine/paging.py), so each data
    shard holds pages [r*P/R, (r+1)*P/R) and the batch MUST arrive
    replica-grouped: block r's rows reference only replica r's pages
    (the engine's ReplicaGroupPlan pads and permutes the batch to make
    this hold). The body rebases each shard's table to its local page
    range via axis_index — the gather view is never built. Returns None
    when the batch doesn't divide over "data" (serving always pads) or
    the mesh's data size disagrees with pool_replicas.
    """
    from ..compat import shard_map
    from jax.sharding import PartitionSpec as P

    b, t, h, d = q.shape
    page_size, kh = k_pool.shape[1], k_pool.shape[2]
    axes_t = _spmd_axes(mesh, h, kh, b)
    if axes_t is None:
        return None
    batch_ax, head_ax, kv_head_ax = axes_t
    kh_local = kh // dict(mesh.shape).get(kv_head_ax, 1) \
        if kv_head_ax else kh
    if not paged_decode_supported(page_size, d, kh_local, h // kh):
        return None
    page_ax = None
    if pool_replicas > 1:
        if (batch_ax != "data"
                or dict(mesh.shape).get("data", 1) != pool_replicas):
            return None
        page_ax = "data"
    per_replica = k_pool.shape[0] // pool_replicas

    q_spec = P(batch_ax, None, head_ax, None)
    pool_spec = P(page_ax, None, kv_head_ax, None)
    quantized = k_scale is not None

    def body(ql, kp, vp, tl, vl, *sc):
        if page_ax is not None:
            tl = tl - jax.lax.axis_index("data") * per_replica
        ks, vs = sc if sc else (None, None)
        return paged_decode_attention(
            ql, kp, vp, tl, vl, sliding_window=sliding_window,
            softcap=softcap, interpret=interpret,
            k_scale=ks, v_scale=vs, kv_bits=kv_bits)

    in_specs = (q_spec, pool_spec, pool_spec,
                P(batch_ax, None), P(batch_ax))
    args = [q, k_pool, v_pool, table.astype(jnp.int32),
            kv_valid.astype(jnp.int32)]
    if quantized:
        in_specs += (pool_spec, pool_spec)
        args += [k_scale, v_scale]
    fn = shard_map(body, mesh=mesh,
                   in_specs=in_specs,
                   out_specs=q_spec, axis_names=_manual_axes(mesh),
                   check_vma=False)
    return fn(*args)


def paged_decode_attention(
    q: jax.Array,                 # [B, 1, H, D] this step's query
    k_pool: jax.Array,            # [P, page_size, K, D] page pool
    v_pool: jax.Array,            # [P, page_size, K, D]
    table: jax.Array,             # [B, pages_per_seq] int32 page table
    kv_valid: jax.Array,          # [B] valid entries INCLUDING this step
    *,
    sliding_window: Optional[int] = None,
    softcap: Optional[float] = None,
    interpret: Optional[bool] = None,
    k_scale: Optional[jax.Array] = None,   # [P, ps, K, G] (ISSUE 11)
    v_scale: Optional[jax.Array] = None,
    kv_bits: int = 8,
) -> jax.Array:
    """Single-position decode attention straight off the page pool.

    The caller must have written this step's K/V into each row's frontier
    page already (a [B]-row scatter — engine/paged_forward.py). The kv
    block index map reads the page table, so only pages holding each
    row's valid prefix are ever DMA'd, and the [B, S, K, D] gather view
    the engine's fallback path materializes is never built. The pool
    keeps its prefill-friendly [P, ps, K, D] layout; a page block
    carries ALL kv heads (1, ps, K, D) and a static in-kernel loop walks
    them — per-head (1, ps, 1, D) blocks are Mosaic-illegal for K > 1,
    and total DMA bytes are identical either way (each page read once
    per row). Returns [B, 1, H, D]. `k_scale`/`v_scale` (ISSUE 11):
    quantized pools dequantize in-kernel — the scale blocks ride the
    same page index map.
    """
    b, t, h, d = q.shape
    assert t == 1, "decode kernel serves exactly one position"
    page_size, kh = k_pool.shape[1], k_pool.shape[2]
    group = h // kh
    pages_per_seq = table.shape[1]
    quantized = k_scale is not None
    if not paged_decode_supported(page_size, d, kh, group):
        raise ValueError(f"unsupported pool shape ps={page_size} D={d}")
    interpret = _interpret() if interpret is None else interpret

    qt = q[:, 0].reshape(b, kh, group, d)

    def kv_index(bi, sb, table_ref, valid_ref):
        hi_blk = (valid_ref[bi] - 1) // page_size
        if sliding_window is None:
            lo_blk = jnp.int32(0)
        else:
            lo_blk = jnp.maximum(
                0, (valid_ref[bi] - sliding_window) // page_size)
        sb = jnp.clip(sb, lo_blk, jnp.maximum(hi_blk, 0))
        return (table_ref[bi, sb], 0, 0, 0)

    in_specs = [
        pl.BlockSpec((1, kh, group, d),
                     lambda bi, sb, t_, v_: (bi, 0, 0, 0)),
        pl.BlockSpec((1, page_size, kh, k_pool.shape[-1]), kv_index),
        pl.BlockSpec((1, page_size, kh, v_pool.shape[-1]), kv_index),
    ]
    operands = [qt, k_pool, v_pool]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, page_size, kh, k_scale.shape[-1]), kv_index),
            pl.BlockSpec((1, page_size, kh, v_scale.shape[-1]), kv_index),
        ]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, pages_per_seq),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, kh, group, d),
            lambda bi, sb, t_, v_: (bi, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((kh, group, _LANES), jnp.float32),
            pltpu.VMEM((kh, group, _LANES), jnp.float32),
            pltpu.VMEM((kh, group, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _paged_decode_kernel, page_size=page_size,
        num_page_blocks=pages_per_seq, kh=kh, group=group,
        sliding_window=sliding_window, softcap=softcap,
        kv_bits=kv_bits, quantized=quantized)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        interpret=interpret,
    )(table.astype(jnp.int32), kv_valid.astype(jnp.int32), *operands)
    return out.reshape(b, 1, h, d)


# --- ragged paged attention (ISSUE 8) ---
#
# Mixed prefill chunks and decode tokens in ONE dispatch (arxiv
# 2604.15464 "Ragged Paged Attention"): the query is a FLAT token buffer
# [T, H, D] carved into per-sequence row runs, each sequence attending
# its own page-table pages. The flat axis is blocked at RAGGED_BLOCK_Q=8
# — the MXU sublane minimum, so a decode token (a 1-row sequence)
# occupies exactly one hardware tile — and the host builder
# (serving_loop.build_ragged_batch) aligns every sequence's run to that
# granularity. Scalar-prefetched per-BLOCK metadata maps each q block to
# its sequence, so the kv index map walks that sequence's pages only:
# one compiled program serves every prefill/decode mix of a fixed token
# budget, which is what retires the scheduler's pow2 row buckets on this
# path.
#
# RAGGED_BLOCK_Q has ONE owner (serving_loop): the host builder aligns
# runs and sizes seq_of_block/block_qstart with it, and the kernel grid
# + VMEM estimate here must agree — two definitions would let a lone
# tuning change silently mis-map blocks to sequences.
from ..serving_loop import RAGGED_BLOCK_Q  # noqa: E402

# Test-visibility counters (tests/conftest.py `ragged_attn` marker
# guard): how many ragged dispatches the engine seam issued since the
# last reset, split kernel vs XLA fallback. A guard that sees zero
# kernel dispatches on a marked test knows the ragged path silently fell
# back (or never ran). The kernel wrapper also counts its own traces so
# direct-kernel unit tests register without an engine.
import threading as _threading

_ragged_lock = _threading.Lock()
_ragged_kernel_count = 0
_ragged_fallback_count = 0


def reset_ragged_counters() -> None:
    global _ragged_kernel_count, _ragged_fallback_count
    with _ragged_lock:
        _ragged_kernel_count = 0
        _ragged_fallback_count = 0


def note_ragged_dispatch(kernel: bool) -> None:
    global _ragged_kernel_count, _ragged_fallback_count
    with _ragged_lock:
        if kernel:
            _ragged_kernel_count += 1
        else:
            _ragged_fallback_count += 1


def ragged_kernel_dispatches() -> int:
    return _ragged_kernel_count


def ragged_fallback_dispatches() -> int:
    return _ragged_fallback_count


def ragged_decline_reason(page_size: int, d: int, kh: int = 1,
                          group: int = 1) -> Optional[str]:
    """Why the ragged kernel cannot serve this pool shape, or None when
    it can — the machine-readable `fallback_reason` the engine records
    per dispatch (the int4mm plan_reason pattern). Pass the LOCAL
    kv-head count under SPMD."""
    if page_size not in (512, 256, 128, 64, 32, 16, 8):
        return f"page_size:{page_size}"
    if _paged_vmem_est(page_size, d, kh, group,
                       RAGGED_BLOCK_Q) > _VMEM_BUDGET:
        return f"vmem:ps={page_size},d={d},kh={kh},g={group}"
    if not _interpret() and d % 128 != 0:
        return f"head_dim:{d}"
    return None


def ragged_supported(page_size: int, d: int, kh: int = 1,
                     group: int = 1) -> bool:
    return ragged_decline_reason(page_size, d, kh, group) is None


def kv_quant_decline_reason(page_size: int, d: int, kh: int, group: int,
                            bits: int = 8,
                            quant_group: int = 32) -> Optional[str]:
    """Why the Pallas kernels cannot serve a QUANTIZED pool of this
    shape, or None when they can — the machine-readable
    `fallback_reason` the engine records (the int4mm plan_reason
    pattern, ISSUE 11). The bf16 kernel gates (page_size block
    legality, VMEM, lane-aligned D) apply unchanged — quantized blocks
    are strictly smaller, so the bf16 VMEM estimate stays a safe upper
    bound; int4 additionally needs an even head_dim whose packed width
    and scale grouping are well-formed. A declined shape serves through
    the XLA dequant fallback (gather view / ragged dense path) — the
    pages stay quantized either way, only the dequant site moves."""
    if bits not in (8, 4):
        return f"kv_bits:{bits}"
    base = ragged_decline_reason(page_size, d, kh, group)
    if base is not None:
        return base
    if bits == 4:
        if d % 2:
            return f"int4_head_dim:{d}"
        from ..kv_quant import KVQuantSpec
        g = KVQuantSpec(bits=4, group=quant_group).effective_group(d)
        if d % g or g % 2:
            # effective_group clamps to >= 2; a grouping that doesn't
            # tile D evenly means no well-formed scale layout exists.
            return f"int4_group:d={d},g={quant_group}"
    return None


def kv_quant_kernel_supported(page_size: int, d: int, kh: int,
                              group: int, bits: int = 8,
                              quant_group: int = 32) -> bool:
    return kv_quant_decline_reason(page_size, d, kh, group, bits,
                                   quant_group) is None


def _ragged_kernel(table_ref, blkseq_ref, blkq_ref, qoffs_ref, valid_ref,
                   q_ref, k_ref, v_ref, *rest,
                   page_size: int, num_page_blocks: int, kh: int,
                   group: int, sliding_window: Optional[int],
                   softcap: Optional[float],
                   kv_bits: int = 8, quantized: bool = False):
    # Grid (q_blocks, pages_per_seq). Identical online-softmax math to
    # _paged_prefill_kernel (shared _prefill_accumulate, all kv heads on
    # one pool block with a static head loop — see _paged_decode_kernel
    # for why per-head pool blocks are Mosaic-illegal); the ragged
    # difference is WHICH sequence a q block serves: blkseq_ref maps the
    # flat-buffer block to its sequence, whose page table / causal
    # frontier then drive the kv index map exactly like the batched
    # kernels' row index. Rows past a sequence's real length are pad
    # rows: they attend the sequence's valid prefix (finite garbage —
    # MASK_VALUE is a large finite negative, so even an all-masked row
    # exponentiates to finite junk) and the host drops their outputs.
    # Quantized pools (ISSUE 11): per-page scale blocks ride the kv
    # index map, dequantized inside _prefill_accumulate.
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = rest
    else:
        o_ref, m_scr, l_scr, acc_scr = rest
        ks_ref = vs_ref = None
    qb = pl.program_id(0)
    sb = pl.program_id(1)

    @pl.when(sb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    seq = blkseq_ref[qb]
    q_start = qoffs_ref[seq] + blkq_ref[qb]
    valid = valid_ref[seq]
    lo, hi = _prefill_blk_bounds(q_start, valid, RAGGED_BLOCK_Q,
                                 page_size, sliding_window)

    @pl.when((sb >= lo) & (sb <= hi))
    def _compute():
        for khi in range(kh):
            m_scr[khi], l_scr[khi], acc_scr[khi] = _prefill_accumulate(
                q_ref[khi].reshape(group * RAGGED_BLOCK_Q, -1),
                k_ref[0, :, khi, :], v_ref[0, :, khi, :], q_start,
                sb * page_size, valid,
                (m_scr[khi], l_scr[khi], acc_scr[khi]), group=group,
                block_q=RAGGED_BLOCK_Q, block_kv=page_size,
                sliding_window=sliding_window, softcap=softcap,
                k_scale=(ks_ref[0, :, khi, :] if quantized else None),
                v_scale=(vs_ref[0, :, khi, :] if quantized else None),
                kv_bits=kv_bits)

    @pl.when(sb == num_page_blocks - 1)
    def _finish():
        d = o_ref.shape[-1]
        for khi in range(kh):
            l = jnp.maximum(l_scr[khi, :, :1], 1e-30)
            o_ref[khi] = (acc_scr[khi] / l).astype(o_ref.dtype) \
                .reshape(group, RAGGED_BLOCK_Q, d)


def ragged_paged_attention(
    q: jax.Array,                 # [T, H, D] flat token buffer
    k_pool: jax.Array,            # [P, page_size, K, D] page pool
    v_pool: jax.Array,            # [P, page_size, K, D]
    tables: jax.Array,            # [S, pages_per_seq] int32 page tables
    seq_of_block: jax.Array,      # [T/8] sequence id of each q block
    block_qstart: jax.Array,      # [T/8] block start row WITHIN its seq
    query_offsets: jax.Array,     # [S] absolute position of seq's row 0
    kv_valid: jax.Array,          # [S] valid kv entries AFTER this call
    *,
    sliding_window: Optional[int] = None,
    softcap: Optional[float] = None,
    interpret: Optional[bool] = None,
    k_scale: Optional[jax.Array] = None,   # [P, ps, K, G] (ISSUE 11)
    v_scale: Optional[jax.Array] = None,
    kv_bits: int = 8,
) -> jax.Array:
    """Mixed prefill/decode attention over a flat token buffer, straight
    off the page pool.

    The flat buffer holds each sequence's query tokens as a contiguous
    run aligned to RAGGED_BLOCK_Q rows (the host builder pads runs with
    inert rows); row j of sequence s has absolute position
    query_offsets[s] + (row within the run), causal within the segment.
    The caller must have scattered every real token's K/V into its
    sequence's frontier pages already (engine/paged_forward.py). One
    compiled shape serves every prefill/decode composition of the same
    T — the no-recompile property the scheduler's ragged segments rely
    on. Returns [T, H, D] in q's dtype; pad-row outputs are garbage and
    must be dropped by the caller.
    """
    t, h, d = q.shape
    page_size, kh = k_pool.shape[1], k_pool.shape[2]
    group = h // kh
    pages_per_seq = tables.shape[1]
    quantized = k_scale is not None
    if t % RAGGED_BLOCK_Q:
        raise ValueError(
            f"flat buffer T={t} must be a multiple of {RAGGED_BLOCK_Q}")
    reason = ragged_decline_reason(page_size, d, kh, group)
    if reason is not None:
        raise ValueError(f"unsupported ragged shape: {reason}")
    interpret = _interpret() if interpret is None else interpret
    # Wrapper-level count (trace time under jit, per call eagerly):
    # lets direct-kernel unit tests satisfy the ragged_attn guard; the
    # engine seam's per-dispatch count is the exact provenance.
    note_ragged_dispatch(kernel=True)

    # [T, H, D] → [K, G, T, D]: q heads grouped by their kv head, flat
    # token axis blocked at RAGGED_BLOCK_Q.
    qt = q.reshape(t, kh, group, d).transpose(1, 2, 0, 3)
    num_blocks = t // RAGGED_BLOCK_Q

    def kv_index(qb, sb, table_ref, blkseq_ref, blkq_ref, qoffs_ref,
                 valid_ref):
        seq = blkseq_ref[qb]
        q_start = qoffs_ref[seq] + blkq_ref[qb]
        lo_blk, hi_blk = _prefill_blk_bounds(
            q_start, valid_ref[seq], RAGGED_BLOCK_Q, page_size,
            sliding_window)
        sb = jnp.clip(sb, lo_blk, jnp.maximum(hi_blk, 0))
        return (table_ref[seq, sb], 0, 0, 0)

    in_specs = [
        pl.BlockSpec((kh, group, RAGGED_BLOCK_Q, d),
                     lambda qb, sb, t_, b_, s_, o_, v_:
                     (0, 0, qb, 0)),
        pl.BlockSpec((1, page_size, kh, k_pool.shape[-1]), kv_index),
        pl.BlockSpec((1, page_size, kh, v_pool.shape[-1]), kv_index),
    ]
    operands = [qt, k_pool, v_pool]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, page_size, kh, k_scale.shape[-1]), kv_index),
            pl.BlockSpec((1, page_size, kh, v_scale.shape[-1]), kv_index),
        ]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(num_blocks, pages_per_seq),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (kh, group, RAGGED_BLOCK_Q, d),
            lambda qb, sb, t_, b_, s_, o_, v_: (0, 0, qb, 0)),
        scratch_shapes=[
            pltpu.VMEM((kh, group * RAGGED_BLOCK_Q, _LANES), jnp.float32),
            pltpu.VMEM((kh, group * RAGGED_BLOCK_Q, _LANES), jnp.float32),
            pltpu.VMEM((kh, group * RAGGED_BLOCK_Q, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _ragged_kernel, page_size=page_size,
        num_page_blocks=pages_per_seq, kh=kh, group=group,
        sliding_window=sliding_window, softcap=softcap,
        kv_bits=kv_bits, quantized=quantized)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        interpret=interpret,
    )(tables.astype(jnp.int32), seq_of_block.astype(jnp.int32),
      block_qstart.astype(jnp.int32), query_offsets.astype(jnp.int32),
      kv_valid.astype(jnp.int32), *operands)
    return out.transpose(2, 0, 1, 3).reshape(t, h, d)


def ragged_paged_spmd(
    mesh,
    q: jax.Array,                 # [T, H, D] flat token buffer
    k_pool: jax.Array, v_pool: jax.Array,
    tables: jax.Array, seq_of_block: jax.Array,
    block_qstart: jax.Array, query_offsets: jax.Array,
    kv_valid: jax.Array,
    *,
    sliding_window: Optional[int] = None,
    softcap: Optional[float] = None,
    interpret: Optional[bool] = None,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    kv_bits: int = 8,
) -> Optional[jax.Array]:
    """ragged_paged_attention under a model-axis mesh via shard_map —
    the flash_attention_spmd head-sharding pattern: kv heads ride
    "model" (matching the pool's sharding), q heads follow their kv
    head, and the flat token buffer plus every metadata array stays
    replicated (attention is embarrassingly parallel over kv heads, so
    the body needs no collectives). Returns None when the head layout
    doesn't partition, or when the mesh has a data axis — the pool's
    page axis shards over "data" on those meshes and a flat buffer
    mixing replicas' rows cannot (the engine then serves the prologue
    path and records the reason)."""
    from ..compat import shard_map
    from jax.sharding import PartitionSpec as P

    t, h, d = q.shape
    page_size, kh = k_pool.shape[1], k_pool.shape[2]
    axes = dict(mesh.shape)
    if axes.get("data", 1) > 1:
        return None
    n_model = axes.get("model", 1)
    if not spmd_partitionable(h, kh, n_model):
        return None
    kv_head_ax = "model" if n_model > 1 and kh % n_model == 0 else None
    head_ax = "model" if n_model > 1 else None
    kh_local = kh // n_model if kv_head_ax else kh
    if not ragged_supported(page_size, d, kh_local, h // kh):
        return None

    q_spec = P(None, head_ax, None)
    pool_spec = P(None, None, kv_head_ax, None)
    meta2 = P(None, None)
    meta1 = P(None)
    quantized = k_scale is not None

    def body(ql, kp, vp, tl, bl, bq, qo, vl, *sc):
        ks, vs = sc if sc else (None, None)
        return ragged_paged_attention(
            ql, kp, vp, tl, bl, bq, qo, vl,
            sliding_window=sliding_window, softcap=softcap,
            interpret=interpret, k_scale=ks, v_scale=vs,
            kv_bits=kv_bits)

    in_specs = (q_spec, pool_spec, pool_spec, meta2,
                meta1, meta1, meta1, meta1)
    args = [q, k_pool, v_pool, tables.astype(jnp.int32),
            seq_of_block.astype(jnp.int32),
            block_qstart.astype(jnp.int32),
            query_offsets.astype(jnp.int32),
            kv_valid.astype(jnp.int32)]
    if quantized:
        in_specs += (pool_spec, pool_spec)
        args += [k_scale, v_scale]
    fn = shard_map(body, mesh=mesh,
                   in_specs=in_specs,
                   out_specs=q_spec, axis_names=_manual_axes(mesh),
                   check_vma=False)
    return fn(*args)


def ragged_decode_attention(
    q: jax.Array,                 # [B, 1, H, D] this step's query
    k: jax.Array,                 # [B, S, K, D] cache incl. this step's K
    v: jax.Array,                 # [B, S, K, D]
    kv_valid: jax.Array,          # [B] valid entries INCLUDING this step
    *,
    sliding_window: Optional[int] = None,
    softcap: Optional[float] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Single-position attention over each row's valid cache prefix.

    The query position is kv_valid-1 (decode always appends), so causality
    reduces to kv_pos < kv_valid. Returns [B, 1, H, D].
    """
    b, t, h, d = q.shape
    assert t == 1, "decode kernel serves exactly one position"
    s, kh = k.shape[1], k.shape[2]
    group = h // kh
    block_kv = _pick_block(s, (512, 256, 128, 64, 32, 16, 8))
    if block_kv is None:
        raise ValueError(f"unsupported cache length S={s}")
    interpret = _interpret() if interpret is None else interpret

    # [B, 1, H, D] → [B, K, G, D]: rows of one kv-head's query group
    qt = q[:, 0].reshape(b, kh, group, d)
    kt = k.transpose(0, 2, 1, 3)        # [B, K, S, D]
    vt = v.transpose(0, 2, 1, 3)
    num_kv_blocks = s // block_kv

    def kv_index(bi, khi, sb, valid_ref):
        hi_blk = (valid_ref[bi] - 1) // block_kv
        if sliding_window is None:
            lo_blk = jnp.int32(0)
        else:
            lo_blk = jnp.maximum(
                0, (valid_ref[bi] - sliding_window) // block_kv)
        sb = jnp.clip(sb, lo_blk, jnp.maximum(hi_blk, 0))
        return (bi, khi, sb, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, kh, num_kv_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, group, d),
                         lambda bi, khi, sb, v_: (bi, khi, 0, 0)),
            pl.BlockSpec((1, 1, block_kv, d), kv_index),
            pl.BlockSpec((1, 1, block_kv, d), kv_index),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, group, d),
            lambda bi, khi, sb, v_: (bi, khi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((group, _LANES), jnp.float32),
            pltpu.VMEM((group, _LANES), jnp.float32),
            pltpu.VMEM((group, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _decode_kernel, block_kv=block_kv, num_kv_blocks=num_kv_blocks,
        group=group, sliding_window=sliding_window, softcap=softcap)
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(qt.shape, q.dtype),
        interpret=interpret,
    )(kv_valid.astype(jnp.int32), qt, kt, vt)
    return out.reshape(b, 1, h, d)
