"""Crash-recovery library seam: journal replay as an importable API.

`resume_from_journal` (ISSUE 12) lived inside `commands/serve.py` and
was reachable only from the CLI, so the streaming gateway (ISSUE 16)
could not restore committed sessions on boot without shelling out.
This module is the factored library seam: the gateway calls it at
startup (`roundtable gateway --resume DIR`) and the CLI re-exports it
(`commands/serve.py`) so the `serve --resume` path stays byte-identical.

The replay contract is unchanged: every committed turn of every
journaled session re-submits through the NORMAL scheduler path with a
1-token budget, so the fresh engine re-prefills the exact committed
token stream through the same reuse/prefix-cache/commit machinery as
live serving, and each session's KV ends at its last committed turn.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from ..core.errors import ConfigError


def resume_from_journal(resume_dir: str, *,
                        config=None,
                        project_root: Optional[str] = None,
                        scheduler=None) -> dict[str, Any]:
    """Replay a session journal through the normal submit path
    (ISSUE 12 crash recovery): every committed turn of every journaled
    session is re-submitted with a 1-token budget, so the fresh
    engine re-prefills the exact committed token stream through the
    same reuse/prefix-cache/commit machinery as live serving and each
    session's KV ends at its last committed turn. Re-prefill is
    acceptable on the crash path — the prefix cache makes repeated
    spans cheap.

    `scheduler` (tests / embedding callers) replays onto that
    scheduler directly; otherwise adapters are seated from `config`
    (or the project's config) and the first tpu-llm engine's shared
    scheduler is used. The journal is attached to the scheduler
    afterwards, so the resumed process keeps journaling new turns into
    the same directory with continued turn numbering.

    Returns {"sessions", "turns", "scheduler"}."""
    from .session_journal import SessionJournal, replay_turns

    journal = SessionJournal(resume_dir)
    sched = scheduler
    if sched is None:
        from ..adapters.factory import initialize_adapters
        from ..core.config import load_config
        config = config or load_config(project_root or os.getcwd())
        adapters = initialize_adapters(config)
        from .scheduler import acquire_scheduler
        for adapter in adapters.values():
            if not hasattr(adapter, "attach_scheduler"):
                continue
            try:
                engine = adapter._get_engine()
                sched, _created = acquire_scheduler(engine)
                break
            except Exception:  # noqa: BLE001 — try the next seat
                continue
        if sched is None:
            raise ConfigError(
                "serve --resume needs at least one tpu-llm knight "
                "whose engine can be built — no scheduler available "
                "to replay onto")
    report: dict[str, Any] = {"sessions": 0, "turns": 0,
                              "scheduler": sched}
    for session in journal.sessions():
        report["turns"] += replay_turns(journal, session, sched.submit)
        report["sessions"] += 1
    if sched.journal is None:
        sched.attach_journal(journal)
    return report
