"""Cross-session radix-tree prefix cache over the paged KV pool.

Every roundtable discussion re-prefills the same bytes: the shared
system prompt, each knight's personality tail, and (across rounds) the
growing transcript. PR 4's donation (`best_donor`) deliberately stays
intra-session — sessions are fault-isolation domains, and a donor SLOT's
lifetime is coupled to its session's recovery ladder. This module adds
the production answer RTP-LLM documents (PAPERS.md): a CONTENT-ADDRESSED
index over the page pool itself, decoupled from any slot's lifetime.

Design (ISSUE 7 tentpole):

- **Radix tree keyed by token blocks.** One node per page-sized token
  block, children keyed by the block's token tuple (content-addressed
  with exact verification — a hash collision can therefore never serve
  wrong bytes). A node maps its block to ONE pool page whose K/V bytes
  are the deterministic function of the token prefix up to it.
- **The index is a reference holder, not an owner.** insert() takes one
  pool reference per node (`PagedKVCache.ref`); slots that later release
  or truncate merely UNREF — the page's bytes survive in the pool for as
  long as anyone (index, slot, offload tier) still references them.
- **attach() is the read path.** `InferenceEngine._prepare_batch` (and
  the PP engine's prepare) call it per row after the slot's own
  reuse_plan: the longest complete-block match extends the row's reuse
  frontier by ALIASING the matched pages (refcount++, zero copy; pages
  on another data replica, and the partial boundary page, device-copy —
  `PagedKVCache.adopt_span`). The attached span is READ-ONLY by
  construction: `ensure_capacity` copy-on-writes any shared page in the
  row's write range before the first divergent write, so two sessions
  sharing a prefix fork exactly at the first page they disagree on.
- **Eviction is LRU over refcount-0 nodes only.** A node whose page some
  live slot (or the offload tier) still references is never reclaimed;
  leaf nodes whose page the index alone holds evict oldest-first, under
  an optional page cap and — last resort — from `_alloc_page` just
  before it would declare pool exhaustion. flush()/drain drop the whole
  index via unref (never force-free).

Safety invariant (the hard part of cross-session sharing): the index
NEVER hands out a writable page, never frees a referenced page, and a
session's fault recovery (slot invalidation, revive) can only ever
unref/clear — it cannot reach into another session's mappings.
"""

from __future__ import annotations

import threading
from typing import Optional

from ..utils import telemetry

# Test-visibility counters (tests/conftest.py `prefix_cache` marker
# guard): a test that CLAIMS prefix-cache coverage but records zero
# attach hits silently ran cache-off serving — fail it loud.
_test_hits = 0
_test_lock = threading.Lock()


def reset_test_counters() -> None:
    global _test_hits
    with _test_lock:
        _test_hits = 0


def hits_seen() -> int:
    return _test_hits


def _note_hit() -> None:
    global _test_hits
    with _test_lock:
        _test_hits += 1


def env_flag(flag: Optional[bool], env_name: str) -> bool:
    """Shared on/off decision for the paged-pool subsystems: an explicit
    config value wins, then the env kill-switch, then default ON. ONE
    definition (prefix cache + offload tier) so the accepted falsy
    spellings can never drift between the two knobs."""
    import os
    if flag is not None:
        return bool(flag)
    env = os.environ.get(env_name)
    if env is not None:
        return env not in ("0", "false", "off")
    return True


def cache_enabled(flag: Optional[bool]) -> bool:
    """The prefix cache's on/off decision for a paged engine (the cache
    is the serving path, not an experiment — default ON)."""
    return env_flag(flag, "ROUNDTABLE_PREFIX_CACHE")


class _Node:
    __slots__ = ("children", "parent", "block", "page", "tick")

    def __init__(self, parent=None, block=None, page=None):
        self.children: dict[tuple, "_Node"] = {}
        self.parent = parent
        self.block = block
        self.page = page
        self.tick = 0


class PrefixCache:
    """The content-addressed index over one PagedKVCache pool.

    Single-writer like the pool itself: every caller already serializes
    on the engine's serve lock (scheduler thread / generate_batch), so
    no internal locking beyond the test counters."""

    def __init__(self, kv, engine: str = "engine",
                 max_pages: Optional[int] = None):
        self.kv = kv
        self.engine = engine
        self.page_size = kv.page_size
        # Default cap: the whole usable pool — the index is bounded by
        # reclaim-under-pressure, and idle capacity spent on cached
        # prefixes is the point. Set prefix_cache_pages to bound it hard.
        self.max_pages = max_pages or kv.usable_pages()
        self.root = _Node()
        self._pages = 0
        # page id -> node (1:1 — a live node's page is ref-held, so an
        # id can back only one node at a time). The offload tier asks
        # `holds_page` to tell a cache-only share (spill the bytes,
        # leave the index copy reclaimable) from a genuine cross-slot
        # share (keep resident); the allocator's write path asks
        # `forget_page` to turn an index-only share exclusive without
        # a copy-on-write allocation.
        self._by_page: dict[int, _Node] = {}
        self._ticks = 0
        # Decision provenance, the int4_paths pattern: cumulative counts
        # surfaced via describe() and mirrored into the registry.
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.inserted_pages = 0
        self.reused_tokens = 0

    # --- introspection ---

    def page_count(self) -> int:
        return self._pages

    def holds_page(self, page: int) -> bool:
        return page in self._by_page

    def node_count(self) -> int:
        n = 0
        stack = [self.root]
        while stack:
            node = stack.pop()
            n += len(node.children)
            stack.extend(node.children.values())
        return n

    def describe(self) -> dict:
        return {
            "pages": self._pages,
            "max_pages": self.max_pages,
            "nodes": self.node_count(),
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "inserted_pages": self.inserted_pages,
            "reused_tokens": self.reused_tokens,
        }

    def _tick(self) -> int:
        self._ticks += 1
        return self._ticks

    def _publish_sizes(self) -> None:
        telemetry.set_gauge("roundtable_prefix_cache_pages", self._pages,
                            engine=self.engine)

    # --- write path ---

    def insert(self, state) -> int:
        """Index every COMPLETE page of a committed slot (PagedKVCache.
        commit calls this). New blocks take one pool reference each;
        blocks already present keep their existing page (first writer
        wins — the bytes are content-equal by construction, and keeping
        the older page preserves its accumulated sharing). Returns how
        many new pages were indexed."""
        ps = self.page_size
        n_pages = min(len(state.tokens) // ps, len(state.pages))
        node = self.root
        added = 0
        tick = self._tick()
        for j in range(n_pages):
            block = tuple(state.tokens[j * ps:(j + 1) * ps])
            child = node.children.get(block)
            if child is None:
                page = state.pages[j]
                child = _Node(parent=node, block=block, page=page)
                node.children[block] = child
                self.kv.ref(page)
                self._pages += 1
                self._by_page[page] = child
                added += 1
            child.tick = tick
            node = child
        if added:
            self.inserted_pages += added
            telemetry.inc("roundtable_prefix_cache_inserted_pages_total",
                          added, engine=self.engine)
            self._publish_sizes()
        if self._pages > self.max_pages:
            self.reclaim(want=self._pages - self.max_pages)
        return added

    # --- read path ---

    def match(self, tokens: list[int]) -> list[_Node]:
        """The longest chain of complete-block nodes prefixing `tokens`
        (LRU-refreshed). Content-verified: children are keyed by the
        literal token tuple, so a match IS prefix equality."""
        ps = self.page_size
        node = self.root
        out: list[_Node] = []
        tick = self._tick()
        j = 0
        while (j + 1) * ps <= len(tokens):
            child = node.children.get(tuple(tokens[j * ps:(j + 1) * ps]))
            if child is None:
                break
            child.tick = tick
            out.append(child)
            node = child
            j += 1
        return out

    def attach(self, name: str, tokens: list[int],
               pinned: tuple[str, ...] = ()) -> int:
        """Raise slot `name`'s cached coverage to the longest complete-
        page prefix of `tokens` present in the index, by aliasing (same
        replica) or copying (cross-replica / boundary) the matched
        pages. Returns the new covered token count, or 0 when the index
        could not extend the slot's own reuse. Respects the at-least-
        one-token-fed rule: coverage never reaches len(tokens)."""
        cap = len(tokens) - 1
        if cap < self.page_size:
            return 0
        nodes = self.match(tokens)
        n = min(len(nodes), cap // self.page_size)
        state = self.kv._slots.get(name)
        have = len(state.tokens) if state is not None else 0
        if n <= 0 or n * self.page_size <= have:
            if not nodes:
                self.misses += 1
                telemetry.inc("roundtable_prefix_cache_misses_total",
                              engine=self.engine)
            return 0
        hi = n * self.page_size
        self.kv.adopt_span(name, [nd.page for nd in nodes[:n]],
                           lo=have, hi=hi, pinned=pinned)
        state = self.kv._slots[name]
        state.tokens = list(tokens[:hi])
        gained = hi - have
        self.hits += 1
        self.reused_tokens += gained
        _note_hit()
        telemetry.inc("roundtable_prefix_cache_hits_total",
                      engine=self.engine)
        telemetry.inc("roundtable_prefix_reused_tokens_total", gained,
                      engine=self.engine)
        return hi

    def attach_rows(self, names: list[str],
                    all_tokens: list[list[int]], offsets: list[int],
                    pinned: tuple[str, ...] = ()) -> int:
        """The per-batch consult both serving engines run after their
        own-slot reuse_plan pass — ONE definition (main engine
        _prepare_batch + PP prepare) so the warmup-exclusion rule and
        the reused accounting can never drift between them. Mutates
        `offsets` in place; returns the tokens the index served."""
        gained = 0
        for i, name in enumerate(names):
            if name.startswith("__warmup_"):
                continue
            got = self.attach(name, all_tokens[i], pinned)
            if got > offsets[i]:
                gained += got - offsets[i]
                offsets[i] = got
        return gained

    # --- eviction / lifecycle ---

    def _evictable_leaves(self, replica: Optional[int]) -> list[_Node]:
        out = []
        stack = list(self.root.children.values())
        while stack:
            node = stack.pop()
            if node.children:
                stack.extend(node.children.values())
            elif self.kv.refcount(node.page) == 1 and (
                    replica is None
                    or self.kv.replica_of_page(node.page) == replica):
                out.append(node)
        return out

    def reclaim(self, replica: Optional[int] = None, want: int = 1) -> int:
        """Evict up to `want` LRU refcount-0 leaf nodes (optionally
        restricted to one data replica's pages), unref'ing their pages
        back to the pool. Interior nodes become leaves as their children
        go and are picked up by subsequent passes. Returns pages freed."""
        freed = 0
        while freed < want:
            leaves = self._evictable_leaves(replica)
            if not leaves:
                break
            victim = min(leaves, key=lambda nd: nd.tick)
            # One pass evicts the oldest chain suffix available, not one
            # node per full rescan.
            while victim is not None and freed < want:
                parent = victim.parent
                del parent.children[victim.block]
                self.kv.unref(victim.page)
                self._pages -= 1
                self._by_page.pop(victim.page, None)
                freed += 1
                self.evictions += 1
                victim = None
                if (parent is not self.root and not parent.children
                        and self.kv.refcount(parent.page) == 1
                        and (replica is None
                             or self.kv.replica_of_page(parent.page)
                             == replica)):
                    victim = parent
        if freed:
            telemetry.inc("roundtable_prefix_cache_evictions_total",
                          freed, engine=self.engine)
            self._publish_sizes()
        return freed

    def forget_page(self, page: int) -> bool:
        """Drop the node backing `page` AND its whole subtree (the
        subtree's chain meaning includes the dropped block, so it can
        never be matched again) — the write path calls this when a slot
        is about to diverge inside a page whose ONLY other holder is
        the index: forgetting makes the page exclusive for free, where
        copy-on-write would burn an allocation and a dispatch to
        preserve an entry this slot's own divergence is invalidating."""
        node = self._by_page.get(page)
        if node is None:
            return False
        del node.parent.children[node.block]
        stack = [node]
        dropped = 0
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            self.kv.unref(n.page)
            self._by_page.pop(n.page, None)
            self._pages -= 1
            dropped += 1
        self.evictions += dropped
        telemetry.inc("roundtable_prefix_cache_evictions_total",
                      dropped, engine=self.engine)
        self._publish_sizes()
        return True

    def drop_all(self) -> int:
        """Unref every indexed page and clear the tree (flush/drain)."""
        dropped = self._pages
        stack = list(self.root.children.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            self.kv.unref(node.page)
        self.root = _Node()
        self._pages = 0
        self._by_page.clear()
        self._publish_sizes()
        return dropped

    def clear(self, unref: bool = True) -> None:
        """Drop the index; unref=False when the pool itself was
        reallocated (revive_if_dead) and the refs table is already
        gone."""
        if unref:
            self.drop_all()
            return
        self.root = _Node()
        self._pages = 0
        self._by_page.clear()
        self._publish_sizes()
