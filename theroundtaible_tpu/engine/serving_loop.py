"""Host-side serving loop pieces shared by the engines.

InferenceEngine (engine.py) and PPEngine (pp_serving.py) dispatch very
different device programs, but the HOST logic around them — chunked
bucketed prefill with the cache-end bucket-shrink guard, the decode
segment loop with deadline checks, and the eos-trim/commit epilogue — is
identical and subtle enough that two copies WILL drift (round-2 review
finding). Each engine passes its dispatch closure; everything else lives
here once.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import telemetry
from . import deadlines, faults

PREFILL_BUCKETS = (64, 128, 256, 512, 1024, 2048)
MAX_PREFILL_CHUNK = 2048
DECODE_SEGMENT = 64  # tokens per decode program; timeout checks in between

# Ragged mixed prefill/decode dispatch (ISSUE 8): the flat token
# buffer's row granularity (the MXU sublane minimum — one decode token
# occupies one 8-row tile) and the default per-dispatch token budget.
# ONE compiled ragged program per budget serves every prefill/decode
# composition, so the budget is the whole "shape grid" on this path —
# a small fixed set of max-token shapes, not per-occupancy buckets.
RAGGED_BLOCK_Q = 8
RAGGED_TOKENS_ENV = "ROUNDTABLE_RAGGED_TOKENS"
RAGGED_DEFER_MIN_ENV = "ROUNDTABLE_RAGGED_DEFER_MIN"


def ragged_token_budget(num_slots: int) -> int:
    """Flat-buffer capacity per ragged dispatch: big enough that a
    typical cold join's leader span streams in ONE dispatch — chunk
    throughput must be bucket-class or deferral just slows the joiner
    down — floored so every resident row's 8-row decode block still
    leaves chunk room. ROUNDTABLE_RAGGED_TOKENS overrides (rounded up
    to a block multiple)."""
    import os
    forced = int(os.environ.get(RAGGED_TOKENS_ENV, "0") or 0)
    if forced > 0:
        return -(-forced // RAGGED_BLOCK_Q) * RAGGED_BLOCK_Q
    return max(1024, RAGGED_BLOCK_Q * num_slots + 64)


def ragged_defer_min() -> int:
    """Suffix-token threshold below which a join keeps the PROLOGUE
    even on a ragged engine: with the prefix cache attached, a warm
    join's remaining prefill is often a few dozen tokens — blocking the
    batch for one tiny bucket dispatch is cheaper than spreading the
    same work across segment-gated ragged ticks. Only genuinely COLD
    prefills (the admission stall the ragged path exists to kill) are
    worth deferring. ROUNDTABLE_RAGGED_DEFER_MIN overrides."""
    import os
    return int(os.environ.get(RAGGED_DEFER_MIN_ENV, "256") or 256)


def ragged_shape_grid(budget: int) -> tuple[int, ...]:
    """The SMALL FIXED GRID of flat-buffer shapes (ISSUE 8): a dispatch
    compiles (and computes) its whole static buffer, pads included, so
    a lone decode step + 30-token tail chunk must not pay for the full
    budget's compute. Shapes {64, 256, 1024, budget} (deduped, capped
    at the budget) — every shape is warmed once, the dispatcher picks
    the smallest that fits the real work, and occupancy drift within a
    shape still compiles nothing. This is shape discipline by MAX-TOKEN
    grid, not per-occupancy row buckets — the grid stays this size
    regardless of max_rows."""
    return tuple(sorted({s for s in (64, 256, 1024, budget)
                         if s <= budget}))


def ragged_pick_shape(grid: tuple[int, ...], want: int) -> int:
    """Smallest grid shape >= want (the last shape when none is)."""
    for s in grid:
        if want <= s:
            return s
    return grid[-1]


def run_dispatch(dispatch: Callable, retry, deadline: float = float("inf"),
                 budget=None, rung: str = "dispatch"):
    """One device dispatch through the shared fault-tolerance AND
    deadline seams: the dispatch-stage injection points fire first (zero
    overhead unarmed — the guard is the module-level faults.ARMED flag),
    the watchdog times the blocking part of the dispatch against its
    rung budget when armed (deadlines.ACTIVE — a wait that exceeds it
    raises HangDetected, which classifies as the non-retryable `hang`
    kind and climbs the ladder like a crash), then the retry policy
    re-runs a transiently-failed dispatch before it surfaces. Failures
    a retry can't fix (timeout/oom/hang/...) pass straight through to
    the caller's degradation rung (RetryPolicy.retryable).

    Scope: retry-in-place helps failures raised BEFORE the device
    program consumes its inputs (host-side validation, dispatch-queue
    errors, the injected faults). The engines' KV programs donate their
    cache buffers (donate_argnums), so a failure that surfaces AFTER
    donation leaves the cache references dead (and a blind re-dispatch
    would die on the same dead buffers — RetryPolicy treats deleted-array
    errors as non-retryable), so that error climbs the ladder to the
    adapter rung, whose serial retry reallocates the buffers
    (engine.revive_kv_if_dead) and re-prefills from scratch
    (tpu_llm._serial_retry)."""

    def call():
        if faults.ARMED:
            faults.inject_dispatch_faults()
        return dispatch()

    def attempt():
        if deadlines.ACTIVE and budget is not None:
            return deadlines.watched_wait(call, budget, rung)
        return call()

    def attempt_traced():
        # "dispatch" is the span tree's leaf rung (ISSUE 5), mirroring
        # the budget rung the watchdog times this wait against. The
        # compile-attribution window (ISSUE 6) is a FALLBACK: engines
        # that already opened a precise (batch, bucket) label keep it;
        # callers that didn't (PP stage dispatches) still get a
        # rung-level label instead of "unlabeled".
        from . import compile_watch
        with compile_watch.label(f"dispatch[{rung}]", fallback=True):
            if telemetry.ACTIVE:
                with telemetry.span("dispatch", stage=rung):
                    return attempt()
            return attempt()

    if retry is None:
        return attempt_traced()
    return retry.run(attempt_traced, deadline=deadline)


def host_sync(fn: Callable, budget=None, rung: str = "decode"):
    """A blocking device→host read through the deadline seam: the read
    is where a wedged device program actually freezes the host loop
    (`int(steps)` / `float(logits[0, 0])` block until the program
    completes), so it gets the same watchdog treatment as a dispatch.
    Unarmed: a direct call behind the module-flag check."""
    def attempt():
        if deadlines.ACTIVE and budget is not None:
            return deadlines.watched_wait(fn, budget, rung)
        return fn()

    if telemetry.ACTIVE:
        with telemetry.span("dispatch", stage=rung, op="host_sync"):
            return attempt()
    return attempt()


class ReplicaGroupPlan:
    """Row permutation + padding that aligns a serving batch with the
    page pool's data-axis replicas (pool-direct paged serving under
    data>1, VERDICT r4 #4).

    shard_map splits the batch axis into contiguous blocks — block r
    lands on data-axis index r — and the per-replica page pool puts
    replica r's pages on exactly that shard. So a pool-direct batch must
    place each row inside the block of the replica that owns its slot's
    pages. The plan computes that layout once per generate_batch call:
    block r holds replica r's rows (original order preserved within the
    block), padded to the largest group size with rows whose page table
    is the replica's scratch page and whose first token is eos (they
    start done and their writes land on scratch, which is never read).

    `pos[i]` is the padded-batch position of original row i; padded
    arrays are built with scatter_rows/scatter_list/pad_table and read
    back with `padded[plan.pos]`.
    """

    def __init__(self, replicas: list[int], n_replicas: int,
                 bucket_group: bool = False):
        groups: list[list[int]] = [[] for _ in range(n_replicas)]
        for i, r in enumerate(replicas):
            groups[r].append(i)
        self.n_replicas = n_replicas
        group = max(1, max(len(g) for g in groups))
        if bucket_group:
            # Round the per-replica block up to a power of two: callers
            # whose batch COMPOSITION changes between dispatches (the
            # session scheduler's decode batch) keep the padded shape on
            # a {R*1, R*2, R*4, ...} grid instead of compiling one
            # program per exact group size. Fixed-composition callers
            # (generate_batch — one plan per call, warmup covers the
            # shapes) leave this off.
            group = pow2_bucket(group)
        self.group = group
        self.b_padded = n_replicas * self.group
        self.pos = np.empty(len(replicas), np.int64)
        pad_positions: list[int] = []
        pad_replicas: list[int] = []
        for r, rows in enumerate(groups):
            for k, i in enumerate(rows):
                self.pos[i] = r * self.group + k
            for k in range(len(rows), self.group):
                pad_positions.append(r * self.group + k)
                pad_replicas.append(r)
        self.pad_positions = np.asarray(pad_positions, np.int64)
        self.pad_replicas = pad_replicas

    def scatter_rows(self, values, pad_value) -> jax.Array:
        """Original-order per-row device/host array → padded array."""
        arr = jnp.asarray(values)
        out = jnp.full((self.b_padded,) + arr.shape[1:], pad_value,
                       arr.dtype)
        return out.at[jnp.asarray(self.pos)].set(arr)

    def scatter_list(self, items: list, pad_item) -> list:
        """Original-order per-row python values → padded list (pad rows
        share the one `pad_item` — callers treat rows as read-only)."""
        out = [pad_item] * self.b_padded
        for i, item in enumerate(items):
            out[self.pos[i]] = item
        return out

    def pad_table(self, table: np.ndarray, scratch_page) -> np.ndarray:
        """[B, pages_per_seq] page table → padded table whose pad rows
        point every entry at their replica's scratch page."""
        out = np.empty((self.b_padded, table.shape[1]), table.dtype)
        out[self.pos] = table
        for p, r in zip(self.pad_positions, self.pad_replicas):
            out[p, :] = scratch_page(r)
        return out


def pow2_bucket(n: int) -> int:
    """Smallest power of two >= n — THE bucketing grid shared by the
    session scheduler's decode batch and ReplicaGroupPlan's
    bucket_group, so the two padded-shape families can never diverge
    into mismatched compiled programs."""
    b = 1
    while b < n:
        b <<= 1
    return b


def clamp_max_new(max_new: int, max_seq_len: int) -> tuple[int, int]:
    """(clamped max_new, segment-padded decode reserve) — ONE
    definition of the decode-budget clamp for both engines and the
    session scheduler: the same value must bound row budgets at
    admission, size the page reserve, and cap eos_trim at retirement,
    or the scheduler and generate_batch drift on token parity.

    The clamp: decode can never exceed half the context (a
    misconfigured max_new_tokens would otherwise drive the prompt
    budget negative and collapse every prompt to [bos]); the reserve
    rounds up to whole DECODE_SEGMENTs because decode runs in whole
    segment programs whose surplus writes must not clamp onto committed
    cache positions."""
    m = max(1, min(max_new, max_seq_len // 2))
    return m, -(-m // DECODE_SEGMENT) * DECODE_SEGMENT


def prompt_budget(max_seq_len: int, max_new_padded: int) -> int:
    """Prompt-token budget once the padded decode reserve is set aside.

    Raises when fewer than 2 tokens remain — head-truncation keeps
    [bos] + the last (budget-1) tokens, so budget ≤ 1 would silently
    collapse every prompt to [bos]: a config error, not a serving
    condition. One definition for both engines."""
    budget = max_seq_len - max_new_padded - 1
    if budget < 2:
        raise ValueError(
            f"max_seq_len {max_seq_len} leaves no prompt room after the "
            f"{max_new_padded}-token decode reserve (segments pad to "
            f"{DECODE_SEGMENT}) — use max_seq_len > {max_new_padded + 2} "
            "or lower max_new_tokens")
    return budget


def bucket_for(n: int) -> int:
    for b in PREFILL_BUCKETS:
        if n <= b:
            return b
    return MAX_PREFILL_CHUNK


def chunked_prefill(
    dispatch: Callable[[np.ndarray, list[int], np.ndarray], jax.Array],
    token_lists: list[list[int]],
    offsets: list[int],
    max_seq_len: int,
    pad_id: int,
    deadline: float = float("inf"),
    retry=None,
    budget=None,
) -> jax.Array:
    """Bucketed multi-chunk prefill. Returns last-token logits [B, V].

    dispatch(chunk [B, bucket], offs, lengths) runs one device program and
    returns that chunk's last-token logits. Every row writes a bucket-wide
    block at its offset; near the cache end the bucket shrinks so no row's
    write overruns the position-aligned layout (dynamic_update_slice would
    silently clamp the offset and corrupt it). Each row's logits are kept
    from the chunk where its REAL tokens ended — later pad-only chunks
    must not clobber them.

    `budget` (engine/deadlines.py): the prefill rung's Budget. Each
    chunk's dispatch runs under the watchdog at the "dispatch" rung, and
    cooperative cancellation/deadline checks run between chunks (a
    single XLA program cannot be interrupted — the boundaries are where
    a drain or an exhausted ancestor budget takes effect).
    """
    b = len(token_lists)
    if budget is not None:
        deadline = min(deadline, budget.deadline)
    offs = list(offsets)
    remaining = [list(t) for t in token_lists]
    final_logits: Optional[jax.Array] = None
    while any(remaining):
        max_len = min(max(len(r) for r in remaining), MAX_PREFILL_CHUNK)
        bucket = bucket_for(max_len)
        allowed = max_seq_len - max(offs)
        if bucket > allowed:
            smaller = [x for x in PREFILL_BUCKETS if x <= allowed]
            bucket = smaller[-1] if smaller else max(allowed, 1)
        chunk = np.full((b, bucket), pad_id, np.int32)
        lengths = np.zeros((b,), np.int32)
        takes = np.zeros((b,), np.int32)
        for i, r in enumerate(remaining):
            take = min(len(r), bucket)
            takes[i] = take
            if take:
                chunk[i, :take] = r[:take]
                del r[:take]
            # Exhausted rows feed one pad at their current offset; it stays
            # outside their committed length and decode overwrites that
            # position with the first real generated token.
            lengths[i] = max(take, 1)
        if budget is not None:
            budget.check()
        last_logits = run_dispatch(
            lambda: dispatch(chunk, offs, lengths), retry, deadline,
            budget=budget)
        if final_logits is None:
            final_logits = last_logits
        else:
            final_logits = jnp.where(jnp.asarray(takes > 0)[:, None],
                                     last_logits, final_logits)
        for i in range(b):
            offs[i] += int(takes[i])
        if time.monotonic() > deadline and any(remaining):
            raise TimeoutError("prefill timed out")
    return final_logits


def row_budget_fn(per_row, sampling_per_turn, max_new: int) -> Callable:
    """Per-segment remaining-row-budget closure, shared by both engines.

    Only an EXPLICIT sampling_per_turn carries per-row max_new_tokens
    budgets (capped by the call-level max_new) — otherwise the call
    level wins uniformly: the engine-default sampling's budget must not
    silently cap an explicit call request. The prefill-sampled first
    token has already consumed one token of every row's budget, hence
    the -1; `budget` is decode_segments' remaining-global count — kept
    as DEVICE arithmetic so the pipelined segment queue never forces a
    host sync."""
    if sampling_per_turn:
        totals = np.asarray(
            [min(p.max_new_tokens, max_new) for p in per_row], np.int32)
    else:
        totals = np.full(len(per_row), max_new, np.int32)
    totals_dev = jnp.asarray(totals, jnp.int32)

    def remaining(budget) -> jax.Array:
        consumed = jnp.int32(max_new) - jnp.asarray(budget, jnp.int32)
        return jnp.maximum(totals_dev - 1 - consumed, 0)

    return remaining


def decode_segments(
    dispatch: Callable,
    first_token: jax.Array,
    start_valid: jax.Array,
    eos_id: int,
    max_new: int,
    deadline: float,
    timeout_s: float,
    retry=None,
    budget=None,
) -> np.ndarray:
    """Segmented decode: one device program per DECODE_SEGMENT tokens with
    host-side timeout/early-exit checks in between (a single XLA program
    cannot be interrupted, so this is how the adapter's per-turn timeout
    contract is honored). The segment size is ALWAYS DECODE_SEGMENT — a
    variable tail would compile a fresh program per distinct length.

    dispatch(cur_last, cur_valid, budget, done0) → (out, steps, last,
    valid, done) runs one segment; budget may be a DEVICE scalar, done0
    is the [B] done mask carried ACROSS segments (rows at eos / their
    row budget skip further decode). Returns the concatenated token
    matrix [B, produced].

    PIPELINED: the next segment is queued from the previous segment's
    DEVICE outputs (budget decremented and done carried with device
    arithmetic) BEFORE the host reads steps/out/done — so the device
    never idles for the host round-trips between segments (material on
    a high-RTT tunnel). When the just-read segment turns out to have
    finished the generation, the speculative segment's while_loop
    condition is false on entry and it costs microseconds; its results
    are discarded.
    """
    b = first_token.shape[0]
    if budget is not None:
        deadline = min(deadline, budget.deadline)
    segments: list[np.ndarray] = []
    produced = 0
    budget_dev = jnp.int32(max_new)
    first_done = first_token == jnp.int32(eos_id)
    cur = run_dispatch(
        lambda: dispatch(first_token, start_valid, budget_dev, first_done),
        retry, deadline, budget=budget)
    seg_idx = 0
    while True:
        # "segment" span (ISSUE 5): one per consumed decode segment —
        # the null-span singleton when telemetry is disarmed, so the
        # hot loop pays one module-flag check inside span().
        with telemetry.span("segment", index=seg_idx, rows=b):
            out, steps, last, valid, done = cur
            budget_dev = budget_dev - steps
            # Speculative queue while the device results are still in
            # flight — but never past the deadline (the host clock is
            # already known; queuing after it would run a whole wasted
            # segment the timeout then waits on). `produced` lags the
            # just-computed segment, so the bound is an upper bound on
            # "more work possible"; the discard case skips the loop body
            # via the carried done mask (and the gather/scatter around
            # it via the engines' all-done cond), costing microseconds.
            timed_out = time.monotonic() > deadline
            cancelled = budget is not None and budget.token.cancelled
            nxt = (run_dispatch(
                lambda: dispatch(last, valid, budget_dev, done),
                retry, deadline, budget=budget)
                if produced + DECODE_SEGMENT < max_new and not timed_out
                and not cancelled
                else None)

            # The segment's host sync is the blocking wait a wedged
            # device program freezes — it goes through the watchdog
            # seam, not a raw np.asarray (the deadline-seam contract for
            # every blocking device wait in the serving paths).
            def read_segment(steps=steps, out=out, done=done):
                n = int(steps)  # forces completion of the segment
                return (n, np.asarray(out)[:, :n],
                        bool(np.all(np.asarray(done))))

            steps_n, seg, all_done = host_sync(read_segment, budget,
                                               "decode")
            segments.append(seg)
            produced += steps_n
        seg_idx += 1
        if produced >= max_new or all_done:
            break
        if cancelled:
            budget.check()  # raises Cancelled with the drain/abort reason
        if timed_out:
            raise TimeoutError(
                f"generation timed out after {timeout_s:.0f}s "
                f"({produced}/{max_new} tokens)")
        cur = nxt
    return (np.concatenate(segments, axis=1) if segments
            else np.zeros((b, 0), np.int32))


class RaggedSeq:
    """One sequence's slice of a ragged dispatch: the tokens it feeds
    this call (a prefill chunk, the single last-sampled token of a
    decode row, or a speculative ``[last, drafts...]`` verify run), the
    absolute position of the first one, its page table row, and its
    sampling params. `n_scores` is how many TRAILING token rows the
    dispatch must score (ISSUE 9): 1 for plain rows (the last-token
    sample), drafts+1 for a verify run. Host-side description only —
    build_ragged_batch turns a list of these into device inputs."""

    __slots__ = ("tokens", "pos", "table", "temperature", "top_k",
                 "top_p", "n_scores", "adapter")

    def __init__(self, tokens: list[int], pos: int, table: np.ndarray,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, n_scores: int = 1,
                 adapter: int = 0):
        self.tokens = tokens
        self.pos = pos
        self.table = table
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.n_scores = n_scores
        # LoRA adapter SLOT of this sequence (ISSUE 10, 0 = base): the
        # flat buffer mixes sequences with different adapters in one
        # dispatch, so identity rides per TOKEN (token_adapter below) -
        # a value, never a shape.
        self.adapter = adapter


def build_ragged_batch(seqs: list[RaggedSeq], *, t_budget: int,
                       s_max: int, pages_per_seq: int, scratch_page: int,
                       pad_id: int, page_size: int,
                       score_width: int = 0,
                       copy_pairs: Optional[list] = None,
                       copy_slots: int = 0) -> dict:
    """Device inputs for one ragged mixed prefill/decode dispatch.

    Every array has a STATIC shape derived from (t_budget, s_max) alone
    — the composition (how many sequences, how the budget splits
    between prefill chunks and decode tokens) lives entirely in the
    VALUES, so occupancy drift and chunk interleaving never compile a
    new program (the property that retires the pow2 row buckets on this
    path). Each sequence occupies a RAGGED_BLOCK_Q-aligned run of the
    flat buffer; the last slot of s_max is the INERT sequence every pad
    row/block points at (kv_valid=1 over the scratch page, one page of
    throwaway compute per unused block). Pad tokens scatter their K/V
    to the scratch page, which no real sequence ever reads.

    Returns the dict the engine's _ragged_dispatch consumes: flat
    tokens/positions/token_pages/token_offs/token_seq [t_budget],
    per-block seq_of_block/block_qstart [t_budget/8], per-seq
    tables/query_offsets/kv_valid/last_rows/temps/top_ks/top_ps
    [s_max, ...], `greedy`, and the accounting fields n_seqs/n_tokens.

    `score_width` > 0 (ISSUE 9, the speculative verify): the dict also
    carries `sample_rows` [s_max, score_width] — for each sequence, the
    flat-buffer rows of its LAST n_scores tokens (pad columns repeat
    the last row; their scores are computed and discarded). The shape
    is a function of (s_max, score_width) alone — score_width is the
    STATIC spec_max_draft+1, so acceptance drift and per-row throttle
    flips change only values, never the compiled program.

    `copy_slots` > 0 (ISSUE 13, tree verify): the dict also carries
    `copy_src`/`copy_dst` [copy_slots] — page pairs the dispatch must
    device-copy BEFORE its K/V scatter (forward_ragged does it per
    layer). A tree row's candidate paths are separate sequences whose
    tables alias private frontier pages, and the partially-committed
    frontier page's committed cells must exist in each private copy —
    a pre-COW folded into the dispatch. The arrays are padded with
    scratch->scratch self-copies, so how many tree rows (0 included)
    actually need copies is a VALUE; copy_slots is static from engine
    config alone (num_slots), so chain/tree/no-spec mixes never
    compile a new program.
    """
    bq = RAGGED_BLOCK_Q
    if t_budget % bq:
        raise ValueError(f"t_budget {t_budget} not a multiple of {bq}")
    nb = t_budget // bq
    inert = s_max - 1
    if len(seqs) > inert:
        raise ValueError(
            f"{len(seqs)} sequences > {inert} (one slot is the inert "
            "pad sequence)")
    tokens = np.full(t_budget, pad_id, np.int32)
    positions = np.zeros(t_budget, np.int32)
    token_pages = np.full(t_budget, scratch_page, np.int32)
    token_offs = np.zeros(t_budget, np.int32)
    token_seq = np.full(t_budget, inert, np.int32)
    seq_of_block = np.full(nb, inert, np.int32)
    block_qstart = np.zeros(nb, np.int32)
    tables = np.full((s_max, pages_per_seq), scratch_page, np.int32)
    query_offsets = np.zeros(s_max, np.int32)
    kv_valid = np.ones(s_max, np.int32)
    last_rows = np.zeros(s_max, np.int32)
    token_adapter = np.zeros(t_budget, np.int32)
    temps = np.ones(s_max, np.float32)
    top_ks = np.zeros(s_max, np.int32)
    top_ps = np.ones(s_max, np.float32)
    sample_rows = (np.zeros((s_max, score_width), np.int32)
                   if score_width > 0 else None)
    copy_src = copy_dst = None
    if copy_slots > 0:
        pairs = list(copy_pairs or [])
        if len(pairs) > copy_slots:
            raise ValueError(
                f"{len(pairs)} copy pairs > copy_slots {copy_slots}")
        copy_src = np.full(copy_slots, scratch_page, np.int32)
        copy_dst = np.full(copy_slots, scratch_page, np.int32)
        for k, (src, dst) in enumerate(pairs):
            copy_src[k] = src
            copy_dst[k] = dst
    elif copy_pairs:
        raise ValueError("copy_pairs given without copy_slots")

    row = 0
    n_tokens = 0
    for i, s in enumerate(seqs):
        n = len(s.tokens)
        if n < 1:
            raise ValueError("RaggedSeq needs at least one token")
        if s.n_scores < 1 or s.n_scores > n:
            raise ValueError(
                f"n_scores {s.n_scores} outside 1..{n} (run length)")
        if score_width and s.n_scores > score_width:
            raise ValueError(
                f"n_scores {s.n_scores} > score_width {score_width}")
        span = -(-n // bq) * bq
        if row + span > t_budget:
            raise ValueError(
                f"sequences overflow the {t_budget}-token budget")
        tokens[row:row + n] = s.tokens
        # Pad rows inside the span continue the position run — their
        # outputs are dropped, the positions only steer (harmless)
        # causal frontiers.
        positions[row:row + span] = s.pos + np.arange(span)
        pos_n = s.pos + np.arange(n)
        token_pages[row:row + n] = s.table[pos_n // page_size]
        token_offs[row:row + n] = pos_n % page_size
        token_seq[row:row + span] = i
        b0 = row // bq
        for k in range(span // bq):
            seq_of_block[b0 + k] = i
            block_qstart[b0 + k] = k * bq
        tables[i] = s.table
        # Pad rows inside the span keep adapter 0: their K/V lands on
        # the scratch page and their outputs are dropped, so the base
        # (zero) delta is both correct and the cheapest.
        token_adapter[row:row + n] = s.adapter
        query_offsets[i] = s.pos
        kv_valid[i] = s.pos + n
        last_rows[i] = row + n - 1
        if sample_rows is not None:
            first = row + n - s.n_scores
            for j in range(score_width):
                sample_rows[i, j] = min(first + j, row + n - 1)
        temps[i] = s.temperature
        top_ks[i] = s.top_k
        top_ps[i] = s.top_p
        row += span
        n_tokens += n
    return {
        "tokens": tokens, "positions": positions,
        "token_pages": token_pages, "token_offs": token_offs,
        "token_seq": token_seq, "seq_of_block": seq_of_block,
        "block_qstart": block_qstart, "tables": tables,
        "query_offsets": query_offsets, "kv_valid": kv_valid,
        "last_rows": last_rows, "temps": temps, "top_ks": top_ks,
        "top_ps": top_ps, "token_adapter": token_adapter,
        "greedy": all(s.temperature <= 0.0 for s in seqs),
        "n_seqs": len(seqs), "n_tokens": n_tokens,
        "score_width": score_width,
        **({"sample_rows": sample_rows} if sample_rows is not None
           else {}),
        **({"copy_src": copy_src, "copy_dst": copy_dst}
           if copy_src is not None else {}),
    }


def eos_trim(ids: list[int], eos_id: int, max_new: int) -> list[int]:
    """Canonical per-row output epilogue: cut at the first eos, cap at
    max_new. ONE definition shared by finalize_outputs and the session
    scheduler's row retirement so a scheduled row's token stream is
    byte-identical to the same row served by generate_batch."""
    if eos_id in ids:
        ids = ids[:ids.index(eos_id)]
    return ids[:max_new]


def finalize_outputs(turns, first_np: np.ndarray, out_np: np.ndarray,
                     all_tokens: list[list[int]], max_new: int,
                     eos_id: int, commit: Callable[[str, list[int]], None],
                     decode: Callable[[list[int]], str],
                     stats) -> list[str]:
    """Eos-trim each row, commit prompt+fed ids for next-turn prefix
    reuse, detokenize, and account decode tokens into stats."""
    results = []
    for i, (name, _) in enumerate(turns):
        ids = eos_trim([int(first_np[i])] + [int(x) for x in out_np[i]],
                       eos_id, max_new)
        stats.decode_tokens += len(ids)
        # cache now holds prompt + every fed token (= all but the last
        # sampled one); commit exactly that for next-turn prefix reuse
        fed = ids[:-1] if ids else []
        commit(name, all_tokens[i] + fed)
        results.append(decode(ids))
    return results
