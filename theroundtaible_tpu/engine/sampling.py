"""Token sampling — greedy, temperature, top-k, top-p.

Pure jit-safe functions over a logits row; the decode loop composes them
under lax.cond-free arithmetic (temperature 0 → greedy via where, not
Python branching).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.0      # 0 = greedy
    top_k: int = 0                # 0 = disabled
    top_p: float = 1.0            # 1 = disabled
    max_new_tokens: int = 1024


def sample_token(logits: jax.Array, key: jax.Array,
                 params: SamplingParams) -> jax.Array:
    """logits: [B, V] f32 → token ids [B]."""
    greedy = jnp.argmax(logits, axis=-1)
    if params.temperature <= 0.0:
        return greedy

    scaled = logits / jnp.maximum(params.temperature, 1e-6)

    if params.top_k > 0:
        kth = jnp.sort(scaled, axis=-1)[:, -params.top_k][:, None]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)

    if params.top_p < 1.0:
        sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cumulative = jnp.cumsum(probs, axis=-1)
        # keep the smallest set whose cumulative prob >= top_p
        cutoff_idx = jnp.sum(cumulative < params.top_p, axis=-1)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None],
                                     axis=-1)
        scaled = jnp.where(scaled < cutoff, -jnp.inf, scaled)

    return jax.random.categorical(key, scaled, axis=-1)
