"""Token sampling — greedy, temperature, top-k, top-p.

Pure jit-safe functions over a logits row; the decode loop composes them
under lax.cond-free arithmetic (temperature 0 → greedy via where, not
Python branching).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.0      # 0 = greedy
    top_k: int = 0                # 0 = disabled
    top_p: float = 1.0            # 1 = disabled
    max_new_tokens: int = 1024


def sample_token(logits: jax.Array, key: jax.Array,
                 params: SamplingParams) -> jax.Array:
    """logits: [B, V] f32 → token ids [B]."""
    greedy = jnp.argmax(logits, axis=-1)
    if params.temperature <= 0.0:
        return greedy

    scaled = logits / jnp.maximum(params.temperature, 1e-6)

    if params.top_k > 0:
        kth = jnp.sort(scaled, axis=-1)[:, -params.top_k][:, None]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)

    if params.top_p < 1.0:
        sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cumulative = jnp.cumsum(probs, axis=-1)
        # keep the smallest set whose cumulative prob >= top_p
        cutoff_idx = jnp.sum(cumulative < params.top_p, axis=-1)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None],
                                     axis=-1)
        scaled = jnp.where(scaled < cutoff, -jnp.inf, scaled)

    return jax.random.categorical(key, scaled, axis=-1)


def sampling_arrays(params_list: list[SamplingParams]):
    """Per-row (temps, top_ks, top_ps) f32/i32/f32 arrays for
    sample_token_batch."""
    return (jnp.asarray([p.temperature for p in params_list], jnp.float32),
            jnp.asarray([p.top_k for p in params_list], jnp.int32),
            jnp.asarray([p.top_p for p in params_list], jnp.float32))


def sample_token_batch(logits: jax.Array, key: jax.Array,
                       temps: jax.Array, top_ks: jax.Array,
                       top_ps: jax.Array) -> jax.Array:
    """Per-ROW sampling parameters as dynamic arrays: heterogeneous knight
    personas (different temperatures per seat) sample correctly inside ONE
    batched program, and changing a sampling config never recompiles
    (sample_token's Python branches bake the params into the program).

    Row semantics match sample_token exactly: temperature <= 0 → greedy;
    top_k == 0 / top_p == 1.0 → disabled; top-k mask applies before the
    top-p cutoff."""
    v = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1)
    scaled = logits / jnp.maximum(temps[:, None], 1e-6)

    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    k_idx = jnp.clip(top_ks - 1, 0, v - 1)
    kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=-1)
    kth = jnp.where((top_ks > 0)[:, None], kth, -jnp.inf)
    scaled = jnp.where(scaled < kth, -jnp.inf, scaled)

    # re-sort after the top-k mask (-inf entries sink to the tail) so the
    # cumulative cutoff sees the same distribution sample_token does
    sorted2 = jnp.sort(scaled, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(sorted2, axis=-1)
    cumulative = jnp.cumsum(probs, axis=-1)
    cutoff_idx = jnp.clip(
        jnp.sum(cumulative < top_ps[:, None], axis=-1), 0, v - 1)
    cutoff = jnp.take_along_axis(sorted2, cutoff_idx[:, None], axis=-1)
    # top_p == 1.0 means DISABLED (matching sample_token, which skips the
    # cutoff entirely): the f32 cumsum can saturate at 1.0 before the last
    # element, which would otherwise mask far-tail tokens.
    cutoff = jnp.where((top_ps < 1.0)[:, None], cutoff, -jnp.inf)
    scaled = jnp.where(scaled < cutoff, -jnp.inf, scaled)

    sampled = jax.random.categorical(key, scaled, axis=-1)
    return jnp.where(temps <= 0.0, greedy, sampled)
