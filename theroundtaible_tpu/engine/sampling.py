"""Token sampling — greedy, temperature, top-k, top-p.

Pure jit-safe functions over a logits row; the decode loop composes them
under lax.cond-free arithmetic (temperature 0 → greedy via where, not
Python branching).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.0      # 0 = greedy
    top_k: int = 0                # 0 = disabled
    top_p: float = 1.0            # 1 = disabled
    max_new_tokens: int = 1024


def sample_token(logits: jax.Array, key: jax.Array,
                 params: SamplingParams) -> jax.Array:
    """logits: [B, V] f32 → token ids [B]."""
    greedy = jnp.argmax(logits, axis=-1)
    if params.temperature <= 0.0:
        return greedy

    scaled = logits / jnp.maximum(params.temperature, 1e-6)

    if params.top_k > 0:
        kth = jnp.sort(scaled, axis=-1)[:, -params.top_k][:, None]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)

    if params.top_p < 1.0:
        sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cumulative = jnp.cumsum(probs, axis=-1)
        # keep the smallest set whose cumulative prob >= top_p
        cutoff_idx = jnp.sum(cumulative < params.top_p, axis=-1)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None],
                                     axis=-1)
        scaled = jnp.where(scaled < cutoff, -jnp.inf, scaled)

    return jax.random.categorical(key, scaled, axis=-1)


def sampling_arrays(params_list: list[SamplingParams]):
    """Per-row (temps, top_ks, top_ps) f32/i32/f32 arrays for
    sample_token_batch."""
    return (jnp.asarray([p.temperature for p in params_list], jnp.float32),
            jnp.asarray([p.top_k for p in params_list], jnp.int32),
            jnp.asarray([p.top_p for p in params_list], jnp.float32))


# Candidate-pool size for the sort-free fast path below. Covers every
# practical top_k (configs use tens); rows whose top_k or top-p cutoff
# exceeds it take the exact full-sort fallback via lax.cond.
_K_CAND = 128


def _exact_tail(scaled, top_ks, top_ps):
    """The original full-sort threshold computation — two descending
    sorts over the whole vocab. Kept as the exact fallback for rows the
    candidate pool cannot prove correct."""
    v = scaled.shape[-1]
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    k_idx = jnp.clip(top_ks - 1, 0, v - 1)
    kth = jnp.take_along_axis(sorted_desc, k_idx[:, None], axis=-1)
    kth = jnp.where((top_ks > 0)[:, None], kth, -jnp.inf)
    scaled = jnp.where(scaled < kth, -jnp.inf, scaled)

    # re-sort after the top-k mask (-inf entries sink to the tail) so the
    # cumulative cutoff sees the same distribution sample_token does
    sorted2 = jnp.sort(scaled, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(sorted2, axis=-1)
    cumulative = jnp.cumsum(probs, axis=-1)
    cutoff_idx = jnp.clip(
        jnp.sum(cumulative < top_ps[:, None], axis=-1), 0, v - 1)
    cutoff = jnp.take_along_axis(sorted2, cutoff_idx[:, None], axis=-1)
    # top_p == 1.0 means DISABLED (matching sample_token, which skips the
    # cutoff entirely): the f32 cumsum can saturate at 1.0 before the last
    # element, which would otherwise mask far-tail tokens.
    cutoff = jnp.where((top_ps < 1.0)[:, None], cutoff, -jnp.inf)
    return jnp.where(scaled < cutoff, -jnp.inf, scaled)


def sampler_mode(params_list: list[SamplingParams]) -> str:
    """Which path sample_token_batch takes for a batch of these per-row
    params — bench provenance (ISSUE 3 satellite: the sort-free sampler
    gets an ATTRIBUTABLE number): "greedy" (every row temp <= 0, single
    argmax — no sampler at all), "sort" (some row's top_k exceeds the
    _K_CAND candidate pool, forcing the exact full-vocab sort fallback),
    or "sort-free" (the candidate-pool fast path; boundary rows whose
    top-p mass outruns the pool may still cond into the exact tail, but
    the hot case stays sort-free)."""
    if all(p.temperature <= 0.0 for p in params_list):
        return "greedy"
    if any(p.top_k > _K_CAND for p in params_list):
        return "sort"
    return "sort-free"


def sample_token_batch(logits: jax.Array, key: jax.Array,
                       temps: jax.Array, top_ks: jax.Array,
                       top_ps: jax.Array) -> jax.Array:
    """Per-ROW sampling parameters as dynamic arrays: heterogeneous knight
    personas (different temperatures per seat) sample correctly inside ONE
    batched program, and changing a sampling config never recompiles
    (sample_token's Python branches bake the params into the program).

    Row semantics match sample_token exactly: temperature <= 0 → greedy;
    top_k == 0 / top_p == 1.0 → disabled; top-k mask applies before the
    top-p cutoff.

    Fast path (the decode-loop hot case): the two thresholds the filters
    need — the k-th logit and the top-p cutoff — are found in a
    `lax.top_k(_K_CAND)` candidate pool instead of two full-vocab
    descending SORTS (at a 256k vocab those sorts dominated sampled
    decode: BENCH_r05 config 2 decoded at ~140 tok/s vs greedy's 205).
    The candidate prefix IS the full sort's prefix, and the softmax is
    recomputed with the same ops (exp of max-shifted values over the
    kept-set sum — max and sum are plain reductions, no sort). The kth
    threshold is exact; the top-p cutoff matches the fallback's up to
    reduction-ORDER rounding of the softmax denominator (the fallback
    sums exps in sorted order, this path in vocab order — ≤ ~1 ulp),
    which can move the kept set by one boundary token only when some
    cumulative value straddles top_p within that rounding. The draw
    stays full-vocab under the SAME key either way. Rows the pool
    cannot prove correct (top_k > _K_CAND, or candidate mass short of
    top_p) trigger the exact full-sort tail via lax.cond — compiled
    once, executed only when needed."""
    v = logits.shape[-1]
    k_cand = min(_K_CAND, v)
    greedy = jnp.argmax(logits, axis=-1)
    scaled = logits / jnp.maximum(temps[:, None], 1e-6)

    cand = jax.lax.top_k(scaled, k_cand)[0]          # [B, k] descending
    k_idx = jnp.clip(top_ks - 1, 0, k_cand - 1)
    kth = jnp.take_along_axis(cand, k_idx[:, None], axis=-1)
    kth = jnp.where((top_ks > 0)[:, None], kth, -jnp.inf)
    m1 = jnp.where(scaled < kth, -jnp.inf, scaled)
    cand1 = jnp.where(cand < kth, -jnp.inf, cand)    # prefix of sort(m1)

    # softmax over the kept set without sorting — the same exp(x - max)
    # / sum ops jax.nn.softmax uses (only the sum's element ORDER can
    # differ; see docstring)
    m_max = jnp.max(m1, axis=-1, keepdims=True)
    denom = jnp.sum(jnp.exp(m1 - m_max), axis=-1, keepdims=True)
    cum = jnp.cumsum(jnp.exp(cand1 - m_max) / denom, axis=-1)
    cutoff_idx = jnp.clip(
        jnp.sum(cum < top_ps[:, None], axis=-1), 0, k_cand - 1)
    cutoff = jnp.take_along_axis(cand1, cutoff_idx[:, None], axis=-1)
    cutoff = jnp.where((top_ps < 1.0)[:, None], cutoff, -jnp.inf)
    masked_fast = jnp.where(m1 < cutoff, -jnp.inf, m1)

    # rows the candidate pool cannot prove: kth outside the pool, or the
    # top-p cutoff beyond the pool's cumulative mass
    bad = (temps > 0.0) & ((top_ks > k_cand)
                           | ((top_ps < 1.0) & (cum[:, -1] < top_ps)))
    # Per-ROW blend, not a batch-wide switch (advisor r5): only the bad
    # rows take the exact full-sort logits; provable rows keep the fast
    # path's even when a batchmate is bad, so a row's sampled token never
    # depends on which other rows share the batch (the fast and exact
    # cutoffs can differ by one ≤~1-ulp boundary token — see docstring).
    # Cost tradeoff: when ANY row is bad the exact tail still computes
    # for the whole batch (its sorts are full-vocab either way); the
    # lax.cond keeps the all-good hot case sort-free.
    masked = jax.lax.cond(
        jnp.any(bad),
        lambda s: jnp.where(bad[:, None], _exact_tail(s, top_ks, top_ps),
                            masked_fast),
        lambda s: masked_fast, scaled)

    sampled = jax.random.categorical(key, masked, axis=-1)
    return jnp.where(temps <= 0.0, greedy, sampled)
