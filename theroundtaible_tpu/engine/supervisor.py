"""Engine supervision — tear a sick engine down, rebuild it, put the
sessions back.

PRs 1/2 recover DISPATCHES (retry → revive → serial fallback →
breaker) and PR 7 made idle KV restorable, but nothing owned the engine
LIFECYCLE: a lost device, a program wedged past the ladder, or an
operator's rolling restart still killed every live session, because no
component could say "this engine is done — quiesce it, rebuild it from
its config, and restore the sessions onto the fresh instance". RTP-LLM
(PAPERS.md) treats exactly this supervised-lifecycle-with-state-handoff
as table stakes for production serving. This module is that layer
(ISSUE 12 tentpole), the robustness spine the serving gateway (ROADMAP
item 1) and the multi-replica tier (item 2) stand on.

The restart cycle (ARCHITECTURE.md "Supervision & recovery"):

1. **Detect.** Three triggers route here instead of the dispatch
   ladder: a `device_lost`-classified failure (the chip itself is gone
   — `core/errors` classifies it FIRST and `faults.RetryPolicy` never
   retries it in place), repeated `hang`-kind failures past the ladder
   (`hang_threshold` consecutive — one hang is the watchdog's business,
   a stream of them means the ENGINE is wedged), or an explicit
   `supervisor().restart(engine)` (rolling restarts, operator action).
   `ROUNDTABLE_SUPERVISOR=0` disarms auto-detection; explicit restarts
   always work.
2. **Quiesce.** The scheduler's admission gate closes
   (`pause_admission` — submits still QUEUE, they are served after the
   restart; nothing is rejected on the rolling path) and in-flight
   requests either finish (manual restart: `quiesce()` waits for
   retirement) or preempt-fail into their adapters' PR-1 ladders
   (crash path: their turn state is gone with the dispatch anyway).
3. **Evacuate.** `HostOffloadTier.evacuate()` moves every remaining
   session fully to host RAM and returns a restorable manifest —
   pool-independent records the fresh engine's tier `adopt()`s, so a
   session idles ACROSS the restart with its KV byte-identical.
4. **Rebuild.** A fresh engine from the SAME config
   (`engine._engine_config`, captured at construction) under bounded
   exponential backoff; `build_attempts` construction failures burn one
   restart, and `max_restarts` exhausted marks the engine DEAD — later
   submits fail fast with a clean classified error and
   `fleet_health()["supervisor"]` says why.
5. **Restore.** The scheduler re-attaches to the fresh engine
   (`compile_watch.reopen_warmup` — post-restart compiles are a
   SANCTIONED warmup phase, so ROUNDTABLE_RECOMPILE_STRICT serving
   crosses a restart without a violation), evacuated sessions restore
   eagerly (failures stay adopted and restore lazily at next submit —
   `_prepare_batch`'s restore seam), and the paused queue resumes.

Everything is observable: `roundtable_engine_restarts_total{reason}`,
`roundtable_engine_restart_seconds`,
`roundtable_sessions_{recovered,lost}_total`, a `supervisor` flight
dump per restart, and `fleet_health()["supervisor"]` / `roundtable
status --health` render the restart history.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..core.errors import AdapterError, classify_error
from ..utils import telemetry

_HISTORY_CAP = 32

# Test-visibility counters (tests/conftest.py `supervision` marker
# guard): a marked test that never crossed an engine restart fails
# LOUD — the supervision it claims to cover silently never ran.
_test_restarts = 0
_test_lock = threading.Lock()


def reset_test_counters() -> None:
    global _test_restarts
    with _test_lock:
        _test_restarts = 0


def restarts_seen() -> int:
    return _test_restarts


def _note_restart() -> None:
    global _test_restarts
    with _test_lock:
        _test_restarts += 1


def supervision_enabled() -> bool:
    """Auto-detection kill switch: ROUNDTABLE_SUPERVISOR=0 keeps the
    PR-1/2 ladder behavior byte-identical (failures surface to the
    adapters; nothing rebuilds). Explicit restart() calls ignore it."""
    return os.environ.get("ROUNDTABLE_SUPERVISOR", "1") not in (
        "0", "false", "off")


# Death notifications (ISSUE 17): the router subscribes so an engine
# that exhausts its restart budget triggers failover of its sessions
# onto surviving replicas. Module-level on purpose — the singleton is
# swapped freely by tests (set_supervisor(None)), and a subscription
# must survive the swap. Callbacks receive (engine, reason, kind) and
# must never raise into the restart path.
_death_callbacks: list = []


def on_engine_dead(cb) -> None:
    if cb not in _death_callbacks:
        _death_callbacks.append(cb)


def remove_death_callback(cb) -> None:
    if cb in _death_callbacks:
        _death_callbacks.remove(cb)


def _notify_dead(engine, reason: str, kind: str) -> None:
    for cb in list(_death_callbacks):
        try:
            cb(engine, reason, kind)
        except Exception:  # noqa: BLE001 — containment must not re-crash
            pass


def engine_key(engine) -> str:
    """Stable identity for supervision state: the engine-cache key when
    the engine came through get_engine (the rebuilt instance inherits
    it), else a per-INSTANCE direct key (tests, ad-hoc engines) made
    sticky by writing it back onto the engine — so two unrelated
    engines that happen to share a model name never pool hang counts or
    restart budgets, while a rebuilt engine (which copies the key)
    keeps its predecessor's budget."""
    key = getattr(engine, "_engine_cache_key", None)
    if key:
        return key
    name = getattr(getattr(engine, "cfg", None), "name", "?")
    key = f"direct:{name}@{id(engine):x}"
    try:
        engine._engine_cache_key = key
    except (AttributeError, TypeError):  # frozen/slotted test doubles
        pass
    return key


class EngineDead(AdapterError):
    """The supervisor exhausted this engine's restart budget — serving
    on it can never succeed again in this process."""

    def __init__(self, message: str, kind: str = "unknown"):
        super().__init__(message, kind=kind)


@dataclass
class _EngineState:
    key: str
    name: str = "engine"
    restarts: int = 0
    failed_restarts: int = 0
    consecutive_hangs: int = 0
    last_hang_at: Optional[float] = None
    dead: bool = False
    dead_reason: str = ""
    dead_kind: str = "unknown"
    last_restart_s: Optional[float] = None
    history: list = field(default_factory=list)

    def note_history(self, entry: dict) -> None:
        self.history.append(entry)
        del self.history[:-_HISTORY_CAP]

    def snapshot(self) -> dict:
        return {
            "engine": self.name,
            "restarts": self.restarts,
            "failed_restarts": self.failed_restarts,
            "consecutive_hangs": self.consecutive_hangs,
            "dead": self.dead,
            "dead_reason": self.dead_reason,
            "last_restart_s": self.last_restart_s,
            "history": list(self.history),
        }


class EngineSupervisor:
    """Supervised engine lifecycle: detection thresholds, the restart
    budget, and the quiesce → evacuate → rebuild → restore cycle."""

    def __init__(self, *, max_restarts: int = 3,
                 build_attempts: int = 3,
                 backoff_s: float = 0.2, backoff_mult: float = 2.0,
                 hang_threshold: int = 2,
                 hang_window_s: float = 60.0,
                 quiesce_timeout_s: float = 30.0):
        self.max_restarts = max_restarts
        self.build_attempts = build_attempts
        self.backoff_s = backoff_s
        self.backoff_mult = backoff_mult
        self.hang_threshold = hang_threshold
        self.hang_window_s = hang_window_s
        self.quiesce_timeout_s = quiesce_timeout_s
        self._states: dict[str, _EngineState] = {}
        self._lock = threading.Lock()
        # Serializes whole restart cycles: two threads must never
        # rebuild one engine concurrently (double-built engines, torn
        # spill adoption).
        self._restart_lock = threading.Lock()
        self.restarts = 0
        self.sessions_recovered = 0
        self.sessions_lost = 0

    # --- state ---

    def _state_for(self, engine) -> _EngineState:
        key = engine_key(engine)
        with self._lock:
            st = self._states.get(key)
            if st is None:
                st = self._states[key] = _EngineState(
                    key=key,
                    name=getattr(getattr(engine, "cfg", None), "name",
                                 "engine"))
            return st

    def dead_reason(self, engine) -> Optional[str]:
        """Why this engine is beyond restarting (None while it isn't) —
        the scheduler's submit gate fails fast on it."""
        with self._lock:
            st = self._states.get(engine_key(engine))
        if st is not None and st.dead:
            return st.dead_reason
        return None

    def reset(self, engine=None) -> None:
        """Forget supervision state (operator override / tests): one
        engine's, or everything."""
        with self._lock:
            if engine is None:
                self._states.clear()
            else:
                self._states.pop(engine_key(engine), None)

    def snapshot(self) -> dict[str, Any]:
        """fleet_health()["supervisor"]: restart totals + per-engine
        state with the bounded restart history."""
        with self._lock:
            states = [st.snapshot() for st in self._states.values()]
        return {
            "restarts": self.restarts,
            "sessions_recovered": self.sessions_recovered,
            "sessions_lost": self.sessions_lost,
            "dead_engines": sum(1 for s in states if s["dead"]),
            "engines": states,
        }

    # --- detection ---

    def handle_dispatch_failure(self, sched, err: BaseException) -> bool:
        """The scheduler-thread detection seam, called after a shared
        dispatch failure that a revive did not explain. Decides whether
        this failure is ENGINE-fatal (device_lost; repeated hangs past
        the ladder; an already-dead engine) and, when it is, performs
        the supervised restart inline — the caller's active requests
        are failed into their adapter ladders as part of the cycle.
        Returns True when the engine was torn down (callers stop
        touching it); False routes the failure to the normal
        preempt-isolate ladder."""
        if not supervision_enabled():
            return False
        engine = sched.engine
        st = self._state_for(engine)
        if st.dead:
            dead = EngineDead(
                f"engine {st.name!r} is dead: {st.dead_reason}",
                kind=st.dead_kind)
            sched.fail_active_requests(dead)
            return True
        kind = classify_error(err)
        if kind == "device_lost":
            trigger = "device_lost"
        elif kind == "hang":
            # "Consecutive" is bounded in TIME, not just in failure
            # order: healthy dispatches never report here, so without a
            # window two unrelated hangs hours apart would read as an
            # escalation.
            now = time.monotonic()
            if (st.last_hang_at is not None
                    and now - st.last_hang_at > self.hang_window_s):
                st.consecutive_hangs = 0
            st.last_hang_at = now
            st.consecutive_hangs += 1
            if st.consecutive_hangs < self.hang_threshold:
                return False
            trigger = "hang_escalation"
        else:
            st.consecutive_hangs = 0
            return False
        if getattr(engine, "_engine_config", None) is None:
            # No rebuild recipe — record the verdict, let the ladder
            # degrade as before (better a sick engine serving retries
            # than a supervisor that can only destroy).
            telemetry.recorder().record(
                "supervisor_unrebuildable", engine=st.name,
                trigger=trigger)
            return False
        try:
            self.restart(engine, reason=trigger, cause=err,
                         scheduler=sched)
        except EngineDead:
            pass    # sessions already failed with the classified error
        except Exception:  # noqa: BLE001 — budgeted failure
            # The cycle failed inside its budget: actives were already
            # failed into their ladders, the queue reopened — the next
            # fatal failure triggers the next (budgeted) attempt.
            pass
        return True

    # --- the restart cycle ---

    def restart(self, engine, *, reason: str = "manual",
                cause: Optional[BaseException] = None,
                scheduler=None,
                rebuild: Optional[Callable[[], Any]] = None,
                warm_batches: Optional[tuple[int, ...]] = None) -> dict:
        """One full supervised restart of `engine`. Returns a report
        dict; raises EngineDead when the restart budget is exhausted
        (the engine is marked dead first, so every later submit fails
        fast with the same classified reason)."""
        with self._restart_lock:
            return self._restart_locked(
                engine, reason=reason, cause=cause, scheduler=scheduler,
                rebuild=rebuild, warm_batches=warm_batches)

    def _restart_locked(self, engine, *, reason, cause, scheduler,
                        rebuild, warm_batches) -> dict:
        st = self._state_for(engine)
        name = st.name
        if st.dead:
            raise EngineDead(
                f"engine {name!r} is dead: {st.dead_reason}",
                kind=st.dead_kind)
        t0 = time.monotonic()
        sched = scheduler
        if sched is None:
            cand = getattr(engine, "_scheduler", None)
            if (cand is not None and not cand.closed
                    and cand.engine is engine):
                sched = cand
        if st.restarts >= self.max_restarts:
            # Budget bounds restart CYCLES, successful or not: an
            # engine that keeps needing rebuilds is flapping — stop
            # serving it before the flapping eats the fleet's wall.
            self._mark_dead(st, engine, sched, cause=cause)
            report = {"engine": name, "reason": reason, "ok": False,
                      "dead": True,
                      "cause": str(cause)[:200] if cause else None}
            # counted=False: no cycle ran — this refusal must not
            # inflate restart totals or put a ~0s sample into the
            # recovery-wall histogram.
            self._finish(st, report, t0, reason, counted=False)
            raise EngineDead(
                f"engine {name!r} is dead: {st.dead_reason}",
                kind=st.dead_kind)
        on_sched_thread = (
            sched is not None
            and threading.current_thread() is sched._thread)
        report: dict[str, Any] = {
            "engine": name, "reason": reason, "restart": st.restarts + 1,
            "cause": str(cause)[:200] if cause else None,
        }
        telemetry.recorder().record(
            "supervisor_restart_begin", engine=name, reason=reason,
            error=str(cause or "")[:200])

        fail_err = cause or RuntimeError(
            f"engine {name!r} restarting ({reason})")
        evac_sessions: list[str] = []
        own_lock = False
        try:
            # The whole cycle is a SANCTIONED warmup phase for this
            # label: the evacuation's spill gathers and the fresh
            # engine's construction/warmup compiles must not read as
            # steady-state violations under ROUNDTABLE_RECOMPILE_STRICT
            # (reattach_engine reopens again after the swap; the owner
            # re-declares once post-restart traffic is warm).
            from . import compile_watch
            compile_watch.reopen_warmup(name)
            # --- quiesce ---
            if sched is not None:
                sched.pause_admission(f"supervisor:{reason}")
                if on_sched_thread:
                    # Crash path, on the serving thread itself: the
                    # failed dispatch's requests cannot finish — fail
                    # them into their adapter ladders now.
                    report["requests_failed"] = \
                        sched.fail_active_requests(fail_err)
                else:
                    drained = sched.quiesce(self.quiesce_timeout_s)
                    report["quiesced_clean"] = drained
                    if not drained:
                        report["requests_failed"] = \
                            sched.force_fail_active(
                                fail_err, timeout_s=5.0)
            if not (on_sched_thread and sched is not None
                    and sched._lock_held):
                # Serialize against direct generate_batch callers; the
                # scheduler thread already holds the serve lock on the
                # crash path.
                lock = getattr(engine, "_serve_lock", None)
                if lock is not None:
                    if not lock.acquire(timeout=self.quiesce_timeout_s):
                        raise TimeoutError(
                            f"engine {name!r} serve lock never freed — "
                            "an in-flight turn outlived the quiesce "
                            "window; restart aborted")
                    own_lock = True

            # --- evacuate ---
            tier = getattr(engine, "kv_offload", None)
            if tier is not None:
                try:
                    manifest = tier.evacuate()
                    evac_sessions = list(manifest["sessions"])
                    report["evacuated"] = {
                        "sessions": len(evac_sessions),
                        "pages_moved": manifest["pages_moved"],
                        "host_bytes": manifest["host_bytes"],
                    }
                except Exception as e:  # noqa: BLE001 — dead pool
                    # A lost device can make the pool unreadable: KV
                    # still resident in it is gone (those sessions'
                    # next turn re-prefills from the transcript /
                    # journal). Sessions ALREADY fully host-resident
                    # survive the pool — adopt() grafts them onto the
                    # fresh tier below and they restore normally, so
                    # they count recovered, not lost.
                    recoverable = set(tier.restorable_sessions())
                    kv = getattr(engine, "kv", None)
                    lost = set()
                    if kv is not None:
                        from .kvcache import session_of
                        try:
                            lost = {session_of(n)
                                    for n in kv.slot_names()} - {""}
                        except Exception:  # noqa: BLE001
                            pass
                    lost |= set(tier.spilled_sessions())
                    lost -= recoverable
                    evac_sessions = sorted(recoverable)
                    report["evacuation_error"] = str(e)[:200]
                    report["sessions_lost"] = len(lost)
                    self._note_lost(len(lost))
                    # A lost session never retires through the
                    # scheduler, so nothing downstream removes its
                    # per-session KV gauge — drop it here or the
                    # registry keeps one dead series per session the
                    # dead pool took (the RT-GAUGE-LEAK rule's
                    # first real-world target, ISSUE 15).
                    self._drop_session_gauges(engine, lost)

            # --- rebuild (bounded exponential backoff) ---
            build = rebuild
            if build is None:
                cfg = getattr(engine, "_engine_config", None)
                if cfg is None:
                    raise RuntimeError(
                        f"engine {name!r} has no rebuild recipe "
                        "(_engine_config) — construct it via "
                        "from_config/get_engine or pass rebuild=")
                build = lambda: type(engine).from_config(dict(cfg))  # noqa: E731
            new_engine = None
            last_err: Optional[BaseException] = None
            for attempt in range(self.build_attempts):
                try:
                    new_engine = build()
                    break
                except Exception as e:  # noqa: BLE001 — budgeted
                    last_err = e
                    telemetry.recorder().record(
                        "supervisor_rebuild_failed", engine=name,
                        attempt=attempt, error=str(e)[:200])
                    if attempt + 1 < self.build_attempts:
                        time.sleep(self.backoff_s
                                   * (self.backoff_mult ** attempt))
            if new_engine is None:
                st.failed_restarts += 1
                raise RuntimeError(
                    f"engine {name!r} rebuild failed after "
                    f"{self.build_attempts} attempt(s): {last_err}"
                ) from last_err

            # --- adopt + warm + restore ---
            new_engine._engine_config = getattr(
                engine, "_engine_config", None)
            if getattr(engine, "_engine_cache_key", None):
                new_engine._engine_cache_key = engine._engine_cache_key
            new_tier = getattr(new_engine, "kv_offload", None)
            if tier is not None and new_tier is not None:
                adopted = new_tier.adopt(tier)
                report["adopted_sessions"] = len(adopted)
            if warm_batches is not None:
                new_engine.warmup(batch_sizes=tuple(warm_batches))
            restored = 0
            if new_tier is not None and evac_sessions:
                for s in evac_sessions:
                    try:
                        restored += 1 if new_tier.restore_session(s) \
                            else 0
                    except Exception:  # noqa: BLE001 — lazy restore
                        # The record was re-filed intact (restore is
                        # all-or-nothing): it restores at the session's
                        # next submit through _prepare_batch instead.
                        pass
                report["restored_sessions"] = restored
                self._note_recovered(len(evac_sessions))

            # --- swap + re-attach ---
            if own_lock:
                engine._serve_lock.release()
                own_lock = False
            elif (on_sched_thread and sched is not None
                  and sched._lock_held):
                sched._release_engine()
            from . import replace_engine
            replace_engine(engine, new_engine)
            cfg = getattr(engine, "_engine_config", None)
            if cfg is not None:
                try:
                    from . import get_breaker
                    get_breaker(cfg).reset()
                except Exception:  # noqa: BLE001 — breaker is advisory
                    pass
            if sched is not None:
                sched.reattach_engine(new_engine)
            report["ok"] = True
        except BaseException as e:
            report["ok"] = False
            report["error"] = str(e)[:200]
            if own_lock:
                engine._serve_lock.release()
            st.restarts += 1
            if st.restarts >= self.max_restarts:
                # This failed cycle consumed the last budget: fail the
                # sessions with the classified reason NOW instead of
                # letting the next trigger discover the corpse.
                self._mark_dead(st, engine, sched, cause=e)
                report["dead"] = True
            elif sched is not None:
                # Budget remains: leave the (old) engine serving its
                # ladder — admission reopens so queued sessions fail
                # through their adapters rather than starving.
                sched.reopen_admission()
            self._finish(st, report, t0, reason)
            if st.dead:
                raise EngineDead(
                    f"engine {name!r} is dead: {st.dead_reason}",
                    kind=st.dead_kind) from e
            raise
        # --- resume ---
        st.restarts += 1
        st.consecutive_hangs = 0
        if sched is not None:
            sched.reopen_admission()
        self._finish(st, report, t0, reason)
        return report

    # --- bookkeeping ---

    def _mark_dead(self, st: _EngineState, engine, sched,
                   cause: Optional[BaseException]) -> None:
        st.dead = True
        st.dead_kind = (classify_error(cause) if cause is not None
                        else "unknown")
        st.dead_reason = (
            f"restart budget exhausted ({st.restarts} restart(s), "
            f"budget {self.max_restarts})"
            + (f": {str(cause)[:200]}" if cause else ""))
        dead = EngineDead(
            f"engine {st.name!r} is dead: {st.dead_reason}",
            kind=st.dead_kind)
        lost = 0
        if sched is not None:
            # Sessions fail with a CLEAN classified error, not a
            # timeout: queued requests reject now; actives fail
            # directly when we ARE the loop thread (the crash path —
            # posting a mailbox to ourselves and waiting on it would
            # stall serving for the full timeout and count nothing),
            # else on the loop's next health check.
            lost += sched.reject_queued(dead)
            if threading.current_thread() is sched._thread:
                lost += sched.fail_active_requests(dead)
            else:
                lost += sched.force_fail_active(dead, timeout_s=5.0)
            sched.reopen_admission()  # submit gate fails fast instead
        self._note_lost(lost)
        cfg = getattr(engine, "_engine_config", None)
        if cfg is not None:
            try:
                from . import get_breaker
                get_breaker(cfg).trip(dead)
            except Exception:  # noqa: BLE001 — breaker is advisory
                pass
        # Every session this dead engine still holds — evacuated to
        # the host tier in an earlier (failed) cycle, or active on a
        # loop the force-fail may never reach — is lost WITHOUT ever
        # retiring through the scheduler, which is the only path that
        # removes its roundtable_session_kv_bytes series. Remove them
        # here: the registry (and every metrics.prom export) must not
        # carry one stale series per session a dead engine took down
        # (ISSUE 15 bugfix; regression-tested in tests/test_analysis).
        stale: set = set()
        tier = getattr(engine, "kv_offload", None)
        if tier is not None:
            try:
                stale |= set(tier.spilled_sessions())
            except Exception:  # noqa: BLE001 — dead tier
                pass
        if sched is not None:
            stale |= {r.session for r in list(sched._active_reqs)}
        self._drop_session_gauges(engine, stale)
        # Replica-labeled when the engine serves as a router replica
        # (ISSUE 17): `roundtable_engine_dead{replica=}` — the router
        # removes the series when the replica is retired, so the
        # registry never keeps one dead series per replica ever rolled.
        rname = getattr(engine, "_replica_name", None)
        if rname is not None:
            telemetry.set_gauge("roundtable_engine_dead", 1.0,
                                engine=st.name, replica=rname)
        else:
            telemetry.set_gauge("roundtable_engine_dead", 1.0,
                                engine=st.name)
        telemetry.recorder().record(
            "supervisor_engine_dead", engine=st.name,
            reason=st.dead_reason)
        # Failure containment (ISSUE 17): tell subscribers (the session
        # router) AFTER the dead state is fully published — the router
        # migrates this engine's journaled sessions to survivors.
        _notify_dead(engine, st.dead_reason or "", st.dead_kind)

    @staticmethod
    def _drop_session_gauges(engine, sessions) -> None:
        """Remove the per-session KV gauge series for sessions the
        supervisor counted LOST — they will never retire through the
        scheduler's remove path. Best-effort: gauge hygiene must never
        turn a restart failure into a crash."""
        perf = getattr(engine, "perf", None)
        if perf is None:
            return
        for s in sessions:
            try:
                perf.publish_session_kv(s, 0)
            except Exception:  # noqa: BLE001 — hygiene only
                pass

    def _note_recovered(self, n: int) -> None:
        if n:
            self.sessions_recovered += n
            telemetry.inc("roundtable_sessions_recovered_total", n)

    def _note_lost(self, n: int) -> None:
        if n:
            self.sessions_lost += n
            telemetry.inc("roundtable_sessions_lost_total", n)

    def _finish(self, st: _EngineState, report: dict, t0: float,
                reason: str, counted: bool = True) -> None:
        """counted=False records history + the flight dump but keeps
        the restart totals and the recovery-wall histogram honest: a
        request REFUSED at entry (budget already exhausted) is not a
        restart cycle."""
        wall = time.monotonic() - t0
        st.last_restart_s = round(wall, 3)
        report["wall_s"] = round(wall, 3)
        st.note_history({k: report.get(k) for k in
                         ("reason", "restart", "ok", "dead", "wall_s",
                          "cause", "error", "restored_sessions")})
        if counted:
            self.restarts += 1
            _note_restart()
            telemetry.inc("roundtable_engine_restarts_total",
                          reason=reason)
            telemetry.REGISTRY.observe(
                "roundtable_engine_restart_seconds", wall)
        # Every restart is an incident with a postmortem (the PR-5
        # flight-recorder discipline): the dump carries the ring —
        # scheduler decisions, the triggering fault — plus this report.
        telemetry.flight_dump("supervisor", extra=dict(report))


# --- process-global supervisor (the breaker-registry pattern) ---

_supervisor: Optional[EngineSupervisor] = None
_supervisor_lock = threading.Lock()


def supervisor() -> EngineSupervisor:
    """The process supervisor singleton — schedulers and fleet surfaces
    share one budget/history store, exactly like the breaker cache."""
    global _supervisor
    with _supervisor_lock:
        if _supervisor is None:
            _supervisor = EngineSupervisor()
        return _supervisor


def set_supervisor(sup: Optional[EngineSupervisor]) -> None:
    """Install a configured supervisor (tests, operators tuning
    budgets). None restores a fresh default on next use."""
    global _supervisor
    with _supervisor_lock:
        _supervisor = sup


def engine_dead_reason(engine) -> Optional[str]:
    """Why `engine` is beyond restarting, without constructing a
    supervisor (the scheduler's submit-gate fast path: one lock + dict
    probe when supervision has never run)."""
    with _supervisor_lock:
        sup = _supervisor
    return sup.dead_reason(engine) if sup is not None else None


def supervisor_snapshot() -> dict[str, Any]:
    """fleet_health's view: never constructs state, cheap when nothing
    has ever restarted."""
    with _supervisor_lock:
        sup = _supervisor
    if sup is None:
        return {"restarts": 0, "sessions_recovered": 0,
                "sessions_lost": 0, "dead_engines": 0, "engines": []}
    return sup.snapshot()
