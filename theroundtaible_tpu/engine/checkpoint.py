"""Checkpoint loading: HuggingFace layouts → the engine's param tree.

Supports safetensors shards and torch .bin shards from a local directory
(gemma/llama/mistral HF layouts share the same module naming), plus orbax
save/restore of the engine's native tree for fast TPU reloads. Random init
is the fallback when no checkpoint is configured (tests, benches).

HF name map (all families):
  model.embed_tokens.weight                  → embedding            [V, E]
  model.layers.N.self_attn.{q,k,v}_proj      → {q,k,v}_proj         [E, H, D]
  model.layers.N.self_attn.o_proj            → o_proj               [H, D, E]
  model.layers.N.mlp.{gate,up,down}_proj     → {gate,up,down}_proj
  model.layers.N.input_layernorm             → input_norm
  model.layers.N.post_attention_layernorm    → pre_mlp_norm
  model.norm.weight                          → final_norm
  lm_head.weight                             → lm_head (untied only)
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Callable, Iterator

import jax.numpy as jnp
import numpy as np

from .models.common import ModelConfig, Params


def _iter_hf_tensors(ckpt_dir: Path) -> Iterator[tuple[str, np.ndarray]]:
    """Yield (name, array) from safetensors or torch-bin shards.

    Safetensors shards go through the native mmap + multithreaded-convert
    reader (native/rt_native.cc) when built; the pure-Python `safetensors`
    package is the fallback."""
    st_files = sorted(ckpt_dir.glob("*.safetensors"))
    if st_files:
        from ..native.loader import iter_safetensors, native_can_read
        for f in st_files:
            if native_can_read(f):
                # streaming: one tensor's f32 copy resident at a time
                yield from iter_safetensors(f)
                continue
            from safetensors import safe_open
            with safe_open(str(f), framework="np") as reader:
                for name in reader.keys():
                    yield name, reader.get_tensor(name)
        return
    bin_files = sorted(ckpt_dir.glob("pytorch_model*.bin"))
    if bin_files:
        import torch
        for f in bin_files:
            state = torch.load(str(f), map_location="cpu",
                               weights_only=True)
            for name, tensor in state.items():
                yield name, tensor.to(torch.float32).numpy()
        return
    raise FileNotFoundError(
        f"No *.safetensors or pytorch_model*.bin in {ckpt_dir}")


def load_hf_checkpoint(ckpt_dir: str | Path, cfg: ModelConfig,
                       dtype=jnp.bfloat16) -> Params:
    """Assemble the engine param tree from an HF checkpoint directory."""
    ckpt_dir = Path(ckpt_dir)
    e, h, k, d = cfg.embed_dim, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim

    layers: list[dict[str, Any]] = [{} for _ in range(cfg.num_layers)]
    params: Params = {"layers": layers}

    def as_jnp(x: np.ndarray) -> jnp.ndarray:
        return jnp.asarray(x.astype(np.float32), dtype=dtype)

    placers: dict[str, Callable[[np.ndarray], jnp.ndarray]] = {
        "q_proj": lambda w: as_jnp(w.T.reshape(e, h, d)),
        "k_proj": lambda w: as_jnp(w.T.reshape(e, k, d)),
        "v_proj": lambda w: as_jnp(w.T.reshape(e, k, d)),
        # HF o_proj.weight is [E, H*D] (out, in); ours is [H, D, E].
        "o_proj": lambda w: as_jnp(w.reshape(e, h, d).transpose(1, 2, 0)),
        "gate_proj": lambda w: as_jnp(w.T),
        "up_proj": lambda w: as_jnp(w.T),
        "down_proj": lambda w: as_jnp(w.T),
    }

    for name, tensor in _iter_hf_tensors(ckpt_dir):
        if name == "model.embed_tokens.weight":
            params["embedding"] = as_jnp(tensor)
        elif name == "model.norm.weight":
            params["final_norm"] = as_jnp(tensor)
        elif name == "lm_head.weight":
            if not cfg.tie_embeddings:
                params["lm_head"] = as_jnp(tensor)
        elif name.startswith("model.layers."):
            parts = name.split(".")
            idx = int(parts[2])
            if idx >= cfg.num_layers:
                continue
            if parts[3] == "self_attn":
                key = parts[4]
                if len(parts) > 5 and parts[5] == "bias":
                    # Qwen2 q/k/v bias: HF [H*D] → ours [H, D]
                    if key == "q_proj":
                        layers[idx]["q_bias"] = as_jnp(tensor.reshape(h, d))
                    elif key == "k_proj":
                        layers[idx]["k_bias"] = as_jnp(tensor.reshape(k, d))
                    elif key == "v_proj":
                        layers[idx]["v_bias"] = as_jnp(tensor.reshape(k, d))
                elif key in placers:
                    layers[idx][key] = placers[key](tensor)
            elif parts[3] == "mlp":
                key = parts[4]
                if key in placers:
                    layers[idx][key] = placers[key](tensor)
            elif parts[3] == "block_sparse_moe" and cfg.num_experts:
                # Mixtral MoE: gate.weight [X, E] router; experts.M.w1/w3
                # [F, E] (gate/up), w2 [E, F] (down). Ours stacks experts
                # leading: gate/up [X, E, F], down [X, F, E].
                layer = layers[idx]
                if parts[4] == "gate":
                    layer["router"] = as_jnp(tensor.T)        # [E, X]
                elif parts[4] == "experts":
                    xi, wname = int(parts[5]), parts[6]
                    if xi >= cfg.num_experts:
                        raise ValueError(
                            f"Checkpoint has expert index {xi} but config "
                            f"{cfg.name} expects {cfg.num_experts} experts "
                            f"— config/checkpoint mismatch")
                    experts = layer.setdefault("experts", {})
                    tgt = {"w1": "gate_proj", "w3": "up_proj",
                           "w2": "down_proj"}.get(wname)
                    if tgt:
                        stack = experts.setdefault(
                            tgt, [None] * cfg.num_experts)
                        stack[xi] = as_jnp(tensor.T)
            elif parts[3] == "input_layernorm":
                layers[idx]["input_norm"] = as_jnp(tensor)
            elif parts[3] == "post_attention_layernorm":
                layers[idx]["pre_mlp_norm"] = as_jnp(tensor)
            elif parts[3] == "pre_feedforward_layernorm":
                layers[idx]["pre_mlp_norm"] = as_jnp(tensor)
            elif parts[3] == "post_feedforward_layernorm":
                layers[idx]["post_mlp_norm"] = as_jnp(tensor)

    if cfg.num_experts:
        for layer in layers:
            experts = layer.get("experts")
            if experts:
                for key, stack in experts.items():
                    if isinstance(stack, list) and all(
                            s is not None for s in stack):
                        experts[key] = jnp.stack(stack)
    _validate_loaded(params, cfg)
    return params


def _validate_loaded(params: Params, cfg: ModelConfig) -> None:
    missing = []
    if "embedding" not in params:
        missing.append("embedding")
    if "final_norm" not in params:
        missing.append("final_norm")
    if not cfg.tie_embeddings and "lm_head" not in params:
        missing.append("lm_head")
    required = {"q_proj", "k_proj", "v_proj", "o_proj", "input_norm",
                "pre_mlp_norm"}
    required |= ({"router", "experts"} if cfg.num_experts
                 else {"gate_proj", "up_proj", "down_proj"})
    if cfg.attn_bias:
        required |= {"q_bias", "k_bias", "v_bias"}
    for i, layer in enumerate(params["layers"]):
        lacking = required - set(layer)
        if lacking:
            missing.append(f"layer{i}:{','.join(sorted(lacking))}")
        experts = layer.get("experts")
        if cfg.num_experts and isinstance(experts, dict):
            for key in ("gate_proj", "up_proj", "down_proj"):
                stack = experts.get(key)
                if stack is None:
                    missing.append(f"layer{i}:experts.{key}")
                elif isinstance(stack, list):
                    holes = [j for j, s in enumerate(stack) if s is None]
                    if holes:
                        missing.append(f"layer{i}:experts.{key}[{holes[:4]}]")
    if missing:
        raise ValueError(f"Checkpoint incomplete, missing: {missing[:8]}")


# --- native (orbax) engine checkpoints ---


def save_native(path: str | Path, params: Params) -> None:
    import orbax.checkpoint as ocp
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(Path(path).absolute(), params)
    ckptr.wait_until_finished()


def restore_native(path: str | Path, cfg: ModelConfig) -> Params:
    import orbax.checkpoint as ocp
    from .models.common import init_params
    import jax
    template = jax.eval_shape(
        lambda: init_params(cfg, jax.random.PRNGKey(0)))
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(Path(path).absolute(), template)


def detect_config_from_hf(ckpt_dir: str | Path) -> dict[str, Any]:
    """Read config.json from an HF checkpoint dir (for model auto-detect)."""
    cfg_path = Path(ckpt_dir) / "config.json"
    if not cfg_path.exists():
        return {}
    return json.loads(cfg_path.read_text())
