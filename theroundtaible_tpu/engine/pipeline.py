"""Pipeline parallelism — GPipe-style microbatched prefill over a "pipe"
mesh axis.

SURVEY.md §2.3: "PP — only needed for models too large for one TP group;
design the mesh abstraction to allow a (pipeline, tensor, data) axis split
even if v0 uses PP=1." This module is that design, shipped working and
tested on the virtual CPU mesh: layers are split into contiguous stages
(one per pipe-axis device, stage parameters stacked and sharded on a
leading stage axis), microbatches flow through the classic
(n_stages + n_micro - 1)-step schedule, and activations move stage→stage
with lax.ppermute over ICI — XLA overlaps the permute with the next
step's compute.

v0 scope: full-sequence prefill compute (logits), the piece PP exists for
(weights too big for one TP group). Decode keeps TP/EP: per-token PP
bubbles dominate at batch sizes this orchestrator produces, so the engine
does not enable PP for its slot-persistent serving loop yet. The module
is the documented seam to widen (stage-local KV caches are the follow-up:
each stage would keep its layer range's slots exactly as kvcache.py does
globally).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .compat import pcast, shard_map

from .models.common import (
    ModelConfig, Params, make_attention_mask, rms_norm, transformer_block)

PIPE_AXIS = "pipe"


def build_pipe_mesh(n_stages: int, devices: Optional[list] = None,
                    n_model: int = 1) -> Mesh:
    """(pipe,) mesh, or a (pipe, model) mesh when n_model > 1 — each
    stage's weights then shard over a TP group of n_model devices (the
    SURVEY §2.3 "(pipeline, tensor, data)" axis split; PP programs stay
    manual over "pipe" and leave "model" to the compiler, so the same
    stage code serves both shapes)."""
    import numpy as np
    devices = devices if devices is not None else jax.devices()
    need = n_stages * n_model
    if len(devices) < need:
        raise ValueError(f"need {need} devices "
                         f"(pipe {n_stages} x model {n_model}), "
                         f"have {len(devices)}")
    if n_model == 1:
        return Mesh(np.array(devices[:n_stages]), (PIPE_AXIS,))
    from .sharding import MODEL_AXIS
    return Mesh(np.array(devices[:need]).reshape(n_stages, n_model),
                (PIPE_AXIS, MODEL_AXIS))


def stack_stage_params(params: Params, cfg: ModelConfig, n_stages: int,
                       mesh: Mesh) -> tuple[Params, Params]:
    """Split the per-layer param list into n_stages contiguous stages.

    Returns (shared, staged): `shared` = embedding/final_norm/lm_head
    (replicated over the pipe axis; sharded over the model axis per
    sharding.param_specs when the mesh has one); `staged` = each layer
    tensor stacked to [n_stages, layers_per_stage, ...], sharded on the
    leading stage axis so each pipe device holds exactly its own layers
    — and, on a (pipe, model) mesh, TP-sharded inside the stage on the
    same dims the main engine shards (param_specs shifted by the two
    stacking dims). Quantized {"q","s"} leaves place via
    quant.quantized_specs. Any dim that doesn't divide its mesh axis
    falls back to replication (sharding._fallback_replicated).
    """
    if cfg.num_layers % n_stages != 0:
        raise ValueError(
            f"{cfg.num_layers} layers do not split into {n_stages} stages")
    per = cfg.num_layers // n_stages

    from .quant import quantized, quantized_specs
    from .sharding import _fallback_replicated, param_specs
    specs = param_specs(cfg)
    if any(quantized(l) for l in
           jax.tree_util.tree_leaves(params, is_leaf=quantized)):
        specs = quantized_specs(specs, params)
    has_model = len(mesh.axis_names) > 1

    stacked = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves).reshape(
            (n_stages, per) + leaves[0].shape),
        *params["layers"])

    def stage_place(x, spec):
        tp = tuple(spec) if has_model else ()
        full = P(PIPE_AXIS, None, *tp)
        return NamedSharding(mesh,
                             _fallback_replicated(full, x.shape, mesh))

    staged = jax.device_put(
        stacked,
        jax.tree_util.tree_map(stage_place, stacked, specs["layers"][0]))

    def shared_place(x, spec):
        full = spec if has_model else P()
        return NamedSharding(mesh,
                             _fallback_replicated(full, x.shape, mesh))

    shared = {k: v for k, v in params.items() if k != "layers"}
    shared_specs = {k: specs.get(k, jax.tree_util.tree_map(
        lambda _: P(), v)) for k, v in shared.items()}
    shared = jax.device_put(
        shared, jax.tree_util.tree_map(shared_place, shared, shared_specs))
    return shared, staged


def make_pp_prefill(cfg: ModelConfig, mesh: Mesh, n_micro: int):
    """Build jit'd fn(shared, staged, tokens [B,T]) → logits [B,T,V].

    B must divide into n_micro microbatches. Schedule: at step i, stage s
    works on microbatch i-s (when 0 ≤ i-s < n_micro); stage 0 injects
    embeddings, the last stage banks its outputs, ppermute advances the
    ring. The rotating-buffer trick keeps shapes static: every stage
    computes every step (idle steps process garbage that is never banked).
    """
    n_stages = mesh.shape[PIPE_AXIS]
    if cfg.num_layers % n_stages != 0:
        raise ValueError(
            f"{cfg.num_layers} layers do not split into {n_stages} stages")

    def stage_compute(stage_layers, x, positions, valid):
        """Run this stage's `per` layers (scan over stacked params)."""
        mask = make_attention_mask(positions, x.shape[1], valid,
                                   cfg.sliding_window)

        def body(h, layer):
            h, _cache = transformer_block(h, layer, cfg, positions, None,
                                          None, mask, kv_valid=valid)
            return h, None

        x, _ = jax.lax.scan(body, x, stage_layers)
        return x

    def pp_fn(shared, staged, tokens, positions, valid):
        # [B,T] → [n_micro, mb, T]
        b, t = tokens.shape
        mb = b // n_micro
        tok_mb = tokens.reshape(n_micro, mb, t)
        pos_mb = positions.reshape(n_micro, mb, t)
        valid_mb = valid.reshape(n_micro, mb)

        # follows the param dtype (bf16 serving, f32 parity tests) — same
        # rule as models/common.py forward
        emb = shared["embedding"][tok_mb]
        if cfg.scale_embeddings:
            emb = emb * jnp.sqrt(
                jnp.float32(cfg.embed_dim)).astype(emb.dtype)

        def per_stage(stage_layers, emb, pos_mb, valid_mb):
            # under shard_map: stage_layers [1, per, ...] — this stage only
            stage_layers = jax.tree_util.tree_map(
                lambda x: x[0], stage_layers)
            stage = jax.lax.axis_index(PIPE_AXIS)
            n_steps = n_stages + n_micro - 1

            # initial carries must be typed as varying over the pipe axis
            # (each stage's loop state diverges immediately)
            state = pcast(jnp.zeros_like(emb[0]), (PIPE_AXIS,),
                          to="varying")
            banked = pcast(jnp.zeros_like(emb), (PIPE_AXIS,),
                           to="varying")

            def step(i, carry):
                state, banked = carry
                # stage 0 injects microbatch i (clamped; only banked when
                # in schedule), others take the permuted activation
                inject = emb[jnp.clip(i, 0, n_micro - 1)]
                x_in = jnp.where(stage == 0,
                                 jnp.where(i < n_micro, inject, state),
                                 state)
                my_mb = jnp.clip(i - stage, 0, n_micro - 1)
                pos = pos_mb[my_mb]
                vld = valid_mb[my_mb]
                out = stage_compute(stage_layers, x_in, pos, vld)
                # last stage banks microbatch j = i - (n_stages-1)
                j = i - (n_stages - 1)
                bank_now = (stage == n_stages - 1) & (j >= 0)
                banked = jnp.where(
                    bank_now,
                    banked.at[jnp.clip(j, 0, n_micro - 1)].set(out),
                    banked)
                state = jax.lax.ppermute(
                    out, PIPE_AXIS,
                    [(s, (s + 1) % n_stages) for s in range(n_stages)])
                return state, banked

            _state, banked = jax.lax.fori_loop(
                0, n_steps, step, (state, banked))
            # replicate the last stage's banked outputs to every stage
            banked = jax.lax.psum(
                jnp.where(stage == n_stages - 1, banked, 0.0)
                .astype(jnp.float32),
                PIPE_AXIS).astype(banked.dtype)
            return banked

        hidden = shard_map(
            per_stage, mesh=mesh,
            in_specs=(P(PIPE_AXIS), P(), P(), P()),
            out_specs=P(),
        )(staged, emb, pos_mb, valid_mb)

        hidden = hidden.reshape(b, t, cfg.embed_dim)
        hidden = rms_norm(hidden, shared["final_norm"], cfg.norm_eps,
                          cfg.rmsnorm_unit_offset)
        head = (shared["embedding"] if cfg.tie_embeddings
                else shared["lm_head"])
        logits = jnp.einsum("bte,ve->btv", hidden, head,
                            preferred_element_type=jnp.float32)
        if cfg.final_logit_softcap is not None:
            logits = cfg.final_logit_softcap * jnp.tanh(
                logits / cfg.final_logit_softcap)
        return logits

    jitted = jax.jit(pp_fn)

    def call(shared, staged, tokens, positions, valid):
        if tokens.shape[0] % n_micro != 0:
            raise ValueError(
                f"batch {tokens.shape[0]} does not split into "
                f"{n_micro} microbatches")
        return jitted(shared, staged, tokens, positions, valid)

    return call
