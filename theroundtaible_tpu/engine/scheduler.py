"""Continuous-batching session scheduler — many discussions, one engine.

Everything below the adapters serves exactly ONE discussion at a time:
`generate_batch` owns the engine's serve lock end-to-end, so a second
session's round serializes behind the first even when the first is deep
in a long decode with most of its rows already at eos. Production TPU
engines get their throughput from continuous batching (RTP-LLM, arxiv
2605.29639), and Ragged Paged Attention (arxiv 2604.15464) shows mixed
prefill/decode batches are the natural TPU shape for it. The paged KV
pool is already slot-granular with copy-on-write sharing — this module
adds the missing piece: the scheduling subsystem above it.

Design, shaped by JAX's static-shape constraints (ISSUE 4 tentpole):

- **Decode batch = the live row set, bucketed, recomposed at segment
  boundaries.** One decode program runs a whole DECODE_SEGMENT
  (serving_loop); between segments the host owns every row's (last,
  valid, done, budget) state, so rows can retire and join freely there
  without touching the device programs. The batch pads to a power-of-two
  bucket (capped at max_rows) with MASKED pad rows — done from step 0,
  zero budget, writes landing on a throwaway slot / the paged scratch
  page — so the compiled decode shapes are {1, 2, 4, ..., max_rows}
  and a retire/join that moves occupancy within a bucket compiles
  nothing mid-serve.
- **Join = chunked prefill into freed capacity.** A queued turn admits at
  a segment boundary: its rows run the same reuse_plan → share_prefixes
  (intra-session cross-knight reuse) → chunked/ring prefill path as
  generate_batch — with every actively-decoding row PINNED so the
  joining batch can never evict a live slot — then its first sampled
  token enters the next decode segment alongside everyone else's rows.
- **Retire = drop out of the next segment.** A row at eos (or out of
  per-row budget) simply stops being dispatched; its session's request
  completes when all its rows are done, committing each slot's tokens
  for next-round prefix reuse. No whole-batch barrier: one session's
  long monologue never holds another session's finished rows hostage.
- **Admission queue with capacity-aware backpressure.** A request whose
  rows cannot fit the SlotBook right now (or whose pages cannot fit the
  PagedKVCache pool next to the pinned live rows) stays queued until
  retirement frees capacity; a request that could NEVER fit this engine
  is refused outright (SchedulerRefused) instead of deadlocking the
  queue. Per-session fairness is FIFO admission with co-scheduled
  rounds: all knights of one round join together or not at all, so
  consensus rounds still fan out in one batch.
- **Sessions are isolation domains.** Slot names are session-namespaced
  (kvcache.scoped_slot — the cross-session "lancelot" collision fix),
  prefix donation never crosses sessions, and a fault in the shared
  decode dispatch degrades by PREEMPTING the batch into per-session
  dispatches: the sick session's request fails into its adapter's
  PR-1 ladder (revive → serial retry → breaker) while every other
  session's rows continue from their host-side state, byte-identical.
- **Composes with the ladders, not around them.** Admission checks the
  fleet drain gate (queued-but-unadmitted requests fail fast with
  DrainingError on drain), per-rung deadlines.Budgets thread session →
  turn → prefill/decode/segment, dispatches run through the
  run_dispatch retry/watchdog seam, and every decision (admit / queue /
  refuse / preempt, queue depth, per-segment batch occupancy) is
  recorded into GenStats.sched and engine.describe()["scheduler"] the
  same way the int4 paths are.

The scheduler serves InferenceEngine only: PPEngine's stage-pipelined
programs have no single decode-segment seam to recompose at (its rounds
still batch and its slot names still namespace — see pp_serving).
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

from ..utils import telemetry
from . import deadlines, faults, trace_hooks
from .kvcache import scoped_slot
from .sampling import SamplingParams, sampling_arrays
from .serving_loop import (DECODE_SEGMENT, RAGGED_BLOCK_Q, RaggedSeq,
                           ReplicaGroupPlan, build_ragged_batch,
                           clamp_max_new, eos_trim, host_sync,
                           pow2_bucket, prompt_budget, run_dispatch)

# How many recent per-segment occupancy samples / decision events the
# provenance surfaces keep (describe(), fleet_health).
_OCCUPANCY_LOG_CAP = 256
_EVENT_LOG_CAP = 64

# Test-visibility counter (tests/conftest.py `scheduler` marker guard):
# the maximum number of live rows any scheduler dispatched in one decode
# segment since the last reset. A guard that sees < 2 here knows the
# scheduler silently degenerated to serial serving.
_test_max_rows = 0
_test_lock = threading.Lock()


def reset_test_counters() -> None:
    global _test_max_rows
    with _test_lock:
        _test_max_rows = 0


def max_rows_seen() -> int:
    return _test_max_rows


def _note_rows(n: int) -> None:
    global _test_max_rows
    with _test_lock:
        if n > _test_max_rows:
            _test_max_rows = n


# Registry of live schedulers (weak — a dropped scheduler must not be
# kept alive by observability): fleet_health() and fleet.drain() walk it.
_registry_lock = threading.Lock()
_instances: list = []


def _register(sched: "SessionScheduler") -> None:
    with _registry_lock:
        _instances.append(weakref.ref(sched))


def schedulers() -> list["SessionScheduler"]:
    """Every live SessionScheduler (fleet_health / fleet.drain)."""
    out = []
    with _registry_lock:
        alive = []
        for ref in _instances:
            s = ref()
            if s is not None:
                alive.append(ref)
                out.append(s)
        _instances[:] = alive
    return out


class SchedulerRefused(RuntimeError):
    """The request can NEVER fit this engine (more knights than slots,
    or more pages than the whole pool) — refused at submission, not
    queued to deadlock. `reason` (ISSUE 16) is the machine-readable
    refusal tag the gateway's shed accounting keys on: the never-fits
    tags ("rows_never_fit", "adapters_never_fit", "pages_never_fit")
    or, for a submit that opted out of queueing behind a closed gate
    (queue_when_paused=False), the pause_admission reason verbatim —
    so shed vs drain vs quiesce refusals stay distinguishable at the
    HTTP boundary instead of dying inside the scheduler."""

    def __init__(self, message: str, reason: Optional[str] = None):
        super().__init__(message)
        self.reason = reason


class SchedulerClosed(RuntimeError):
    """submit() after close()."""


class DeadlineExpired(RuntimeError):
    """The request's SLO budget was already spent at submission — it
    fails fast at the queue mouth, before any prefill dispatch or slot
    acquisition (gateway deadline propagation, ISSUE 16). The message
    deliberately carries no classify_error marker words so the
    ERROR_KIND_TABLE entry ("deadline_expired") wins over the
    message-sniffing timeout ladder."""


@dataclass(eq=False)
class _Row:
    """One knight's decode row: host-side state between segments.
    Identity equality (eq=False): rows are tracked by membership in
    their request's list, and two rows can transiently hold identical
    field values."""

    name: str                    # session-scoped slot name
    tokens: list[int]            # truncated prompt ids (committed base)
    sampling: SamplingParams
    max_new: int                 # per-row token cap (<= request cap)
    slot_id: int = -1            # contiguous layouts only (paged: -1)
    produced: list[int] = field(default_factory=list)  # [first, ...]
    last: int = 0
    valid: int = 0
    done: bool = False
    # Ragged chunk-interleaved admission (ISSUE 8): prompt tokens not
    # yet prefilled — fed as chunks of the live decode segment's ragged
    # dispatches; `pos` is the next write position. A row with pending
    # tokens is FILLING, never dispatched for decode; its first sampled
    # token arrives with the dispatch consuming its last chunk. A
    # `blocked` filling row is a deferred-share LAGGARD: its chunks wait
    # until the round's leader has written the common span, at which
    # point the span aliases in and the row unblocks (_apply_share_plans).
    pending: list[int] = field(default_factory=list)
    pos: int = 0
    blocked: bool = False
    # Speculative decoding (ISSUE 9): per-row drafter + adaptive
    # throttle (engine/spec_decode.RowSpec); None on spec-off engines.
    spec: Optional[Any] = None
    # Multi-LoRA persona (ISSUE 10): this row's adapter SLOT in the
    # engine's LoraStore (0 = base). A value, never a shape: mixed-
    # adapter segments run the same compiled programs as base ones.
    adapter_slot: int = 0
    # Committed-token streaming seam (ISSUE 16): how many eos-trimmed
    # tokens of this row have already been flushed to the request's
    # on_commit callback. eos_trim is prefix-stable as `produced`
    # grows, so ids[streamed:] is exactly the new committed span —
    # tree-spec multi-token commits stream for free.
    streamed: int = 0


class _Request:
    """One session round: queued → active → done|failed."""

    __slots__ = ("session", "turns", "sampling_per_turn", "max_new",
                 "timeout_s", "budget", "event", "result", "error",
                 "enqueued", "admitted_at", "rows", "stats", "deadline",
                 "turn_budget", "dec_budget", "abandoned", "seg_count",
                 "occ_sum", "occ_max", "sess_max", "requeues",
                 "fits_below", "tele_ctx", "tele", "first_token_at",
                 "share_plans", "spec_drafted", "spec_accepted",
                 "adapters", "adapters_held", "on_commit")

    def __init__(self, session, turns, sampling_per_turn, max_new,
                 timeout_s, budget, stats, adapters=None):
        self.session = session
        self.turns = turns
        self.sampling_per_turn = sampling_per_turn
        # Per-turn LoRA persona adapter ids (ISSUE 10; None = base).
        # adapters_held flips once acquire() took residency refs, so
        # failure paths release exactly what admission took.
        self.adapters = adapters
        self.adapters_held = False
        self.max_new = max_new
        self.timeout_s = timeout_s
        self.budget = budget
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.enqueued = time.monotonic()
        self.admitted_at: Optional[float] = None
        self.rows: list[_Row] = []
        self.stats = stats
        self.deadline = float("inf")
        self.turn_budget = None
        self.dec_budget = None
        self.abandoned = False
        self.seg_count = 0
        self.occ_sum = 0
        self.occ_max = 0
        self.sess_max = 0
        self.requeues = 0        # admissions undone on pool exhaustion
        self.fits_below = None   # re-admit only once active rows < this
        # TTFT (ISSUE 8): when the LAST of this request's rows got its
        # first sampled token — the moment every knight of the round
        # has tokens flowing. sched stats report it against `enqueued`.
        self.first_token_at: Optional[float] = None
        # Deferred leader-span share plans (ragged admission): the
        # laggards alias the common span once the leader's chunks have
        # written it. [{"leader": _Row, "hi": int,
        # "followers": [(_Row, lo), ...]}]
        self.share_plans: list[dict] = []
        # Speculation provenance (ISSUE 9): this request's drafted /
        # accepted totals — lands in GenStats.sched["spec"] at retire.
        self.spec_drafted = 0
        self.spec_accepted = 0
        # Telemetry (ISSUE 5): the submitter thread's span context, so
        # this request's "turn" span parents into ITS discussion trace
        # even though the scheduler thread emits it; `tele` is that
        # span while the request is active.
        self.tele_ctx = telemetry.current_context() \
            if telemetry.ACTIVE else None
        self.tele = None
        # Committed-token streaming (ISSUE 16): called on the LOOP
        # thread with {"type": "tokens"|"retired"|"failed", ...} events
        # at segment-commit boundaries. A raising callback is disabled
        # (set to None) — a broken consumer must never wedge serving.
        self.on_commit = None


class SessionScheduler:
    """Admits concurrent discussion sessions onto one InferenceEngine
    and continuously batches their decode segments.

    One scheduler per engine: `scheduler_for(engine)` returns the
    attached instance or builds one. Threads call `submit(session,
    turns, ...)` (the TpuLlmAdapter routes through it when attached);
    a dedicated scheduler thread owns the engine's serve lock while any
    session is active, so direct generate_batch callers and fleet.drain
    still serialize correctly against scheduled work."""

    def __init__(self, engine, *, admit_hold_s: float = 0.0,
                 max_rows: Optional[int] = None,
                 idle_spill_s: Optional[float] = None,
                 journal=None):
        # The continuous-batching loop recomposes rows at the decode
        # SEGMENT seam — it needs the single-program engine's compiled
        # closures. PPEngine has no such seam (stage-pipelined decode).
        for attr in ("_prefill", "_decode_loop", "_share_prefixes"):
            if not hasattr(engine, attr):
                raise TypeError(
                    "SessionScheduler requires the single-program "
                    "InferenceEngine (missing %r); pipe-mesh engines "
                    "serve round-level batches — use session-namespaced "
                    "generate_batch calls instead" % attr)
        self.engine = engine
        self.admit_hold_s = admit_hold_s
        self.max_rows = min(max_rows or engine.kv.num_slots,
                            engine.kv.num_slots)
        # Host-RAM KV offload policy (ISSUE 7): per-session last-activity
        # drives spill decisions — under page pressure at admission an
        # idle session's KV moves to host RAM (kv_offload tier) INSTEAD
        # of the allocator destroying it by eviction; with idle_spill_s
        # set, sessions idle longer than that spill proactively each
        # tick. Spilled sessions restore transparently on their next
        # submit (engine._prepare_batch's restore seam) with no
        # re-prefill. None = pressure-driven only.
        self.idle_spill_s = idle_spill_s
        self._last_active: dict[str, float] = {}
        self.spills = 0
        self._queue: deque[_Request] = deque()
        self._active: list[_Row] = []         # rows, admission order
        self._active_reqs: list[_Request] = []
        self._row_req: dict[int, _Request] = {}  # id(row) -> request
        self._cv = threading.Condition()
        self._stop = False
        self.closed = False
        self._lock_held = False
        # The lock OBJECT actually held (ISSUE 12): a supervised engine
        # rebuild swaps self.engine mid-lifetime, and releasing
        # "self.engine._serve_lock" after a swap would release the NEW
        # engine's (unheld) lock while leaking the old one.
        self._held_lock: Optional[threading.Lock] = None
        # Admission gate (ISSUE 12): while set, queued requests stay
        # QUEUED (the supervisor's quiesce / fleet.drain) — nothing is
        # admitted and nothing is rejected; reopen_admission (or
        # fleet.resume) lifts it. A reason string, None = open.
        self._paused: Optional[str] = None
        # Thread-safe preempt mailbox (ISSUE 12): force_fail_active
        # posts an error here; the loop thread consumes it at its next
        # health check — request state stays single-writer.
        self._force_fail: Optional[BaseException] = None
        # Durable session journal (ISSUE 12): when attached, every
        # retired round appends one fsynced committed-turn record, so a
        # hard process crash resumes at the last committed turn
        # (engine/session_journal.py; serve --resume replays it).
        self._journal = journal
        # THIS scheduler's journal provenance (the journal object is
        # shared across every scheduler of a serve root — its own
        # .records/.errors are fleet-wide and would double-count when
        # describe() outputs are summed per scheduler).
        self.journal_turns = 0
        self.journal_errors = 0
        # Decision provenance (ISSUE 4: recorded like the int4 paths).
        self.admitted = 0
        self.refused = 0
        self.completed = 0
        self.failed = 0
        self.rejected_draining = 0
        self.rejected_other = 0       # close()/loop-error rejections
        self.deadline_expired = 0     # SLO-spent submits failed fast
        self.preemptions = 0          # fault-isolation preempts
        self.segments = 0
        self.max_occupancy = 0
        self.queued_peak = 0
        # Ragged chunk-interleaved admission provenance (ISSUE 8):
        # mixed dispatches issued, joins that prefilled through them,
        # and the per-phase token split of every segment (ragged AND
        # while-loop) — bumped in lockstep with their registry series
        # like every other counter here.
        self.ragged_segments = 0
        self.ragged_joins = 0
        self.segment_prefill_tokens = 0
        self.segment_decode_tokens = 0
        # Speculative verify dispatches issued (ISSUE 9) — bumped in
        # lockstep with its registry series like every counter here.
        self.spec_segments = 0
        self._occupancy: deque[int] = deque(maxlen=_OCCUPANCY_LOG_CAP)
        self._events: deque[dict] = deque(maxlen=_EVENT_LOG_CAP)
        # Registry label for this scheduler's series (ISSUE 5): every
        # decision counter below publishes into the shared registry in
        # LOCKSTEP (_bump), so describe() and the registry can never
        # disagree — the single-source-of-truth migration.
        self._tname = getattr(engine.cfg, "name", "engine")
        # Replica identity (ISSUE 17): set by the session router when
        # this scheduler serves as one replica of a data-parallel
        # fleet. N replicas of one model share `_tname` (same config),
        # so every registry series this scheduler writes additionally
        # carries `replica=` once set — and the router removes the
        # labeled series when the replica retires (RT-GAUGE-LEAK).
        self.replica: Optional[str] = None
        # Attaching a scheduler ADDS compile surface (pipelined-segment
        # carries, pinned-row joins) to an engine whose warmup() may
        # already have declared steady state — reopen the warmup phase
        # so the scheduler's warm traffic compiles freely; the caller
        # re-declares via declare_warmup_complete() once covered.
        from . import compile_watch
        compile_watch.reopen_warmup(self._tname)
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"session-scheduler-{getattr(engine.cfg, 'name', '?')}")
        engine._scheduler = self           # describe() provenance
        _register(self)
        self._thread.start()

    # ------------------------------------------------------------------
    # submission
    # ------------------------------------------------------------------

    def submit(self, session: str, turns: list[tuple[str, Any]], *,
               max_new_tokens: Optional[int] = None,
               timeout_s: float = 600.0,
               sampling_per_turn: Optional[list[SamplingParams]] = None,
               budget=None, adapters_per_turn=None):
        """Serve one session round through the shared batch. Blocks the
        calling (session) thread until the round completes; returns
        (responses, GenStats) — the generate_batch_with_stats contract,
        so the adapter ladder above is unchanged. `adapters_per_turn`
        (ISSUE 10): per-knight LoRA persona ids (None = base) —
        co-batched rows with DIFFERENT adapters share one decode
        segment on the shared base model."""
        req = self.submit_async(
            session, turns, max_new_tokens=max_new_tokens,
            timeout_s=timeout_s, sampling_per_turn=sampling_per_turn,
            budget=budget, adapters_per_turn=adapters_per_turn)
        return self.wait(req)

    def submit_async(self, session, turns, *, max_new_tokens=None,
                     timeout_s: float = 600.0, sampling_per_turn=None,
                     budget=None, adapters_per_turn=None,
                     on_commit=None,
                     queue_when_paused: bool = True) -> _Request:
        if self.closed:
            raise SchedulerClosed("scheduler is closed")
        if not turns:
            raise ValueError("submit() needs at least one turn")
        # Drain gate at the QUEUE mouth: a request that would only ever
        # wait out its budget behind a drain fails fast instead
        # (fleet.drain satellite).
        deadlines.check_admission()
        # Deadline propagation (ISSUE 16): a request whose SLO budget
        # is ALREADY spent fails fast here — before slot acquisition or
        # any prefill dispatch — with its own classified kind, instead
        # of occupying queue/batch capacity just to time out.
        if budget is not None and budget.expired:
            with self._cv:  # submitter threads race each other here
                self._bump("deadline_expired")
            self._event("deadline_expired", session=session)
            raise DeadlineExpired(
                f"session {session!r} submitted with its SLO budget "
                "already spent — refused before any prefill dispatch")
        # Gateway shed seam (ISSUE 16): callers that shed instead of
        # queueing (the HTTP front door) opt out of the pause gate's
        # wait-in-queue default; the refusal carries the pause reason
        # verbatim so drain/quiesce/shed are machine-distinguishable.
        if not queue_when_paused:
            paused = self._paused
            if paused is not None:
                with self._cv:
                    self._bump("refused")
                self._event("refuse", session=session,
                            reason=f"admission paused: {paused}")
                raise SchedulerRefused(
                    f"session {session!r} refused while admission is "
                    f"paused ({paused}) — caller sheds instead of "
                    "queueing behind a closed gate", reason=paused)
        engine = self.engine
        # Dead-engine gate (ISSUE 12): the supervisor exhausted this
        # engine's restart budget — every submit fails fast with the
        # same classified reason instead of queueing into a corpse.
        from ..core.errors import classify_error
        from .supervisor import EngineDead, engine_dead_reason
        dead = engine_dead_reason(engine)
        if dead is not None:
            # The reason string carries the terminal cause, so the
            # classified kind survives into the adapter ladder's error
            # accounting (device_lost stays device_lost).
            raise EngineDead(
                f"engine {self._tname!r} is dead: {dead}",
                kind=classify_error(RuntimeError(dead)))
        # Against max_rows, not num_slots: a request wider than the
        # scheduler's batch would pass a slots-only check, then sit at
        # the FIFO head forever (admission only examines the head) and
        # starve every later session for its whole timeout.
        if len(turns) > self.max_rows:
            with self._cv:  # submitter threads race each other here
                self._bump("refused")
            self._event("refuse", session=session,
                        reason=f"{len(turns)} rows > max_rows "
                               f"{self.max_rows}")
            raise SchedulerRefused(
                f"session {session!r} needs {len(turns)} rows but this "
                f"scheduler batches at most {self.max_rows} (num_slots "
                f"{engine.kv.num_slots}) — raise num_slots / max_rows",
                reason="rows_never_fit")
        max_new = max_new_tokens or engine.sampling.max_new_tokens
        store = getattr(engine, "lora", None)
        if store is None:
            adapters_per_turn = None
        elif adapters_per_turn is not None:
            # Validated at the QUEUE mouth (ISSUE 10): a request naming
            # more distinct personas than the store can ever hold
            # deadlocks the FIFO head if queued; unknown personas fail
            # the submitter now instead of at admission. The distinct-
            # count case is a REFUSAL (counted, like the rows/pages
            # never-fits); the rest share LoraStore.validate with the
            # direct generate path so the two cannot drift.
            distinct = {a for a in adapters_per_turn if a is not None}
            if (len(adapters_per_turn) == len(turns)
                    and len(distinct) > store.max_adapters):
                with self._cv:
                    self._bump("refused")
                self._event("refuse", session=session,
                            reason=f"{len(distinct)} adapters > store "
                                   f"{store.max_adapters}")
                raise SchedulerRefused(
                    f"session {session!r} names {len(distinct)} "
                    f"distinct lora adapters but the store holds at "
                    f"most {store.max_adapters} — raise "
                    "lora.max_adapters", reason="adapters_never_fit")
            store.validate(adapters_per_turn, len(turns))
        if engine.kv_layout == "paged":
            # Never-fits = LOWER bound (1-token prompts): a request
            # generate_batch could serve must never be refused here.
            need = self._pages_needed(turns, max_new, minimal=True)
            if need > engine.kv.usable_pages():
                with self._cv:
                    self._bump("refused")
                self._event("refuse", session=session,
                            reason=f"{need} pages > pool "
                                   f"{engine.kv.usable_pages()}")
                raise SchedulerRefused(
                    f"session {session!r} needs at least {need} KV pages "
                    f"but the pool holds {engine.kv.usable_pages()} — "
                    "raise num_pages or lower max_new_tokens",
                    reason="pages_never_fit")
        req = _Request(session, list(turns), sampling_per_turn, max_new,
                       timeout_s, budget, self._fresh_stats(),
                       adapters=adapters_per_turn)
        req.on_commit = on_commit
        with self._cv:
            # Re-checked under the lock: close() flips `closed` and
            # drains the queue under this same lock, so a request can
            # never land in a queue no thread will ever tick again.
            if self.closed or self._stop:
                raise SchedulerClosed("scheduler is closed")
            self._queue.append(req)
            self.queued_peak = max(self.queued_peak, len(self._queue))
            self._last_active[session] = time.monotonic()
            self._cv.notify_all()
        return req

    def wait(self, req: _Request):
        """Block until `req` resolves; re-raise its failure.

        The outer bound only catches a WEDGED scheduler, never a
        healthy one: the scheduler restarts the request's clock when
        admission begins (_start_request sets admitted_at; queue time
        is bounded separately in _admit_queued), so the waiter's
        deadline tracks admitted_at + timeout_s + grace — re-evaluated
        each slice, since admission can happen while we wait. Every
        budget/deadline failure in a healthy scheduler resolves the
        event long before this fires."""
        grace = 60.0
        while not req.event.is_set():
            base = (req.admitted_at if req.admitted_at is not None
                    else req.enqueued)
            deadline = base + req.timeout_s + grace
            slice_s = deadline - time.monotonic()
            if slice_s <= 0:
                req.abandoned = True
                with self._cv:
                    self._cv.notify_all()
                raise TimeoutError(
                    f"scheduler did not resolve session {req.session!r} "
                    f"within {req.timeout_s + grace:.0f}s of admission "
                    "(scheduler wedged?)")
            req.event.wait(timeout=min(slice_s, 5.0))
        if req.error is not None:
            raise req.error
        return req.result

    def _fresh_stats(self):
        from .engine import GenStats
        return GenStats()

    def _pages_needed(self, turns, max_new: int,
                      minimal: bool = False) -> int:
        """Page-demand estimate of a request, with max_new clamped the
        way the serving paths clamp it. `minimal=True` is the never-fits
        LOWER bound (1-token prompts — refusal must never reject what
        generate_batch would serve); otherwise prompt lengths are
        estimated from the actual inputs (exact for pre-tokenized
        lists, chars/token ratio for strings, capped at the prompt
        budget) for queue backpressure."""
        engine = self.engine
        kv = engine.kv
        max_new, max_new_padded = clamp_max_new(max_new,
                                                engine.max_seq_len)
        budget_tok = prompt_budget(engine.max_seq_len, max_new_padded)
        total = 0
        for _name, prompt in turns:
            if minimal:
                est = 1
            elif isinstance(prompt, list):
                est = min(len(prompt), budget_tok)
            else:
                cpt = max(engine.chars_per_token(), 0.25)
                est = min(int(len(prompt) / cpt * 1.25) + 1, budget_tok)
            total += -(-(est + max_new_padded) // kv.page_size)
        return total

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------

    def _series_labels(self) -> dict[str, str]:
        """Labels for this scheduler's registry series: engine-keyed as
        always, plus `replica=` when the router named this scheduler a
        fleet replica (N replicas share one engine config name)."""
        if self.replica is not None:
            return {"engine": self._tname, "replica": self.replica}
        return {"engine": self._tname}

    def set_replica(self, name: Optional[str]) -> None:
        """Name this scheduler's fleet replica (ISSUE 17). The router
        calls this once at fleet build; passing None detaches (used by
        retire, after the labeled series were removed)."""
        self.replica = name

    def _bump(self, counter: str, n: int = 1) -> None:
        """Increment a decision counter AND its registry series in one
        place — no counter can move without the registry seeing it
        (the drift test pins describe()'s keys to these series)."""
        setattr(self, counter, getattr(self, counter) + n)
        telemetry.inc(f"roundtable_sched_{counter}_total", n,
                      **self._series_labels())

    def _event(self, kind: str, **fields) -> None:
        e = {"event": kind, "at": round(time.monotonic(), 3)}
        e.update(fields)
        with self._cv:  # RLock — safe from paths already holding it
            self._events.append(e)
        # Mirror into the flight recorder (bounded ring): a hang/trip
        # dump then carries the scheduler's recent decisions alongside
        # the engine's spans — the cross-format stitching ISSUE 5 ends.
        telemetry.recorder().record(f"sched_{kind}", engine=self._tname,
                                    **{k: v for k, v in fields.items()
                                       if k not in ("kind", "at")})
        telemetry.set_gauge("roundtable_sched_queue_depth",
                            len(self._queue), **self._series_labels())
        telemetry.set_gauge("roundtable_sched_active_rows",
                            len(self._active), **self._series_labels())

    def describe(self) -> dict[str, Any]:
        """Scheduler provenance for engine.describe() / bench records —
        the decision log the int4 paths set the precedent for. The
        deque copies take the cv lock: callers poll this from
        monitoring/bench threads while the loop appends, and iterating
        a deque mid-append raises."""
        with self._cv:
            occ = list(self._occupancy)
            events = list(self._events)
        return {
            "admitted": self.admitted,
            "refused": self.refused,
            "completed": self.completed,
            "failed": self.failed,
            "rejected_draining": self.rejected_draining,
            "rejected_other": self.rejected_other,
            "deadline_expired": self.deadline_expired,
            "preemptions": self.preemptions,
            "segments": self.segments,
            "ragged_segments": self.ragged_segments,
            "ragged_joins": self.ragged_joins,
            "spec_segments": self.spec_segments,
            "segment_prefill_tokens": self.segment_prefill_tokens,
            "segment_decode_tokens": self.segment_decode_tokens,
            "queued": len(self._queue),
            "queued_peak": self.queued_peak,
            "active_rows": len(self._active),
            "max_occupancy": self.max_occupancy,
            "occupancy_mean": (round(sum(occ) / len(occ), 2)
                               if occ else 0.0),
            "occupancy_recent": occ[-32:],
            "spills": self.spills,
            "spilled_sessions": len(getattr(
                self.engine, "kv_offload", None).spilled_sessions())
            if getattr(self.engine, "kv_offload", None) is not None
            else 0,
            "paused": self._paused,
            # Machine-readable admission state (ISSUE 16): the gateway
            # and status views key shed decisions on this instead of
            # string-matching events. Nested keys ride under the one
            # bound top-level key.
            "admission": {
                "paused": self._paused,
                "open": self._paused is None and not self.closed,
                "queued": len(self._queue),
            },
            "journal_turns": self.journal_turns,
            "journal_errors": self.journal_errors,
            "events": events,
        }

    def snapshot(self) -> dict[str, Any]:
        """Cheap roll-up for fleet_health(): queue depth + per-session
        state (queued / active with live row count)."""
        sessions: dict[str, str] = {}
        with self._cv:
            for req in self._queue:
                sessions.setdefault(req.session, "queued")
        for req in list(self._active_reqs):
            live = sum(1 for r in req.rows if not r.done)
            sessions[req.session] = f"active({live} live rows)"
        # Spilled-session state only for LIVE schedulers: a closed
        # scheduler's engine may outlive it (module fixtures, the engine
        # cache), and its snapshot claiming host-RAM sessions would make
        # fleet_health point operators at a scheduler that serves
        # nothing.
        tier = getattr(self.engine, "kv_offload", None)
        if tier is not None and not self.closed:
            for s in tier.spilled_sessions():
                sessions.setdefault(s, "spilled(host RAM)")
        return {
            "engine": getattr(self.engine.cfg, "name", "?"),
            "queued": len(self._queue),
            "active_rows": len(self._active),
            "sessions": sessions,
            "paused": self._paused,
            "closed": self.closed,
        }

    def declare_warmup_complete(self) -> None:
        """Declare this scheduler's compile set closed (ISSUE 6): the
        caller has warmed every bucket composition it intends to serve
        (engine.warmup(batch_sizes=...) + representative traffic), so
        any later compile is a mid-serve recompile — counted and
        flight-dumped always, fatal under ROUNDTABLE_RECOMPILE_STRICT=1
        (the pow2-bucket invariant, enforced instead of assumed)."""
        from . import compile_watch
        compile_watch.warmup_complete(self._tname)

    # ------------------------------------------------------------------
    # drain / lifecycle
    # ------------------------------------------------------------------

    def reject_queued(self, error: Optional[BaseException] = None) -> int:
        """Fail every queued-but-unadmitted request immediately (the
        fleet.drain satellite: a queued session gets a clean
        DrainingError instead of waiting out its budget). Active
        requests finish their rounds normally. Returns the count.

        Provenance stays truthful: only drain rejections count as
        `rejected_draining` / event `reject_drain`; close() and
        loop-error rejections land under `rejected_other` with the
        error class named, so describe() never claims a drain that
        never happened."""
        error = error or deadlines.DrainingError(
            "fleet is draining: queued session was never admitted "
            "(fleet.resume() re-opens admission)")
        draining = isinstance(error, deadlines.DrainingError)
        rejected: list[_Request] = []
        with self._cv:
            while self._queue:
                rejected.append(self._queue.popleft())
        for req in rejected:
            req.error = error
            req.event.set()
            with self._cv:  # drain/close threads race the loop thread
                if draining:
                    self._bump("rejected_draining")
                else:
                    self._bump("rejected_other")
            if draining:
                self._event("reject_drain", session=req.session)
            else:
                self._event("reject", session=req.session,
                            reason=type(error).__name__)
        return len(rejected)

    def pause_admission(self, reason: str = "paused") -> None:
        """Close the admission gate (ISSUE 12): queued and newly
        submitted requests WAIT (nothing is rejected); active requests
        keep serving. The supervisor's quiesce and fleet.drain use
        this; reopen_admission (or fleet.resume) lifts it."""
        with self._cv:
            if self._paused is None:
                self._paused = reason
        self._event("pause_admission", reason=reason)

    def reopen_admission(self) -> None:
        """Re-open the admission gate and wake the loop — the
        fleet.resume satellite: a drained/supervised scheduler's queue
        must actually resume admitting, not just stop rejecting."""
        with self._cv:
            was = self._paused
            self._paused = None
            self._cv.notify_all()
        if was is not None:
            self._event("reopen_admission", was=was)

    @property
    def paused(self) -> Optional[str]:
        return self._paused

    def quiesce(self, timeout_s: float = 30.0) -> bool:
        """Pause admission and wait (from a non-loop thread) for every
        ACTIVE request to retire or fail — the supervisor's step 2.
        Returns True when the batch drained clean within `timeout_s`
        (queued requests stay queued; they serve after the restart)."""
        self.pause_admission("quiesce")
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while self._active_reqs and time.monotonic() < deadline:
                # Retirement doesn't notify the cv — the timeout doubles
                # as the poll cadence.
                self._cv.wait(timeout=0.05)
        return not self._active_reqs

    def fail_active_requests(self, err: BaseException) -> int:
        """Fail every active request with `err` — LOOP-THREAD ONLY (the
        supervisor's crash path runs on this thread inside the failed
        dispatch's tick). Returns the count."""
        reqs = list(self._active_reqs)
        for req in reqs:
            self._fail_request(req, err)
        return len(reqs)

    def force_fail_active(self, err: BaseException,
                          timeout_s: float = 5.0) -> int:
        """Thread-safe preempt: ask the loop to fail every active
        request with `err` at its next health check, then wait for it.
        The supervisor's quiesce-timeout fallback — request state is
        single-writer (the loop thread), so an external thread must
        never mutate it directly. Returns requests failed (best
        effort: the loop may be wedged in a device wait, in which case
        the watchdog — not this call — unwedges it)."""
        with self._cv:
            n = len(self._active_reqs)
            if n == 0:
                return 0
            self._force_fail = err
            self._cv.notify_all()
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if not self._active_reqs:
                return n
            time.sleep(0.02)
        return n - len(self._active_reqs)

    def reattach_engine(self, new_engine) -> None:
        """Point this scheduler at a REBUILT engine (the supervisor's
        step 5). Caller contract: admission is paused, no requests are
        active, and the old engine's serve lock is not held by this
        scheduler. The rebuilt engine re-enters warmup (reopen_warmup)
        so its fresh compiles are sanctioned under
        ROUNDTABLE_RECOMPILE_STRICT — the caller re-declares via
        declare_warmup_complete() once post-restart traffic is warm."""
        from . import compile_watch
        self.engine = new_engine
        new_engine._scheduler = self
        self.max_rows = min(self.max_rows, new_engine.kv.num_slots)
        compile_watch.reopen_warmup(self._tname)
        self._event("reattach_engine")

    def attach_journal(self, journal) -> None:
        """Attach a durable session journal (engine/session_journal):
        every retired round appends one fsynced committed-turn record."""
        self._journal = journal

    @property
    def journal(self):
        return self._journal

    def close(self, timeout_s: float = 30.0) -> None:
        """Stop the loop: queued requests are rejected, active requests
        are allowed `timeout_s` to finish, then the thread exits."""
        self.closed = True
        self.reject_queued(SchedulerClosed(
            "scheduler closed before this session was admitted"))
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._thread.join(timeout=timeout_s)

    # ------------------------------------------------------------------
    # the scheduler loop
    # ------------------------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cv:
                # A paused scheduler with only queued work sleeps: the
                # queue cannot be admitted until reopen_admission
                # notifies, and a busy-tick would spin the loop.
                while (not self._active and not self._stop
                       and not self._idle_spill_due()
                       and (not self._queue or self._paused)):
                    self._cv.wait(timeout=0.25)
                    if self._queue and self._paused:
                        # Paused with queued work: tick at the wait
                        # cadence anyway so queue-deadline sweeps still
                        # run (a request must die at ITS timeout even
                        # while admission is gated).
                        break
                if self._stop and not self._active and not self._queue:
                    break
            try:
                self._tick()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                # An unexpected scheduler bug must not wedge every
                # submitter: fail all in-flight work with the error.
                self._event("loop_error", error=str(e))
                for req in list(self._active_reqs):
                    self._fail_request(req, e)
                self.reject_queued(e)
            if not self._active:
                self._release_engine()
        self._release_engine()

    def _tick(self) -> None:
        if deadlines.DRAINING:
            self.reject_queued()
        if self._stop:
            self.reject_queued(SchedulerClosed("scheduler closed"))
        self._check_request_health()
        self._sweep_queue()
        self._prune_last_active()
        self._spill_idle_by_age()
        self._admit_queued()
        live = [r for r in self._active
                if not r.done and not r.pending]
        filling = [r for r in self._active
                   if not r.done and r.pending]
        if filling:
            # Chunk-interleaved admission (ISSUE 8): while any row is
            # still prefilling, segments are RAGGED mixed dispatches —
            # every live row decodes one token while the filling rows'
            # chunks ride the same program. Steady state (no filling
            # rows) keeps the pipelined while-loop segments.
            self._run_ragged_segment(live, filling)
        elif live:
            # Speculative phase (ISSUE 9): with no fills pending and
            # drafts available, one verify dispatch advances every row
            # by 1..spec_max_draft+1 tokens; otherwise the pipelined
            # while-loop segments serve. One dispatch per tick, so
            # joins/retires recompose at every boundary — the
            # _may_speculate composition rules by construction.
            if not self._run_spec_segment(live):
                self._run_segment(live)
        self._flush_streams()
        self._retire_finished()
        self._check_request_health()

    def _acquire_engine(self) -> None:
        if not self._lock_held:
            lock = self.engine._serve_lock
            lock.acquire()
            self._held_lock = lock
            self._lock_held = True

    def _release_engine(self) -> None:
        if self._lock_held:
            self._lock_held = False
            lock, self._held_lock = self._held_lock, None
            lock.release()

    # --- admission ---

    def _sweep_queue(self) -> None:
        """Fail expired/abandoned requests ANYWHERE in the queue — not
        just the head: a request stuck behind a non-fitting head must
        still die at ITS deadline with an honest queue timeout, not
        escape 60s later through the waiter's anti-wedge bound."""
        now = time.monotonic()
        expired: list[_Request] = []
        abandoned: list[_Request] = []
        with self._cv:
            keep: deque[_Request] = deque()
            for req in self._queue:
                if req.abandoned:
                    # A blocking waiter is simply gone — drop silently.
                    # A STREAMING submitter (on_commit) still needs the
                    # terminal event: without it the gateway's stream
                    # state never finishes and its inflight gauge
                    # leaks (ISSUE 19 abandonment regression).
                    if req.on_commit is not None:
                        abandoned.append(req)
                    continue
                if ((req.budget is not None and req.budget.expired)
                        or now - req.enqueued > req.timeout_s):
                    expired.append(req)
                else:
                    keep.append(req)
            self._queue = keep
        for req in abandoned:
            self._fail_request(req, TimeoutError(
                f"session {req.session!r} abandoned by its waiter "
                "while queued"))
        for req in expired:
            self._fail_request(req, TimeoutError(
                f"session {req.session!r} timed out in the admission "
                "queue before any capacity freed"))

    def _admit_queued(self) -> None:
        while True:
            with self._cv:
                if not self._queue or self._paused:
                    # Paused (supervisor quiesce / fleet drain): queued
                    # requests WAIT — they are served after the gate
                    # reopens, never rejected here.
                    return
                req = self._queue[0]
                # Batch-formation hold: with an EMPTY batch, wait up to
                # admit_hold_s since the head request enqueued so
                # co-arriving sessions join the same first segment
                # (deterministic co-scheduling for tests/benches).
                if (self.admit_hold_s and not self._active):
                    remaining = (req.enqueued + self.admit_hold_s
                                 - time.monotonic())
                    if remaining > 0:
                        self._cv.wait(timeout=remaining)
                        continue
                if not self._fits_now(req):
                    # Backpressure: keep it QUEUED — retirement frees
                    # capacity. (Never-fits was refused at submit.)
                    self._event("queue_wait", session=req.session,
                                queued=len(self._queue))
                    return
                self._queue.popleft()
            self._acquire_engine()
            try:
                self._start_request(req)
            except Exception as e:  # noqa: BLE001 — per-request contain
                if self._requeue_on_exhaustion(req, e):
                    return
                # _prepare_batch may have acquired slots/pages before
                # raising; req.rows is still empty, so _fail_request's
                # release loop would free nothing — undo explicitly or
                # the orphans distort _fits_now until LRU pressure.
                self._release_request_slots(req)
                self._release_adapters(req)
                self._fail_request(req, e)
                # Engine-fatal triage runs on the admission path too: a
                # device_lost during the admission prefill must reach
                # the supervisor (rebuild + restore), not leave a sick
                # engine serving the remaining sessions.
                if self._supervisor_intervened(e):
                    return
                self._after_engine_failure(e)

    def _release_request_slots(self, req: _Request) -> None:
        """Undo a partial admission: release every slot this request's
        turns may have acquired (scheduler thread only — KV host state
        is single-writer by design)."""
        for name, _prompt in req.turns:
            try:
                self.engine.kv.release(scoped_slot(req.session, name))
            except Exception:  # noqa: BLE001 — best-effort undo
                pass

    def _requeue_on_exhaustion(self, req: _Request,
                               err: BaseException) -> bool:
        """The page-demand estimate under-counted (token-dense prompts)
        and admission hit real pool exhaustion while other sessions
        hold pages: that is BACKPRESSURE, not a request failure — undo
        the partial admission (release this request's slots; active
        rows are pinned and untouched) and requeue at the head, gated
        on the batch actually shrinking before the next attempt."""
        if (not self._active or req.requeues >= 8
                or not isinstance(err, RuntimeError)
                or "pool exhausted" not in str(err).lower()):
            return False
        self._release_request_slots(req)
        self._release_adapters(req)
        req.requeues += 1
        telemetry.inc("roundtable_sched_requeues_total",
                      engine=self._tname)
        req.fits_below = len(self._active)
        req.admitted_at = None
        with self._cv:
            self._queue.appendleft(req)
        self._event("requeue", session=req.session,
                    reason="page pool exhausted",
                    fits_below=req.fits_below)
        return True

    def _fits_now(self, req: _Request) -> bool:
        engine = self.engine
        if len(self._active) + len(req.turns) > self.max_rows:
            return False
        if (req.fits_below is not None
                and len(self._active) >= req.fits_below):
            # A previous admission of this request hit REAL pool
            # exhaustion at this batch size — wait for retirement to
            # actually shrink the batch before re-attempting.
            return False
        store = getattr(engine, "lora", None)
        if (store is not None and req.adapters
                and not store.can_admit(req.adapters)):
            # Adapter-residency backpressure (ISSUE 10): every store
            # slot is referenced by live rows — retirement frees refs,
            # then the LRU evicts and this request's personas load.
            return False
        if engine.kv_layout == "paged" and self._active:
            # Pages the live rows have pinned are untouchable; the rest
            # of the pool (free or held by idle evictable slots) is what
            # a join can claim.
            kv = engine.kv
            pinned = kv.pages_held([r.name for r in self._active])
            avail = kv.usable_pages() - pinned
            if self._pages_needed(req.turns, req.max_new) > avail:
                return False
        return True

    # --- host-RAM KV offload policy (ISSUE 7) ---

    def _spillable_sessions(self, exclude: set[str]) -> list[str]:
        """Sessions whose slots sit idle in the pool: namespaced, not
        actively decoding, not queued, not excluded — ordered least-
        recently-active first."""
        from .kvcache import session_of
        busy = {session_of(r.name) for r in self._active}
        with self._cv:
            busy |= {r.session for r in self._queue}
        busy |= exclude
        seen: dict[str, None] = {}
        for n in self.engine.kv.slot_names():
            s = session_of(n)
            if s and s not in busy:
                seen.setdefault(s)
        return sorted(seen, key=lambda s: self._last_active.get(s, 0.0))

    def _spill_sessions(self, sessions: list[str], reason: str,
                        want_pages: Optional[int] = None) -> int:
        tier = getattr(self.engine, "kv_offload", None)
        if tier is None:
            return 0
        kv = self.engine.kv
        spilled = 0
        for s in sessions:
            free0 = kv.free_pages()
            n = tier.spill_session(s)
            if n:
                spilled += 1
                with self._cv:
                    self._bump("spills")
                self._event("spill", session=s, reason=reason,
                            slots=n, pages_freed=kv.free_pages() - free0)
            if want_pages is not None and kv.free_pages() >= want_pages:
                break
        return spilled

    _LAST_ACTIVE_PRUNE_AT = 1024

    def _prune_last_active(self) -> None:
        """Bound the last-activity map: a long-lived scheduler admits a
        fresh uuid-tagged session id per discussion, and an entry per
        dead session forever is the same slow leak the per-session KV
        gauges already had to fix (PR 6's remove_gauge). Entries whose
        session holds no pool slots, no spill record, and is neither
        active nor queued are gone for good — drop them once the map
        outgrows the threshold (amortized: one sweep per ~1024 dead
        sessions, host dict math only)."""
        if len(self._last_active) <= self._LAST_ACTIVE_PRUNE_AT:
            return
        from .kvcache import session_of
        keep = {session_of(n) for n in self.engine.kv.slot_names()}
        tier = getattr(self.engine, "kv_offload", None)
        if tier is not None:
            keep |= set(tier.spilled_sessions())
        keep |= {r.session for r in self._active_reqs}
        # The sweep holds the cv: submit() threads insert new sessions
        # into this dict under the same lock, and a resize mid-iteration
        # would raise out of _tick and fail every in-flight request.
        with self._cv:
            keep |= {r.session for r in self._queue}
            for s in [s for s in self._last_active if s not in keep]:
                del self._last_active[s]

    def _idle_spill_due(self) -> bool:
        """True when the proactive idle policy has work — the loop's
        idle wait must wake for it, or an otherwise-quiet scheduler
        would never run the spill tick."""
        if (self.idle_spill_s is None or self._paused
                or getattr(self.engine, "kv_offload", None) is None):
            # Paused must mirror _spill_idle_by_age's gate: if "due"
            # stayed True while the spill tick refused to run, the idle
            # wait would never sleep and the loop would busy-spin for
            # the whole pause window.
            return False
        now = time.monotonic()
        return any(now - self._last_active.get(s, now)
                   >= self.idle_spill_s
                   for s in self._spillable_sessions(set()))

    def _spill_idle_by_age(self) -> None:
        """Proactive idle spill (idle_spill_s set): a session that has
        not submitted for idle_spill_s releases its HBM pages to host
        RAM — a consensus round can sit for minutes while humans type,
        and resident-but-idle KV is exactly the capacity ceiling this
        tier lifts."""
        if (self.idle_spill_s is None or self._paused
                or getattr(self.engine, "kv_offload", None) is None):
            # Paused: the supervisor may hold (or be about to take) the
            # serve lock for an engine swap — don't contend for it.
            return
        now = time.monotonic()
        idle = [s for s in self._spillable_sessions(set())
                if now - self._last_active.get(s, now)
                >= self.idle_spill_s]
        if not idle:
            return
        self._acquire_engine()
        try:
            self._spill_sessions(idle, reason="idle")
        finally:
            if not self._active:
                self._release_engine()

    def _spill_for_pressure(self, req: _Request) -> None:
        """Admission-time pressure valve: when the pool's FREE pages
        cannot cover the incoming request's estimate, spill idle
        sessions (least-recently-active first) BEFORE _prepare_batch
        runs — otherwise the allocator's LRU eviction would destroy
        exactly the idle caches that make those sessions' next turns
        cheap. The admission itself then proceeds instead of queueing
        behind capacity that idle sessions were hoarding."""
        engine = self.engine
        if (getattr(engine, "kv_offload", None) is None
                or engine.kv_layout != "paged"):
            return
        # NEW-page demand, not the whole-prompt estimate: in steady
        # state a session's next turn is mostly its own committed
        # transcript, already paged in under its scoped slots — counting
        # those pages as demand would declare pressure on every
        # admission past ~half occupancy and churn idle sessions
        # through spill/restore for pages the turn never needed.
        scoped = [scoped_slot(req.session, n) for n, _ in req.turns]
        need = (self._pages_needed(req.turns, req.max_new)
                - engine.kv.pages_held(scoped))
        free = engine.kv.free_pages()
        if need <= free:
            return
        self._spill_sessions(
            self._spillable_sessions(exclude={req.session}),
            reason="pressure", want_pages=need)

    def _start_request(self, req: _Request) -> None:
        """Admission: the engine's own pre-decode phase
        (InferenceEngine._prepare_batch — reuse-plan → intra-session
        prefix share → chunked prefill → first-token sample; ONE
        definition, so scheduler admission can never drift from
        generate_batch on token parity), with every live row pinned
        against eviction. Loop-thread only (single-writer counter
        bumps need no cv — RT-LOCK-BUMP contract)."""
        engine = self.engine
        # Admission STARTS the request's clock (queue time is bounded
        # separately in _admit_queued): the scheduler-side deadline and
        # the waiter's anti-wedge bound both key off this moment.
        req.admitted_at = time.monotonic()
        if faults.ARMED and len(req.turns) > 1:
            # Same chaos point as the engine's batched path: a corrupt-KV
            # fault fails the fan-out before slot bookkeeping mutates,
            # so the adapter's serial-retry rung takes over per session.
            faults.maybe_inject("kv_corrupt")
        t0 = time.monotonic()
        stats = req.stats
        turn_budget = req.budget if req.budget is not None \
            else deadlines.Budget.root(req.timeout_s, rung="turn")
        deadline = min(turn_budget.deadline,
                       time.monotonic() + req.timeout_s)
        pre_budget = turn_budget.child("prefill")
        max_new, max_new_padded = clamp_max_new(req.max_new,
                                                engine.max_seq_len)

        self._spill_for_pressure(req)
        # Adapter residency (ISSUE 10): taken on the scheduler thread
        # while it holds the engine serve lock, so a load's stacked-
        # tensor swap can never race a dispatch's argument capture.
        # Refs are held for the REQUEST's lifetime (rows keep decoding
        # across segments) and released at retire/fail.
        store = getattr(engine, "lora", None)
        row_slots = None
        if store is not None:
            ads = req.adapters or [None] * len(req.turns)
            row_slots = store.acquire(ads)
            req.adapters = ads
            req.adapters_held = True
        active_names = tuple(r.name for r in self._active)
        scoped_turns = [(scoped_slot(req.session, n), p)
                        for n, p in req.turns]
        # Chunk-interleaved admission (ISSUE 8): with live rows decoding
        # and the engine's ragged path on, the prologue's chunked
        # prefill is DEFERRED — admission does only the host/aliasing
        # work, and the suffixes join the live decode segment as ragged
        # chunks. An empty batch keeps the prologue (there is no decode
        # to interleave with, and the bucketed chunks are bigger).
        # ROUNDTABLE_RAGGED_ATTN=0 restores the prologue unconditionally.
        # Defer only onto the KERNEL path: an engine whose pool the
        # kernel declined at build time (xla_ragged — the memory-heavy
        # dense fallback, "never the serving default") keeps the
        # prologue for joins; the fallback still serves fills already
        # in flight when a mid-serve degrade flips the path.
        deferred = (getattr(engine, "ragged_path", None)
                    == "pallas_ragged" and bool(self._active))
        prep = engine._prepare_batch(
            scoped_turns, max_new_padded, deadline, pre_budget,
            req.sampling_per_turn, extra_pinned=active_names,
            defer_prefill=deferred, adapters=req.adapters)
        # The engine may resolve a WARM join back to the prologue
        # (suffix below ragged_defer_min — blocking one tiny bucket
        # dispatch beats segment-gated chunk ticks); first_np says
        # which mode actually served.
        deferred = prep["first_np"] is None
        stats.prefill_tokens = prep["prefill_tokens"]
        stats.reused_tokens = prep["reused_tokens"]
        stats.prefix_reused_tokens = prep["prefix_reused_tokens"]
        stats.prefill_seconds = time.monotonic() - t0
        if row_slots and any(row_slots) and prep["first_np"] is not None:
            engine.note_lora_tokens(sum(
                len(t) - o for t, o, sl in zip(prep["all_tokens"],
                                               prep["offsets"],
                                               row_slots) if sl))

        eos = engine.tokenizer.eos_id
        per_row = prep["per_row"]
        rows = []
        for i, scoped in enumerate(prep["names"]):
            # Only an EXPLICIT sampling_per_turn carries per-row caps —
            # the engine-default sampling's budget must not silently cap
            # the call-level request (serving_loop.row_budget_fn rule).
            row_cap = (min(per_row[i].max_new_tokens, max_new)
                       if req.sampling_per_turn else max_new)
            toks = prep["all_tokens"][i]
            if deferred:
                off = prep["offsets"][i]
                if off >= len(toks):
                    # Full-prefix cache hit: re-feed the last prompt
                    # token (identical K/V bytes at its own position)
                    # so the join still samples a first token; COW the
                    # rewritten cell out of any shared page first.
                    off = len(toks) - 1
                    engine.kv.ensure_capacity(
                        scoped, len(toks), write_from=off,
                        pinned=tuple(prep["names"]) + active_names)
                rows.append(_Row(
                    name=scoped, tokens=toks, sampling=per_row[i],
                    max_new=row_cap, slot_id=prep["slot_ids"][i],
                    pending=list(toks[off:]), pos=off, valid=off,
                    adapter_slot=(row_slots[i] if row_slots else 0)))
            else:
                tok = int(prep["first_np"][i])
                rows.append(_Row(
                    name=scoped, tokens=toks,
                    sampling=per_row[i], max_new=row_cap,
                    slot_id=prep["slot_ids"][i], produced=[tok],
                    last=tok, valid=len(toks),
                    done=(tok == eos),
                    adapter_slot=(row_slots[i] if row_slots else 0)))
        req.rows = rows
        if engine.spec_decode:
            # Per-row self-drafters (ISSUE 9): the corpus is the row's
            # OWN prompt — which carries the whole transcript and any
            # prefix-cache-attached context — extended incrementally as
            # output tokens commit (RowSpec.drafter.sync before every
            # draft). Host dict work only, O(prompt) once per admission.
            from .spec_decode import RowSpec
            # Device drafters (model/lora) keep their state in the
            # shadow draft slots — skip the per-row O(prompt) n-gram
            # index entirely (prompts carry whole transcripts); a
            # later hot-swap to ngram rebuilds it lazily in
            # _spec_drafts.
            kind = getattr(engine, "spec_drafter", None) or "ngram"
            for r in rows:
                r.spec = RowSpec(
                    list(r.tokens) if kind == "ngram" else None,
                    kind=kind)
        if deferred:
            # Deferred leader-span plans (the last prologue dispatch,
            # gone): laggard rows BLOCK until the leader's chunks write
            # the common span, then alias it in (_apply_share_plans).
            req.share_plans = [
                {"leader": rows[p["leader"]], "hi": p["hi"],
                 "followers": [(rows[i], lo) for i, lo in
                               p["followers"]]}
                for p in prep.get("share_plan", [])]
            for plan in req.share_plans:
                for f, _lo in plan["followers"]:
                    f.blocked = True
        req.turn_budget = turn_budget
        req.dec_budget = turn_budget.child("decode")
        req.deadline = deadline
        if not deferred:
            req.first_token_at = time.monotonic()
        self._active.extend(rows)
        self._active_reqs.append(req)
        for r in rows:
            self._row_req[id(r)] = req
        self._bump("admitted")
        if deferred:
            self._bump("ragged_joins")
        if telemetry.ACTIVE:
            # The request's "turn" span: lives across segments (ended at
            # retire/fail), parented to the SUBMITTER's trace so spans
            # from the scheduler thread land in the right discussion.
            req.tele = telemetry.start_span(
                "turn", parent=req.tele_ctx, session=req.session,
                engine=self._tname, rows=len(rows), scheduled=True,
                queue_wait_s=round(req.admitted_at - req.enqueued, 3))
        self._event("admit", session=req.session, rows=len(rows),
                    queue_wait_s=round(req.admitted_at - req.enqueued, 3),
                    reused_tokens=stats.reused_tokens,
                    ragged_join=deferred)

    # --- the decode segment ---

    def _run_segment(self, live: list[_Row]) -> None:
        """Run one or more DECODE_SEGMENTs over the live rows,
        PIPELINED like serving_loop.decode_segments: while composition
        cannot change (no queued session, nobody waiting to retire,
        work remaining), the next segment is dispatched from the
        previous segment's DEVICE outputs BEFORE the host reads them —
        the device never idles on the per-segment host round-trip
        (material on a high-RTT tunnel). The mini-loop exits whenever
        the batch must recompose (join pending, a request fully done,
        budgets/deadline/drain) and _tick takes over."""
        ctx = self._build_batch(live)
        # The clock starts BEFORE the first dispatch (ISSUE 9 perfmodel
        # satellite): on synchronous backends the jit call itself runs
        # the compute, so starting after it attributed ~zero decode
        # seconds to every single-segment turn — and its 'tok/s' then
        # read as thousands. Dispatch-issue time is part of the
        # segment's wall on async backends too.
        t_prev = time.monotonic()
        try:
            handles = self._dispatch(ctx)
        except Exception as e:  # noqa: BLE001 — preempt-isolate ladder
            self._handle_segment_failure(live, e)
            return
        while True:
            spec_ctx = spec_handles = spec_err = None
            if self._may_speculate(ctx):
                spec_ctx = self._advance(ctx, handles)
                try:
                    spec_handles = self._dispatch(spec_ctx)
                except Exception as e:  # noqa: BLE001 — handled below
                    # The in-flight segment is still unread; read it
                    # first so host state is consistent, THEN ladder
                    # the speculative dispatch's failure.
                    spec_err = e
            alive = [r for r in ctx["rows"] if not r.done]
            counts = self._account_segment(alive)
            try:
                # Scheduler-side "segment" span (sink-less: it spans
                # SEVERAL sessions' traces, so it lands in the flight
                # recorder ring rather than any one session's JSONL).
                with telemetry.span("segment", engine=self._tname,
                                    rows=len(alive), scheduled=True):
                    steps = self._read_segment(ctx, handles)
            except Exception as e:  # noqa: BLE001 — preempt-isolate
                self._handle_segment_failure(alive, e)
                return
            now = time.monotonic()
            self._attribute_wall(counts, now - t_prev)
            # Per-phase token split (ISSUE 8): a while-loop segment is
            # pure decode — counted into the same series the ragged
            # mixed segments split, so the two paths share one ledger.
            self._note_segment_tokens(0, steps * len(alive))
            # Live roofline sample at the segment boundary (ISSUE 6):
            # this segment's aggregate decode rate vs the engine's
            # weight-streaming ceiling, as a bw_utilization gauge.
            perf = getattr(self.engine, "perf", None)
            if perf is not None:
                perf.publish_decode_sample(
                    steps * len(alive), now - t_prev,
                    lora_bytes_per_token=self._lora_bytes_per_token(
                        alive))
            t_prev = now
            if spec_err is not None:
                still = [r for r in alive
                         if not r.done and id(r) in self._row_req]
                if still:
                    self._handle_segment_failure(still, spec_err)
                return
            if spec_handles is None:
                return
            ctx, handles = spec_ctx, spec_handles

    # --- the ragged mixed segment (ISSUE 8) ---

    def _note_segment_tokens(self, prefill: int, decode: int) -> None:
        """Per-phase token split of a consumed segment — the counters
        AND their registry series move together (the _bump rule), so
        describe() and the drift lint stay honest for mixed batches."""
        if prefill:
            self.segment_prefill_tokens += prefill
            telemetry.inc("roundtable_segment_prefill_tokens_total",
                          prefill, engine=self._tname)
        if decode:
            self.segment_decode_tokens += decode
            telemetry.inc("roundtable_segment_decode_tokens_total",
                          decode, engine=self._tname)

    def _apply_share_plans(self) -> None:
        """Alias deferred leader spans whose leader chunks have written
        the common span (kvcache.share_prefixes defer_span contract):
        laggards' tables take the leader's span pages (whole pages
        alias, boundary pages device-copy — the same one-shape padded
        copier admission aliasing uses) and the rows unblock, their
        pending already trimmed to the post-span tail at admission."""
        for req in list(self._active_reqs):
            if not req.share_plans:
                continue
            remaining = []
            failed: Optional[BaseException] = None
            for plan in req.share_plans:
                leader = plan["leader"]
                if leader.pos < plan["hi"]:
                    remaining.append(plan)
                    continue
                pinned = tuple(r.name for r in self._active)
                _max_new, padded = clamp_max_new(
                    req.max_new, self.engine.max_seq_len)
                try:
                    for f, lo in plan["followers"]:
                        self.engine.kv.alias_span(
                            leader.name, f.name, lo, plan["hi"], pinned)
                        # Tail capacity (deferred from admission so the
                        # span pages arrive SHARED, not as transient
                        # exclusive allocations the alias would
                        # replace).
                        self.engine.kv.ensure_capacity(
                            f.name, len(f.tokens) + padded,
                            write_from=plan["hi"], pinned=pinned)
                        f.blocked = False
                except Exception as e:  # noqa: BLE001 — contain per req
                    # Pool exhaustion mid-join (the prologue path's
                    # equivalent was a requeue at admission): fail ONLY
                    # this request into its adapter ladder — an escape
                    # to _loop's catch-all would take every in-flight
                    # session down with it.
                    failed = e
                    break
                self._event("share_alias", session=req.session,
                            hi=plan["hi"],
                            followers=len(plan["followers"]))
            if failed is not None:
                self._fail_request(req, failed)
                continue
            req.share_plans = remaining

    def _run_ragged_segment(self, live: list[_Row],
                            filling: list[_Row]) -> None:
        """One RAGGED mixed dispatch: every live decode row advances one
        token while the filling rows' next prefill chunks ride the SAME
        program — the admission prologue's replacement (arxiv
        2604.15464; RTP-LLM's chunked-prefill-joins-the-decode-batch
        shape). The flat buffer is token-budgeted, not row-bucketed:
        one compiled shape serves every composition, so occupancy drift
        and chunk interleaving compile nothing. The loop runs one
        dispatch per _tick so joins/retires/admissions interleave at
        every boundary."""
        engine = self.engine
        budget_slots = engine.ragged_tokens
        # A leader that finished its span in the previous dispatch
        # unblocks its laggards BEFORE packing, so their chunks join
        # this very segment.
        self._apply_share_plans()
        filling = [r for r in filling if not r.done and r.pending
                   and not r.blocked]
        if not filling:
            if live:
                self._run_segment(live)
            return
        # A decode row costs one RAGGED_BLOCK_Q tile; keep at least one
        # block of chunk room or the mix degenerates.
        if RAGGED_BLOCK_Q * (len(live) + 1) > budget_slots:
            # Flat buffer cannot carry every live row plus prefill work
            # — decode this segment on the compiled bucket path instead
            # (recorded; prefill continues next tick, never silently
            # stalled).
            self._event("ragged_overflow", rows=len(live))
            if live:
                self._run_segment(live)
            return
        reqs = self._reqs_of(live + filling)
        remaining = min((req.turn_budget.remaining() for req in reqs),
                        default=float("inf"))
        seg_budget = deadlines.Budget.root(
            None if remaining == float("inf") else remaining,
            rung="decode")
        deadline = min((req.deadline for req in reqs),
                       default=float("inf"))

        # Pick the smallest warmed flat-buffer shape that fits the REAL
        # work (serving_loop.ragged_shape_grid): a dispatch computes its
        # whole static buffer, so a lone decode step + tail chunk must
        # not pay the full budget's compute.
        from .serving_loop import ragged_pick_shape
        want = RAGGED_BLOCK_Q * len(live) + sum(
            -(-len(r.pending) // RAGGED_BLOCK_Q) * RAGGED_BLOCK_Q
            for r in filling)
        shape = ragged_pick_shape(engine.ragged_shapes,
                                  min(want, budget_slots))
        seqs: list[RaggedSeq] = []
        rows_in: list[tuple[str, _Row, int]] = []
        for r in live:
            seqs.append(RaggedSeq(
                [r.last], r.valid, engine.kv.table_for([r.name])[0],
                temperature=r.sampling.temperature,
                top_k=r.sampling.top_k, top_p=r.sampling.top_p,
                adapter=r.adapter_slot))
            rows_in.append(("decode", r, 1))
        slots_left = shape - RAGGED_BLOCK_Q * len(live)
        for r in filling:
            if slots_left < RAGGED_BLOCK_Q:
                break
            take = min(len(r.pending), slots_left)
            seqs.append(RaggedSeq(
                list(r.pending[:take]), r.pos,
                engine.kv.table_for([r.name])[0],
                temperature=r.sampling.temperature,
                top_k=r.sampling.top_k, top_p=r.sampling.top_p,
                adapter=r.adapter_slot))
            rows_in.append(("prefill", r, take))
            slots_left -= -(-take // RAGGED_BLOCK_Q) * RAGGED_BLOCK_Q
        batch = build_ragged_batch(
            seqs, t_budget=shape, s_max=engine.kv.num_slots + 1,
            pages_per_seq=engine.kv.pages_per_seq,
            scratch_page=engine.kv.scratch_page(0),
            pad_id=engine.tokenizer.pad_id,
            page_size=engine.kv.page_size)

        t0 = time.monotonic()
        try:
            with telemetry.span("segment", engine=self._tname,
                                rows=len(seqs), scheduled=True,
                                ragged=True):
                handles = run_dispatch(
                    lambda: engine._ragged_dispatch(batch),
                    engine.retry, deadline, budget=seg_budget)
                nxt = host_sync(lambda: np.asarray(handles), seg_budget,
                                "decode")
        except Exception as e:  # noqa: BLE001 — preempt-isolate ladder
            self._handle_ragged_failure(live, filling, e)
            return
        wall = time.monotonic() - t0

        eos = engine.tokenizer.eos_id
        now = time.monotonic()
        n_prefill = n_decode = 0
        lora_toks = 0
        for i, (kind, r, take) in enumerate(rows_in):
            tok = int(nxt[i])
            req = self._row_req.get(id(r))
            if kind == "decode":
                r.produced.append(tok)
                r.last = tok
                r.valid += 1
                r.done = (tok == eos) or len(r.produced) >= r.max_new
                n_decode += 1
                if r.adapter_slot:
                    lora_toks += 1
            else:
                del r.pending[:take]
                r.pos += take
                n_prefill += take
                if r.adapter_slot:
                    lora_toks += take
                if not r.pending:
                    # Join complete: the chunk that finished the prompt
                    # also sampled the row's first token (the prologue's
                    # first_np, one dispatch earlier than it ever was).
                    r.produced = [tok]
                    r.last = tok
                    r.valid = r.pos
                    # The join token counts against the row's budget: a
                    # max_new_tokens=1 row (journal replay) is DONE here
                    # — leaving it live would hand the spec segment a
                    # zero-room row next tick.
                    r.done = (tok == eos) or len(r.produced) >= r.max_new
                    if (req is not None and req.first_token_at is None
                            and all(not rr.pending for rr in req.rows)):
                        req.first_token_at = now
                        self._event(
                            "join_complete", session=req.session,
                            ttft_s=round(now - req.enqueued, 3))

        # Provenance + attribution: the mixed dispatch splits its wall
        # by per-row token counts — decode rows' share lands in their
        # requests' decode_seconds, chunk tokens in prefill_seconds —
        # and the perfmodel gauges get the same split (a mixed batch
        # must not mislabel its roofline fraction).
        engine.note_lora_tokens(lora_toks)
        self.ragged_segments += 1
        telemetry.inc("roundtable_sched_ragged_segments_total",
                      engine=self._tname)
        self._note_segment_tokens(n_prefill, n_decode)
        occ = len(seqs)
        self.max_occupancy = max(self.max_occupancy, occ)
        with self._cv:
            self._occupancy.append(occ)
        telemetry.set_gauge("roundtable_sched_occupancy", occ,
                            engine=self._tname)
        _note_rows(occ)
        total = max(n_prefill + n_decode, 1)
        sessions = len(reqs)
        for kind, r, take in rows_in:
            req = self._row_req.get(id(r))
            if req is None:
                continue
            share = wall * take / total
            if kind == "decode":
                req.stats.decode_seconds += share
            else:
                req.stats.prefill_seconds += share
        for req in reqs:
            req.seg_count += 1
            req.occ_sum += occ
            req.occ_max = max(req.occ_max, occ)
            req.sess_max = max(req.sess_max, sessions)
        perf = getattr(engine, "perf", None)
        if perf is not None:
            perf.publish_mixed_sample(
                n_prefill, n_decode, wall,
                lora_bytes_per_token=self._lora_bytes_per_token(
                    [r for _k, r, _t in rows_in]))
            for req in reqs:
                perf.publish_session_kv(
                    req.session, sum(r.valid for r in req.rows))

    def _handle_ragged_failure(self, live: list[_Row],
                               filling: list[_Row],
                               err: BaseException) -> None:
        """A ragged mixed dispatch failed. Donation-death first (shared
        pools — everyone fails into their adapter ladders); otherwise
        PREEMPT: requests with rows mid-prefill fail alone (their pages
        hold a half-written chunk; the adapter ladder re-prefills from
        the prompt), while decode-only sessions re-dispatch through the
        compiled segment path from intact host+KV state. Loop-thread
        only (single-writer counter bumps need no cv)."""
        if self._supervisor_intervened(err):
            return
        if self._after_engine_failure(err):
            return
        self._bump("preemptions")
        self._event("preempt_isolate", error=str(err)[:200], ragged=True,
                    sessions=[req.session
                              for req in self._reqs_of(live + filling)])
        for req in self._reqs_of(live + filling):
            if req not in self._active_reqs:
                continue
            if any(r.pending for r in req.rows):
                self._fail_request(req, err)
                continue
            mine = [r for r in live if r in req.rows and not r.done]
            if not mine:
                continue
            t0 = time.monotonic()
            try:
                self._dispatch_rows(mine)
            except Exception as e:  # noqa: BLE001 — per-session contain
                if self._after_engine_failure(e):
                    return
                self._fail_request(req, e)
                continue
            req.stats.decode_seconds += time.monotonic() - t0

    # --- the speculative verify segment (ISSUE 9) ---

    def _spec_drafts(self, live: list[_Row],
                     probe: bool = False, dispatch=None,
                     read=None) -> Optional[dict]:
        """Per-row draft proposals for one verify dispatch (ISSUE 13:
        drafter-aware): each spec-enabled row that `should_draft` —
        unthrottled, or throttled-but-re-probing — proposes up to
        `branch` candidate PATHS (chain drafters: one), capped by its
        remaining token budget (a verify commits up to depth+1 tokens,
        so a row with <= 1 remaining never drafts). The ngram drafter
        proposes host-side per row; model/LoRA drafters batch all rows
        through the engine's DeviceDrafter (ordinary ragged dispatches
        against the shadow draft slots). Returns {id(row): [path,...]}
        or None when NO row drafts — the tick then serves the plain
        pipelined segments, which is exactly the 1-token-decode
        fallback the adaptive throttle promises (a non-accepting batch
        must never pay more dispatches than plain decode)."""
        engine = self.engine
        if (not getattr(engine, "spec_decode", False)
                or not engine.ragged_enabled):
            return None
        if RAGGED_BLOCK_Q * len(live) > engine.ragged_tokens:
            return None  # flat buffer cannot carry every live row
        tree = getattr(engine, "spec_tree", None)
        branch = engine.spec_branch if tree else 1
        depth = min(tree["depth"], engine.spec_max_draft) if tree \
            else engine.spec_max_draft
        dd = getattr(engine, "spec_device_drafter", None)

        def cap_of(r: _Row) -> int:
            if r.spec is None or not r.spec.should_draft(len(r.produced)):
                return 0
            return min(depth, r.max_new - len(r.produced) - 1)

        if dd is not None:
            from .spec_decode import DraftUnavailable
            if probe:
                # Eligibility alone answers _may_speculate — a device
                # drafter always proposes >= 1 token for an eligible
                # row, and probing must cost neither draft dispatches
                # nor the O(transcript) context copies below.
                return ({"__probe__": True}
                        if any(cap_of(r) >= 1 for r in live) else None)
            rows = []
            for r in live:
                c = cap_of(r)
                if c >= 1:
                    # Incremental context cache: extend with the newly
                    # committed tokens only — never re-concatenate the
                    # whole transcript per tick.
                    cc = r.spec.ctx
                    if cc is None:
                        cc = r.spec.ctx = list(r.tokens)
                    need = len(r.tokens) + len(r.produced)
                    if len(cc) < need:
                        cc.extend(r.produced[len(cc) - len(r.tokens):])
                    rows.append((id(r), r.name, cc, c, branch))
            if not rows:
                return None
            pinned = tuple(r.name for r in self._active)
            try:
                proposals = dd.propose(engine, rows, pinned=pinned,
                                       dispatch=dispatch, read=read)
            except DraftUnavailable as e:
                # Slot/page pressure ONLY (the drafter's own benign
                # capacity signal): the batch is too big to shadow —
                # serve plain decode this tick (never evict live rows
                # to draft for them) with the reason on record. Device
                # dispatch failures propagate to _run_spec_segment's
                # ragged failure ladder (donation-death check included).
                self._event("spec_draft_unavailable",
                            error=str(e)[:160])
                return None
            drafts = {id(r): proposals.get(id(r), []) for r in live}
            return drafts if any(drafts.values()) else None

        drafts: dict[int, list[list[int]]] = {}
        any_draft = False
        for r in live:
            paths: list[list[int]] = []
            cap = cap_of(r)
            if cap >= 1:
                if r.spec.drafter is None:
                    # Hot-swapped from a device drafter to ngram
                    # mid-flight: build this row's index lazily (the
                    # admission-time build is skipped under device
                    # drafters — whole-transcript prompts make it real
                    # host CPU/memory).
                    from .spec_decode import NGramDrafter
                    r.spec.drafter = NGramDrafter(list(r.tokens))
                    r.spec.kind = "ngram"
                r.spec.drafter.sync_parts(r.tokens, r.produced)
                if branch > 1:
                    paths = r.spec.drafter.draft_paths(cap, branch)
                else:
                    # Chain config keeps the PR-9 seam exactly
                    # (draft_paths(n, 1)[0] is byte-identical, but
                    # draft() is the method fakes/benches intercept).
                    d = r.spec.drafter.draft(cap)
                    paths = [d] if d else []
                if not paths and not probe:
                    # The probe reached the drafter and it proposed
                    # NOTHING (context not draftable): the probe is
                    # resolved FAILED — wait a whole interval again
                    # instead of re-drafting every tick (no-op for
                    # unthrottled rows).
                    r.spec.probe_failed(len(r.produced))
            drafts[id(r)] = paths
            if paths:
                any_draft = True
                if probe:
                    # The _may_speculate caller only asks WHETHER a
                    # verify tick exists — don't compute the rest of
                    # the batch's proposals just to discard them (the
                    # real segment recomputes from fresh host state
                    # next tick anyway).
                    return drafts
        return drafts if any_draft else None

    def _run_spec_segment(self, live: list[_Row]) -> bool:
        """One speculative verify dispatch over the live rows (ISSUE 9
        tentpole, ISSUE 13 tree generalization): every speculating row
        packs its candidate paths as short multi-token runs of the PR-8
        flat buffer (throttled / draftless rows ride as plain 1-token
        runs — mixed chain/tree/no-spec widths are VALUES, not shapes),
        forward_ragged scores every draft position in one forward via
        the static score_width gather, and the host walks the accepted
        chain/tree path and commits it plus the correction/bonus token.

        Tree rows: path 0 (the main chain) writes through the row's
        REAL page table exactly like PR-9; each extra root branch
        becomes one more sequence whose table swaps the touched pages
        for pages LOANED from the free list (take_free_pages — never
        evicting resident state; a short free list degrades the row
        back to chain), with the partially-committed frontier page
        pre-COW'd in-dispatch (build_ragged_batch copy_pairs) so every
        path's causal reads see the committed cells. When the accepted
        walk ends on a non-trunk path, its loaned pages ARE the
        committed K/V — swap_in_page adopts them into the row's table
        and the trunk's rejected bytes go back to the free list; every
        other loan returns untouched. PagedKVCache.commit still
        publishes only literally-committed tokens, so the prefix cache
        can never attach a rejected branch.

        Greedy rows are byte-identical to 1-token decode by the argmax
        walk rule; sampled rows follow exact per-edge rejection
        sampling (engine/spec_decode docstring). Returns False WITHOUT
        dispatching when no row drafts; a dispatch failure is handled
        exactly like a ragged decode failure (drafts discarded, loans
        returned, the preempt-isolate ladder re-dispatches from intact
        host state)."""
        engine = self.engine
        reqs = self._reqs_of(live)
        remaining = min((req.turn_budget.remaining() for req in reqs),
                        default=float("inf"))
        seg_budget = deadlines.Budget.root(
            None if remaining == float("inf") else remaining,
            rung="decode")
        deadline = min((req.deadline for req in reqs),
                       default=float("inf"))

        def draft_dispatch(b):
            # Draft dispatches ride the SAME watchdog/retry/budget
            # seams the verify dispatch uses — a hang mid-propose must
            # hit the deadline ladder, not block the scheduler thread.
            return run_dispatch(lambda: engine._ragged_dispatch(b),
                                engine.retry, deadline,
                                budget=seg_budget)

        def draft_read(h):
            if isinstance(h, tuple):
                return host_sync(
                    lambda: tuple(np.asarray(x) for x in h),
                    seg_budget, "decode")
            return host_sync(lambda: np.asarray(h), seg_budget,
                             "decode")

        try:
            drafts_of = self._spec_drafts(live, dispatch=draft_dispatch,
                                          read=draft_read)
        except Exception as e:  # noqa: BLE001 — preempt-isolate ladder
            # A DEVICE failure during drafting is indistinguishable
            # from a decode failure (draft dispatches donate the same
            # pools): the ragged failure path's donation-death check +
            # per-session re-dispatch applies verbatim. Benign capacity
            # pressure (DraftUnavailable) was already absorbed inside
            # _spec_drafts.
            self._handle_ragged_failure(live, [], e)
            return True
        if drafts_of is None:
            return False

        from .serving_loop import ragged_pick_shape
        kv = engine.kv
        ps = kv.page_size
        # Pack main runs first (chain behavior unchanged), then extra
        # tree paths while the flat buffer, the static copy-slot block
        # and the free list allow — degradation is per-path and the
        # batch stays pure values.
        seqs: list[RaggedSeq] = []
        entries: list[dict] = []
        copy_pairs: list[tuple[int, int]] = []
        blocks_budget = engine.ragged_tokens // RAGGED_BLOCK_Q
        copy_budget = engine.spec_copy_slots
        for r in live:
            paths = drafts_of.get(id(r)) or []
            main = list(paths[0]) if paths else []
            e = {"row": r, "used": ([main] if paths else []),
                 "rows_idx": [len(seqs)], "loans": []}
            seqs.append(RaggedSeq(
                [r.last] + main, r.valid, kv.table_for([r.name])[0],
                temperature=r.sampling.temperature,
                top_k=r.sampling.top_k, top_p=r.sampling.top_p,
                n_scores=len(main) + 1, adapter=r.adapter_slot))
            entries.append(e)
        for e in entries:
            r = e["row"]
            paths = drafts_of.get(id(r)) or []
            if len(paths) <= 1:
                continue
            state = kv.acquire(r.name)
            base_table = kv.table_for([r.name])[0]
            for p in paths[1:]:
                if len(seqs) >= blocks_budget or copy_budget <= 0:
                    break
                lo = r.valid // ps
                hi = (r.valid + len(p)) // ps
                loan = kv.take_free_pages(hi - lo + 1,
                                          replica=state.replica)
                if loan is None:
                    break  # free list short: this row degrades to chain
                ptable = np.array(base_table, copy=True)
                for k, j in enumerate(range(lo, hi + 1)):
                    ptable[j] = loan[k]
                # Only the frontier page holds committed cells the
                # path's causal reads need — deeper touched pages start
                # past `valid` and are written before they are read.
                copy_pairs.append((int(base_table[lo]), loan[0]))
                copy_budget -= 1
                e["rows_idx"].append(len(seqs))
                e["loans"].append((lo, loan))
                e["used"].append(list(p))
                seqs.append(RaggedSeq(
                    [r.last] + list(p), r.valid, ptable,
                    temperature=r.sampling.temperature,
                    top_k=r.sampling.top_k, top_p=r.sampling.top_p,
                    n_scores=len(p) + 1, adapter=r.adapter_slot))

        def return_all_loans():
            for e in entries:
                for _lo, loan in e["loans"]:
                    kv.give_back_pages(loan)

        want = RAGGED_BLOCK_Q * len(seqs)
        shape = ragged_pick_shape(engine.ragged_shapes,
                                  min(want, engine.ragged_tokens))
        batch = build_ragged_batch(
            seqs, t_budget=shape, s_max=engine.spec_s_max,
            pages_per_seq=kv.pages_per_seq,
            scratch_page=kv.scratch_page(0),
            pad_id=engine.tokenizer.pad_id,
            page_size=ps,
            score_width=engine.spec_max_draft + 1,
            copy_pairs=copy_pairs,
            copy_slots=engine.spec_copy_slots)

        t0 = time.monotonic()
        try:
            with telemetry.span("segment", engine=self._tname,
                                rows=len(seqs), scheduled=True,
                                spec=True):
                handles = run_dispatch(
                    lambda: engine._ragged_dispatch(batch),
                    engine.retry, deadline, budget=seg_budget)
                nxt = host_sync(lambda: np.asarray(handles), seg_budget,
                                "decode")
        except Exception as e:  # noqa: BLE001 — preempt-isolate ladder
            # Indistinguishable from a decode failure: host state is
            # untouched (the drafts are discarded with the dispatch and
            # the loaned pages return to the free list), so the ragged
            # failure path's donation-death check + per-session
            # re-dispatch applies verbatim.
            return_all_loans()
            self._handle_ragged_failure(live, [], e)
            return True
        wall = time.monotonic() - t0

        eos = engine.tokenizer.eos_id
        from .spec_decode import (accept_prefix, accept_tree,
                                  note_tree_row)
        n_emit = 0
        lora_toks = 0
        drafted_tot = 0
        accepted_tot = 0
        tree_nodes_tot = 0
        tree_rows_tot = 0
        emits: dict[int, tuple[_Request, int]] = {}
        for e in entries:
            r = e["row"]
            used = e["used"]
            if len(used) <= 1:
                d = used[0] if used else []
                props = [int(x)
                         for x in nxt[e["rows_idx"][0], :len(d) + 1]]
                emit, a = accept_prefix(d, props)
                winner = 0
                drafted_row = len(d)
            else:
                props_list = [
                    [int(x) for x in nxt[si, :len(used[k]) + 1]]
                    for k, si in enumerate(e["rows_idx"])]
                emit, a, winner = accept_tree(used, props_list)
                drafted_row = sum(len(p) for p in used)
            # EOS inside an accepted prefix truncates exactly as
            # eos_trim does: tokens past the eos are never committed
            # (plain decode would never have produced them).
            if eos in emit:
                emit = emit[:emit.index(eos) + 1]
            room = r.max_new - len(r.produced)
            if len(emit) > room:
                emit = emit[:room]
            r.produced.extend(emit)
            if emit:
                r.last = emit[-1]
            r.valid += len(emit)
            r.done = (r.last == eos) or len(r.produced) >= r.max_new
            if r.adapter_slot:
                lora_toks += len(emit)
            # Accepted-for-accounting = drafts actually COMMITTED:
            # eos/budget truncation can drop matched drafts, and every
            # acceptance metric must equal served work (a fully-matched
            # [A, eos, B, C] draft commits 2 tokens, not 4). min(a,
            # len(emit)) also covers the eos-was-a-draft case, where
            # every emitted token is a matched draft and none is the
            # free correction — the rule holds for tree EDGES verbatim
            # (ISSUE 13 satellite: EOS inside an accepted path counts
            # only committed tokens).
            acc = min(a, len(emit))
            if e["loans"]:
                # Loan settlement: the winner path's pages covering the
                # committed span adopt into the row's table (their
                # cells hold the accepted K/V, pre-COW'd + written
                # in-dispatch); everything else returns to the free
                # list. Winner 0 is the trunk — its writes went through
                # the real table, so every loan returns.
                for m, (lo, loan) in enumerate(e["loans"]):
                    if m == winner - 1:
                        keep_hi = (r.valid - 1) // ps
                        for k, j in enumerate(range(lo, lo + len(loan))):
                            if j <= keep_hi:
                                kv.swap_in_page(r.name, j, loan[k])
                            else:
                                kv.give_back_pages([loan[k]])
                    else:
                        kv.give_back_pages(loan)
            if len(used) > 1:
                tree_nodes_tot += drafted_row
                tree_rows_tot += 1
                note_tree_row(drafted_row, acc)
            req = self._row_req.get(id(r))
            if req is not None:
                prev = emits.get(id(req))
                emits[id(req)] = (req,
                                  (prev[1] if prev else 0) + len(emit))
                if drafted_row:
                    req.spec_drafted += drafted_row
                    req.spec_accepted += acc
            n_emit += len(emit)
            if drafted_row and r.spec is not None:
                drafted_tot += drafted_row
                accepted_tot += acc
                tripped = r.spec.note(drafted_row, acc)
                if r.spec.disabled:
                    # Throttled (now or still): restart the re-probe
                    # interval from the row's current committed length
                    # (ISSUE 13 hysteresis satellite).
                    r.spec.mark_idle(len(r.produced))
                # Gauge AFTER note: the window now includes this
                # dispatch, so the first drafted dispatch reports its
                # real rate instead of a false 0.0 (and later values
                # never lag a dispatch behind).
                telemetry.set_gauge(
                    "roundtable_spec_row_acceptance_rate",
                    round(r.spec.rate(), 4),
                    engine=self._tname, row=r.name)
                if tripped:
                    # Adaptive throttle tripped: this row decodes
                    # 1-token (with periodic re-probes) from here on —
                    # one flight event, the ISSUE 9 telemetry
                    # satellite.
                    engine.note_spec_throttle()
                    telemetry.recorder().record(
                        "spec_throttle", engine=self._tname,
                        session=req.session if req else "",
                        row=r.name, rate=round(r.spec.rate(), 3))
                    self._event("spec_throttle", row=r.name,
                                rate=round(r.spec.rate(), 3))
        engine.note_lora_tokens(lora_toks)
        engine.note_spec_dispatch(drafted_tot, accepted_tot,
                                  rows=len(live),
                                  tree_nodes=tree_nodes_tot,
                                  tree_rows=tree_rows_tot)

        self.spec_segments += 1
        telemetry.inc("roundtable_sched_spec_segments_total",
                      engine=self._tname)
        self._note_segment_tokens(0, n_emit)
        occ = len(seqs)
        self.max_occupancy = max(self.max_occupancy, occ)
        with self._cv:
            self._occupancy.append(occ)
        telemetry.set_gauge("roundtable_sched_occupancy", occ,
                            engine=self._tname)
        _note_rows(occ)
        sessions = len(reqs)
        for req, n in emits.values():
            req.stats.decode_seconds += wall * n / max(n_emit, 1)
        for req in reqs:
            req.seg_count += 1
            req.occ_sum += occ
            req.occ_max = max(req.occ_max, occ)
            req.sess_max = max(req.sess_max, sessions)
        perf = getattr(engine, "perf", None)
        if perf is not None:
            # Accepted vs dispatch tokens split (ISSUE 9 perfmodel
            # satellite): the forward streamed weights ONCE for
            # len(live) rows — that is the roofline-relevant count; the
            # accepted total is the user-visible rate and must not
            # report >100% bandwidth utilization.
            perf.publish_mixed_sample(
                0, n_emit, wall, decode_dispatch_tokens=len(live),
                lora_bytes_per_token=self._lora_bytes_per_token(live))
            for req in reqs:
                perf.publish_session_kv(
                    req.session, sum(r.valid for r in req.rows))
        return True

    def _may_speculate(self, ctx: dict) -> bool:
        """Queue the next segment before reading this one ONLY when the
        composition is certain to survive it: no queued session (a join
        must not wait behind a speculative segment), no request whose
        rows are all done (retirement resolves a submitter — never
        delay it), work plausibly remaining, nothing cancelled, and the
        deadline not passed (decode_segments' own speculation rules)."""
        if self._stop or deadlines.DRAINING:
            return False
        if any(r.pending for r in self._active):
            # Ragged fills are waiting (overflow fallback segment, or a
            # blocked laggard about to unblock) — a speculative segment
            # would starve their chunks for another whole segment.
            return False
        if ctx["budgets_max"] <= DECODE_SEGMENT:
            return False  # this segment may finish everything
        if time.monotonic() >= ctx["deadline"]:
            return False
        with self._cv:
            if self._queue:
                return False
        if self._spec_drafts([r for r in ctx["rows"] if not r.done],
                             probe=True) is not None:
            # A verify tick is available (ISSUE 9): pipelining another
            # whole 64-token segment would decode past it at 1
            # token/forward — exit the mini-loop so _tick runs the
            # speculative phase at the next boundary. Probe mode: this
            # check runs per mini-loop iteration AFTER the cheap exits
            # and stops at the first draftable row.
            return False
        for req in ctx["reqs"]:
            if req not in self._active_reqs or req.abandoned:
                return False
            if req.rows and all(r.done for r in req.rows):
                return False
            if req.turn_budget.token.cancelled or req.turn_budget.expired:
                return False
        return True

    def _lora_bytes_per_token(self, rows: list[_Row]):
        """This sample's mean adapter bytes streamed per decoded token
        (ISSUE 10 perfmodel satellite): the exact mix, so the roofline
        gauges neither overreport base-only segments against a lora-
        discounted ceiling nor persona segments against the base one.
        None on lora-off engines (the perf default applies)."""
        store = getattr(self.engine, "lora", None)
        if store is None or not rows:
            return None
        n_ad = sum(1 for r in rows if r.adapter_slot)
        return store.streamed_bytes_per_token() * n_ad / len(rows)

    def _reqs_of(self, rows: list[_Row]) -> list[_Request]:
        seen: dict[int, _Request] = {}
        for r in rows:
            req = self._row_req.get(id(r))
            if req is not None:
                seen.setdefault(id(req), req)
        return list(seen.values())

    def _account_segment(self, alive: list[_Row]) -> dict:
        """Occupancy provenance for one consumed segment; returns the
        per-request live-row counts ({id: (req, n)}) the wall
        attribution reuses — one pass over the rows, not a rescan per
        row. Loop-thread only (single-writer counter bumps need no
        cv)."""
        counts: dict[int, tuple[_Request, int]] = {}
        for r in alive:
            req = self._row_req.get(id(r))
            if req is None:
                continue
            prev = counts.get(id(req))
            counts[id(req)] = (req, (prev[1] + 1) if prev else 1)
        occ = len(alive)
        sessions = len(counts)
        self._bump("segments")
        self.max_occupancy = max(self.max_occupancy, occ)
        with self._cv:
            self._occupancy.append(occ)
        telemetry.set_gauge("roundtable_sched_occupancy", occ,
                            engine=self._tname)
        _note_rows(occ)
        perf = getattr(self.engine, "perf", None)
        for req, _n in counts.values():
            req.seg_count += 1
            req.occ_sum += occ
            req.occ_max = max(req.occ_max, occ)
            req.sess_max = max(req.sess_max, sessions)
            if perf is not None:
                # Per-session KV-footprint series (the memory ledger's
                # session dimension): cached tokens across the
                # session's live rows, priced at KV bytes/token.
                perf.publish_session_kv(
                    req.session, sum(r.valid for r in req.rows))
        return counts

    def _attribute_wall(self, counts: dict, wall: float) -> None:
        """Attribute a segment's wall to its sessions by live-row share —
        sums over requests equal the real wall, so aggregate tok/s stays
        honest under co-scheduling."""
        total = sum(n for _req, n in counts.values())
        for req, n in counts.values():
            req.stats.decode_seconds += wall * n / max(total, 1)

    def _row_bucket(self, n: int) -> int:
        """Decode batch sizes round up to powers of two (capped at
        max_rows) so the set of compiled decode programs is
        {1, 2, 4, ..., max_rows} instead of one per exact live-row
        count — a retire/join that changes occupancy inside a bucket
        compiles nothing mid-serve (the ISSUE 4 fixed-size-bucketed
        batch with an active-row mask)."""
        return min(pow2_bucket(n), self.max_rows)

    def _dispatch_rows(self, rows: list[_Row]) -> None:
        """One unpipelined DECODE_SEGMENT over `rows` — the
        fault-isolation re-dispatch path (_handle_segment_failure runs
        each session's rows alone through this)."""
        ctx = self._build_batch(rows)
        self._read_segment(ctx, self._dispatch(ctx))

    def _build_batch(self, rows: list[_Row]) -> dict:
        """Device arrays for one DECODE_SEGMENT over `rows`.

        The batch pads to _row_bucket with MASKED pad rows (done from
        step 0, zero budget): contiguous pads point at a throwaway slot
        (SlotBook.scratch_slot — identical bytes from every pad row, so
        the duplicate-index scatter is deterministic), paged pads point
        their whole table at the scratch page. Under data>1 pool-direct
        the ReplicaGroupPlan already dictates the padded shape, so
        bucketing is skipped there."""
        engine = self.engine
        names = [r.name for r in rows]
        eos = engine.tokenizer.eos_id
        reqs = self._reqs_of(rows)
        remaining = min((req.turn_budget.remaining() for req in reqs),
                        default=float("inf"))
        seg_budget = deadlines.Budget.root(
            None if remaining == float("inf") else remaining,
            rung="decode")
        deadline = min((req.deadline for req in reqs),
                       default=float("inf"))

        last = np.asarray([r.last for r in rows], np.int32)
        valid = np.asarray([r.valid for r in rows], np.int32)
        done0 = np.zeros(len(rows), bool)
        budgets = np.asarray(
            [max(r.max_new - len(r.produced), 0) for r in rows], np.int32)
        temps_l = [r.sampling.temperature for r in rows]
        top_ks_l = [r.sampling.top_k for r in rows]
        top_ps_l = [r.sampling.top_p for r in rows]
        greedy = all(t <= 0.0 for t in temps_l)

        plan = None
        tables = None
        slot_idx = None
        pad = 0
        if engine.kv_layout == "paged":
            tables_np = engine.kv.table_for(names)
            if engine.paged_direct and engine._paged_replicas > 1:
                # bucket_group: the plan's padded shape must stay on the
                # {R*1, R*2, R*4, ...} grid as occupancy drifts, or
                # every retire/join would compile a fresh decode program
                # mid-serve on exactly the multi-replica engines where
                # that stall hurts most.
                plan = ReplicaGroupPlan(
                    [engine.kv.replica_of(n) for n in names],
                    engine._paged_replicas, bucket_group=True)
                tables_np = plan.pad_table(tables_np,
                                           engine.kv.scratch_page)
            else:
                pad = self._row_bucket(len(rows)) - len(rows)
                if pad:
                    scratch = np.full(
                        (pad, tables_np.shape[1]),
                        engine.kv.scratch_page(0), tables_np.dtype)
                    tables_np = np.concatenate([tables_np, scratch])
            tables = jnp.asarray(tables_np)
        else:
            slots = [r.slot_id for r in rows]
            pad = self._row_bucket(len(rows)) - len(rows)
            if pad:
                pad_slot = engine.kv.scratch_slot(
                    pinned=tuple(r.name for r in self._active))
                if pad_slot is None:
                    pad = 0  # every slot pinned: exact-size dispatch
                else:
                    slots = slots + [pad_slot] * pad
            slot_idx = jnp.asarray(slots, jnp.int32)
        if pad:
            last = np.concatenate([last, np.full(pad, eos, np.int32)])
            valid = np.concatenate([valid, np.ones(pad, np.int32)])
            done0 = np.concatenate([done0, np.ones(pad, bool)])
            budgets = np.concatenate([budgets, np.zeros(pad, np.int32)])
            temps_l += [1.0] * pad
            top_ks_l += [0] * pad
            top_ps_l += [1.0] * pad
        temps, top_ks, top_ps = sampling_arrays(
            [SamplingParams(temperature=t, top_k=k, top_p=p)
             for t, k, p in zip(temps_l, top_ks_l, top_ps_l)])

        lora = None
        if getattr(engine, "lora", None) is not None:
            # Per-row adapter slots (ISSUE 10): pad rows ride the base
            # (zero) adapter — their delta is exactly zero and their
            # outputs are masked anyway. A value, so mixed-adapter
            # recomposition compiles nothing.
            slots = [r.adapter_slot for r in rows]
            ids = (plan.scatter_list(slots, 0) if plan is not None
                   else slots + [0] * pad)
            lora = engine._lora_args(ids)
        if plan is not None:
            last_d = plan.scatter_rows(last, np.int32(eos))
            valid_d = plan.scatter_rows(valid, 1)
            done_d = plan.scatter_rows(done0, True)
            budgets_d = plan.scatter_rows(budgets, 0)
            temps = plan.scatter_rows(np.asarray(temps), 1.0)
            top_ks = plan.scatter_rows(np.asarray(top_ks), 0)
            top_ps = plan.scatter_rows(np.asarray(top_ps), 1.0)
        else:
            last_d = jnp.asarray(last)
            valid_d = jnp.asarray(valid)
            done_d = jnp.asarray(done0)
            budgets_d = jnp.asarray(budgets)
        return {
            "rows": rows, "reqs": reqs, "plan": plan, "tables": tables,
            "slot_idx": slot_idx, "last_d": last_d, "valid_d": valid_d,
            "done_d": done_d, "budgets_d": budgets_d, "temps": temps,
            "top_ks": top_ks, "top_ps": top_ps, "greedy": greedy,
            "seg_budget": seg_budget, "deadline": deadline,
            "budgets_max": int(budgets.max()) if len(budgets) else 0,
            "lora": lora,
        }

    def _dispatch(self, ctx: dict):
        """Dispatch one segment for `ctx` through the engine's shared
        decode seams (_decode_dispatch_paged/_slots — same degrade rung
        + commit_guard as generate_batch) and the run_dispatch
        retry/watchdog seam. Returns DEVICE handles; the host read
        happens in _read_segment, possibly after the next segment is
        already queued."""
        engine = self.engine

        def dispatch():
            if ctx["tables"] is not None:
                return engine._decode_dispatch_paged(
                    ctx["tables"], ctx["last_d"], ctx["valid_d"],
                    engine._next_key(), jnp.int32(DECODE_SEGMENT),
                    ctx["temps"], ctx["top_ks"], ctx["top_ps"],
                    ctx["budgets_d"], ctx["done_d"],
                    greedy=ctx["greedy"], lora=ctx["lora"])
            return engine._decode_dispatch_slots(
                ctx["slot_idx"], ctx["last_d"], ctx["valid_d"],
                engine._next_key(), jnp.int32(DECODE_SEGMENT),
                ctx["temps"], ctx["top_ks"], ctx["top_ps"],
                ctx["budgets_d"], ctx["done_d"], greedy=ctx["greedy"],
                lora=ctx["lora"])

        return run_dispatch(dispatch, engine.retry, ctx["deadline"],
                            budget=ctx["seg_budget"])

    def _advance(self, ctx: dict, handles) -> dict:
        """The next segment's ctx from this segment's DEVICE outputs —
        pure device arithmetic (decode_segments' pipelining carry), no
        host sync: done/valid/last carry, per-row budgets decrement by
        the steps actually taken."""
        _out, steps, l2, v2, d2 = handles
        nxt = dict(ctx)
        nxt["last_d"], nxt["valid_d"], nxt["done_d"] = l2, v2, d2
        nxt["budgets_d"] = jnp.maximum(ctx["budgets_d"] - steps, 0)
        # Host-side upper-bound estimate for _may_speculate (the device
        # value is not worth a sync): each segment consumes at most
        # DECODE_SEGMENT of every row's budget.
        nxt["budgets_max"] = ctx["budgets_max"] - DECODE_SEGMENT
        return nxt

    def _read_segment(self, ctx: dict, handles) -> int:
        """Host-read one segment's results (through the watchdog seam —
        this is where a wedged program freezes the host) and fold them
        into the rows' host state. Returns the steps the segment
        actually took (the roofline sample's token count)."""
        out, steps, l2, v2, d2 = handles
        plan = ctx["plan"]

        def read():
            n = int(steps)  # forces completion of the segment
            return (n, np.asarray(out)[:, :n], np.asarray(l2),
                    np.asarray(v2), np.asarray(d2))

        n, out_np, last_np, valid_np, done_np = host_sync(
            read, ctx["seg_budget"], "decode")
        if plan is not None:
            out_np = out_np[plan.pos]
            last_np = last_np[plan.pos]
            valid_np = valid_np[plan.pos]
            done_np = done_np[plan.pos]
        lora_toks = 0
        eos = self.engine.tokenizer.eos_id
        for i, r in enumerate(ctx["rows"]):
            if r.done:
                continue  # masked rows emit eos filler — not output
            row = [int(x) for x in out_np[i]]
            r.produced.extend(row)
            r.last = int(last_np[i])
            r.valid = int(valid_np[i])
            r.done = bool(done_np[i]) or len(r.produced) >= r.max_new
            if r.adapter_slot:
                # Count tokens up to (and including) the row's eos —
                # post-eos filler is not served work, and the direct
                # generate path counts eos-trimmed exactly; the two
                # definitions of apply_tokens must agree.
                lora_toks += (row.index(eos) + 1 if eos in row
                              else len(row))
        self.engine.note_lora_tokens(lora_toks)
        return n

    # --- failure containment ---

    def _handle_segment_failure(self, live: list[_Row],
                                err: BaseException) -> None:
        """The shared decode dispatch failed. If donation consumed the
        (shared!) KV buffers, every session's cache is gone — fail them
        all into their adapters' revive/serial-retry ladders. Otherwise
        PREEMPT the batch into per-session dispatches: the session the
        fault follows fails alone; everyone else's rows re-run their
        segment from intact host+KV state, byte-identical. Loop-thread
        only (single-writer counter bumps need no cv)."""
        if self._supervisor_intervened(err):
            return
        if self._after_engine_failure(err):
            return
        self._bump("preemptions")
        self._event("preempt_isolate", error=str(err)[:200],
                    sessions=[req.session for req in self._reqs_of(live)])
        for req in self._reqs_of(live):
            mine = [r for r in live if r in req.rows]
            t0 = time.monotonic()
            try:
                self._dispatch_rows(mine)
            except Exception as e:  # noqa: BLE001 — per-session contain
                if self._after_engine_failure(e):
                    return
                self._fail_request(req, e)
                continue
            req.stats.decode_seconds += time.monotonic() - t0

    def _supervisor_intervened(self, err: BaseException) -> bool:
        """Engine-fatal triage BEFORE the dispatch ladder (ISSUE 12):
        device_lost failures, repeated hangs past the ladder, and
        already-dead engines route to the EngineSupervisor, which tears
        the engine down, rebuilds it, and restores the evacuated
        sessions — all inline on this (the loop) thread. Returns True
        when the supervisor took over (the batch is gone: actives were
        failed into their adapter ladders as part of the quiesce);
        False lets preempt-isolate / revive handle it as before."""
        try:
            from .supervisor import supervisor
            return supervisor().handle_dispatch_failure(self, err)
        except Exception as e:  # noqa: BLE001 — triage must not mask err
            self._event("supervisor_error", error=str(e)[:200])
            return False

    def _after_engine_failure(self, err: BaseException) -> bool:
        """Donation-death check after ANY engine dispatch failure: a
        revive means every slot's bytes are gone — no per-session state
        survives, so every active request fails (their adapter ladders
        rebuild from prompts). Returns True when that happened."""
        try:
            revived = self.engine.revive_kv_if_dead()
        except Exception:  # noqa: BLE001 — the original error wins
            revived = False
        if not revived:
            return False
        self._event("revive_fail_all", error=str(err)[:200])
        for req in list(self._active_reqs):
            self._fail_request(req, err, release=False)
        return True

    def _release_adapters(self, req: _Request) -> None:
        store = getattr(self.engine, "lora", None)
        if store is not None and req.adapters_held:
            req.adapters_held = False
            store.release(req.adapters or [])

    def _fail_request(self, req: _Request, err: BaseException,
                      release: bool = True) -> None:
        """Fail one active request into its submitter. Loop-thread
        only — request state is single-writer (external threads go
        through force_fail_active's mailbox), so counter bumps here
        need no cv."""
        self._release_adapters(req)
        if release:
            for r in req.rows:
                try:
                    self.engine.kv.release(r.name)
                except Exception:  # noqa: BLE001 — the error wins
                    pass
        if req.on_commit is not None:
            from ..core.errors import classify_error
            self._stream_notify(req, {
                "type": "failed", "error": str(err)[:200],
                "kind": classify_error(err)})
        self._drop_request(req)
        self._last_active[req.session] = time.monotonic()
        req.error = err
        self._bump("failed")
        perf = getattr(self.engine, "perf", None)
        if perf is not None:
            perf.publish_session_kv(req.session, 0)
        if req.tele is not None:
            req.tele.end(status=f"error:{type(err).__name__}")
            req.tele = None
        self._event("fail", session=req.session,
                    error=str(err)[:200])
        req.event.set()

    def _drop_request(self, req: _Request) -> None:
        if req in self._active_reqs:
            self._active_reqs.remove(req)
        dd = getattr(self.engine, "spec_device_drafter", None)
        for r in req.rows:
            self._row_req.pop(id(r), None)
            if dd is not None:
                # The row's shadow draft slot dies with it (ISSUE 13):
                # its pages free, and a future session reusing the name
                # starts its drafter cold instead of diverged.
                try:
                    dd.end_row(self.engine, r.name)
                except Exception:  # noqa: BLE001 — cleanup best-effort
                    pass
            if r.spec is not None and r.spec.drafted:
                # Row-labeled acceptance gauges die with the row:
                # session-scoped names are uuid-tagged per serve call,
                # so a kept series per row ever served would grow the
                # registry without bound (the PR-6 remove_gauge lesson).
                telemetry.REGISTRY.remove_gauge(
                    "roundtable_spec_row_acceptance_rate",
                    engine=self._tname, row=r.name)
        self._active = [r for r in self._active if r not in req.rows]

    # --- committed-token streaming (ISSUE 16) ---

    def _stream_notify(self, req: _Request, event: dict) -> None:
        """Deliver one stream event to req.on_commit — loop-thread
        only. A raising callback is disabled for the rest of the
        request (counted + evented): a broken consumer costs ITS
        stream, never the batch."""
        cb = req.on_commit
        if cb is None:
            return
        try:
            cb(event)
        except Exception as e:  # noqa: BLE001 — consumer must not wedge serving
            req.on_commit = None
            telemetry.inc("roundtable_sched_stream_errors_total",
                          engine=self._tname)
            self._event("stream_error", session=req.session,
                        error=str(e)[:200])

    def _stream_flush(self, req: _Request) -> None:
        """Push each row's NEW committed tokens (eos-trimmed, so the
        stream never carries post-eos filler and matches the journal's
        `produced` exactly) to the request's on_commit callback."""
        if req.on_commit is None:
            return
        engine = self.engine
        eos = engine.tokenizer.eos_id
        max_new, _padded = clamp_max_new(req.max_new,
                                         engine.max_seq_len)
        for i, r in enumerate(req.rows):
            ids = eos_trim(list(r.produced), eos, max_new)
            if len(ids) <= r.streamed:
                continue
            new = ids[r.streamed:]
            # queue_wait_s rides every tokens event (ISSUE 20): the
            # gateway's critical-path trace carves the scheduler queue
            # wait out of its submit→first-token lump, so the TTFT
            # waterfall separates "waiting for a slot" from prefill.
            self._stream_notify(req, {
                "type": "tokens", "row": i, "knight": req.turns[i][0],
                "tokens": new, "done": r.done,
                "queue_wait_s": round(
                    (req.admitted_at or req.enqueued) - req.enqueued, 3)})
            if req.on_commit is None:
                return  # callback died mid-flush
            r.streamed = len(ids)

    def _flush_streams(self) -> None:
        """The streaming seam's tick hook: after every segment fold
        (ragged, spec, while-loop — all land in rows' `produced`),
        flush each streaming request's newly committed span. Tokens
        flush at SEGMENT boundaries, the same grain retirement and the
        journal observe — a streamed token is always a committed one."""
        for req in list(self._active_reqs):
            if req.on_commit is not None:
                self._stream_flush(req)

    # --- retirement ---

    def _retire_finished(self) -> None:
        """Retire every all-done request: eos-trim, journal, stats,
        per-session gauge removal. Loop-thread only (single-writer
        counter bumps need no cv)."""
        engine = self.engine
        eos = engine.tokenizer.eos_id
        for req in list(self._active_reqs):
            if not req.rows or not all(r.done for r in req.rows):
                continue
            max_new, _padded = clamp_max_new(req.max_new,
                                             engine.max_seq_len)
            texts = []
            for r in req.rows:
                ids = eos_trim(list(r.produced), eos, max_new)
                req.stats.decode_tokens += len(ids)
                # Commit prompt + every FED token (= all but the last
                # sampled one) for next-round prefix reuse — the
                # finalize_outputs contract. Persona rows never feed
                # the cross-session prefix cache (index=False): their
                # pages hold adapter-tinted K/V (ISSUE 10).
                fed = ids[:-1] if ids else []
                engine.kv.commit(r.name, r.tokens + fed,
                                 index=not r.adapter_slot)
                texts.append(engine.tokenizer.decode(ids))
            # (roundtable_lora_apply_tokens_total was bumped per
            # DISPATCH as the tokens were served — retire must not
            # count them again.)
            self._release_adapters(req)
            if self._journal is not None:
                # Durable commit point (ISSUE 12): the round's results
                # are about to be handed back — journal them fsynced
                # FIRST, so the record on disk never claims less than
                # the submitter saw.
                self._journal_retired(req, eos, max_new)
            req.stats.int4_paths = engine.int4_path_report()
            req.stats.sched = {
                "queue_wait_s": round(
                    (req.admitted_at or req.enqueued) - req.enqueued, 3),
                "segments": req.seg_count,
                "occupancy_mean": (round(req.occ_sum / req.seg_count, 2)
                                   if req.seg_count else 0.0),
                "occupancy_max": req.occ_max,
                "sessions_max": req.sess_max,
            }
            if req.first_token_at is not None:
                # TTFT (ISSUE 8): submit → every row of the round has
                # its first sampled token. The offered-load bench's
                # headline percentile reads this from metrics.json.
                req.stats.sched["ttft_s"] = round(
                    req.first_token_at - req.enqueued, 3)
            if req.spec_drafted:
                # Speculation provenance (ISSUE 9): rides adapter
                # stats into metrics.json like queue_wait/ttft do.
                req.stats.sched["spec"] = {
                    "drafted": req.spec_drafted,
                    "accepted": req.spec_accepted,
                    "acceptance_rate": round(
                        req.spec_accepted / req.spec_drafted, 3),
                }
            if req.adapters and any(a is not None
                                    for a in req.adapters):
                # Persona provenance (ISSUE 10): which LoRA adapter
                # served each knight of this round.
                req.stats.sched["lora_adapters"] = list(req.adapters)
            if req.on_commit is not None:
                # Streaming epilogue (ISSUE 16): the journal record is
                # already fsynced above, so "retired" tells the gateway
                # the turn is DURABLE — safe to finalize event ids.
                self._stream_flush(req)
                self._stream_notify(req, {"type": "retired"})
            self._drop_request(req)
            self._last_active[req.session] = time.monotonic()
            req.result = (texts, req.stats)
            self._bump("completed")
            if req.tele is not None:
                req.tele.set_attr("decode_tokens",
                                  req.stats.decode_tokens)
                req.tele.set_attr("occupancy_max", req.occ_max)
                req.tele.end()
                req.tele = None
            trace_hooks.publish_gen_stats(
                req.stats, self._tname,
                perf=getattr(engine, "perf", None))
            perf = getattr(engine, "perf", None)
            if perf is not None:
                # Retired session's KV series reads empty, not stale.
                perf.publish_session_kv(req.session, 0)
            trace_hooks.publish_memory_ledger(engine)
            self._event("retire", session=req.session,
                        decode_tokens=req.stats.decode_tokens,
                        occupancy_max=req.occ_max)
            req.event.set()

    def _journal_retired(self, req: _Request, eos: int,
                         max_new: int) -> None:
        """Append this retired round's committed-turn record to the
        session journal (engine/session_journal.py). Guarded end to
        end: a journal failure costs durability, never availability —
        the round still retires and the submitter still gets its
        result (record_turn itself degrades OSErrors to a counter)."""
        try:
            ads = req.adapters or [None] * len(req.rows)
            rows = []
            for (knight, prompt), r, adapter in zip(req.turns, req.rows,
                                                    ads):
                rows.append({
                    "knight": knight,
                    "prompt": prompt,
                    "prompt_tokens": list(r.tokens),
                    "produced": eos_trim(list(r.produced), eos, max_new),
                    "adapter": adapter,
                })
            rec = self._journal.record_turn(req.session, rows,
                                            engine=self._tname,
                                            replica=self.replica)
            if rec is not None:
                self.journal_turns += 1
            elif not self._journal._suspended:
                # record_turn degraded an OSError to None (suspension
                # during replay also returns None, but that is not an
                # error).
                self.journal_errors += 1
        except Exception as e:  # noqa: BLE001 — durability < availability
            self.journal_errors += 1
            self._event("journal_error", session=req.session,
                        error=str(e)[:200])

    # --- per-request health (budgets / cancellation / abandonment) ---

    def _check_request_health(self) -> None:
        forced = self._force_fail
        if forced is not None:
            # force_fail_active's mailbox (ISSUE 12): the supervisor's
            # quiesce-timeout fallback posted an error; every active
            # request fails with it HERE, on the loop thread — request
            # state is single-writer.
            self._force_fail = None
            for req in list(self._active_reqs):
                self._fail_request(req, forced)
        now = time.monotonic()
        for req in list(self._active_reqs):
            if req.abandoned:
                self._fail_request(req, TimeoutError(
                    f"session {req.session!r} abandoned by its waiter"))
                continue
            try:
                req.turn_budget.token.check()
            except deadlines.Cancelled as e:
                self._fail_request(req, e)
                continue
            if now > req.deadline or req.turn_budget.expired:
                produced = sum(
                    max(len(r.produced) - 1, 0) for r in req.rows)
                self._fail_request(req, TimeoutError(
                    f"generation timed out after "
                    f"{req.timeout_s:.0f}s ({produced} decode tokens "
                    "across the session's rows)"))


_scheduler_for_lock = threading.Lock()


def acquire_scheduler(engine, **opts) -> tuple[SessionScheduler, bool]:
    """(scheduler, created): the engine's attached scheduler, building
    one on first use — every concurrent session sharing an engine must
    share its scheduler (two schedulers would fight over the serve lock
    and the decode batch would never actually mix sessions). The
    created flag is decided INSIDE the lock: callers that close only
    schedulers they created (serve_discussions) must not mislabel a
    concurrently-created instance as their own and close it under
    someone else's live sessions."""
    with _scheduler_for_lock:
        existing = getattr(engine, "_scheduler", None)
        if existing is not None and not existing.closed:
            return existing, False
        return SessionScheduler(engine, **opts), True


def scheduler_for(engine, **opts) -> SessionScheduler:
    """acquire_scheduler for callers that don't track ownership."""
    return acquire_scheduler(engine, **opts)[0]
