"""Heterogeneous multi-model fleet planning.

BASELINE.md config 3 serves three DIFFERENT checkpoints (Gemma-7B /
Llama-3-8B / Mistral-7B) from one pod at once — a capability with no
reference counterpart (the reference time-multiplexes Ollama's single GPU;
SURVEY.md §2.3 "heterogeneous multi-model scheduler"). The TPU answer is
spatial: partition the pod's chips into disjoint per-model submeshes sized
by each model's weight footprint, so every model is resident and the
orchestrator can fan a round out to all knights concurrently.

`plan_fleet` runs at adapter-initialization time (before any engine is
built): it groups the knights' tpu-llm engine configs by model identity,
sizes each group's submesh (power-of-two growth, weighted by parameter
bytes), and injects the chosen device indices into each config. Engines
then build their meshes over exactly those chips.
"""

from __future__ import annotations

import warnings
from typing import Any, Optional

from .models.common import ModelConfig
from .models.registry import get_model_config

_DTYPE_BYTES = {"bfloat16": 2, "float16": 2, "float32": 4}


def estimate_param_count(cfg: ModelConfig) -> int:
    """Closed-form parameter count (no arrays built)."""
    e, h, k, d, f = (cfg.embed_dim, cfg.num_heads, cfg.num_kv_heads,
                     cfg.head_dim, cfg.mlp_dim)
    mlp = 3 * e * f
    if cfg.num_experts:
        mlp = cfg.num_experts * 3 * e * f + e * cfg.num_experts  # + router
    per_layer = 2 * e * h * d + 2 * e * k * d + mlp + 2 * e
    if cfg.attn_bias:  # Qwen2: q/k/v projection biases
        per_layer += h * d + 2 * k * d
    total = cfg.num_layers * per_layer + cfg.vocab_size * e + e
    if not cfg.tie_embeddings:
        total += cfg.vocab_size * e
    return total


def estimate_engine_hbm_bytes(engine_cfg: dict[str, Any],
                              model_cfg: Optional[ModelConfig] = None) -> int:
    """Closed-form resident HBM bytes for one engine (no arrays built):
    weights (quant-aware) + KV pool + an activation/workspace margin.

    Approximate by design — the point is to catch a fleet misconfiguration
    at plan time with a clear message instead of minutes later as an
    opaque XLA allocation error. Margins err high (weights dominate)."""
    if model_cfg is None:
        model_cfg = get_model_config(engine_cfg.get("model", "tiny-gemma"))
    max_seq = int(engine_cfg.get("max_seq_len") or model_cfg.max_seq_len)
    n_params = estimate_param_count(model_cfg)
    dtype_b = _DTYPE_BYTES.get(engine_cfg.get("dtype", "bfloat16"), 2)
    # int8: 1 byte per weight + per-output-channel scales (~a few % of
    # leaf count) — 1.05 covers every registry family's scale overhead.
    # int4: packed nibbles (0.5 B) + per-group scales (2 B / 64-group)
    # — 0.58 covers scales plus the few leaves that fall back to int8.
    quant = engine_cfg.get("quant")
    w_bytes = int(n_params * (1.05 if quant == "int8"
                              else 0.58 if quant == "int4"
                              else dtype_b))
    num_slots = int(engine_cfg.get("num_slots", 4))
    kv_bytes = (num_slots * max_seq * model_cfg.num_layers * 2
                * model_cfg.num_kv_heads * model_cfg.head_dim * dtype_b)
    if engine_cfg.get("kv_layout") == "paged":
        # Default pool halves the contiguous budget. Total across the
        # submesh: the page axis shards over "data" and kv heads over
        # "model" (engine/paging.py per-replica pools), so
        # check_fleet_fits' whole-estimate/group-size division is exact
        # for paged KV too — the pool is no longer replicated per
        # data replica (advisor r3 underestimate, closed).
        kv_bytes //= 2
        # Quantized KV pages (ISSUE 11): charge cells at the CONFIGURED
        # page dtype width, not bf16. resolve_spec applies the same
        # ROUNDTABLE_KV_QUANT kill-switch the engine applies, so the
        # plan matches what construction will actually allocate. With
        # an explicit num_pages the pool bytes follow the quantized
        # cell directly; the DEFAULT pool keeps the bf16 byte budget by
        # design (page_ratio x more pages in the same bytes — the
        # 2-4x-sessions payoff), so kv_bytes stays the halved budget.
        num_pages = engine_cfg.get("num_pages")
        if num_pages is not None:
            from .kv_quant import cell_bytes_per_token, resolve_spec
            kvq = engine_cfg.get("kv_quant")
            spec = (resolve_spec(kvq)[0] if kvq and kvq != "none"
                    else None)
            page_size = int(engine_cfg.get("page_size", 128))
            kv_bytes = int(int(num_pages) * page_size
                           * cell_bytes_per_token(model_cfg, spec,
                                                  dtype_b))
    lora_bytes = 0
    lora_cfg = engine_cfg.get("lora")
    if lora_cfg:
        # Multi-LoRA adapter store (ISSUE 10): stacked A/B tensors are
        # allocated for every slot up front (shapes are config-static)
        # — charged by the same closed form the store itself derives
        # from (engine/lora.stack_bytes_for: shared defaults, the
        # `targets:` restriction, int8 at one byte per element), so
        # the plan cannot drift from the real allocation.
        from .lora import stack_bytes_for
        lora_bytes = stack_bytes_for(model_cfg, lora_cfg,
                                     dtype_bytes=dtype_b)
    # Activations + XLA workspace: prefill chunks are ≤2048 tokens, so
    # this is small next to 7B-class weights; floor it for tiny models.
    margin = max(256 << 20, w_bytes // 16)
    return w_bytes + kv_bytes + lora_bytes + margin


# HBM per chip by device_kind, for backends that don't report
# memory_stats (the axon TPU plugin returns None). Public TPU specs.
_DEVICE_KIND_HBM = {
    "TPU v5 lite": 16 << 30,
    "TPU v5e": 16 << 30,
    "TPU v5": 95 << 30,         # v5p
    "TPU v5p": 95 << 30,
    "TPU v4": 32 << 30,
    "TPU v6 lite": 32 << 30,    # Trillium
    "TPU v3": 16 << 30,
    "TPU v2": 8 << 30,
}
# Fraction of raw capacity treated as plannable: the runtime reserves a
# slice and serving needs workspace for concurrently-dispatched prefill
# programs. Calibrated against a real failure: a trio estimated at
# 12.4 GiB resident OOM'd at concurrent prefill on a 16 GiB v5e, so
# plannable is set below that observed ceiling.
_HBM_UTILIZATION = 0.75


def device_memory_bytes() -> Optional[int]:
    """Plannable per-device HBM bytes: memory_stats' bytes_limit where
    the backend reports it, else a device_kind table — both scaled by
    _HBM_UTILIZATION. None (no check) when neither source knows."""
    import jax
    try:
        dev = jax.devices()[0]
    except Exception:
        return None
    try:
        stats = dev.memory_stats()
    except Exception:
        # A plugin whose memory_stats RAISES (vs axon's None) still gets
        # the device_kind fallback below.
        stats = None
    raw = (stats or {}).get("bytes_limit")
    if not raw:
        raw = _DEVICE_KIND_HBM.get(getattr(dev, "device_kind", ""))
    return int(raw * _HBM_UTILIZATION) if raw else None


def partition_devices(weights: list[int], n_devices: int) -> list[list[int]]:
    """Split device indices 0..n-1 into one contiguous group per weight.

    Every group starts at 1 device; remaining devices are granted by
    repeated DOUBLING (keeps each submesh a power of two, so TP axis sizes
    divide heads/mlp cleanly), always to the group with the highest
    bytes-per-device. Groups are contiguous index ranges — on a real slice,
    neighboring indices are ICI neighbors, so a submesh's collectives stay
    on-torus. Leftover devices (when no group can double) stay idle.

    If there are more models than devices, groups share: model i gets
    device i % n_devices (time-multiplexed residency, still correct —
    XLA serializes programs per device).
    """
    m = len(weights)
    if m == 0:
        return []
    if n_devices < m:
        return [[i % n_devices] for i in range(m)]

    sizes = [1] * m
    remaining = n_devices - m
    while True:
        # candidate = most HBM-pressured group whose doubling fits
        best, best_load = None, -1.0
        for i in range(m):
            if sizes[i] <= remaining:
                load = weights[i] / sizes[i]
                if load > best_load:
                    best, best_load = i, load
        if best is None:
            break
        remaining -= sizes[best]
        sizes[best] *= 2

    groups: list[list[int]] = []
    start = 0
    for size in sizes:
        groups.append(list(range(start, start + size)))
        start += size
    return groups


def _engine_identity(cfg: dict[str, Any]) -> str:
    """Two configs with the same identity share one engine (and submesh)."""
    return f"{cfg.get('model', 'tiny-gemma')}|{cfg.get('checkpoint', '')}"


def check_fleet_fits(identities: dict[str, list[dict[str, Any]]],
                     groups: list[list[int]],
                     budget_bytes: int) -> None:
    """Validate every device's resident-bytes total against its HBM.

    Per-group per-device bytes = the group's engine estimate divided by
    its submesh size (TP shards weights and KV); groups sharing a device
    (models > devices) accumulate. An over-budget device triggers the
    degrade path: the largest offending group whose config does NOT set
    quant explicitly flips to int8 with a warning; if no flippable group
    remains and a device is still over, raise with the full breakdown —
    a clear plan-time error instead of an opaque XLA allocation failure
    minutes into engine builds (VERDICT r2 weak #3).
    """
    items = list(identities.items())

    def per_device_totals():
        from . import _cache_key
        totals: dict[int, float] = {}
        contrib = []  # (ident, cfgs, group, per_dev_bytes)
        for (ident, cfgs), group in zip(items, groups):
            # One identity can still build SEVERAL resident engines: the
            # engine cache keys on more than (model, checkpoint) — e.g.
            # two knights with different max_seq_len — so charge each
            # distinct engine config, not the identity once.
            distinct = {_cache_key(c): c for c in cfgs}
            per_dev = 0.0
            for c in distinct.values():
                try:
                    per_dev += (estimate_engine_hbm_bytes(c)
                                / max(len(group), 1))
                except ValueError:
                    pass  # unknown model: same tolerance as the weights
                    # loop — plan proceeds, XLA is the backstop
            contrib.append((ident, cfgs, group, per_dev))
            for dev in group:
                totals[dev] = totals.get(dev, 0.0) + per_dev
        return totals, contrib

    while True:
        totals, contrib = per_device_totals()
        over = {d: t for d, t in totals.items() if t > budget_bytes}
        if not over:
            return
        worst_dev = max(over, key=over.get)
        # Two degrade tiers: bf16 → int8, then (still over) int8 → int4.
        # Only AUTO-degraded int8 re-flips — an operator's explicit
        # quant/dtype choice is never rewritten.
        flippable = [(ident, cfgs, per_dev)
                     for ident, cfgs, group, per_dev in contrib
                     if worst_dev in group
                     # EVERY config in the group must be unpinned — the
                     # flip rewrites them all, and an explicit
                     # quant/float32 choice is the operator's to keep
                     and all((("quant" not in c)
                              or (c.get("_quant_auto_degraded")
                                  and c.get("quant") == "int8"))
                             and c.get("dtype", "bfloat16") != "float32"
                             for c in cfgs)]
        if not flippable:
            def gib(x): return f"{x / (1 << 30):.1f} GiB"
            lines = "; ".join(
                f"{ident.split('|')[0]}: {gib(per_dev)}/device over "
                f"{len(group)} device(s)"
                for ident, _c, group, per_dev in contrib)
            raise ValueError(
                f"Fleet does not fit: device {worst_dev} needs "
                f"{gib(over[worst_dev])} of {gib(budget_bytes)} HBM "
                f"({lines}). Fix: quant='int8'/'int4' on the big models, "
                "fewer models per chip, smaller max_seq_len/num_slots, "
                "or more devices.")
        ident, cfgs, per_dev = max(flippable, key=lambda x: x[2])
        next_quant = ("int4" if cfgs[0].get("quant") == "int8"
                      else "int8")
        warnings.warn(
            f"Fleet over HBM budget on device {worst_dev}: quantizing "
            f"{ident.split('|')[0]} to {next_quant} to fit; set "
            "quant explicitly to override", stacklevel=3)
        for c in cfgs:
            c["quant"] = next_quant
            # Surfaced in the engine's describe() as e.g. "int8
            # (auto-degraded)" — a non-interactive/driver run can easily
            # miss the warning stream, and the serving numerics silently
            # differ from what the operator configured (advisor r3).
            c["_quant_auto_degraded"] = True


def fleet_health() -> dict[str, Any]:
    """Health roll-up of every resident engine's circuit breaker (ISSUE 1
    engine→adapter-fallback rung): per-engine snapshots keyed exactly like
    the engine cache, plus open/total counts. A fleet where `open > 0`
    has at least one engine the adapters are routing around; `degraded`
    additionally counts engines with recent (not yet trip-level)
    consecutive failures. `draining` reports the admission gate and
    `hangs` the watchdog's recent hang detections (ISSUE 2 time ladder).
    `schedulers` (ISSUE 4) snapshots every live continuous-batching
    session scheduler: queue depth and per-session state, so an operator
    can see WHO is waiting behind a drain or a full batch. Cheap —
    host-side counters only, no device work — so status surfaces can
    poll it per round."""
    from . import breaker_snapshots, deadlines
    from ..utils import telemetry
    from .scheduler import schedulers
    snaps = breaker_snapshots()
    sched_snaps = [s.snapshot() for s in schedulers()]
    return {
        "engines": snaps,
        "total": len(snaps),
        "open": sum(1 for s in snaps if s["open"]),
        "degraded": sum(1 for s in snaps
                        if s["failures"] > 0 and not s["open"]),
        "draining": deadlines.DRAINING,
        "hangs": len(deadlines.hang_log()),
        "schedulers": sched_snaps,
        "queued_sessions": sum(s["queued"] for s in sched_snaps),
        # ISSUE 5: the unified store's view — hang/fault/breaker/sched
        # counters, flight-recorder state — so fleet_health is a window
        # onto the SAME registry bench records and status render.
        "telemetry": telemetry.registry_view(),
        # ISSUE 6: compile-observatory roll-up — is the fleet in steady
        # state, and has anything recompiled mid-serve since?
        "perf": _perf_rollup(),
        # ISSUE 12: the supervisor's restart history — totals, dead
        # engines and WHY, per-engine restart budgets. Cheap: reads the
        # process singleton's host-side state, never constructs it.
        "supervisor": _supervisor_rollup(),
        # ISSUE 17: the session router's fleet view when one is active
        # (multi-replica serving) — per-replica liveness + assignment
        # counts, migration/failover/roll history. None without one.
        "router": _router_rollup(),
    }


def _perf_rollup() -> dict[str, Any]:
    from .compile_watch import summary
    s = summary()
    return {"compile_mode": s["mode"], "compiles": s["compiles"],
            "steady_state": s["steady_state"],
            "steady_state_compiles": s["steady_state_compiles"],
            "strict": s["strict"]}


def _supervisor_rollup() -> dict[str, Any]:
    from .supervisor import supervisor_snapshot
    return supervisor_snapshot()


def _router_rollup() -> Optional[dict[str, Any]]:
    from ..router.core import active_router
    r = active_router()
    return r.describe() if r is not None else None


def drain(timeout_s: float = 30.0, flush_kv: bool = True) -> dict[str, Any]:
    """Graceful fleet drain (ISSUE 2): stop admitting turns, let every
    in-flight generation finish its rung, then flush per-knight KV state.

    Sequence:
    1. Flip the module-level admission gate (deadlines.begin_drain) —
       every later `generate_batch*` call on ANY resident engine raises
       DrainingError; calls already past the gate (in flight, or queued
       on a serve lock) complete normally.
    1b. Reject every QUEUED-but-unadmitted session scheduler request
       immediately with a clean DrainingError (ISSUE 4 satellite: a
       queued session must not wait out its whole budget just to learn
       the fleet is going away); the schedulers' ACTIVE sessions finish
       their rounds like any in-flight turn, releasing the serve locks
       step 2 waits on.
    2. For each resident engine, acquire its serve lock within
       `timeout_s` — acquisition IS the proof that in-flight work
       finished — and, holding it, flush every per-knight slot through
       the cache's normal release path (SlotBook.flush: paged pools
       decref/free their pages — including the cross-session prefix
       cache's index, which UNREFS its held pages rather than
       force-freeing (ISSUE 7), so a drained paged pool reads zero
       pages in use; contiguous slots return to the free list). An
       engine whose in-flight turn outlives the timeout is reported
       `in_flight_drained: False` and left unflushed. Host-RAM spill
       records (kv_offload) survive a drain — a resumed fleet restores
       idle sessions without re-prefill.

    Admission stays closed after drain() returns (the caller is shutting
    down, checkpointing, or re-seating); `resume()` re-opens it. Returns
    a report: per-engine flush counts and whether the drain was clean."""
    import time
    from . import _engines, _lock, deadlines
    from ..utils import telemetry
    from .scheduler import schedulers
    deadlines.begin_drain()
    # The drain is itself a postmortem trigger (ISSUE 5): the ring holds
    # whatever the fleet was doing when the operator pulled the cord.
    telemetry.recorder().record("drain_begin", timeout_s=timeout_s)
    dump_path = telemetry.flight_dump("drain")
    deadline = time.monotonic() + timeout_s
    # Queued scheduler sessions fail fast NOW — their submitters were
    # never admitted, so there is nothing to wait for; active sessions
    # drain through the serve-lock wait below like any in-flight turn.
    # The admission gate closes too (ISSUE 12): a drained scheduler
    # must not race new admissions against the flush below — resume()
    # reopens it (the module DRAINING flag alone left the gate shut).
    for s in schedulers():
        s.pause_admission("fleet.drain")
    rejected = sum(s.reject_queued() for s in schedulers())
    with _lock:
        engines = list(_engines.items())
    report: dict[str, Any] = {"draining": True, "clean": True,
                              "engines": [],
                              "queued_sessions_rejected": rejected,
                              "telemetry_dump": dump_path}
    for key, eng in engines:
        entry: dict[str, Any] = {
            "engine": getattr(getattr(eng, "cfg", None), "name", key)}
        lock = getattr(eng, "_serve_lock", None)
        acquired = True
        if lock is not None:
            acquired = lock.acquire(
                timeout=max(deadline - time.monotonic(), 0.0))
        entry["in_flight_drained"] = acquired
        if acquired:
            try:
                if flush_kv:
                    # Best-effort per engine: one cache's flush failure
                    # must not abandon the remaining engines mid-drain.
                    try:
                        entry["flushed_slots"] = eng.kv.flush()
                        # Spilled sessions' kept-resident pages are the
                        # only thing left between a flushed paged pool
                        # and zero pages in use — evacuate them to host
                        # RAM (ISSUE 7): the drain claim stays true and
                        # the sessions still resume without re-prefill
                        # after fleet.resume().
                        tier = getattr(eng, "kv_offload", None)
                        if tier is not None:
                            # evacuate() returns a restorable manifest
                            # (ISSUE 12); the drain report keeps its
                            # historical pages-count key.
                            manifest = tier.evacuate()
                            entry["evacuated_pages"] = \
                                manifest["pages_moved"]
                    except Exception as e:  # noqa: BLE001
                        entry["flush_error"] = str(e)
                        report["clean"] = False
            finally:
                if lock is not None:
                    lock.release()
        else:
            report["clean"] = False
        report["engines"].append(entry)
    return report


def resume() -> None:
    """Re-open admission after a drain (fleet_health()['draining'] goes
    False; engines accept new turns again).

    Also re-opens every attached scheduler's admission gate (ISSUE 12
    satellite): drain() closes the per-scheduler gates, and flipping
    only the module-level DRAINING flag left a drained scheduler's
    queue paused forever — submits after resume() queued but never
    admitted. Reopening is idempotent and wakes the loops."""
    from . import deadlines
    from .scheduler import schedulers
    deadlines.end_drain()
    for s in schedulers():
        s.reopen_admission()


def plan_fleet(engine_configs: list[dict[str, Any]],
               n_devices: Optional[int] = None,
               budget_bytes: Optional[int] = None) -> None:
    """Assign disjoint device groups to heterogeneous engine configs.

    Mutates each config dict, setting "devices" (a list of device indices
    into jax.devices()) — and, when a group would overflow its devices'
    HBM, degrading unpinned configs to int8 or raising a clear error
    (check_fleet_fits). No-ops when: fewer than two distinct models, any
    config already pins "devices" or "mesh" (explicit layout wins), or
    device count can't be determined.
    """
    configs = [c for c in engine_configs if c is not None]
    if any(c.get("devices") or c.get("mesh") for c in configs):
        return
    # Multi-host: join the process group before the jax.devices() below
    # initializes a single-process backend (engine/distributed.py).
    from .distributed import maybe_init_distributed
    maybe_init_distributed()
    identities: dict[str, list[dict[str, Any]]] = {}
    for c in configs:
        identities.setdefault(_engine_identity(c), []).append(c)
    if len(identities) < 2:
        return

    if n_devices is None:
        import jax
        n_devices = len(jax.devices())

    weights = []
    for ident, cfgs in identities.items():
        model_name = cfgs[0].get("model", "tiny-gemma")
        try:
            weights.append(estimate_param_count(get_model_config(model_name)))
        except ValueError:
            weights.append(1)
    groups = partition_devices(weights, n_devices)
    if budget_bytes is None:
        budget_bytes = device_memory_bytes()
    if budget_bytes:
        check_fleet_fits(identities, groups, budget_bytes)
    for (ident, cfgs), group in zip(identities.items(), groups):
        for c in cfgs:
            c["devices"] = list(group)
