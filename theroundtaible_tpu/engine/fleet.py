"""Heterogeneous multi-model fleet planning.

BASELINE.md config 3 serves three DIFFERENT checkpoints (Gemma-7B /
Llama-3-8B / Mistral-7B) from one pod at once — a capability with no
reference counterpart (the reference time-multiplexes Ollama's single GPU;
SURVEY.md §2.3 "heterogeneous multi-model scheduler"). The TPU answer is
spatial: partition the pod's chips into disjoint per-model submeshes sized
by each model's weight footprint, so every model is resident and the
orchestrator can fan a round out to all knights concurrently.

`plan_fleet` runs at adapter-initialization time (before any engine is
built): it groups the knights' tpu-llm engine configs by model identity,
sizes each group's submesh (power-of-two growth, weighted by parameter
bytes), and injects the chosen device indices into each config. Engines
then build their meshes over exactly those chips.
"""

from __future__ import annotations

from typing import Any, Optional

from .models.common import ModelConfig
from .models.registry import get_model_config


def estimate_param_count(cfg: ModelConfig) -> int:
    """Closed-form parameter count (no arrays built)."""
    e, h, k, d, f = (cfg.embed_dim, cfg.num_heads, cfg.num_kv_heads,
                     cfg.head_dim, cfg.mlp_dim)
    mlp = 3 * e * f
    if cfg.num_experts:
        mlp = cfg.num_experts * 3 * e * f + e * cfg.num_experts  # + router
    per_layer = 2 * e * h * d + 2 * e * k * d + mlp + 2 * e
    total = cfg.num_layers * per_layer + cfg.vocab_size * e + e
    if not cfg.tie_embeddings:
        total += cfg.vocab_size * e
    return total


def partition_devices(weights: list[int], n_devices: int) -> list[list[int]]:
    """Split device indices 0..n-1 into one contiguous group per weight.

    Every group starts at 1 device; remaining devices are granted by
    repeated DOUBLING (keeps each submesh a power of two, so TP axis sizes
    divide heads/mlp cleanly), always to the group with the highest
    bytes-per-device. Groups are contiguous index ranges — on a real slice,
    neighboring indices are ICI neighbors, so a submesh's collectives stay
    on-torus. Leftover devices (when no group can double) stay idle.

    If there are more models than devices, groups share: model i gets
    device i % n_devices (time-multiplexed residency, still correct —
    XLA serializes programs per device).
    """
    m = len(weights)
    if m == 0:
        return []
    if n_devices < m:
        return [[i % n_devices] for i in range(m)]

    sizes = [1] * m
    remaining = n_devices - m
    while True:
        # candidate = most HBM-pressured group whose doubling fits
        best, best_load = None, -1.0
        for i in range(m):
            if sizes[i] <= remaining:
                load = weights[i] / sizes[i]
                if load > best_load:
                    best, best_load = i, load
        if best is None:
            break
        remaining -= sizes[best]
        sizes[best] *= 2

    groups: list[list[int]] = []
    start = 0
    for size in sizes:
        groups.append(list(range(start, start + size)))
        start += size
    return groups


def _engine_identity(cfg: dict[str, Any]) -> str:
    """Two configs with the same identity share one engine (and submesh)."""
    return f"{cfg.get('model', 'tiny-gemma')}|{cfg.get('checkpoint', '')}"


def plan_fleet(engine_configs: list[dict[str, Any]],
               n_devices: Optional[int] = None) -> None:
    """Assign disjoint device groups to heterogeneous engine configs.

    Mutates each config dict, setting "devices" (a list of device indices
    into jax.devices()). No-ops when: fewer than two distinct models, any
    config already pins "devices" or "mesh" (explicit layout wins), or
    device count can't be determined.
    """
    configs = [c for c in engine_configs if c is not None]
    if any(c.get("devices") or c.get("mesh") for c in configs):
        return
    # Multi-host: join the process group before the jax.devices() below
    # initializes a single-process backend (engine/distributed.py).
    from .distributed import maybe_init_distributed
    maybe_init_distributed()
    identities: dict[str, list[dict[str, Any]]] = {}
    for c in configs:
        identities.setdefault(_engine_identity(c), []).append(c)
    if len(identities) < 2:
        return

    if n_devices is None:
        import jax
        n_devices = len(jax.devices())

    weights = []
    for ident, cfgs in identities.items():
        model_name = cfgs[0].get("model", "tiny-gemma")
        try:
            weights.append(estimate_param_count(get_model_config(model_name)))
        except ValueError:
            weights.append(1)
    groups = partition_devices(weights, n_devices)
    for (ident, cfgs), group in zip(identities.items(), groups):
        for c in cfgs:
            c["devices"] = list(group)
